"""Percolator: reverse search — match documents against stored queries.

Re-design of modules/percolator (PercolatorFieldMapper + PercolateQuery
Builder): queries are indexed as documents with a `percolator`-typed field;
a `percolate` query takes candidate document(s), and matches the stored
queries that would have matched them. The candidate doc set is tiny (1..n),
so matching runs host-side with a direct query evaluator over a one-doc
parsed view — no device round trip (the reference similarly builds an
in-memory single-doc index per percolation).
"""

from __future__ import annotations

import fnmatch
import re
from typing import Any, Dict, List, Optional, Tuple

from opensearch_tpu.common.errors import QueryShardError
from opensearch_tpu.search import dsl


class DocView:
    """Parsed candidate document: analyzed terms + raw values per field."""

    def __init__(self, mapper, source: dict):
        self.mapper = mapper
        parsed = mapper.parse_document("_percolate", source)
        self.fields = parsed.fields
        self.source = source

    def terms(self, field: str) -> List[str]:
        pf = self.fields.get(field)
        if pf is None:
            return []
        if pf.terms:
            return [t for t, _ in pf.terms]
        if pf.exact_values:
            return [str(v) for v in pf.exact_values]
        return []

    def positions(self, field: str) -> List[Tuple[str, int]]:
        pf = self.fields.get(field)
        return list(pf.terms or []) if pf is not None else []

    def numeric(self, field: str) -> List[float]:
        pf = self.fields.get(field)
        if pf is None:
            return []
        return [float(v) for v in (pf.numeric_values or [])]

    def exists(self, field: str) -> bool:
        return field in self.fields


def matches(node: dsl.QueryNode, doc: DocView) -> bool:
    """Host evaluation of a parsed query against one document — the
    MemoryIndex-equivalent match path. Scoring-free (percolate hits score
    constant like the reference's verified-candidate path)."""
    m = _MATCHERS.get(type(node))
    if m is None:
        raise QueryShardError(
            f"query [{type(node).__name__}] is not supported in a "
            f"percolator context")
    return m(node, doc)


def _match_terms(node, doc) -> bool:
    ft = doc.mapper.get_field(node.field)
    if ft is not None and ft.is_text:
        analyzer = doc.mapper.analysis.get(ft.search_analyzer or ft.analyzer)
        wanted = [t for t, _ in analyzer.analyze(str(node.query))]
    else:
        wanted = [str(node.query)]
    have = set(doc.terms(node.field))
    if not wanted:
        return False
    hits = [t in have for t in wanted]
    if node.operator == "and":
        return all(hits)
    from opensearch_tpu.search.dsl import parse_minimum_should_match
    msm = parse_minimum_should_match(node.minimum_should_match,
                                     len(wanted)) \
        if node.minimum_should_match is not None else 1
    return sum(hits) >= max(1, msm)


def _match_phrase(node, doc) -> bool:
    ft = doc.mapper.get_field(node.field)
    if ft is None:
        return False
    analyzer = doc.mapper.analysis.get(ft.search_analyzer or ft.analyzer)
    wanted = [t for t, _ in analyzer.analyze(str(node.query))]
    if not wanted:
        return False
    pos = doc.positions(node.field)
    index: Dict[str, List[int]] = {}
    for term, p in pos:
        index.setdefault(term, []).append(p)
    if any(t not in index for t in wanted):
        return False
    slop = node.slop
    for start in index[wanted[0]]:
        ok = True
        prev = start
        for t in wanted[1:]:
            nxt = [p for p in index[t] if prev < p <= prev + 1 + slop]
            if not nxt:
                ok = False
                break
            prev = min(nxt)
        if ok:
            return True
    return False


def _match_term(node, doc) -> bool:
    value = str(node.value)
    if getattr(node, "case_insensitive", False):
        return value.lower() in {t.lower() for t in doc.terms(node.field)}
    return value in doc.terms(node.field)


def _match_range(node, doc) -> bool:
    ft = doc.mapper.get_field(node.field)
    values = doc.numeric(node.field)
    if not values:
        return False
    conv = (lambda v: ft.to_comparable(v)) if ft is not None else float
    for v in values:
        ok = True
        if node.gte is not None and v < conv(node.gte):
            ok = False
        if node.gt is not None and v <= conv(node.gt):
            ok = False
        if node.lte is not None and v > conv(node.lte):
            ok = False
        if node.lt is not None and v >= conv(node.lt):
            ok = False
        if ok:
            return True
    return False


def _match_bool(node, doc) -> bool:
    for clause in list(node.must) + list(node.filter):
        if not matches(clause, doc):
            return False
    for clause in node.must_not:
        if matches(clause, doc):
            return False
    if node.should:
        hits = sum(1 for c in node.should if matches(c, doc))
        from opensearch_tpu.search.dsl import parse_minimum_should_match
        if node.minimum_should_match is not None:
            msm = parse_minimum_should_match(node.minimum_should_match,
                                             len(node.should))
        else:
            msm = 1 if not (node.must or node.filter) else 0
        return hits >= msm
    return True


_MATCHERS = {
    dsl.MatchAllQuery: lambda n, d: True,
    dsl.MatchNoneQuery: lambda n, d: False,
    dsl.MatchQuery: _match_terms,
    dsl.MatchPhraseQuery: _match_phrase,
    dsl.TermQuery: _match_term,
    dsl.TermsQuery: lambda n, d: any(str(v) in d.terms(n.field)
                                     for v in n.values),
    dsl.RangeQuery: _match_range,
    dsl.ExistsQuery: lambda n, d: d.exists(n.field),
    dsl.PrefixQuery: lambda n, d: any(t.startswith(str(n.value))
                                      for t in d.terms(n.field)),
    dsl.WildcardQuery: lambda n, d: any(
        fnmatch.fnmatchcase(t, str(n.value)) for t in d.terms(n.field)),
    dsl.RegexpQuery: lambda n, d: any(
        re.fullmatch(str(n.value), t) for t in d.terms(n.field)),
    dsl.BoolQuery: _match_bool,
    dsl.ConstantScoreQuery: lambda n, d: matches(n.filter, d),
    dsl.DisMaxQuery: lambda n, d: any(matches(c, d) for c in n.queries),
    dsl.IdsQuery: lambda n, d: False,
}


def execute_percolate(executors, node: "dsl.PercolateQuery", k: int,
                      body: dict) -> dict:
    """Run a standalone percolate search: scan stored-query docs, keep
    those whose query matches any candidate document."""
    import time
    start = time.monotonic()
    hits = []
    total = 0
    for ex in executors:
        mapper = ex.reader.mapper
        docs = [DocView(mapper, d) for d in node.documents]
        for seg in ex.reader.segments:
            for ord_ in range(seg.num_docs):
                if not seg.live[ord_]:
                    continue
                source = seg.sources[ord_]
                query_body = source.get(node.field)
                if query_body is None:
                    continue
                stored = dsl.parse_query(query_body)
                slots = [i for i, d in enumerate(docs)
                         if matches(stored, d)]
                if slots:
                    total += 1
                    if len(hits) < k:
                        hit = {"_index": ex.reader.index_name,
                               "_id": seg.doc_ids[ord_], "_score": 1.0,
                               "_source": source}
                        if len(docs) > 1:
                            hit["fields"] = {
                                "_percolator_document_slot": slots}
                        hits.append(hit)
    size = int(body.get("size", 10))
    return {
        "took": int((time.monotonic() - start) * 1000),
        "timed_out": False,
        "_shards": {"total": len(executors), "successful": len(executors),
                    "skipped": 0, "failed": 0},
        "hits": {"total": {"value": total, "relation": "eq"},
                 "max_score": 1.0 if hits else None,
                 "hits": hits[:size]},
    }
