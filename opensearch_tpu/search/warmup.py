"""Executable warmup: ahead-of-time compile registered query shapes.

The agg-config p99 cliff (VERDICT round 5: 557.9 / 384.2 ms p99 against
~2.5 ms p50) is the first-(plan-struct, shape-bucket) XLA compile landing
inside the serving path — the msearch envelope caches executables per
(plan structure, input shapes, batch bucket), so every NEW combination
pays a full compile on the query that first exhibits it.  The reference
has the same problem shape (JVM warmup + Lucene query caches) and solves
it with index warmers (index/IndexWarmer.java); here the analog is
executable-level:

- every msearch group records a (plan-struct, shape-bucket) signature plus
  one representative body into a node-wide registry (record());
- the registry persists as JSON under the node data dir, so a restarted
  node knows yesterday's traffic shapes before the first query arrives;
- an index-open / node-start hook (warm_index / warm_all) REPLAYS each
  registered entry — the representative body, duplicated to its recorded
  batch bucket — through the normal msearch path with the request cache
  bypassed, compiling exactly the executables production traffic will hit;
- the XLA compiles themselves go through jax's persistent compilation
  cache (configure() points it under the data dir), so a replayed compile
  after restart is a disk hit, not a fresh HLO build.

Warmup stats surface on _nodes/stats (rest/actions.py) and bench.py
reports warmup time as its own field — compile cost is moved off the
query path and accounted for, never hidden.
"""

from __future__ import annotations

import json
import os
import threading
import time
import weakref
from collections import OrderedDict
from typing import Any, Dict, List, Optional

from opensearch_tpu.common.errors import SettingsError
from opensearch_tpu.common.settings import _parse_bool
from opensearch_tpu.search.compile import struct_fingerprint

# registry bound: LRU over distinct (plan-struct, shape-bucket) sigs —
# a node serving a real workload sees tens of shapes, not thousands; the
# cap keeps pathological shape churn (randomized tests) bounded
MAX_ENTRIES = 256

# throttle for write-through persistence: at most one registry write per
# this many seconds (record() sits on the msearch hot path)
_PERSIST_INTERVAL_S = 5.0


class WarmupRegistry:
    """Node-wide registry of compiled-executable signatures + replay."""

    def __init__(self):
        self._entries: "OrderedDict[str, dict]" = OrderedDict()
        self._sig_memo: Dict[Any, str] = {}
        self._lock = threading.Lock()
        self._path: Optional[str] = None
        self._dirty = False
        self._last_persist = 0.0
        self._recording = True
        self._atexit_registered = False
        # tuned by Node from settings (search.warmup.budget_ms /
        # search.warmup_on_open); IndicesService.open_index reads them
        self.default_budget_s = 10.0
        self.warm_on_open = True
        self.stats_ = {
            "recorded": 0, "loaded": 0, "warmup_runs": 0,
            "warmed_entries": 0, "warmup_errors": 0, "skipped_entries": 0,
            "last_warmup_ms": 0.0, "compile_cache_dir": None,
        }

    # ------------------------------------------------------------ configure

    def configure(self, data_path: Optional[str],
                  compile_cache: bool = True,
                  min_compile_secs: float = 0.0) -> None:
        """Bind the registry to a node data dir: load persisted entries and
        point jax's persistent compilation cache under it, so executables
        survive process restarts (first compile after restart = disk read).
        Both artifacts live under the gateway's _state dir — top-level
        directories in the data path are index data and would be reported
        as dangling indices."""
        if data_path is None:
            return
        state_dir = os.path.join(data_path, "_state")
        try:
            os.makedirs(state_dir, exist_ok=True)
        except OSError:
            return
        path = os.path.join(state_dir, "warmup_registry.json")
        with self._lock:
            self._path = path
        self.load(path)
        if not self._atexit_registered:
            # dirty entries that never met the throttle window still land
            # on disk at interpreter exit
            import atexit
            atexit.register(self.flush)
            self._atexit_registered = True
        if compile_cache:
            self.enable_compile_cache(os.path.join(state_dir, "xla_cache"),
                                      min_compile_secs)

    def enable_compile_cache(self, cache_dir: str,
                             min_compile_secs: float = 0.0) -> None:
        """jax persistent compilation cache (works on the CPU backend too).
        Guarded per-flag: absent config names on older jax are skipped."""
        import jax
        try:
            os.makedirs(cache_dir, exist_ok=True)
        except OSError:
            return
        for name, value in (
                ("jax_compilation_cache_dir", cache_dir),
                ("jax_persistent_cache_min_compile_time_secs",
                 min_compile_secs),
                ("jax_persistent_cache_min_entry_size_bytes", -1)):
            try:
                jax.config.update(name, value)
            except Exception:   # except-ok: jax-version compatibility -- absent config names on older jax are skipped
                pass
        self.stats_["compile_cache_dir"] = cache_dir

    # -------------------------------------------------------------- record

    def record(self, index_name: str, body: dict, b_pad: int,
               sig_material: Any) -> None:
        """Register one msearch group's executable signature. Called per
        group per batch — memoized fingerprinting + LRU keep it O(dict)."""
        if not self._recording:
            return
        # the index is part of the identity: warm_executor filters
        # replays by index, so a same-shaped registration from another
        # index must create its OWN entry — deduping across indices
        # leaves the later index with nothing to replay
        key = (index_name, sig_material)
        sig = self._sig_memo.get(key)
        if sig is None:
            sig = struct_fingerprint(key)
            if len(self._sig_memo) > 4 * MAX_ENTRIES:
                self._sig_memo.clear()
            self._sig_memo[key] = sig
        with self._lock:
            if sig in self._entries:
                self._entries.move_to_end(sig)
                known = True
            else:
                known = False
        if known:
            # still give throttled persistence a chance: a burst of new
            # shapes inside one throttle window leaves _dirty set, and
            # steady-state traffic (all-known sigs) is what eventually
            # writes it through
            self._maybe_persist()
            return
        with self._lock:
            try:
                body_json = json.dumps(body)
            except (TypeError, ValueError):
                return                 # non-serializable body: skip
            self._entries[sig] = {"index": index_name,
                                  "body": json.loads(body_json),
                                  "b_pad": int(b_pad)}
            while len(self._entries) > MAX_ENTRIES:
                self._entries.popitem(last=False)
            self.stats_["recorded"] += 1
            self._dirty = True
        self._maybe_persist()

    # ------------------------------------------------------------- persist

    def _maybe_persist(self) -> None:
        if self._path is None or not self._dirty:
            return
        now = time.monotonic()
        if now - self._last_persist < _PERSIST_INTERVAL_S:
            return
        self.flush()

    def flush(self) -> None:
        """Write the registry through to disk (atomic rename)."""
        with self._lock:
            if self._path is None or not self._dirty:
                return
            path = self._path
            payload = json.dumps({"version": 1,
                                  "entries": self._entries}, indent=0)
            self._dirty = False
            self._last_persist = time.monotonic()
        try:
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                f.write(payload)
            os.replace(tmp, path)
        except OSError:
            pass

    def load(self, path: str) -> int:
        """Merge persisted entries (disk entries lose to in-memory ones)."""
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, ValueError):
            return 0
        loaded = 0
        with self._lock:
            for sig, entry in (data.get("entries") or {}).items():
                if not isinstance(entry, dict) or "body" not in entry:
                    continue
                if sig not in self._entries:
                    self._entries[sig] = entry
                    loaded += 1
            self.stats_["loaded"] += loaded
        return loaded

    # ---------------------------------------------------------------- warm

    def entries(self, index_name: Optional[str] = None) -> List[dict]:
        with self._lock:
            return [dict(e) for e in self._entries.values()
                    if index_name is None or e.get("index") == index_name]

    def registered_count(self, index_name: Optional[str] = None) -> int:
        """Registered (plan-struct, shape-bucket) entries for an index
        without copying bodies — the churn ledger (ISSUE 13) stamps this
        on every refresh/merge record: a `recompile` verdict with
        registered entries means a replay could pre-compile the new
        shape bucket off the serving path; zero means the first query
        pays the cliff with no warmup to ride."""
        with self._lock:
            if index_name is None:
                return len(self._entries)
            return sum(1 for e in self._entries.values()
                       if e.get("index") == index_name)

    def warm_executor(self, executor, index_name: Optional[str] = None,
                      budget_s: Optional[float] = None) -> dict:
        """Replay registered entries through one shard executor. Returns
        {"warmed": n, "errors": n, "took_ms": t}."""
        t0 = time.monotonic()
        warmed = errors = 0
        entries = self.entries(index_name)
        self._recording = False
        # replay transfers record under a `warmup.`-prefixed channel so
        # the ledger's serving channels stay uncontaminated while replay
        # traffic stays attributable (telemetry/ledger.py)
        from opensearch_tpu.telemetry import TELEMETRY as _tel
        with _tel.ledger.tagged("warmup"):
            warmed, errors = self._warm_entries(executor, entries,
                                                budget_s, t0)
        took = (time.monotonic() - t0) * 1000
        self.stats_["warmup_runs"] += 1
        self.stats_["warmed_entries"] += warmed
        self.stats_["warmup_errors"] += errors
        self.stats_["last_warmup_ms"] = round(took, 2)
        # mirror into the telemetry registry so _nodes/stats' `telemetry`
        # section carries warmup replays next to the compile counters
        _tel.metrics.counter("warmup.replays").inc(warmed)
        _tel.metrics.counter("warmup.errors").inc(errors)
        _tel.metrics.histogram("warmup.replay_ms").observe(took)
        return {"warmed": warmed, "errors": errors,
                "took_ms": round(took, 2)}

    def _warm_entries(self, executor, entries, budget_s, t0):
        """The replay loop proper; returns (warmed, errors)."""
        warmed = errors = 0
        try:
            for entry in entries:
                if budget_s is not None and \
                        time.monotonic() - t0 > budget_s:
                    self.stats_["skipped_entries"] += 1
                    continue
                try:
                    bodies = [entry["body"]] * max(int(entry.get(
                        "b_pad", 1)), 1)

                    def _replay(bodies=bodies):
                        # fault site + bounded transient retry: a flaky
                        # replay costs a retry, not the whole entry —
                        # and a permanently failing entry costs only
                        # itself (errors += 1), never index-open
                        from opensearch_tpu.common import faults
                        if faults.ENABLED:
                            faults.fire("warmup.replay")
                        # waves=1: the recorded b_pad already reflects
                        # any serving-time wave split, so the replay
                        # must not re-split it — one wave reproduces
                        # the registered (plan-struct, shape-bucket,
                        # b_pad) executable exactly
                        executor.multi_search(bodies,
                                              _bypass_request_cache=True,
                                              waves=1)
                    from opensearch_tpu.common import retry as _retry
                    _retry.call_with_retry(_replay, label="warmup.replay")
                    warmed += 1
                except Exception:   # except-ok: replay isolation -- a permanently failing entry costs only itself, never index-open
                    errors += 1
        finally:
            self._recording = True
        return warmed, errors

    def warm_index(self, index_name: str, shard_executors,
                   budget_s: Optional[float] = None) -> dict:
        """Index-open hook: AOT-compile this index's registered executables
        (reference analog: IndexWarmer running registered warmers on a new
        reader before it serves searches). `budget_s` (default
        `default_budget_s`, settable via search.warmup.budget_ms) is ONE
        deadline shared across all shards, not per shard."""
        if budget_s is None:
            budget_s = self.default_budget_s
        t0 = time.monotonic()
        out = {"warmed": 0, "errors": 0, "took_ms": 0.0}
        for ex in shard_executors:
            remaining = None if budget_s is None else \
                max(budget_s - (time.monotonic() - t0), 0.0)
            r = self.warm_executor(ex, index_name, remaining)
            out["warmed"] += r["warmed"]
            out["errors"] += r["errors"]
        out["took_ms"] = round((time.monotonic() - t0) * 1000, 2)
        self.flush()
        return out

    def warm_all(self, indices_service, budget_s: Optional[float] = 30.0
                 ) -> dict:
        """Node-start hook: warm every index that has registered entries."""
        t0 = time.monotonic()
        out = {"warmed": 0, "errors": 0, "took_ms": 0.0}
        names = {e.get("index") for e in self.entries()}
        for name in sorted(n for n in names if n):
            if name not in indices_service.indices:
                self.stats_["skipped_entries"] += 1
                continue
            svc = indices_service.indices[name]
            if getattr(svc, "closed", False):
                continue
            remaining = None if budget_s is None else \
                max(budget_s - (time.monotonic() - t0), 0.0)
            r = self.warm_index(name, [s.executor for s in svc.shards],
                                remaining)
            out["warmed"] += r["warmed"]
            out["errors"] += r["errors"]
        out["took_ms"] = round((time.monotonic() - t0) * 1000, 2)
        self.flush()
        return out

    # --------------------------------------------------------------- stats

    def stats(self) -> dict:
        with self._lock:
            return {**self.stats_, "registered": len(self._entries),
                    "registry_path": self._path}

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._sig_memo.clear()
            self._dirty = False


class Precompiler:
    """Off-path shape precompilation (ISSUE 16): a background worker
    that replays the warmup registry against a shard executor whenever
    a segment publish lands a novel device shape bucket, so the ~400 ms
    first-touch XLA cliff is paid on this helper thread instead of the
    first user query over the new segment.

    Flow: ShardReader collects novel shape fingerprints at upload;
    IndexShard hands them here (request()) right after the churn record
    publishes; the worker coalesces pending requests per index, replays
    the registry via WARMUP.warm_executor under offpath_compiles() (so
    the compiles count as `search.xla_compile_offpath`, not serving
    cache misses), then flips the pending churn verdicts to
    `precompiled` via the ledger's verdict lifecycle.

    No-op discipline (gate-lint row, bench.py pristine assert): OFF by
    default, `gate()` returns None when disabled — the refresh path
    pays one attribute load + branch. `POST /_warmup/_precompile`
    (sweep()) works even while disabled: it is an explicit operator
    trigger, not the hot path."""

    def __init__(self):
        self.enabled = False
        # barrier mode (second-level flag, like the shedder's
        # shape_enabled): a publish STAGES the new (segments, device)
        # pair, replays the registry against it on the publishing
        # thread with only that thread seeing the stage, then commits —
        # serving threads can never observe a segment set whose
        # executables are uncompiled, so recompile-on-serve is zero by
        # construction (async mode merely races the first query).
        # Costs the publishing thread the replay; visibility of each
        # refresh is delayed by the compile, exactly like a longer
        # refresh interval.
        self.barrier = False
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._queue: List[dict] = []
        self._queued_sigs: set = set()
        self._thread: Optional[threading.Thread] = None
        self._stop = False
        # one replay pass's compile budget (shared deadline across the
        # registry, same semantics as warm_executor's budget_s)
        self.budget_ms = 2000.0
        self.stats_ = {"requests": 0, "runs": 0, "warmed": 0,
                       "errors": 0, "deduped": 0, "last_run_ms": 0.0}

    # ------------------------------------------------------------- gate

    def gate(self):
        """None when disabled (the no-op discipline); self when on."""
        if not self.enabled:
            return None
        return self

    # ---------------------------------------------------------- request

    def request(self, executor, index_name: str, shapes,
                churn_id: Optional[int] = None) -> None:
        """Enqueue a precompile pass for `executor` covering the given
        novel shape fingerprints. Deduplicates against already-queued
        shapes — a burst of refreshes publishing the same shape bucket
        costs one replay, not one per refresh."""
        if not self.enabled:
            return
        with self._cv:
            fresh = [s for s in shapes if s not in self._queued_sigs]
            if not fresh and churn_id is None:
                self.stats_["deduped"] += 1
                return
            self._queued_sigs.update(fresh)
            self._queue.append({
                "executor": weakref.ref(executor),
                "index": index_name,
                "shapes": fresh,
                "churn_ids": [churn_id] if churn_id is not None else [],
            })
            self.stats_["requests"] += 1
            self._cv.notify()

    # ----------------------------------------------------------- worker

    def _take_locked(self) -> Optional[dict]:
        """Pop + coalesce every queued request for the head entry's
        index into one batch (merged churn ids, shapes released from
        the dedupe set). Caller holds the lock."""
        if not self._queue:
            return None
        head = self._queue[0]
        batch = {"executor": head["executor"], "index": head["index"],
                 "churn_ids": [], "shapes": []}
        rest = []
        for req in self._queue:
            if req["index"] == batch["index"]:
                batch["churn_ids"].extend(req["churn_ids"])
                batch["shapes"].extend(req["shapes"])
            else:
                rest.append(req)
        self._queue = rest
        for s in batch["shapes"]:
            self._queued_sigs.discard(s)
        return batch

    def _service(self, batch: dict) -> None:
        executor = batch["executor"]()
        if executor is None:
            return                        # shard closed; nothing to warm
        from opensearch_tpu.search.executor import offpath_compiles
        from opensearch_tpu.telemetry import TELEMETRY as _tel
        t0 = time.monotonic()
        try:
            with offpath_compiles():
                r = WARMUP.warm_executor(executor, batch["index"],
                                         budget_s=self.budget_ms / 1000.0)
        except Exception:   # except-ok: worker isolation -- a failing replay pass must not kill the precompile thread
            self.stats_["errors"] += 1
            return
        took = (time.monotonic() - t0) * 1000
        with self._lock:
            self.stats_["runs"] += 1
            self.stats_["warmed"] += r["warmed"]
            self.stats_["errors"] += r["errors"]
            self.stats_["last_run_ms"] = round(took, 2)
        _tel.metrics.counter("precompile.runs").inc()
        _tel.metrics.histogram("precompile.run_ms").observe(took)
        if batch["churn_ids"]:
            _tel.churn.mark_precompiled(batch["churn_ids"], took)

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._stop:
                    self._cv.wait(timeout=0.5)
                if self._stop and not self._queue:
                    return
                batch = self._take_locked()
            if batch is not None:
                self._service(batch)

    def run_pending(self) -> int:
        """Synchronously drain the queue on the calling thread — the
        deterministic path for tests and the REST trigger."""
        n = 0
        while True:
            with self._lock:
                batch = self._take_locked()
            if batch is None:
                return n
            self._service(batch)
            n += 1

    def precompile_staged(self, executor, index_name: str) -> float:
        """Barrier-mode replay: warm `executor` on the CALLING (i.e.
        publishing) thread — the caller holds the reader's stage open
        and made it thread-visible, so the compiles land against the
        exact pair about to publish. Returns the replay wall ms."""
        from opensearch_tpu.search.executor import offpath_compiles
        from opensearch_tpu.telemetry import TELEMETRY as _tel
        t0 = time.monotonic()
        try:
            with offpath_compiles():
                r = WARMUP.warm_executor(executor, index_name,
                                         budget_s=self.budget_ms / 1000.0)
        except Exception:   # except-ok: publish isolation -- a failing replay must not abort the refresh that triggered it
            self.stats_["errors"] += 1
            return 0.0
        took = (time.monotonic() - t0) * 1000
        with self._lock:
            self.stats_["runs"] += 1
            self.stats_["warmed"] += r["warmed"]
            self.stats_["errors"] += r["errors"]
            self.stats_["last_run_ms"] = round(took, 2)
        _tel.metrics.counter("precompile.runs").inc()
        _tel.metrics.histogram("precompile.run_ms").observe(took)
        return took

    # ------------------------------------------------------------ sweep

    def sweep(self, indices_service, index_name: Optional[str] = None,
              budget_s: Optional[float] = None) -> dict:
        """`POST /_warmup/_precompile`: replay the registry for one
        index (or all) on the calling thread, compiles attributed
        off-path. Deliberately works even while the background worker
        is disabled — an explicit operator trigger is opt-in by
        construction."""
        from opensearch_tpu.search.executor import offpath_compiles
        with offpath_compiles():
            if index_name is None:
                return WARMUP.warm_all(indices_service, budget_s)
            if index_name not in indices_service.indices:
                from opensearch_tpu.common.errors import \
                    IndexNotFoundError
                raise IndexNotFoundError(index_name)
            svc = indices_service.indices[index_name]
            return WARMUP.warm_index(
                index_name, [s.executor for s in svc.shards], budget_s)

    # --------------------------------------------------------- lifecycle

    def set_enabled(self, on: bool) -> None:
        on = bool(on)
        if on == self.enabled:
            return
        if on:
            self.enabled = True
            self._stop = False
            self._thread = threading.Thread(target=self._run,
                                            name="tpu-precompile",
                                            daemon=True)
            self._thread.start()
        else:
            self.enabled = False
            with self._cv:
                self._stop = True
                self._cv.notify_all()
            t = self._thread
            if t is not None:
                t.join(timeout=2.0)
            self._thread = None
            with self._lock:
                self._queue = []
                self._queued_sigs.clear()

    # ---------------------------------------------------------- settings

    @staticmethod
    def parse_settings(flat: dict) -> dict:
        """Strict parse of precompiler settings (WaveScheduler idiom):
        returns {enabled, budget_ms} with None for absent keys."""
        def _num(key, cast):
            if key not in flat:
                return None
            try:
                return cast(flat[key])
            except (TypeError, ValueError):
                raise SettingsError(
                    f"invalid value for [{key}]: [{flat[key]}]")
        out = {"enabled": None, "budget_ms": None, "barrier": None}
        if "search.precompile.enabled" in flat:
            out["enabled"] = _parse_bool(
                flat["search.precompile.enabled"],
                "search.precompile.enabled")
        if "search.precompile.barrier" in flat:
            out["barrier"] = _parse_bool(
                flat["search.precompile.barrier"],
                "search.precompile.barrier")
        out["budget_ms"] = _num("search.precompile.budget_ms", float)
        return out

    def apply_settings(self, flat: dict) -> None:
        parsed = self.parse_settings(flat)
        if parsed["budget_ms"] is not None:
            self.budget_ms = parsed["budget_ms"]
        if parsed["barrier"] is not None:
            self.barrier = parsed["barrier"]
        if parsed["enabled"] is not None:
            self.set_enabled(parsed["enabled"])

    # ------------------------------------------------------------- stats

    def stats(self) -> dict:
        with self._lock:
            return {**self.stats_, "enabled": self.enabled,
                    "barrier": self.barrier,
                    "queued": len(self._queue),
                    "budget_ms": self.budget_ms}


# node-wide singletons, like REQUEST_CACHE / QUERY_CACHE
WARMUP = WarmupRegistry()
PRECOMPILE = Precompiler()
