"""Scroll + point-in-time search contexts.

Re-design of the reference's keep-alive reader contexts: scroll
(search/internal/LegacyReaderContext + SearchScrollAsyncAction) and PIT
(CreatePitController, search/internal/PitReaderContext.java). A context pins
per-shard `PinnedReader` snapshots — segments are immutable arrays, so a pin
is reference-holding, no file leases needed. Scroll pagination rides the
search_after cursor machinery in controller.execute_search with the internal
(shard, seg, ord) tiebreak, matching the reference's scroll-by-last-doc
semantics.
"""

from __future__ import annotations

import secrets
import time
from typing import Dict, List, Optional

from opensearch_tpu.common.errors import IllegalArgumentError, ParsingError
from opensearch_tpu.common.settings import parse_time_value
from opensearch_tpu.search.controller import execute_search
from opensearch_tpu.search.executor import PinnedReader, SearchExecutor


class _Context:
    __slots__ = ("executors", "filters", "body", "expiry_s", "keep_alive_s",
                 "cursor_values", "cursor_tiebreak")

    def __init__(self, executors, filters, body, keep_alive_s):
        self.executors = executors
        self.filters = filters
        self.body = body
        self.keep_alive_s = keep_alive_s
        self.expiry_s = time.monotonic() + keep_alive_s
        self.cursor_values = None
        self.cursor_tiebreak = None

    def touch(self, keep_alive: Optional[str]):
        if keep_alive:
            self.keep_alive_s = parse_time_value(keep_alive, "keep_alive")
        self.expiry_s = time.monotonic() + self.keep_alive_s


def _pin_executors(node, index_expr):
    names = node.indices.resolve(index_expr, allow_no_indices=True)
    executors, filters = [], []
    for name in names:
        svc = node.indices.get(name)
        alias_filter = node.indices.alias_filter(index_expr or "", name)
        for shard in svc.shards:
            pinned = SearchExecutor(PinnedReader(shard.executor.reader))
            pinned.max_result_window = shard.executor.max_result_window
            executors.append(pinned)
            filters.append(alias_filter)
    return executors, filters


def _purge_expired(store: Dict[str, _Context]):
    now = time.monotonic()
    for key in [k for k, ctx in store.items() if ctx.expiry_s < now]:
        del store[key]


# -------------------------------------------------------------------- scroll

def start_scroll(node, index_expr, body, keep_alive: str) -> dict:
    _purge_expired(node.scroll_contexts)
    keep_alive_s = parse_time_value(keep_alive or "1m", "scroll")
    body = dict(body or {})
    if isinstance(body.get("query"), dict) and "hybrid" in body["query"]:
        # hybrid pages rank by the combined normalized score, which has
        # no stable search_after cursor — same rejection as the reference
        from opensearch_tpu.common.errors import IllegalArgumentError
        raise IllegalArgumentError(
            "[scroll] is not supported with a [hybrid] query")
    body.pop("from", None)
    executors, filters = _pin_executors(node, index_expr)
    ctx = _Context(executors, filters, body, keep_alive_s)
    scroll_id = secrets.token_urlsafe(24)
    node.scroll_contexts[scroll_id] = ctx
    res = execute_search(executors, body, extra_filters=filters)
    _advance(ctx, res)
    res["_scroll_id"] = scroll_id
    return res


def continue_scroll(node, scroll_id: str, keep_alive: Optional[str]) -> dict:
    _purge_expired(node.scroll_contexts)
    ctx = node.scroll_contexts.get(scroll_id)
    if ctx is None:
        raise SearchContextMissingError(scroll_id)
    ctx.touch(keep_alive)
    if ctx.cursor_values is None:
        # previous page was empty: stay empty
        res = execute_search(ctx.executors, {**ctx.body, "size": 0},
                             extra_filters=ctx.filters)
        res["hits"]["hits"] = []
    else:
        body = dict(ctx.body)
        body["search_after"] = ctx.cursor_values
        res = execute_search(ctx.executors, body, extra_filters=ctx.filters,
                             cursor_tiebreak=ctx.cursor_tiebreak)
        _advance(ctx, res)
    res["_scroll_id"] = scroll_id
    return res


def delete_scrolls(node, ids: Optional[List[str]]) -> dict:
    if ids is None:
        n = len(node.scroll_contexts)
        node.scroll_contexts.clear()
        return {"succeeded": True, "num_freed": n}
    n = 0
    for sid in ids:
        if node.scroll_contexts.pop(sid, None) is not None:
            n += 1
    return {"succeeded": True, "num_freed": n}


def _advance(ctx: _Context, res: dict):
    cursor = res.pop("_page_cursor", None)
    if cursor is not None:
        ctx.cursor_values = cursor["values"]
        ctx.cursor_tiebreak = tuple(cursor["tiebreak"])
    else:
        ctx.cursor_values = None
        ctx.cursor_tiebreak = None


# ----------------------------------------------------------------------- PIT

def create_pit(node, index_expr, keep_alive: str) -> dict:
    _purge_expired(node.pit_contexts)
    keep_alive_s = parse_time_value(keep_alive, "keep_alive")
    executors, filters = _pin_executors(node, index_expr)
    ctx = _Context(executors, filters, {}, keep_alive_s)
    pit_id = secrets.token_urlsafe(24)
    node.pit_contexts[pit_id] = ctx
    return {"pit_id": pit_id,
            "_shards": {"total": len(executors),
                        "successful": len(executors), "skipped": 0,
                        "failed": 0},
            "creation_time": int(time.time() * 1000)}


def search_with_pit(node, body: dict) -> dict:
    _purge_expired(node.pit_contexts)
    pit = body.get("pit") or {}
    pit_id = pit.get("id")
    ctx = node.pit_contexts.get(pit_id)
    if ctx is None:
        raise SearchContextMissingError(pit_id)
    ctx.touch(pit.get("keep_alive"))
    body = {k: v for k, v in body.items() if k != "pit"}
    res = execute_search(ctx.executors, body, extra_filters=ctx.filters)
    res.pop("_page_cursor", None)
    res["pit_id"] = pit_id
    return res


def delete_pits(node, ids: Optional[List[str]]) -> dict:
    if ids is None:
        freed = [{"pit_id": pid, "successful": True}
                 for pid in node.pit_contexts]
        node.pit_contexts.clear()
        return {"pits": freed}
    freed = []
    for pid in ids:
        ok = node.pit_contexts.pop(pid, None) is not None
        freed.append({"pit_id": pid, "successful": ok})
    return {"pits": freed}


class SearchContextMissingError(IllegalArgumentError):
    status = 404
    error_type = "search_context_missing_exception"

    def __init__(self, context_id):
        super().__init__(f"No search context found for id [{context_id}]")
