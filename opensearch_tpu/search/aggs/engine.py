"""Aggregation engine: builder tree → device collection program → partials.

The TPU re-design of the reference's Aggregator/LeafBucketCollector machinery
(search/aggregations/Aggregator.java:60, BucketsAggregator.java:70
collectBucket, 491 files of per-doc collector loops): instead of walking docs
one at a time, every bucket aggregation becomes

    bucket_of_rank (host lookup table over the field's sorted unique values)
    → a segment-STATIC per-lane bin assignment (factored bucket context)
    → a masked binned reduction into flat [parent_card * own_card] bins

Bucket membership is FACTORED (see eval_aggs): the bin a (doc, value) lane
lands in is segment-static for field-driven bucketing, while every
query-dependent condition lives in a dynamic mask. That factorization picks
the reduction kernel (_binned_sums): bit-packed popcount for counts,
one-hot matmul (MXU) for float sums — both of which share their static side
across a whole vmapped _msearch query batch — and scatter-add only for
data-dependent bins (nested joins, dedup). Metric aggregations collect only
the partials their render needs (_METRIC_NEEDS: avg = sum+cnt, not the full
five-reduction battery). Nesting uses the classic flattened-ordinal trick
(parent_ord * child_card + child_ord), like the reference's bucketOrd
composition.

Approximation policy: the reference uses TDigest percentiles and HLL++
cardinality; here both are EXACT, computed from per-bucket value-rank
histograms / presence bitmaps (feasible because doc values are rank-encoded
per segment), merged on the host by value.

The compiled structure is static per (agg tree, segment); partial arrays are
merged across segments/shards host-side by bucket key (reference analog:
InternalAggregation.reduce, search/aggregations/InternalAggregation.java:64).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field as dc_field, replace as dc_replace
from typing import Any, Dict, List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from opensearch_tpu.common.errors import (
    IllegalArgumentError, ParsingError, QueryShardError)
from opensearch_tpu.index.mapper import MapperService, format_date_millis, parse_date_millis
from opensearch_tpu.index.segment import Segment, ident_pairs, pad_bucket
from opensearch_tpu.search import dsl
from opensearch_tpu.search.aggs.parse import AggNode
from opensearch_tpu.search.compile import Compiler, Plan, _resolve_date_math
from opensearch_tpu.search.plan_eval import _eval_plan

MAX_AGG_BINS = 1 << 24  # guard for presence/histogram bitmaps
POS_INF = np.float32(np.inf)
NEG_INF = np.float32(-np.inf)

# Binned ADD-reductions with at most this many bins run as one-hot matmuls
# instead of scatter-adds: bin assignments are segment-static (so the
# one-hot matrix stays unbatched under a query-batch vmap and the MXU does
# the reduction), where XLA's scatter lowers to a serial loop on CPU and a
# slow path on TPU. f32 accumulation is exact for counts < 2^24.
AGG_GEMM_MAX_BINS = 256
# ...and at most this many one-hot ELEMENTS (lanes × bins): the GEMM's
# [n, bins] f32 operand is materialized, so an unbounded n would turn the
# old O(n) scatter memory into gigabytes on big segments. 2^25 f32 =
# 128 MB. The popcount path's bitmask is 32× smaller per element.
AGG_GEMM_MAX_ELEMS = 1 << 25
AGG_POPCOUNT_MAX_ELEMS = 1 << 30

# Input arrays that are segment/node-static by construction (host-computed
# lookup tables): their CONTENT is part of the plan signature, so a batched
# runner may legally pass one copy for a whole same-signature group
# (executor passes them with in_axes=None). Everything else is per-query.
CONST_INPUT_KEYS = frozenset({"table", "doc_bucket"})

# calendar interval lengths used for fixed bucketing (calendar-aware month/
# year boundaries are generated host-side as explicit boundary arrays)
_FIXED_MS = {"ms": 1, "1ms": 1, "s": 1000, "1s": 1000, "second": 1000,
             "m": 60000, "1m": 60000, "minute": 60000,
             "h": 3600000, "1h": 3600000, "hour": 3600000,
             "d": 86400000, "1d": 86400000, "day": 86400000,
             "w": 604800000, "1w": 604800000, "week": 604800000}


@dataclass
class AggPlan:
    """Compiled aggregation node for one segment."""
    name: str
    kind: str
    static: tuple = ()
    inputs: Dict[str, np.ndarray] = dc_field(default_factory=dict)
    children: List["AggPlan"] = dc_field(default_factory=list)
    query_plan: Optional[Plan] = None      # filter aggs
    query_plans: List[Plan] = dc_field(default_factory=list)  # adjacency
    render: Dict[str, Any] = dc_field(default_factory=dict)  # host-only
    # segment-static arrays CLOSED OVER by the device program instead of
    # riding the input envelope (fused bucket_bits/presence_bits kinds):
    # zero per-batch pack/upload bytes, zero in-program recompute. Content
    # is hashed into sig() so two plans share an executable only when the
    # embedded constants are identical.
    const_inputs: Dict[str, np.ndarray] = dc_field(default_factory=dict)

    def sig(self):
        cached = getattr(self, "_sig", None)
        if cached is not None:
            return cached
        import hashlib

        def leaf_sig(k, v):
            if k in CONST_INPUT_KEYS:
                # content hash: two queries share an executable (and the
                # executable may close over / share ONE copy of the array)
                # only when the table itself is identical
                return (k, v.shape, str(v.dtype),
                        hashlib.sha1(np.ascontiguousarray(v).tobytes())
                        .hexdigest())
            return (k, v.shape, str(v.dtype))

        out = (self.kind, self.static,
               tuple(sorted(leaf_sig(k, v)
                            for k, v in self.inputs.items())),
               self.query_plan.sig() if self.query_plan is not None else None,
               tuple(q.sig() for q in self.query_plans),
               tuple(c.sig() for c in self.children),
               tuple(sorted(
                   (k, v.shape, str(v.dtype),
                    hashlib.sha1(np.ascontiguousarray(v).tobytes())
                    .hexdigest())
                   for k, v in self.const_inputs.items())))
        # plans are immutable post-compile and now shared across queries
        # via the reader memo — hash the const tables once
        object.__setattr__(self, "_sig", out)
        return out

    def flatten_inputs(self, out):
        out.append(self.inputs)
        if self.query_plan is not None:
            self.query_plan.flatten_inputs(out)
        for q in self.query_plans:
            q.flatten_inputs(out)
        for c in self.children:
            c.flatten_inputs(out)
        return out


@dataclass(frozen=True)
class _Ctx:
    mapper: MapperService
    seg: Segment
    meta: Any
    compiler: Compiler
    d_pad: int
    # True only while compiling a TOP-LEVEL agg node: root nodes see the
    # sentinel parent context (pbin=None, parent_card=1) at eval time, the
    # precondition for the fused bucket_bits/presence_bits kinds
    root: bool = False
    # False for cross-row tracing paths (SPMD): fused kinds embed
    # segment-specific constants in the executable, which a single program
    # traced from row 0 would wrongly apply to every row
    fused: bool = True


def _register_const_bytes(plans: List[AggPlan], seg: Segment) -> None:
    """Account the fused kinds' embedded constant tables (bucket_bits /
    presence_bits bitmask words etc.) for the device-memory gauge: they
    are content-baked into the executable, so they occupy HBM for the
    executable's lifetime. The per-(sig, input) byte map lives ON the
    segment object and is summed by the executor's weak-ref reader
    provider — lifetime tracks liveness exactly (index delete, shard
    close, clone replacement all drop the object from the sum), with no
    release hook to forget."""
    table = getattr(seg, "_agg_const_bytes", None)
    if table is None:
        table = seg._agg_const_bytes = {}
    for p in plans:
        consts = getattr(p, "const_inputs", None) or {}
        for name, arr in consts.items():
            table[(p.sig(), name)] = int(getattr(arr, "nbytes", 0))
        if p.children:
            _register_const_bytes(p.children, seg)


def compile_aggs(nodes: List[AggNode], mapper: MapperService, seg: Segment,
                 meta, compiler: Compiler,
                 allow_fused: bool = True) -> List[AggPlan]:
    ctx = _Ctx(mapper, seg, meta, compiler, pad_bucket(max(seg.num_docs, 1)),
               fused=allow_fused)
    plans = [_compile_node(n, ctx, root=True) for n in nodes]
    _register_const_bytes(plans, seg)
    return plans


def _num_col(ctx: _Ctx, field: str):
    return ctx.seg.numeric_dv.get(field)


def _ident_pairs(col) -> bool:
    return ident_pairs(col)


def _bucket_lookup_plan(node: AggNode, ctx: _Ctx, kind: str,
                        bucket_of_rank: np.ndarray, card: int,
                        render: dict, children_card_mult: bool = True) -> AggPlan:
    u_pad = pad_bucket(max(len(bucket_of_rank), 1), minimum=8)
    table = np.full(u_pad, -1, dtype=np.int32)
    table[:len(bucket_of_rank)] = bucket_of_rank
    children = [_compile_node(c, ctx) for c in node.children]
    col = (ctx.seg.ordinal_dv.get(node.field)
           if kind == "bucket_ord" else _num_col(ctx, node.field))
    return AggPlan(name=node.name, kind=kind,
                   static=(node.field, card,
                           col is not None and _ident_pairs(col)),
                   inputs={"table": table},
                   children=children, render=render)


# ------------------------------------------------- fused leaf bucketing
#
# Root-level bucket aggregations with no sub-aggregations (the dashboard
# hot shape: date_histogram / histogram / range / cardinality next to a
# query) compile to ONE popcount reduction against per-bucket lane
# bitmasks precomputed on the host at (agg, segment) compile time and
# embedded in the executable as constants. The round-5 kernel rebuilt the
# [bins, lanes] membership mask + bit-packing INSIDE the device program on
# every batch (the "static side" of _binned_sums) — ~6M ops per
# date_histogram batch that depend only on segment-static tables. Here
# that work runs once per compile (memoized with the agg plan), the
# envelope carries zero table bytes, and the per-query device work drops
# to pack(ok) + popcount(ok & binbits).

def _pack_lane_bits(bins: np.ndarray, card: int, n_pad: int) -> np.ndarray:
    """Host bit-pack: lane→bin assignment (int, <0 = none) → uint32
    [card, n_pad/32] per-bucket lane masks, bit order matching the device
    _pack_bits (bit j of word w = lane w*32+j)."""
    words = np.zeros((card, n_pad // 32), dtype=np.uint32)
    lanes = np.nonzero((bins >= 0) & (bins < card))[0].astype(np.int64)
    if len(lanes):
        np.bitwise_or.at(
            words, (bins[lanes], lanes // 32),
            np.left_shift(np.uint32(1), (lanes % 32).astype(np.uint32)))
    return words


def _fused_gate(ctx: _Ctx, node: AggNode, card: int, nv_pad: int) -> bool:
    return (ctx.root and ctx.fused and not node.children and card >= 1
            and card <= AGG_GEMM_MAX_BINS and nv_pad % 32 == 0
            and card * nv_pad <= AGG_POPCOUNT_MAX_ELEMS)


def _fused_bits_plan(node: AggNode, ctx: _Ctx, col, src: str,
                     lane_bins: np.ndarray, card: int, render: dict,
                     kind: str = "bucket_bits") -> AggPlan:
    nv_pad = pad_bucket(max(len(col.doc_ids), 1))
    bins = np.full(nv_pad, -1, dtype=np.int64)
    bins[:len(lane_bins)] = lane_bins
    binbits = _pack_lane_bits(bins, card, nv_pad)
    return AggPlan(node.name, kind,
                   static=(node.field, card, _ident_pairs(col), src),
                   const_inputs={"binbits": binbits}, render=render)


def _parse_duration_ms(v) -> int:
    """Date-histogram offset: "1h" / "-30m" / raw millis."""
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        return int(v)
    s = str(v).strip()
    sign = 1
    if s[:1] in ("+", "-"):
        sign = -1 if s[0] == "-" else 1
        s = s[1:]
    if s in _FIXED_MS:
        return sign * _FIXED_MS[s]
    if s[-2:] == "ms" and s[:-2].isdigit():
        # before the single-char suffix branch: '500ms' must not parse
        # as '500m' + trailing junk or fail outright
        return sign * int(s[:-2])
    if s[:-1].isdigit() and s[-1:] in "smhdw":
        return sign * int(s[:-1]) * _FIXED_MS[s[-1]]
    if s.isdigit():
        return sign * int(s)
    raise ParsingError(f"failed to parse [offset]: [{v}]")


def _parse_time_zone(tz) -> int:
    """time_zone → fixed UTC offset in ms. Fixed offsets exact; named
    zones use their standard offset at a representative instant (DST
    transitions inside one histogram are out of scope — documented)."""
    if tz in (None, "", "UTC", "Z"):
        return 0
    s = str(tz)
    m = re.match(r"^([+-])(\d{1,2})(?::?(\d{2}))?$", s)
    if m:
        sign = -1 if m.group(1) == "-" else 1
        return sign * (int(m.group(2)) * 3600_000
                       + int(m.group(3) or 0) * 60_000)
    try:
        from zoneinfo import ZoneInfo
        import datetime as _dt
        off = ZoneInfo(s).utcoffset(
            _dt.datetime(2024, 1, 15, tzinfo=_dt.timezone.utc))
        return int(off.total_seconds() * 1000)
    except (KeyError, ValueError, OSError, ImportError):
        raise ParsingError(f"failed to parse time zone [{tz}]")


def hist_step_shift(body: dict, kind: str):
    """(step, shift) of a fixed-interval histogram/date_histogram body,
    where bucket key = floor((v + shift) / step) * step - shift.
    None for calendar intervals. Shared with the reduce-side renderers
    (gap fill / extended_bounds need the key lattice, not just the
    observed keys)."""
    if kind == "histogram":
        interval = float(body.get("interval", 0) or 0)
        if interval <= 0:
            return None
        return interval, -float(body.get("offset", 0.0))
    unit = str(body.get("calendar_interval") or body.get("fixed_interval")
               or body.get("interval") or "")
    if unit in _FIXED_MS:
        step = _FIXED_MS[unit]
    elif unit[:-1].isdigit() and unit[-1:] in "smhdw":
        step = int(unit[:-1]) * _FIXED_MS[unit[-1]]
    else:
        return None
    shift = (_parse_time_zone(body.get("time_zone"))
             - _parse_duration_ms(body.get("offset", 0)))
    return step, shift


def _compile_node(node: AggNode, ctx: _Ctx, root: bool = False) -> AggPlan:
    fn = _COMPILERS.get(node.type)
    if fn is None:
        raise QueryShardError(f"aggregation type [{node.type}] is not supported")
    if ctx.root != root:
        # child compiles (the default) demote the root flag; only
        # compile_aggs promotes it for top-level nodes
        ctx = dc_replace(ctx, root=root)
    return fn(node, ctx)


# ----------------------------------------------------------------- buckets

def _c_terms(node: AggNode, ctx: _Ctx) -> AggPlan:
    field = node.field
    if field is None:
        raise ParsingError(f"[terms] aggregation [{node.name}] requires a field")
    ocol = ctx.seg.ordinal_dv.get(field)
    if ocol is not None:
        card = max(len(ocol.dictionary), 1)
        children = [_compile_node(c, ctx) for c in node.children]
        return AggPlan(node.name, "bucket_ord",
                       static=(field, card, _ident_pairs(ocol)),
                       children=children,
                       render={"keys": list(ocol.dictionary), "body": node.body,
                               "kind": "terms"})
    col = _num_col(ctx, field)
    if col is None:
        return AggPlan(node.name, "empty", render={"body": node.body,
                                                   "kind": "terms", "keys": []})
    card = max(len(col.unique), 1)
    bucket_of_rank = np.arange(len(col.unique), dtype=np.int32)
    ft = ctx.mapper.get_field(field)
    keys = [_render_numeric_key(v, ft) for v in col.unique]
    return _bucket_lookup_plan(node, ctx, "bucket_num", bucket_of_rank, card,
                               render={"keys": keys, "body": node.body,
                                       "kind": "terms"})


def _render_numeric_key(v: float, ft) -> Any:
    if ft is not None and ft.is_bool:
        return bool(v)
    if ft is not None and ft.is_date:
        return int(v)
    return int(v) if float(v).is_integer() else float(v)


def _c_histogram(node: AggNode, ctx: _Ctx) -> AggPlan:
    field = node.field
    interval = node.body.get("interval")
    if not field or not interval:
        raise ParsingError("[histogram] requires [field] and [interval]")
    interval = float(interval)
    if interval <= 0:
        raise ParsingError("[interval] must be > 0")
    offset = float(node.body.get("offset", 0.0))
    col = _num_col(ctx, field)
    if col is None or len(col.unique) == 0:
        return AggPlan(node.name, "empty",
                       render={"body": node.body, "kind": "histogram",
                               "interval": interval, "offset": offset,
                               "step": interval, "shift": -offset,
                               "keys": []})
    lo_key = np.floor((col.unique[0] - offset) / interval)
    buckets = np.floor((col.unique - offset) / interval) - lo_key
    card = int(buckets[-1]) + 1
    keys = [float(lo_key + i) * interval + offset for i in range(card)]
    render = {"keys": keys, "body": node.body, "kind": "histogram",
              "step": interval, "shift": -offset}
    nv_pad = pad_bucket(max(len(col.doc_ids), 1))
    if _fused_gate(ctx, node, card, nv_pad):
        lane_bins = buckets.astype(np.int64)[col.value_ords]
        return _fused_bits_plan(node, ctx, col, "numeric", lane_bins, card,
                                render)
    return _bucket_lookup_plan(node, ctx, "bucket_num",
                               buckets.astype(np.int32), card, render)


def _calendar_boundaries(lo_ms: float, hi_ms: float, unit: str) -> List[int]:
    """Host-generated calendar-aware bucket boundaries (month/quarter/year)."""
    import datetime as _dt
    start = _dt.datetime.fromtimestamp(lo_ms / 1000.0, tz=_dt.timezone.utc)
    out = []
    if unit in ("M", "1M", "month"):
        cur = start.replace(day=1, hour=0, minute=0, second=0, microsecond=0)
        step_months = 1
    elif unit in ("q", "1q", "quarter"):
        cur = start.replace(month=((start.month - 1) // 3) * 3 + 1, day=1,
                            hour=0, minute=0, second=0, microsecond=0)
        step_months = 3
    else:  # year
        cur = start.replace(month=1, day=1, hour=0, minute=0, second=0,
                            microsecond=0)
        step_months = 12
    while cur.timestamp() * 1000 <= hi_ms:
        out.append(int(cur.timestamp() * 1000))
        month = cur.month - 1 + step_months
        cur = cur.replace(year=cur.year + month // 12, month=month % 12 + 1)
    out.append(int(cur.timestamp() * 1000))
    return out


_CALENDAR_APPROX_MS = {"M": 2_592_000_000, "1M": 2_592_000_000,
                       "month": 2_592_000_000,
                       "q": 7_776_000_000, "1q": 7_776_000_000,
                       "quarter": 7_776_000_000,
                       "y": 31_536_000_000, "1y": 31_536_000_000,
                       "year": 31_536_000_000}


def _interval_ms(body: dict) -> int:
    """Interval in ms for fixed bucketing. Calendar month/quarter/year use
    fixed approximations (30/90/365 days) — composite sources bucket on
    fixed widths; the standalone date_histogram path uses true calendar
    boundaries via _calendar_boundaries."""
    unit = str(body.get("calendar_interval") or body.get("fixed_interval")
               or body.get("interval") or "1d")
    if unit in _FIXED_MS:
        return _FIXED_MS[unit]
    if unit in _CALENDAR_APPROX_MS:
        return _CALENDAR_APPROX_MS[unit]
    if unit[:-1].isdigit() and unit[-1] in "smhdw":
        return int(unit[:-1]) * _FIXED_MS[unit[-1]]
    raise ParsingError(f"unknown date interval [{unit}]")


def _c_date_histogram(node: AggNode, ctx: _Ctx) -> AggPlan:
    field = node.field
    interval = (node.body.get("calendar_interval")
                or node.body.get("fixed_interval")
                or node.body.get("interval"))
    if not field or not interval:
        raise ParsingError("[date_histogram] requires [field] and an interval")
    # shift = tz - offset: bucket ordinal of a timestamp is
    # floor((ts + shift) / step) and the reported UTC key is
    # ordinal * step - shift (rounding happens in offset-shifted local
    # time — DateHistogramAggregationBuilder's Rounding semantics)
    tz = _parse_time_zone(node.body.get("time_zone"))
    off = _parse_duration_ms(node.body.get("offset", 0))
    shift = tz - off
    col = _num_col(ctx, field)
    unit = str(interval)
    fixed = hist_step_shift(node.body, "date_histogram")
    empty_render = {"body": node.body, "kind": "date_histogram",
                    "keys": [], "interval": interval}
    if fixed is not None:
        empty_render["step"], empty_render["shift"] = fixed
    else:
        empty_render["calendar"] = True
    if col is None or len(col.unique) == 0:
        return AggPlan(node.name, "empty", render=empty_render)
    if fixed is not None:
        step, _ = fixed
        b_abs = np.floor((col.unique + shift) / step).astype(np.int64)
        lo_key = int(b_abs[0])
        buckets = b_abs - lo_key
        card = int(buckets[-1]) + 1
        keys = [(lo_key + i) * step - shift for i in range(card)]
        render = {"keys": keys, "body": node.body, "kind": "date_histogram",
                  "step": step, "shift": shift}
    else:
        bounds = _calendar_boundaries(float(col.unique[0]) + shift,
                                      float(col.unique[-1]) + shift, unit)
        bounds = [b - shift for b in bounds]
        buckets = np.searchsorted(np.asarray(bounds, dtype=np.float64),  # sync-ok: host -- compile-time bucket table from a Python list
                                  col.unique, side="right") - 1
        card = len(bounds) - 1
        keys = bounds[:-1]
        render = {"keys": keys, "body": node.body, "kind": "date_histogram",
                  "calendar": True}
    # key strings rendered once per (agg, segment) compile — the memoized
    # plan serves every query of a dashboard workload, where the old path
    # re-formatted every bucket of every query in the respond phase
    render["keys_str"] = [format_date_millis(int(k)) for k in keys]
    nv_pad = pad_bucket(max(len(col.doc_ids), 1))
    if _fused_gate(ctx, node, card, nv_pad):
        lane_bins = buckets.astype(np.int64)[col.value_ords]
        return _fused_bits_plan(node, ctx, col, "numeric", lane_bins, card,
                                render)
    return _bucket_lookup_plan(node, ctx, "bucket_num",
                               buckets.astype(np.int32), card, render)


def _c_range(node: AggNode, ctx: _Ctx) -> AggPlan:
    field = node.field
    ranges = node.body.get("ranges")
    if not field or not ranges:
        raise ParsingError("[range] aggregation requires [field] and [ranges]")
    ft = ctx.mapper.get_field(field)
    col = _num_col(ctx, field)
    is_date = node.type == "date_range" or (ft is not None and ft.is_date)

    def conv(v):
        if v is None:
            return None
        if is_date and isinstance(v, str):
            v = _resolve_date_math(v)
            return float(parse_date_millis(v) if isinstance(v, str) else v)
        return float(ft.to_comparable(v)) if ft is not None else float(v)

    specs = []
    for r in ranges:
        frm, to = conv(r.get("from")), conv(r.get("to"))
        key = r.get("key")
        if key is None:
            f_str = "*" if frm is None else (
                format_date_millis(int(frm)) if is_date else _fmt_num(frm))
            t_str = "*" if to is None else (
                format_date_millis(int(to)) if is_date else _fmt_num(to))
            key = f"{f_str}-{t_str}"
        specs.append((key, frm, to))
    render = {"kind": node.type, "specs": specs, "body": node.body,
              "is_date": is_date}
    if col is None or len(col.unique) == 0:
        return AggPlan(node.name, "empty", render=render)
    u = col.unique
    nv_pad = pad_bucket(max(len(col.doc_ids), 1))
    if _fused_gate(ctx, node, max(len(specs), 1), nv_pad):
        # fused leaf ranges: one bitmask row per range (rows independent,
        # so overlapping ranges need no sub-plan slots), one popcount
        # reduction for the whole [ranges] agg
        words = np.zeros((len(specs), nv_pad // 32), dtype=np.uint32)
        lanes = np.arange(len(col.doc_ids), dtype=np.int64)
        vo = col.value_ords
        for i, (_, frm, to) in enumerate(specs):
            lo = 0 if frm is None else int(np.searchsorted(u, frm, "left"))
            hi = len(u) if to is None else int(np.searchsorted(u, to, "left"))
            sel = lanes[(vo >= lo) & (vo < hi)]
            if len(sel):
                np.bitwise_or.at(
                    words[i], sel // 32,
                    np.left_shift(np.uint32(1),
                                  (sel % 32).astype(np.uint32)))
        return AggPlan(node.name, "bucket_bits",
                       static=(field, len(specs), _ident_pairs(col),
                               "numeric"),
                       const_inputs={"binbits": words}, render=render)
    # ranges can overlap → one sub-plan slot per range (card = len ranges),
    # membership computed per range via rank-interval table
    sub_plans = []
    for i, (_, frm, to) in enumerate(specs):
        lo = 0 if frm is None else int(np.searchsorted(u, frm, "left"))
        hi = len(u) if to is None else int(np.searchsorted(u, to, "left"))
        u_pad = pad_bucket(max(len(u), 1), minimum=8)
        table = np.full(u_pad, -1, dtype=np.int32)
        table[lo:hi] = 0
        sub_plans.append(AggPlan(f"{node.name}#{i}", "bucket_num",
                                 static=(field, 1, _ident_pairs(col)),
                                 inputs={"table": table},
                                 children=[_compile_node(c, ctx)
                                           for c in node.children]))
    return AggPlan(node.name, "multi", static=(len(sub_plans),),
                   children=sub_plans, render=render)


def _fmt_num(v: float) -> str:
    return str(int(v)) if float(v).is_integer() else str(v)


def _c_nested(node: AggNode, ctx: _Ctx) -> AggPlan:
    """Switch the doc set to a nested path's child rows; bucket ordinals
    follow each child's root (bucket/nested/NestedAggregator.java)."""
    path = (node.body or {}).get("path")
    paths = getattr(ctx.seg, "nested_paths", [])
    path_ord = paths.index(path) if path in paths else -1
    children = [_compile_node(c, ctx) for c in node.children]
    return AggPlan(node.name, "nested",
                   inputs={"path_ord": np.asarray(path_ord, np.int32)},  # sync-ok: host -- scalar plan constant
                   children=children, render={"kind": "filter"})


def _c_reverse_nested(node: AggNode, ctx: _Ctx) -> AggPlan:
    if (node.body or {}).get("path"):
        # intermediate-level join-back needs hierarchical parent
        # pointers the flat block encoding doesn't keep — refuse loudly
        raise QueryShardError(
            "[reverse_nested] with an explicit [path] is not supported; "
            "omit path to join back to the root level")
    children = [_compile_node(c, ctx) for c in node.children]
    return AggPlan(node.name, "reverse_nested", children=children,
                   render={"kind": "filter"})


def _c_filter(node: AggNode, ctx: _Ctx) -> AggPlan:
    qnode = dsl.parse_query(node.body if node.body else {"match_all": {}})
    qplan = ctx.compiler.compile(qnode, ctx.seg, ctx.meta)
    children = [_compile_node(c, ctx) for c in node.children]
    return AggPlan(node.name, "filter", query_plan=qplan, children=children,
                   render={"kind": "filter"})


def _c_filters(node: AggNode, ctx: _Ctx) -> AggPlan:
    filters = node.body.get("filters")
    if filters is None:
        raise ParsingError("[filters] aggregation requires [filters]")
    if isinstance(filters, dict):
        names = list(filters.keys())
        queries = [filters[n] for n in names]
        keyed = True
    else:
        names = [str(i) for i in range(len(filters))]
        queries = list(filters)
        keyed = False
    subs = []
    for n, q in zip(names, queries):
        qplan = ctx.compiler.compile(dsl.parse_query(q), ctx.seg, ctx.meta)
        subs.append(AggPlan(n, "filter", query_plan=qplan,
                            children=[_compile_node(c, ctx)
                                      for c in node.children]))
    return AggPlan(node.name, "multi", static=(len(subs),), children=subs,
                   render={"kind": "filters", "names": names, "keyed": keyed})


def _c_global(node: AggNode, ctx: _Ctx) -> AggPlan:
    children = [_compile_node(c, ctx) for c in node.children]
    return AggPlan(node.name, "global", children=children,
                   render={"kind": "global"})


def _c_missing(node: AggNode, ctx: _Ctx) -> AggPlan:
    field = node.field
    if field is None:
        raise ParsingError("[missing] aggregation requires a field")
    if field in ctx.seg.numeric_dv:
        static = ("numeric", field)
    elif field in ctx.seg.ordinal_dv:
        static = ("ordinal", field)
    elif field in ctx.seg.vector_dv:
        static = ("vector", field)
    else:
        static = ("none", field)
    children = [_compile_node(c, ctx) for c in node.children]
    return AggPlan(node.name, "missing", static=static, children=children,
                   render={"kind": "missing"})


# ----------------------------------------------------------------- metrics

# which device partials each metric render consumes (reduce._merge_metric);
# cnt also powers the has-any-value null handling for min/max/avg
_METRIC_NEEDS = {
    "min": ("cnt", "min"), "max": ("cnt", "max"), "avg": ("cnt", "sum"),
    "sum": ("cnt", "sum"), "value_count": ("cnt",),
    "stats": ("cnt", "max", "min", "sum"),
    "extended_stats": ("cnt", "max", "min", "sum", "sumsq"),
}


def _c_metric(node: AggNode, ctx: _Ctx) -> AggPlan:
    field = node.field
    if field is None:
        raise ParsingError(f"[{node.type}] aggregation [{node.name}] requires "
                           f"a field")
    render = {"kind": node.type, "body": node.body}
    if field in ctx.seg.numeric_dv:
        ft = ctx.mapper.get_field(field)
        render["is_date"] = bool(ft is not None and ft.is_date)
        # collect only the partials the metric's render needs: avg wants
        # (sum, cnt), not the full 5-reduction stats battery
        needs = _METRIC_NEEDS.get(node.type,
                                  ("cnt", "max", "min", "sum", "sumsq"))
        missing = node.body.get("missing")
        return AggPlan(node.name, "metric_num",
                       static=(field, needs,
                               _ident_pairs(ctx.seg.numeric_dv[field]),
                               None if missing is None else float(missing)),
                       render=render)
    if node.body.get("missing") is not None and field not in \
            ctx.seg.ordinal_dv:
        # field absent from the whole segment but a missing substitute is
        # given: every doc contributes the substitute (metric over a
        # constant) — compile as metric_missing_only
        needs = _METRIC_NEEDS.get(node.type,
                                  ("cnt", "max", "min", "sum", "sumsq"))
        return AggPlan(node.name, "metric_missing_only",
                       static=(needs, float(node.body["missing"])),
                       render=render)
    if field in ctx.seg.ordinal_dv and node.type == "value_count":
        return AggPlan(node.name, "count_ord",
                       static=(field,
                               _ident_pairs(ctx.seg.ordinal_dv[field])),
                       render=render)
    return AggPlan(node.name, "empty", render=render)


def _c_cardinality(node: AggNode, ctx: _Ctx) -> AggPlan:
    field = node.field
    if field is None:
        raise ParsingError("[cardinality] aggregation requires a field")
    render = {"kind": "cardinality", "body": node.body}
    if field in ctx.seg.ordinal_dv:
        col = ctx.seg.ordinal_dv[field]
        card = max(len(col.dictionary), 1)
        render["keys"] = list(col.dictionary)
        nv_pad = pad_bucket(max(len(col.doc_ids), 1))
        if _fused_gate(ctx, node, card, nv_pad):
            return _fused_bits_plan(node, ctx, col, "ordinal",
                                    col.ords.astype(np.int64), card, render,
                                    kind="presence_bits")
        return AggPlan(node.name, "presence_ord",
                       static=(field, card, _ident_pairs(col)),
                       render=render)
    if field in ctx.seg.numeric_dv:
        col = ctx.seg.numeric_dv[field]
        u = col.unique
        render["values"] = u
        card = max(len(u), 1)
        nv_pad = pad_bucket(max(len(col.doc_ids), 1))
        if _fused_gate(ctx, node, card, nv_pad):
            return _fused_bits_plan(node, ctx, col, "numeric",
                                    col.value_ords.astype(np.int64), card,
                                    render, kind="presence_bits")
        return AggPlan(node.name, "presence_num",
                       static=(field, card, _ident_pairs(col)),
                       render=render)
    return AggPlan(node.name, "empty", render=render)


def _c_percentiles(node: AggNode, ctx: _Ctx) -> AggPlan:
    field = node.field
    if field is None:
        raise ParsingError(f"[{node.type}] aggregation requires a field")
    render = {"kind": node.type, "body": node.body}
    if field in ctx.seg.numeric_dv:
        u = ctx.seg.numeric_dv[field].unique
        render["values"] = u
        return AggPlan(node.name, "value_hist",
                       static=(field, max(len(u), 1),
                               _ident_pairs(ctx.seg.numeric_dv[field])),
                       render=render)
    return AggPlan(node.name, "empty", render=render)


def _c_weighted_avg(node: AggNode, ctx: _Ctx) -> AggPlan:
    vspec = node.body.get("value", {})
    wspec = node.body.get("weight", {})
    vf, wf = vspec.get("field"), wspec.get("field")
    if not vf or not wf:
        raise ParsingError("[weighted_avg] requires value.field and weight.field")
    render = {"kind": "weighted_avg", "body": node.body}
    if vf in ctx.seg.numeric_dv and wf in ctx.seg.numeric_dv:
        return AggPlan(node.name, "weighted_avg",
                       static=(vf, wf,
                               _ident_pairs(ctx.seg.numeric_dv[vf])),
                       render=render)
    return AggPlan(node.name, "empty", render=render)


# ---------------------------------------------------- dense-bucket family
#
# A host-precomputed per-doc bucket id (int32[d_pad], -1 = no bucket) feeds
# one generic device kind ("bucket_dense"): the host does the irregular
# string/tuple work once per (agg, segment) compile, the device does the
# massively-regular scatter-count. geohash grids, composite tuples,
# multi_terms and auto intervals all ride this path.

def _dense_first_value(ctx: _Ctx, field: str):
    """Per-doc first numeric value + exists (host numpy)."""
    col = _num_col(ctx, field)
    d = ctx.seg.num_docs
    if col is None:
        return None, np.zeros(d, dtype=bool)
    value = np.zeros(d, dtype=np.float64)
    # doc_ids are grouped ascending: first occurrence = smallest value
    docs, first_idx = np.unique(col.doc_ids, return_index=True)
    value[docs] = col.values[first_idx]
    return value, col.exists.copy()


def _dense_first_ord(ctx: _Ctx, field: str):
    col = ctx.seg.ordinal_dv.get(field)
    d = ctx.seg.num_docs
    if col is None:
        return None, np.zeros(d, dtype=bool), []
    ords = np.zeros(d, dtype=np.int64)
    docs, first_idx = np.unique(col.doc_ids, return_index=True)
    ords[docs] = col.ords[first_idx]
    return ords, col.exists.copy(), list(col.dictionary)


def _bucket_dense_plan(node: AggNode, ctx: _Ctx, doc_bucket: np.ndarray,
                       card: int, render: dict) -> AggPlan:
    padded = np.full(ctx.d_pad, -1, dtype=np.int32)
    padded[:len(doc_bucket)] = doc_bucket
    children = [_compile_node(c, ctx) for c in node.children]
    return AggPlan(node.name, "bucket_dense", static=(card,),
                   inputs={"doc_bucket": padded}, children=children,
                   render=render)


def _source_encoding(ctx: _Ctx, name: str, spec: dict):
    """One composite/multi_terms source → (per-doc code, exists, keys)."""
    stype, body = next(iter(spec.items())) if len(spec) == 1 \
        else ("terms", spec)
    field = body.get("field")
    ocol = ctx.seg.ordinal_dv.get(field)
    if ocol is not None:
        ords, exists, keys = _dense_first_ord(ctx, field)
        return ords, exists, keys
    value, exists = _dense_first_value(ctx, field)
    if value is None:
        return None, exists, []
    ft = ctx.mapper.get_field(field)
    if stype == "histogram":
        interval = float(body["interval"])
        codes_raw = np.floor(value / interval) * interval
    elif stype == "date_histogram":
        iv = _interval_ms(body)
        codes_raw = np.floor(value / iv) * iv
    else:
        codes_raw = value
    uniq = np.unique(codes_raw[exists]) if exists.any() else np.array([])
    code_of = {v: i for i, v in enumerate(uniq)}
    codes = np.array([code_of.get(v, -1) for v in codes_raw], dtype=np.int64)
    keys = [_render_numeric_key(v, ft) for v in uniq]
    return codes, exists, keys


def _c_composite(node: AggNode, ctx: _Ctx) -> AggPlan:
    sources = node.body.get("sources")
    if not sources:
        raise ParsingError(f"[composite] aggregation [{node.name}] requires "
                           f"[sources]")
    source_specs = []
    for s in sources:
        if len(s) != 1:
            raise ParsingError("[composite] source must have one name")
        sname, sbody = next(iter(s.items()))
        source_specs.append((sname, sbody))
    d = ctx.seg.num_docs
    combined = np.zeros(d, dtype=np.int64)
    all_exist = np.ones(d, dtype=bool)
    key_lists = []
    names = []
    for sname, sbody in source_specs:
        codes, exists, keys = _source_encoding(ctx, sname, sbody)
        names.append(sname)
        key_lists.append(keys)
        if codes is None or not keys:
            all_exist[:] = False
            combined[:] = -1
            continue
        combined = combined * len(keys) + np.where(exists, codes, 0)
        all_exist &= exists
    card = max(int(np.prod([max(len(k), 1) for k in key_lists])), 1)
    doc_bucket = np.where(all_exist, combined, -1).astype(np.int32)
    render = {"kind": node.type, "body": node.body, "sources": names,
              "key_lists": key_lists}
    return _bucket_dense_plan(node, ctx, doc_bucket, card, render)


def _c_multi_terms(node: AggNode, ctx: _Ctx) -> AggPlan:
    terms = node.body.get("terms")
    if not terms or len(terms) < 2:
        raise ParsingError(f"[multi_terms] aggregation [{node.name}] "
                           f"requires at least 2 [terms]")
    synthetic = AggNode(node.name, "multi_terms",
                        {"sources": [{f"t{i}": {"terms": t}}
                                     for i, t in enumerate(terms)],
                         **node.body},
                        children=node.children)
    plan = _c_composite(synthetic, ctx)
    plan.render["kind"] = "multi_terms"
    return plan


def _c_auto_date_histogram(node: AggNode, ctx: _Ctx) -> AggPlan:
    """Pick the smallest calendar interval that keeps bucket count under
    `buckets` (AutoDateHistogramAggregationBuilder.RoundingInfos)."""
    target = int(node.body.get("buckets", 10))
    col = _num_col(ctx, node.field)
    if col is None or not len(col.unique):
        return AggPlan(node.name, "empty",
                       render={"kind": "auto_date_histogram", "keys": [],
                               "body": node.body})
    lo, hi = float(col.unique[0]), float(col.unique[-1])
    candidates = [("1s", 1000), ("1m", 60_000), ("1h", 3_600_000),
                  ("1d", 86_400_000), ("7d", 7 * 86_400_000),
                  ("1M", 30 * 86_400_000), ("3M", 90 * 86_400_000),
                  ("1y", 365 * 86_400_000)]
    chosen_label, chosen_ms = candidates[-1]
    for label, ms in candidates:
        if (hi - lo) / ms + 1 <= target:
            chosen_label, chosen_ms = label, ms
            break
    clone = AggNode(node.name, "date_histogram",
                    {**node.body,
                     "fixed_interval": f"{chosen_ms // 1000}s"},
                    children=node.children)
    plan = _c_date_histogram(clone, ctx)
    plan.render["kind"] = "auto_date_histogram"
    plan.render["interval"] = chosen_label
    return plan


def _c_significant_terms(node: AggNode, ctx: _Ctx) -> AggPlan:
    """Foreground counts on device; background (index-wide) doc counts
    gathered host-side at compile. Scores reduce with the JLH heuristic.
    Exact for single-valued fields (subset size = Σ fg counts)."""
    field = node.field
    ocol = ctx.seg.ordinal_dv.get(field)
    if ocol is None:
        return AggPlan(node.name, "empty",
                       render={"kind": "significant_terms", "keys": [],
                               "body": node.body})
    plan = _c_terms(node, ctx)
    bg = np.zeros(len(ocol.dictionary), dtype=np.int64)
    seen_pairs = set()
    for doc, o in zip(ocol.doc_ids, ocol.ords):
        if (doc, o) not in seen_pairs:
            seen_pairs.add((doc, o))
            bg[o] += 1
    plan.render = {"kind": "significant_terms", "keys": list(ocol.dictionary),
                   "body": node.body, "bg": bg.tolist(),  # sync-ok: host -- bg counts are a host numpy accumulator
                   "bg_total": int(ctx.seg.num_docs)}
    return plan


def _c_adjacency_matrix(node: AggNode, ctx: _Ctx) -> AggPlan:
    filters = node.body.get("filters")
    if not isinstance(filters, dict) or not filters:
        raise ParsingError(f"[adjacency_matrix] aggregation [{node.name}] "
                           f"requires [filters]")
    names = sorted(filters)
    children = []
    for name in names:
        qnode = dsl.parse_query(filters[name])
        children.append(ctx.compiler.compile(qnode, ctx.seg, ctx.meta))
    return AggPlan(node.name, "adjacency", static=(len(names),),
                   query_plans=children,
                   render={"kind": "adjacency_matrix", "names": names,
                           "body": node.body})


def _c_geo_bounds(node: AggNode, ctx: _Ctx) -> AggPlan:
    return AggPlan(node.name, "geo_metric",
                   static=(node.field,),
                   render={"kind": node.type, "body": node.body})


def _c_geohash_grid(node: AggNode, ctx: _Ctx) -> AggPlan:
    precision = int(node.body.get("precision", 5))
    lat, lat_exists = _dense_first_value(ctx, f"{node.field}.lat")
    lon, _ = _dense_first_value(ctx, f"{node.field}.lon")
    if lat is None or lon is None:
        return AggPlan(node.name, "empty",
                       render={"kind": "grid", "keys": [], "body": node.body})
    if node.type == "geotile_grid":
        keys_raw = [_geotile(la, lo, precision) if e else None
                    for la, lo, e in zip(lat, lon, lat_exists)]
    else:
        keys_raw = [_geohash(la, lo, precision) if e else None
                    for la, lo, e in zip(lat, lon, lat_exists)]
    uniq = sorted({k for k in keys_raw if k is not None})
    code_of = {k: i for i, k in enumerate(uniq)}
    doc_bucket = np.array([code_of.get(k, -1) for k in keys_raw],
                          dtype=np.int32)
    return _bucket_dense_plan(node, ctx, doc_bucket, max(len(uniq), 1),
                              render={"kind": "grid", "keys": uniq,
                                      "body": node.body})


_BASE32 = "0123456789bcdefghjkmnpqrstuvwxyz"


def _geohash(lat: float, lon: float, precision: int) -> str:
    lat_lo, lat_hi = -90.0, 90.0
    lon_lo, lon_hi = -180.0, 180.0
    out = []
    bit = 0
    ch = 0
    even = True
    while len(out) < precision:
        if even:
            mid = (lon_lo + lon_hi) / 2
            if lon >= mid:
                ch = ch * 2 + 1
                lon_lo = mid
            else:
                ch = ch * 2
                lon_hi = mid
        else:
            mid = (lat_lo + lat_hi) / 2
            if lat >= mid:
                ch = ch * 2 + 1
                lat_lo = mid
            else:
                ch = ch * 2
                lat_hi = mid
        even = not even
        bit += 1
        if bit == 5:
            out.append(_BASE32[ch])
            bit = 0
            ch = 0
    return "".join(out)


def _geotile(lat: float, lon: float, zoom: int) -> str:
    import math
    n = 2 ** zoom
    x = int((lon + 180.0) / 360.0 * n)
    lat_r = math.radians(max(min(lat, 85.0511), -85.0511))
    y = int((1.0 - math.log(math.tan(lat_r) + 1 / math.cos(lat_r))
             / math.pi) / 2.0 * n)
    return f"{zoom}/{min(x, n - 1)}/{min(y, n - 1)}"


def _c_matrix_stats(node: AggNode, ctx: _Ctx) -> AggPlan:
    fields = node.body.get("fields")
    if not fields:
        raise ParsingError(f"[matrix_stats] aggregation [{node.name}] "
                           f"requires [fields]")
    return AggPlan(node.name, "matrix_stats", static=(tuple(fields),),
                   render={"kind": "matrix_stats", "fields": list(fields),
                           "body": node.body})


_COMPILERS = {
    "terms": _c_terms,
    "histogram": _c_histogram,
    "date_histogram": _c_date_histogram,
    "range": _c_range,
    "date_range": _c_range,
    "ip_range": _c_range,
    "filter": _c_filter,
    "filters": _c_filters,
    "nested": _c_nested,
    "reverse_nested": _c_reverse_nested,
    "global": _c_global,
    "missing": _c_missing,
    "min": _c_metric, "max": _c_metric, "sum": _c_metric, "avg": _c_metric,
    "value_count": _c_metric, "stats": _c_metric, "extended_stats": _c_metric,
    "median_absolute_deviation": _c_percentiles,
    "cardinality": _c_cardinality,
    "percentiles": _c_percentiles,
    "percentile_ranks": _c_percentiles,
    "weighted_avg": _c_weighted_avg,
    "composite": _c_composite,
    "multi_terms": _c_multi_terms,
    "auto_date_histogram": _c_auto_date_histogram,
    "significant_terms": _c_significant_terms,
    "adjacency_matrix": _c_adjacency_matrix,
    "geohash_grid": _c_geohash_grid,
    "geotile_grid": _c_geohash_grid,
    "geo_bounds": _c_geo_bounds,
    "geo_centroid": _c_geo_bounds,
    "matrix_stats": _c_matrix_stats,
}


# ---------------------------------------------------------------- device eval

def eval_aggs(plans: List[AggPlan], seg: Dict, inputs: List[Dict],
              cursor: List[int], mask, outs: List):
    """Trace the collection program. mask: eligible docs [Dp] bool (the
    query's result set). Appends each node's partial arrays dict to
    `outs` in traversal order.

    Bucket membership is threaded as a FACTORED context (bin, pmask,
    card, static) instead of the dense parent_eff ordinal vector of the
    scatter design: `bin` [Dp] int32 is the parent bucket id (-1 = none)
    and is segment-STATIC for field-driven bucketing (terms / histogram /
    filter / missing / dense-bucket trees), while every query-dependent
    condition accumulates in `pmask` [Dp] bool. With static bins, binned
    add-reductions become one-hot matmuls whose one-hot matrix is shared
    across a vmapped query batch (see _binned_sums) — the MXU path the
    reference's per-doc collector loops can't express. Kinds whose bins
    are genuinely data-dependent (nested joins, dedup) drop to the
    scatter path by passing static=False."""
    # root context sentinels: pbin=None ⇒ every doc is in bucket 0 (no
    # per-doc gather needed), pmask=None ⇒ no accumulated dynamic parent
    # constraint (skips a gather + AND per agg node on the hot path)
    ctx = (None, None, 1, True)
    for plan in plans:
        _eval_agg(plan, seg, inputs, cursor, mask, ctx, outs)


def _pack_bits(ok):
    """bool [..., n] → uint32 [..., n/32] bitmask (n % 32 == 0)."""
    x = ok.reshape(ok.shape[:-1] + (-1, 32)).astype(jnp.uint32)
    w = jnp.left_shift(jnp.uint32(1), jnp.arange(32, dtype=jnp.uint32))
    return (x * w).sum(-1).astype(jnp.uint32)


def _binned_sums(bin_lanes, total: int, contribs, static_bins: bool):
    """Per-bin Σ of each (values, out_dtype) contrib. bin_lanes: [n]
    int32; entries outside [0, total) drop. Contribs carry the DYNAMIC
    eligibility (ineligible lanes contribute 0); bin_lanes carries the
    static structure.

    Kernel choice, fastest first:
    - bool contribs (bucket/count/presence — the hot shapes): bit-packed
      popcount against static per-bin bitmasks. Exact integer counts at
      ~1/20th the ops of the one-hot matmul; pure VPU/AVX work.
    - float contribs with static bins: ONE [n, total] one-hot serves
      every query of a vmapped batch, reduced as a [B, n] × [n, total]
      matmul (the MXU path). f32 accumulation exact below 2^24.
    - dynamic bins or many bins: scatter-add.
    """
    n = bin_lanes.shape[0]
    out: List[Any] = [None] * len(contribs)
    if static_bins and total <= AGG_GEMM_MAX_BINS:
        bool_idx = [i for i, (v, dt) in enumerate(contribs)
                    if v.dtype == jnp.bool_ and n % 32 == 0
                    and n * total <= AGG_POPCOUNT_MAX_ELEMS]
        if bool_idx:
            binmask = (bin_lanes[None, :]
                       == jnp.arange(total, dtype=bin_lanes.dtype)[:, None])
            binbits = _pack_bits(binmask)            # [total, n/32] static
            for i in bool_idx:
                v, dt = contribs[i]
                okbits = _pack_bits(v)               # [n/32]
                inter = okbits[None, :] & binbits    # [total, n/32]
                out[i] = jax.lax.population_count(inter).sum(-1).astype(dt)
        rest = [i for i in range(len(contribs)) if out[i] is None]
        if rest and n * total <= AGG_GEMM_MAX_ELEMS:
            onehot = (bin_lanes[:, None]
                      == jnp.arange(total, dtype=bin_lanes.dtype)).astype(
                jnp.float32)
            for i in rest:
                v, dt = contribs[i]
                s = v.astype(jnp.float32) @ onehot
                out[i] = s.astype(dt)
            return out
        if not rest:
            return out
        safe = jnp.where((bin_lanes >= 0) & (bin_lanes < total),
                         bin_lanes, total)
        for i in rest:
            v, dt = contribs[i]
            out[i] = jnp.zeros(total, dt).at[safe].add(
                v.astype(dt), mode="drop")
        return out
    safe = jnp.where((bin_lanes >= 0) & (bin_lanes < total),
                     bin_lanes, total)
    return [jnp.zeros(total, dt).at[safe].add(v.astype(dt), mode="drop")
            for v, dt in contribs]


def _pairs_context(seg, col, mask, parent_eff, d_pad):
    doc_ids = col["doc_ids"]
    valid = doc_ids >= 0
    safe_doc = jnp.where(valid, doc_ids, 0)
    ok = valid & mask[safe_doc]
    parent = parent_eff[safe_doc]
    return safe_doc, ok & (parent >= 0), parent


def _ctx_parent_eff(ctx, d_pad):
    """Collapse the factored context back to the dense parent ordinal
    vector ([Dp] int32, -1 = no bucket) for kinds on the scatter path."""
    pbin, pmask, pcard, _ = ctx
    if pbin is None and pmask is None:
        return jnp.zeros(d_pad, jnp.int32)
    if pbin is None:
        return jnp.where(pmask, 0, -1)
    if pmask is None:
        return pbin
    return jnp.where(pmask & (pbin >= 0), pbin, -1)


def _take_doc(arr, safe_doc, ident: bool):
    """arr[safe_doc], but a contiguous SLICE when the pairs layout is the
    identity (doc k ↔ lane k): XLA gathers are scalar loops on CPU and a
    serial path on TPU; slices vectorize. Tail lanes then carry arr[k]
    for padding k — every consumer masks them with the static bin_ok."""
    if ident:
        n = safe_doc.shape[0]
        m = arr.shape[-1]
        if n == m:
            return arr
        if n < m:
            return arr[..., :n]
    return arr[..., safe_doc] if arr.ndim > 1 else arr[safe_doc]


def _gather_ok(mask, pmask, safe_doc, ident: bool = False):
    """Dynamic doc-eligibility for a pairs gather, skipping the parent
    gather when no dynamic parent constraint exists (root sentinel)."""
    ok = _take_doc(mask, safe_doc, ident)
    if pmask is not None:
        ok = ok & _take_doc(pmask, safe_doc, ident)
    return ok


def _and_pmask(pmask, extra):
    return extra if pmask is None else (pmask & extra)


def _eval_agg(plan: AggPlan, seg: Dict, inputs: List[Dict], cursor: List[int],
              mask, ctx, outs: List):
    my = inputs[cursor[0]]
    cursor[0] += 1
    d_pad = seg["live"].shape[0]
    kind = plan.kind
    pbin, pmask, parent_card, pstatic = ctx

    if kind == "empty":
        outs.append({})
        child_ctx = (jnp.full(d_pad, -1, jnp.int32), pmask, parent_card,
                     True)
        for c in plan.children:
            _eval_agg(c, seg, inputs, cursor, mask, child_ctx, outs)
        return

    if kind == "multi":
        outs.append({})
        for c in plan.children:
            _eval_agg(c, seg, inputs, cursor, mask, ctx, outs)
        return

    if kind in ("bucket_bits", "presence_bits"):
        # fused leaf bucketing: the whole static side (lane→bin mapping,
        # membership bitmasks, bit packing) was precomputed at compile and
        # rides the executable as a constant — per query the device packs
        # the dynamic eligibility and popcounts it against each bucket row
        field, card, ident, src = plan.static
        col = seg[src][field]
        doc_ids = col["doc_ids"]
        valid = doc_ids >= 0
        safe_doc = jnp.where(valid, doc_ids, 0)
        ok = _gather_ok(mask, pmask, safe_doc, ident)
        binbits = jnp.asarray(plan.const_inputs["binbits"])  # [card, n/32]
        okbits = _pack_bits(ok)                              # [n/32]
        counts = jax.lax.population_count(
            okbits[None, :] & binbits).sum(-1).astype(jnp.int32)
        outs.append({"counts": counts} if kind == "bucket_bits"
                    else {"present": counts > 0})
        return

    if kind in ("bucket_ord", "bucket_num"):
        field, card, ident = plan.static
        col = seg["ordinal" if kind == "bucket_ord" else "numeric"][field]
        ords = col["ords"] if kind == "bucket_ord" else col["val_ords"]
        doc_ids = col["doc_ids"]
        valid = doc_ids >= 0
        safe_doc = jnp.where(valid, doc_ids, 0)
        b = my["table"][ords] if kind == "bucket_num" else ords
        total = parent_card * card
        # static side: which bin each (doc, value) pair lands in
        bin_ok = valid & (b >= 0 if kind == "bucket_num" else True)
        base = 0
        if pbin is not None:
            pb = _take_doc(pbin, safe_doc, ident)
            bin_ok = bin_ok & (pb >= 0)
            base = pb * card
        bin_lanes = jnp.where(bin_ok, base + b, total)
        # dynamic side: whether the pair's doc is in the query/parent set
        ok_dyn = _gather_ok(mask, pmask, safe_doc, ident)
        (counts,) = _binned_sums(bin_lanes, total,
                                 [(ok_dyn & bin_ok, jnp.int32)], pstatic)
        outs.append({"counts": counts})
        if plan.children:
            # dense per-doc child bucket from the STATIC pair structure
            # (multi-valued docs keep the max bin — the engine's
            # single-bucket simplification); dynamic membership rides the
            # child pmask, so this scatter stays unbatched under vmap
            child_bin = jnp.full(d_pad, -1, jnp.int32).at[
                jnp.where(bin_ok, safe_doc, d_pad)].max(
                jnp.where(bin_ok, bin_lanes, -1), mode="drop")
            child_ctx = (child_bin, _and_pmask(pmask, mask), total,
                         pstatic)
            for c in plan.children:
                _eval_agg(c, seg, inputs, cursor, mask, child_ctx, outs)
        return

    if kind == "filter":
        scores, matches = _eval_plan(plan.query_plan, seg, inputs, cursor)
        bin_lanes = jnp.zeros(d_pad, jnp.int32) if pbin is None \
            else jnp.where(pbin >= 0, pbin, parent_card)
        own_dyn = matches & mask
        if pmask is not None:
            own_dyn = own_dyn & pmask
        (counts,) = _binned_sums(bin_lanes, parent_card,
                                 [(own_dyn, jnp.int32)], pstatic)
        outs.append({"counts": counts})
        child_ctx = (pbin, _and_pmask(pmask, mask & matches), parent_card,
                     pstatic)
        for c in plan.children:
            _eval_agg(c, seg, inputs, cursor, mask, child_ctx, outs)
        return

    if kind == "global":
        gmask = seg["live"] & (jnp.arange(d_pad, dtype=jnp.int32)
                               < seg["live"].shape[0])
        # num_docs bound is enforced by live padding (padding rows are
        # dead); the query mask is deliberately IGNORED (GlobalAggregator)
        bin_lanes = jnp.zeros(d_pad, jnp.int32) if pbin is None \
            else jnp.where(pbin >= 0, pbin, parent_card)
        own_dyn = gmask if pmask is None else (gmask & pmask)
        (counts,) = _binned_sums(bin_lanes, parent_card,
                                 [(own_dyn, jnp.int32)], pstatic)
        outs.append({"counts": counts})
        for c in plan.children:
            _eval_agg(c, seg, inputs, cursor, gmask, ctx, outs)
        return

    if kind == "missing":
        ctype, field = plan.static
        if ctype == "numeric":
            exists = seg["numeric"][field]["exists"]
        elif ctype == "ordinal":
            exists = seg["ordinal"][field]["exists"]
        elif ctype == "vector":
            exists = seg["vector"][field]["exists"]
        else:
            exists = jnp.zeros(d_pad, jnp.bool_)
        # field existence is segment-static: fold it into the bin side
        miss_bin = jnp.where(exists, -1,
                             jnp.zeros(d_pad, jnp.int32)
                             if pbin is None else pbin)
        bin_lanes = jnp.where(miss_bin >= 0, miss_bin, parent_card)
        own_dyn = mask if pmask is None else (mask & pmask)
        (counts,) = _binned_sums(bin_lanes, parent_card,
                                 [(own_dyn, jnp.int32)], pstatic)
        outs.append({"counts": counts})
        child_ctx = (miss_bin, _and_pmask(pmask, mask), parent_card,
                     pstatic)
        for c in plan.children:
            _eval_agg(c, seg, inputs, cursor, mask, child_ctx, outs)
        return

    if kind == "bucket_dense":
        card, = plan.static
        b = my["doc_bucket"]
        total = parent_card * card
        bin_ok = b >= 0
        base = 0
        if pbin is not None:
            bin_ok = bin_ok & (pbin >= 0)
            base = pbin * card
        bin_lanes = jnp.where(bin_ok, base + b, total)
        own_dyn = mask if pmask is None else (mask & pmask)
        (counts,) = _binned_sums(bin_lanes, total,
                                 [(own_dyn & bin_ok, jnp.int32)],
                                 pstatic)
        outs.append({"counts": counts})
        child_bin = jnp.where(bin_ok, bin_lanes, -1)
        child_ctx = (child_bin, _and_pmask(pmask, mask), total, pstatic)
        for c in plan.children:
            _eval_agg(c, seg, inputs, cursor, mask, child_ctx, outs)
        return

    if kind == "metric_missing_only":
        needs, missing = plan.static
        bin_lanes = (jnp.zeros(d_pad, jnp.int32) if pbin is None
                     else jnp.where(pbin >= 0, pbin, parent_card))
        okm = mask if pmask is None else (mask & pmask)
        out = {}
        parts = []
        if "cnt" in needs:
            parts.append(("cnt", okm, jnp.int32))
        if "sum" in needs:
            parts.append(("sum", okm.astype(jnp.float32) * missing,
                          jnp.float32))
        if "sumsq" in needs:
            parts.append(("sumsq",
                          okm.astype(jnp.float32) * (missing * missing),
                          jnp.float32))
        if parts:
            sums = _binned_sums(bin_lanes, parent_card,
                                [(v, dt) for _, v, dt in parts], pstatic)
            for (nm, _, _), v in zip(parts, sums):
                out[nm] = v
        eff = jnp.where(okm & (bin_lanes < parent_card), bin_lanes,
                        parent_card)
        if "min" in needs:
            out["min"] = jnp.full(parent_card, POS_INF, jnp.float32).at[
                eff].min(jnp.where(okm, missing, POS_INF), mode="drop")
        if "max" in needs:
            out["max"] = jnp.full(parent_card, NEG_INF, jnp.float32).at[
                eff].max(jnp.where(okm, missing, NEG_INF), mode="drop")
        outs.append(out)
        return

    if kind == "metric_num":
        field, needs, ident, missing = (plan.static + (None,))[:4] \
            if len(plan.static) < 4 else plan.static
        col = seg["numeric"][field]
        doc_ids = col["doc_ids"]
        valid = doc_ids >= 0
        safe_doc = jnp.where(valid, doc_ids, 0)
        bin_ok = valid
        pb = 0
        if pbin is not None:
            pb = _take_doc(pbin, safe_doc, ident)
            bin_ok = bin_ok & (pb >= 0)
        bin_lanes = jnp.where(bin_ok, pb, parent_card)
        ok_dyn = _gather_ok(mask, pmask, safe_doc, ident) & bin_ok
        v = col["values_f32"]
        out: Dict[str, Any] = {}
        gemm_parts = []
        if "cnt" in needs:
            gemm_parts.append(("cnt", ok_dyn, jnp.int32))
        if "sum" in needs:
            gemm_parts.append(("sum", jnp.where(ok_dyn, v, 0.0),
                               jnp.float32))
        if "sumsq" in needs:
            gemm_parts.append(("sumsq", jnp.where(ok_dyn, v * v, 0.0),
                               jnp.float32))
        if gemm_parts:
            sums = _binned_sums(bin_lanes, parent_card,
                                [(c, dt) for _, c, dt in gemm_parts],
                                pstatic)
            for (name, _, _), s in zip(gemm_parts, sums):
                out[name] = s
        # min/max have no matmul form — masked scatter reductions
        eff = jnp.where(ok_dyn, bin_lanes, parent_card)
        if "min" in needs:
            out["min"] = jnp.full(parent_card, POS_INF, jnp.float32).at[
                eff].min(jnp.where(ok_dyn, v, POS_INF), mode="drop")
        if "max" in needs:
            out["max"] = jnp.full(parent_card, NEG_INF, jnp.float32).at[
                eff].max(jnp.where(ok_dyn, v, NEG_INF), mode="drop")
        if missing is not None:
            # docs WITHOUT the field contribute the substitute value
            # (ValuesSourceConfig#missing) — doc-space contributions on
            # top of the pairs-space reductions above
            exists = col["exists"]
            bin_m = (jnp.zeros(d_pad, jnp.int32) if pbin is None
                     else jnp.where(pbin >= 0, pbin, parent_card))
            okm = (mask if pmask is None else (mask & pmask)) & ~exists
            parts = []
            if "cnt" in needs:
                parts.append(("cnt", okm, jnp.int32))
            if "sum" in needs:
                parts.append(("sum", okm.astype(jnp.float32) * missing,
                              jnp.float32))
            if "sumsq" in needs:
                parts.append(("sumsq", okm.astype(jnp.float32)
                              * (missing * missing), jnp.float32))
            if parts:
                sums_m = _binned_sums(bin_m, parent_card,
                                      [(vv, dt) for _, vv, dt in parts],
                                      pstatic)
                for (nm, _, _), vv in zip(parts, sums_m):
                    out[nm] = out[nm] + vv
            eff_m = jnp.where(okm & (bin_m < parent_card), bin_m,
                              parent_card)
            if "min" in needs:
                out["min"] = out["min"].at[eff_m].min(
                    jnp.where(okm, jnp.float32(missing), POS_INF),
                    mode="drop")
            if "max" in needs:
                out["max"] = out["max"].at[eff_m].max(
                    jnp.where(okm, jnp.float32(missing), NEG_INF),
                    mode="drop")
        outs.append(out)
        return

    if kind == "count_ord":
        field, ident = plan.static
        col = seg["ordinal"][field]
        doc_ids = col["doc_ids"]
        valid = doc_ids >= 0
        safe_doc = jnp.where(valid, doc_ids, 0)
        bin_ok = valid
        pb = 0
        if pbin is not None:
            pb = _take_doc(pbin, safe_doc, ident)
            bin_ok = bin_ok & (pb >= 0)
        bin_lanes = jnp.where(bin_ok, pb, parent_card)
        ok_dyn = _gather_ok(mask, pmask, safe_doc, ident) & bin_ok
        (cnt,) = _binned_sums(bin_lanes, parent_card,
                              [(ok_dyn, jnp.int32)], pstatic)
        outs.append({"cnt": cnt})
        return

    if kind in ("presence_ord", "presence_num", "value_hist"):
        field, card, ident = plan.static
        col = seg["ordinal" if kind == "presence_ord" else "numeric"][field]
        ords = col["ords"] if kind == "presence_ord" else col["val_ords"]
        doc_ids = col["doc_ids"]
        total = parent_card * card
        if total > MAX_AGG_BINS:
            raise IllegalArgumentError(
                f"aggregation [{plan.name}] needs {total} bins "
                f"(> {MAX_AGG_BINS}); reduce bucket count or cardinality")
        valid = doc_ids >= 0
        safe_doc = jnp.where(valid, doc_ids, 0)
        bin_ok = valid
        base = 0
        if pbin is not None:
            pb = _take_doc(pbin, safe_doc, ident)
            bin_ok = bin_ok & (pb >= 0)
            base = pb * card
        bin_lanes = jnp.where(bin_ok, base + ords, total)
        ok_dyn = _gather_ok(mask, pmask, safe_doc, ident) & bin_ok
        (hist,) = _binned_sums(bin_lanes, total,
                               [(ok_dyn, jnp.int32)], pstatic)
        if kind == "value_hist":
            outs.append({"hist": hist})
        else:
            outs.append({"present": hist > 0})
        return

    if kind == "weighted_avg":
        vf, wf, ident = plan.static
        vcol = seg["numeric"][vf]
        wcol = seg["numeric"][wf]
        doc_ids = vcol["doc_ids"]
        valid = doc_ids >= 0
        safe_doc = jnp.where(valid, doc_ids, 0)
        bin_ok = valid
        pb = 0
        if pbin is not None:
            pb = _take_doc(pbin, safe_doc, ident)
            bin_ok = bin_ok & (pb >= 0)
        bin_lanes = jnp.where(bin_ok, pb, parent_card)
        # dense single-value weight per doc via min_rank decode
        w_dense = wcol["unique_f32"][jnp.clip(wcol["min_rank"], 0,
                                              wcol["unique_f32"].shape[0] - 1)]
        w = jnp.where(wcol["exists"][safe_doc], w_dense[safe_doc], 0.0)
        ok_dyn = (_gather_ok(mask, pmask, safe_doc, ident) & bin_ok
                  & wcol["exists"][safe_doc])
        v = vcol["values_f32"]
        sum_wv, sum_w = _binned_sums(
            bin_lanes, parent_card,
            [(jnp.where(ok_dyn, v * w, 0.0), jnp.float32),
             (jnp.where(ok_dyn, w, 0.0), jnp.float32)], pstatic)
        outs.append({"sum_wv": sum_wv, "sum_w": sum_w})
        return

    # ---- scatter-path kinds: bins are data-dependent (joins, dedup) or
    # rarely hot; they consume the dense parent ordinal vector and hand
    # their children a dynamic (static=False) context
    parent_eff = _ctx_parent_eff(ctx, d_pad)

    if kind == "nested":
        # doc set becomes the path's child rows whose ROOT is in the
        # current bucket set; each child inherits its root's bucket ord
        # (bucket/nested/NestedAggregator.java)
        pptr = seg["parent_ptr"]
        safe_p = jnp.where(pptr >= 0, pptr, 0)
        own = (seg["nested_path"] == my["path_ord"]) \
            & (my["path_ord"] >= 0) & seg["live"] & (pptr >= 0) \
            & mask[safe_p] & (parent_eff[safe_p] >= 0)
        child_eff = jnp.where(own, parent_eff[safe_p], -1)
        eff = jnp.where(own, child_eff, parent_card)
        counts = jnp.zeros(parent_card, jnp.int32).at[eff].add(
            own.astype(jnp.int32), mode="drop")
        outs.append({"counts": counts})
        child_ctx = (child_eff, jnp.ones(d_pad, jnp.bool_), parent_card,
                     False)
        for c in plan.children:
            _eval_agg(c, seg, inputs, cursor, own, child_ctx, outs)
        return

    if kind == "reverse_nested":
        # back to root rows (ReverseNestedAggregator.java): the bucket
        # count is DISTINCT roots per bucket — dedup (bucket, root) pairs
        # with a two-key sort + run-start flags, since one root's children
        # may sit in several buckets
        import jax as _jax
        pptr = seg["parent_ptr"]
        sel = mask & (parent_eff >= 0) & (pptr >= 0)
        eff_k = jnp.where(sel, parent_eff, parent_card)
        root_k = jnp.where(sel, pptr, d_pad)
        se, sr = _jax.lax.sort([eff_k, root_k], num_keys=2)
        first = jnp.concatenate([
            jnp.ones((1,), bool),
            (se[1:] != se[:-1]) | (sr[1:] != sr[:-1])])
        valid = first & (se < parent_card)
        counts = jnp.zeros(parent_card, jnp.int32).at[
            jnp.where(valid, se, parent_card)].add(
            valid.astype(jnp.int32), mode="drop")
        outs.append({"counts": counts})
        # sub-aggs evaluate over root rows; a root carries ONE bucket ord
        # (the engine's dense child_eff convention — same single-bucket
        # simplification bucket_ord applies to multi-valued fields)
        idx = jnp.where(sel, pptr, d_pad)
        root_eff = jnp.full(d_pad, -1, jnp.int32).at[idx].max(
            jnp.where(sel, parent_eff, -1), mode="drop")
        own = root_eff >= 0
        child_ctx = (root_eff, jnp.ones(d_pad, jnp.bool_), parent_card,
                     False)
        for c in plan.children:
            _eval_agg(c, seg, inputs, cursor, own, child_ctx, outs)
        return

    if kind == "adjacency":
        n_filters, = plan.static
        masks = []
        for qp in plan.query_plans:
            _, m = _eval_plan(qp, seg, inputs, cursor)
            masks.append(m & mask & (parent_eff >= 0))
        parent = jnp.where(parent_eff >= 0, parent_eff, 0)
        out: Dict[str, Any] = {}
        for i in range(n_filters):
            for j in range(i, n_filters):
                own = masks[i] & masks[j]
                eff = jnp.where(own, parent, parent_card)
                out[f"c_{i}_{j}"] = jnp.zeros(
                    parent_card, jnp.int32).at[eff].add(
                    own.astype(jnp.int32), mode="drop")
        outs.append(out)
        return

    if kind == "matrix_stats":
        from opensearch_tpu.search.plan_eval import dense_numeric
        fields = plan.static[0]
        dense = {}
        for f in fields:
            if f in seg["numeric"]:
                dense[f] = dense_numeric(seg, f, d_pad)
        out = {}
        parent = jnp.where(parent_eff >= 0, parent_eff, 0)
        for f in fields:
            if f not in dense:
                continue
            v, exists, _ = dense[f]
            own = mask & (parent_eff >= 0) & exists
            eff = jnp.where(own, parent, parent_card)
            zeros = lambda: jnp.zeros(parent_card, jnp.float32)  # noqa: E731
            vv = jnp.where(own, v, 0.0)
            out[f"{f}::cnt"] = jnp.zeros(parent_card, jnp.int32).at[eff].add(
                own.astype(jnp.int32), mode="drop")
            out[f"{f}::sum"] = zeros().at[eff].add(vv, mode="drop")
            out[f"{f}::sum2"] = zeros().at[eff].add(vv * vv, mode="drop")
            out[f"{f}::sum3"] = zeros().at[eff].add(vv ** 3, mode="drop")
            out[f"{f}::sum4"] = zeros().at[eff].add(vv ** 4, mode="drop")
        for i, fa in enumerate(fields):
            for fb in fields[i + 1:]:
                if fa not in dense or fb not in dense:
                    continue
                va, ea, _ = dense[fa]
                vb, eb, _ = dense[fb]
                own = mask & (parent_eff >= 0) & ea & eb
                eff = jnp.where(own, parent, parent_card)
                out[f"{fa}*{fb}::sumxy"] = jnp.zeros(
                    parent_card, jnp.float32).at[eff].add(
                    jnp.where(own, va * vb, 0.0), mode="drop")
                out[f"{fa}*{fb}::cnt"] = jnp.zeros(
                    parent_card, jnp.int32).at[eff].add(
                    own.astype(jnp.int32), mode="drop")
                out[f"{fa}*{fb}::sumx"] = jnp.zeros(
                    parent_card, jnp.float32).at[eff].add(
                    jnp.where(own, va, 0.0), mode="drop")
                out[f"{fa}*{fb}::sumy"] = jnp.zeros(
                    parent_card, jnp.float32).at[eff].add(
                    jnp.where(own, vb, 0.0), mode="drop")
        outs.append(out)
        return

    if kind == "geo_metric":
        from opensearch_tpu.search.plan_eval import dense_numeric
        field = plan.static[0]
        lat_key, lon_key = f"{field}.lat", f"{field}.lon"
        if lat_key not in seg["numeric"]:
            outs.append({})
            return
        lat, exists, _ = dense_numeric(seg, lat_key, d_pad)
        lon, _, _ = dense_numeric(seg, lon_key, d_pad)
        own = mask & (parent_eff >= 0) & exists
        parent = jnp.where(parent_eff >= 0, parent_eff, 0)
        eff = jnp.where(own, parent, parent_card)
        outs.append({
            "cnt": jnp.zeros(parent_card, jnp.int32).at[eff].add(
                own.astype(jnp.int32), mode="drop"),
            "sum_lat": jnp.zeros(parent_card, jnp.float32).at[eff].add(
                jnp.where(own, lat, 0.0), mode="drop"),
            "sum_lon": jnp.zeros(parent_card, jnp.float32).at[eff].add(
                jnp.where(own, lon, 0.0), mode="drop"),
            "min_lat": jnp.full(parent_card, POS_INF, jnp.float32)
                .at[eff].min(jnp.where(own, lat, POS_INF), mode="drop"),
            "max_lat": jnp.full(parent_card, NEG_INF, jnp.float32)
                .at[eff].max(jnp.where(own, lat, NEG_INF), mode="drop"),
            "min_lon": jnp.full(parent_card, POS_INF, jnp.float32)
                .at[eff].min(jnp.where(own, lon, POS_INF), mode="drop"),
            "max_lon": jnp.full(parent_card, NEG_INF, jnp.float32)
                .at[eff].max(jnp.where(own, lon, NEG_INF), mode="drop"),
        })
        return

    raise QueryShardError(f"unknown aggregation plan kind [{plan.kind}]")
