from opensearch_tpu.search.aggs.parse import parse_aggs  # noqa: F401
