"""Host-side aggregation reduce + response rendering.

Reference: the two-level reduce in search/aggregations/InternalAggregation.java:64
(per-shard partial trees merged by SearchPhaseController → final rendering) and
the per-type InternalAggregations. Device partials arrive as flat numpy arrays
per (segment, node); this module merges them by bucket key across segments
(shards merge the same way at the coordinator) and renders the REST
"aggregations" response shapes. Pipeline aggregations run on the reduced tree
(reference: PipelineAggregator.reduce), implemented in pipeline.py.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from opensearch_tpu.common.errors import IllegalArgumentError, ParsingError
from opensearch_tpu.index.mapper import format_date_millis
from opensearch_tpu.search.aggs.engine import AggPlan

DEFAULT_PERCENTS = [1.0, 5.0, 25.0, 50.0, 75.0, 95.0, 99.0]


class Decoded:
    """One segment's decoded partials for one plan node."""
    __slots__ = ("plan", "out", "children")

    def __init__(self, plan: AggPlan, out: dict, children: List["Decoded"]):
        self.plan = plan
        self.out = out
        self.children = children


def decode_outputs(plans: List[AggPlan], outs: List[dict]) -> List[Decoded]:
    cursor = [0]

    def walk(plan: AggPlan) -> Decoded:
        out = {k: np.asarray(v) for k, v in outs[cursor[0]].items()}  # sync-ok: host -- outputs already fetched by the collect phase
        cursor[0] += 1
        if plan.query_plan is not None:
            pass  # query plan consumed no output slots (inputs only)
        children = [walk(c) for c in plan.children]
        return Decoded(plan, out, children)

    return [walk(p) for p in plans]


def reduce_aggs(per_segment: List[List[Decoded]]) -> Dict[str, Any]:
    """per_segment: one decoded top-level list per segment, same node order."""
    if not per_segment:
        return {}
    n_top = len(per_segment[0])
    result: Dict[str, Any] = {}
    for i in range(n_top):
        entries = [(seg_nodes[i], 0) for seg_nodes in per_segment]
        name = per_segment[0][i].plan.name
        result[name] = _merge_node(entries)
    return result


def _merge_node(entries: List[Tuple[Decoded, int]]) -> Dict[str, Any]:
    """entries: (decoded node, parent bucket index within that segment)."""
    plan = entries[0][0].plan
    kind = plan.kind
    render = plan.render

    if kind == "empty":
        return _render_empty(render)

    if kind in ("bucket_ord", "bucket_num", "bucket_bits"):
        rkind = render.get("kind", "terms")
        if rkind == "terms":
            return _merge_terms(entries)
        if rkind == "significant_terms":
            return _merge_significant_terms(entries)
        if rkind in ("range", "date_range", "ip_range"):
            return _merge_ranges_fused(entries)
        out = _merge_histogram(entries)
        if rkind == "auto_date_histogram":
            out["interval"] = render.get("interval")
        return out

    if kind == "bucket_dense":
        rkind = render.get("kind")
        if rkind in ("composite", "multi_terms"):
            return _merge_composite(entries, multi=rkind == "multi_terms")
        return _merge_grid(entries)

    if kind == "adjacency":
        return _merge_adjacency(entries)

    if kind == "matrix_stats":
        return _merge_matrix_stats(entries)

    if kind == "geo_metric":
        return _merge_geo(entries)

    if kind == "multi":
        rkind = render.get("kind")
        if rkind == "filters":
            return _merge_filters(entries)
        return _merge_ranges(entries)

    if kind in ("filter", "global", "missing", "nested", "reverse_nested"):
        count = sum(int(d.out["counts"][p]) for d, p in entries
                    if "counts" in d.out)
        result = {"doc_count": count}
        result.update(_merge_children(entries, lambda p: p))
        return result

    if kind in ("metric_num", "metric_missing_only"):
        return _merge_metric(entries)

    if kind == "count_ord":
        cnt = sum(int(d.out["cnt"][p]) for d, p in entries if "cnt" in d.out)
        return {"value": cnt}

    if kind in ("presence_ord", "presence_num", "presence_bits"):
        return _merge_cardinality(entries)

    if kind == "value_hist":
        return _merge_value_hist(entries)

    if kind == "weighted_avg":
        sum_wv = sum(float(d.out["sum_wv"][p]) for d, p in entries
                     if "sum_wv" in d.out)
        sum_w = sum(float(d.out["sum_w"][p]) for d, p in entries
                    if "sum_w" in d.out)
        return {"value": (sum_wv / sum_w) if sum_w else None}

    raise IllegalArgumentError(f"cannot reduce aggregation kind [{kind}]")


def _merge_children(entries: List[Tuple[Decoded, int]], child_index_fn
                    ) -> Dict[str, Any]:
    """Merge each child slot across segments; child_index_fn maps this node's
    parent index to the child's flattened parent index."""
    first = entries[0][0]
    out: Dict[str, Any] = {}
    for j, child in enumerate(first.children):
        child_entries = [(d.children[j], child_index_fn(p)) for d, p in entries]
        out[child.plan.name] = _merge_node(child_entries)
    return out


def _render_empty(render: dict) -> Dict[str, Any]:
    rkind = render.get("kind", "")
    if rkind in ("terms",):
        return {"doc_count_error_upper_bound": 0, "sum_other_doc_count": 0,
                "buckets": []}
    if rkind in ("histogram", "date_histogram"):
        body = render.get("body", {})
        if int(body.get("min_doc_count", 0)) == 0 \
                and body.get("extended_bounds"):
            eb = _hist_eb_keys(render, body)
            step = render.get("step")
            if eb is not None and None not in eb and step:
                lo, hi = eb
                is_date = rkind == "date_histogram"
                buckets = []
                k = lo
                while k <= hi + step / 2:
                    b: Dict[str, Any] = {"key": int(k) if is_date else k,
                                         "doc_count": 0}
                    if is_date:
                        b["key_as_string"] = format_date_millis(int(k))
                    buckets.append(b)
                    k += step
                return {"buckets": buckets}
        return {"buckets": []}
    if rkind in ("range", "date_range", "ip_range"):
        specs = render.get("specs", [])
        buckets = []
        for key, frm, to in specs:
            b = {"key": key, "doc_count": 0}
            if frm is not None:
                b["from"] = frm
            if to is not None:
                b["to"] = to
            buckets.append(b)
        return {"buckets": buckets}
    if rkind in ("min", "max", "avg", "median_absolute_deviation"):
        return {"value": None}
    if rkind in ("sum", "value_count", "cardinality"):
        return {"value": 0}
    if rkind == "stats":
        return {"count": 0, "min": None, "max": None, "avg": None, "sum": 0.0}
    if rkind == "extended_stats":
        return {"count": 0, "min": None, "max": None, "avg": None, "sum": 0.0,
                "sum_of_squares": None, "variance": None, "std_deviation": None}
    if rkind in ("percentiles", "percentile_ranks"):
        return {"values": {}}
    if rkind == "weighted_avg":
        return {"value": None}
    return {"doc_count": 0}


# ------------------------------------------------------------------ buckets

def _merge_terms(entries: List[Tuple[Decoded, int]]) -> Dict[str, Any]:
    plan = entries[0][0].plan
    body = plan.render.get("body", {})
    size = int(body.get("size", 10))
    min_doc_count = int(body.get("min_doc_count", 1))
    order = body.get("order", {"_count": "desc"})
    if isinstance(order, list):
        order = order[0] if order else {"_count": "desc"}
    (order_key, order_dir), = order.items() if order else (("_count", "desc"),)

    acc: Dict[Any, Dict[str, Any]] = {}
    for d, p in entries:
        if "counts" not in d.out:
            continue
        keys = d.plan.render["keys"]
        card = d.plan.static[1]
        counts = d.out["counts"]
        base = p * card
        for c in range(min(card, len(keys))):
            n = int(counts[base + c])
            if n <= 0:
                continue
            slot = acc.setdefault(keys[c], {"doc_count": 0, "segments": []})
            slot["doc_count"] += n
            slot["segments"].append((d, p, c))

    total = sum(v["doc_count"] for v in acc.values())

    def sort_key(item):
        key, slot = item
        if order_key == "_key":
            return key
        return slot["doc_count"]
    reverse = (order_dir == "desc")
    items = sorted(acc.items(), key=sort_key, reverse=reverse)
    if order_key == "_count":  # secondary: key ascending (reference contract)
        items = sorted(items, key=lambda kv: _orderable(kv[0]))
        items = sorted(items, key=lambda kv: kv[1]["doc_count"],
                       reverse=reverse)

    buckets = []
    taken = 0
    for key, slot in items:
        if slot["doc_count"] < min_doc_count:
            continue
        if taken >= size:
            break
        taken += 1
        bucket: Dict[str, Any] = {"key": key, "doc_count": slot["doc_count"]}
        first = entries[0][0]
        for j, child in enumerate(first.children):
            child_entries = [(d.children[j], p * d.plan.static[1] + c)
                             for d, p, c in slot["segments"]]
            bucket[child.plan.name] = _merge_node(child_entries)
        buckets.append(bucket)
    shown = sum(b["doc_count"] for b in buckets)
    return {"doc_count_error_upper_bound": 0,
            "sum_other_doc_count": total - shown,
            "buckets": buckets}


def _orderable(key):
    return (0, key) if isinstance(key, (int, float, bool)) else (1, str(key))


def _hist_eb_keys(render: dict, body: dict):
    """extended_bounds clamped onto the bucket-key lattice → (lo, hi) keys
    (either side may be None). Fixed-step histograms only — calendar
    intervals have no arithmetic lattice to extend along."""
    eb = body.get("extended_bounds")
    step = render.get("step")
    if not eb or not step or render.get("calendar"):
        return None

    def conv(v):
        if v is None:
            return None
        if isinstance(v, str):
            from opensearch_tpu.index.mapper import parse_date_millis
            from opensearch_tpu.search.compile import _resolve_date_math
            v = _resolve_date_math(v)
            if isinstance(v, str):
                v = parse_date_millis(v)
        return float(v)

    shift = float(render.get("shift", 0.0))
    lo, hi = conv(eb.get("min")), conv(eb.get("max"))

    def key_of(v):
        return math.floor((v + shift) / step) * step - shift

    return ((None if lo is None else key_of(lo)),
            (None if hi is None else key_of(hi)))


def _trim_zero_edges(buckets: List[dict], min_doc_count: int,
                     eb_keys) -> List[dict]:
    """Histogram buckets exist between the min and max COLLECTED buckets
    (plus extended_bounds) — the compiled key table spans the segment's
    whole data range, so a query-filtered histogram must drop the
    leading/trailing zero-count buckets outside the matched span
    (reference: InternalHistogram.addEmptyBuckets fills between the
    first and last non-empty bucket only)."""
    if min_doc_count != 0 or not buckets:
        return buckets
    nz = [i for i, b in enumerate(buckets) if b["doc_count"] > 0]
    lo = buckets[nz[0]]["key"] if nz else None
    hi = buckets[nz[-1]]["key"] if nz else None
    if eb_keys is not None:
        eb_lo, eb_hi = eb_keys
        if eb_lo is not None:
            lo = eb_lo if lo is None else min(lo, eb_lo)
        if eb_hi is not None:
            hi = eb_hi if hi is None else max(hi, eb_hi)
    if lo is None:
        return []
    return [b for b in buckets if lo <= b["key"] <= hi]


def _merge_histogram(entries: List[Tuple[Decoded, int]]) -> Dict[str, Any]:
    plan = entries[0][0].plan
    render = plan.render
    body = render.get("body", {})
    min_doc_count = int(body.get("min_doc_count", 0))
    is_date = render.get("kind") == "date_histogram"
    eb_keys = _hist_eb_keys(render, body) if body.get("extended_bounds") \
        else None

    # single-segment, leaf histogram (the dashboard hot shape): render
    # straight from the counts array — no per-bucket dict accumulation,
    # key strings precomputed at compile (render["keys_str"])
    if (len(entries) == 1 and not entries[0][0].children and eb_keys is None
            and "counts" in entries[0][0].out):
        d, p = entries[0]
        card = d.plan.static[1]
        keys = d.plan.render["keys"]
        keys_str = d.plan.render.get("keys_str")
        counts = np.asarray(d.out["counts"])[p * card:(p + 1) * card]  # sync-ok: host -- decoded partials are host arrays
        counts = counts[:len(keys)].tolist()  # sync-ok: host -- decoded partials are host arrays
        if is_date:
            if keys_str is None:
                keys_str = [format_date_millis(int(k)) for k in keys]
            buckets = [{"key": int(k), "doc_count": c, "key_as_string": ks}
                       for k, ks, c in zip(keys, keys_str, counts)
                       if c >= min_doc_count]
        else:
            buckets = [{"key": k, "doc_count": c}
                       for k, c in zip(keys, counts) if c >= min_doc_count]
        return {"buckets": _trim_zero_edges(buckets, min_doc_count, None)}

    acc: Dict[float, Dict[str, Any]] = {}
    for d, p in entries:
        if "counts" not in d.out:
            continue
        keys = d.plan.render["keys"]
        card = d.plan.static[1]
        counts = d.out["counts"]
        base = p * card
        for c in range(min(card, len(keys))):
            n = int(counts[base + c])
            slot = acc.setdefault(keys[c], {"doc_count": 0, "segments": []})
            slot["doc_count"] += n
            if n > 0 or True:
                slot["segments"].append((d, p, c))

    if not acc and eb_keys is None:
        return {"buckets": []}
    all_keys = sorted(acc.keys())
    # fill gaps for min_doc_count == 0 between observed bounds (fixed step
    # only) and out to extended_bounds when given
    if min_doc_count == 0 and not render.get("calendar"):
        step = render.get("step")
        if step is None and len(all_keys) >= 2:
            # legacy plans carry no lattice info: infer from observed keys
            steps = sorted({round(b - a, 9)
                            for a, b in zip(all_keys, all_keys[1:])})
            step = steps[0] if steps and steps[0] > 0 else None
        if step:
            lo = all_keys[0] if all_keys else None
            hi = all_keys[-1] if all_keys else None
            if eb_keys is not None:
                eb_lo, eb_hi = eb_keys
                lo = eb_lo if lo is None else \
                    (lo if eb_lo is None else min(lo, eb_lo))
                hi = eb_hi if hi is None else \
                    (hi if eb_hi is None else max(hi, eb_hi))
            if lo is not None and hi is not None:
                base_key = lo
                seen = {round((ak - base_key) / step) for ak in all_keys}
                q = 0
                k = base_key
                while k <= hi + step / 2:
                    if q not in seen:
                        acc[k] = {"doc_count": 0, "segments": []}
                    q += 1
                    k = base_key + q * step
                all_keys = sorted(acc.keys())

    first = entries[0][0]
    buckets = []
    for key in all_keys:
        slot = acc[key]
        if slot["doc_count"] < min_doc_count:
            continue
        bucket: Dict[str, Any] = {"key": int(key) if is_date else key,
                                  "doc_count": slot["doc_count"]}
        if is_date:
            bucket["key_as_string"] = format_date_millis(int(key))
        for j, child in enumerate(first.children):
            child_entries = [(d.children[j], p * d.plan.static[1] + c)
                             for d, p, c in slot["segments"]]
            if child_entries:
                bucket[child.plan.name] = _merge_node(child_entries)
            else:
                bucket[child.plan.name] = _render_empty(child.plan.render)
        buckets.append(bucket)
    return {"buckets": _trim_zero_edges(buckets, min_doc_count, eb_keys)}


def _merge_ranges(entries: List[Tuple[Decoded, int]]) -> Dict[str, Any]:
    plan = entries[0][0].plan
    render = plan.render
    specs = render.get("specs", [])
    is_date = render.get("is_date", False)
    buckets = []
    for i, (key, frm, to) in enumerate(specs):
        sub_entries = [(d.children[i], p) for d, p in entries
                       if i < len(d.children)]
        count = sum(int(d.out["counts"][p]) for d, p in sub_entries
                    if "counts" in d.out)
        bucket: Dict[str, Any] = {"key": key, "doc_count": count}
        if frm is not None:
            bucket["from"] = frm
            if is_date:
                bucket["from_as_string"] = format_date_millis(int(frm))
        if to is not None:
            bucket["to"] = to
            if is_date:
                bucket["to_as_string"] = format_date_millis(int(to))
        bucket.update(_merge_children(sub_entries, lambda p: p))
        buckets.append(bucket)
    return {"buckets": buckets}


def _merge_ranges_fused(entries: List[Tuple[Decoded, int]]) -> Dict[str, Any]:
    """Range buckets from the fused bucket_bits kind: one counts row per
    range spec (overlap-safe), no per-range sub-plans to walk."""
    plan = entries[0][0].plan
    render = plan.render
    specs = render.get("specs", [])
    is_date = render.get("is_date", False)
    buckets = []
    for i, (key, frm, to) in enumerate(specs):
        count = 0
        for d, p in entries:
            if d.plan.kind == "bucket_bits" and "counts" in d.out:
                count += int(d.out["counts"][i])
            elif d.plan.kind == "multi" and i < len(d.children) \
                    and "counts" in d.children[i].out:
                count += int(d.children[i].out["counts"][p])
        bucket: Dict[str, Any] = {"key": key, "doc_count": count}
        if frm is not None:
            bucket["from"] = frm
            if is_date:
                bucket["from_as_string"] = format_date_millis(int(frm))
        if to is not None:
            bucket["to"] = to
            if is_date:
                bucket["to_as_string"] = format_date_millis(int(to))
        buckets.append(bucket)
    return {"buckets": buckets}


def _merge_filters(entries: List[Tuple[Decoded, int]]) -> Dict[str, Any]:
    plan = entries[0][0].plan
    names = plan.render["names"]
    keyed = plan.render["keyed"]
    results = []
    for i, name in enumerate(names):
        sub_entries = [(d.children[i], p) for d, p in entries]
        results.append(_merge_node(sub_entries))
    if keyed:
        return {"buckets": {n: r for n, r in zip(names, results)}}
    return {"buckets": results}


# ------------------------------------------------------------------ metrics

def _merge_metric(entries: List[Tuple[Decoded, int]]) -> Dict[str, Any]:
    plan = entries[0][0].plan
    mtype = plan.render.get("kind", "stats")
    is_date = plan.render.get("is_date", False)
    total_sum = 0.0
    total_cnt = 0
    total_sumsq = 0.0
    vmin, vmax = math.inf, -math.inf
    for d, p in entries:
        if not d.out:
            continue
        # only the partials this metric's needs-set collected are present
        # (engine._METRIC_NEEDS)
        if "sum" in d.out:
            total_sum += float(d.out["sum"][p])
        if "cnt" in d.out:
            total_cnt += int(d.out["cnt"][p])
        if "sumsq" in d.out:
            total_sumsq += float(d.out["sumsq"][p])
        if "min" in d.out:
            vmin = min(vmin, float(d.out["min"][p]))
        if "max" in d.out:
            vmax = max(vmax, float(d.out["max"][p]))
    has = total_cnt > 0

    def dateify(v):
        return v

    if mtype == "min":
        out = {"value": vmin if has else None}
    elif mtype == "max":
        out = {"value": vmax if has else None}
    elif mtype == "sum":
        out = {"value": total_sum}
    elif mtype == "avg":
        out = {"value": (total_sum / total_cnt) if has else None}
    elif mtype == "value_count":
        out = {"value": total_cnt}
    elif mtype in ("stats", "extended_stats"):
        out = {"count": total_cnt,
               "min": vmin if has else None,
               "max": vmax if has else None,
               "avg": (total_sum / total_cnt) if has else None,
               "sum": total_sum}
        if mtype == "extended_stats":
            if has:
                mean = total_sum / total_cnt
                variance = max(total_sumsq / total_cnt - mean * mean, 0.0)
                std = math.sqrt(variance)
                out.update({
                    "sum_of_squares": total_sumsq,
                    "variance": variance,
                    "std_deviation": std,
                    "std_deviation_bounds": {"upper": mean + 2 * std,
                                             "lower": mean - 2 * std},
                })
            else:
                out.update({"sum_of_squares": None, "variance": None,
                            "std_deviation": None,
                            "std_deviation_bounds": {"upper": None,
                                                     "lower": None}})
    else:
        raise IllegalArgumentError(f"unknown metric type [{mtype}]")
    if is_date and mtype in ("min", "max") and out.get("value") is not None:
        out["value_as_string"] = format_date_millis(int(out["value"]))
    return out


def _merge_cardinality(entries: List[Tuple[Decoded, int]]) -> Dict[str, Any]:
    live = [(d, p) for d, p in entries if "present" in d.out]
    if len(live) == 1:
        # single segment: the presence bitmap's popcount IS the exact
        # cardinality — no key materialization
        d, p = live[0]
        card = d.plan.static[1]
        present = np.asarray(d.out["present"][p * card:(p + 1) * card])  # sync-ok: host -- decoded partials are host arrays
        n_keys = len(d.plan.render["keys"]
                     if "keys" in d.plan.render
                     else d.plan.render.get("values", ()))
        return {"value": int(np.count_nonzero(present[:n_keys]))}
    distinct = set()
    for d, p in live:
        card = d.plan.static[1]
        present = d.out["present"][p * card:(p + 1) * card]
        if "keys" in d.plan.render:
            keys = d.plan.render["keys"]
            for c in np.nonzero(present)[0]:
                if c < len(keys):
                    distinct.add(keys[int(c)])
        else:
            values = d.plan.render.get("values", ())
            for c in np.nonzero(present)[0]:
                if c < len(values):
                    distinct.add(float(values[int(c)]))
    return {"value": len(distinct)}


def _value_counts(entries: List[Tuple[Decoded, int]]) -> Tuple[np.ndarray, np.ndarray]:
    acc: Dict[float, int] = {}
    for d, p in entries:
        if "hist" not in d.out:
            continue
        card = d.plan.static[1]
        hist = d.out["hist"][p * card:(p + 1) * card]
        values = d.plan.render["values"]
        for c in np.nonzero(hist)[0]:
            if c < len(values):
                v = float(values[int(c)])
                acc[v] = acc.get(v, 0) + int(hist[int(c)])
    if not acc:
        return np.zeros(0), np.zeros(0, dtype=np.int64)
    vals = np.array(sorted(acc.keys()))
    counts = np.array([acc[v] for v in vals], dtype=np.int64)
    return vals, counts


def percentile_from_counts(vals: np.ndarray, counts: np.ndarray,
                           q: float) -> Optional[float]:
    """Exact linear-interpolated percentile over a weighted multiset
    (numpy 'linear' method; replaces the reference's TDigest approximation)."""
    n = int(counts.sum())
    if n == 0:
        return None
    pos = (q / 100.0) * (n - 1)
    lo_i = int(math.floor(pos))
    hi_i = min(lo_i + 1, n - 1)
    frac = pos - lo_i
    cum = np.cumsum(counts)
    lo_v = float(vals[np.searchsorted(cum, lo_i + 1)])
    hi_v = float(vals[np.searchsorted(cum, hi_i + 1)])
    return lo_v + (hi_v - lo_v) * frac


def _merge_value_hist(entries: List[Tuple[Decoded, int]]) -> Dict[str, Any]:
    plan = entries[0][0].plan
    kind = plan.render.get("kind", "percentiles")
    body = plan.render.get("body", {})
    vals, counts = _value_counts(entries)
    if kind == "percentiles":
        percents = body.get("percents", DEFAULT_PERCENTS)
        return {"values": {f"{float(q)}": percentile_from_counts(vals, counts, q)
                           for q in percents}}
    if kind == "percentile_ranks":
        targets = body.get("values", [])
        n = int(counts.sum())
        out = {}
        for t in targets:
            if n == 0:
                out[f"{float(t)}"] = None
            else:
                below = int(counts[vals <= float(t)].sum())
                out[f"{float(t)}"] = 100.0 * below / n
        return {"values": out}
    if kind == "median_absolute_deviation":
        if counts.sum() == 0:
            return {"value": None}
        median = percentile_from_counts(vals, counts, 50.0)
        dev = np.abs(vals - median)
        order = np.argsort(dev)
        return {"value": percentile_from_counts(dev[order], counts[order], 50.0)}
    raise IllegalArgumentError(f"unknown value-hist agg [{kind}]")


# ------------------------------------------------- extended bucket mergers

def _merge_composite(entries: List[Tuple[Decoded, int]],
                     multi: bool) -> Dict[str, Any]:
    """Composite (paginated multi-source tuples) and multi_terms share the
    mixed-radix bucket layout; they differ only in rendering/sort/paging."""
    plan = entries[0][0].plan
    render = plan.render
    sources = render["sources"]
    merged: Dict[tuple, Tuple[int, List[Tuple[Decoded, int, int]]]] = {}
    for d, p in entries:
        counts = d.out.get("counts")
        if counts is None:
            continue
        # key lists are PER SEGMENT (each segment has its own dictionary)
        key_lists = d.plan.render["key_lists"]
        radices = [max(len(k), 1) for k in key_lists]
        card = int(np.prod(radices))
        base = p * card
        nz = np.nonzero(np.asarray(counts[base:base + card]))[0]  # sync-ok: host -- decoded partials are host arrays
        for flat in nz:
            rest = int(flat)
            digits = []
            for r in reversed(radices):
                digits.append(rest % r)
                rest //= r
            digits.reverse()
            key = tuple(key_lists[i][digit]
                        for i, digit in enumerate(digits))
            cnt, members = merged.setdefault(key, (0, []))
            merged[key] = (cnt + int(counts[base + flat]),
                           members + [(d, p, int(flat))])
    body = render.get("body", {})
    size = int(body.get("size", 10))
    if multi:
        items = sorted(merged.items(), key=lambda kv: (-kv[1][0], kv[0]))
        buckets = []
        for key, (cnt, members) in items[:size]:
            b = {"key": list(key),
                 "key_as_string": "|".join(str(k) for k in key),
                 "doc_count": cnt}
            b.update(_merge_composite_children(plan, members))
            buckets.append(b)
        return {"doc_count_error_upper_bound": 0, "sum_other_doc_count":
                sum(c for _, (c, _) in items[size:]),
                "buckets": buckets}
    # composite: key-ordered pagination with after_key
    after = body.get("after")
    items = sorted(merged.items(), key=lambda kv: _tuple_sort_key(kv[0]))
    if after is not None:
        after_tuple = tuple(after[s] for s in sources)
        items = [kv for kv in items
                 if _tuple_sort_key(kv[0]) > _tuple_sort_key(after_tuple)]
    page = items[:size]
    buckets = []
    for key, (cnt, members) in page:
        b = {"key": dict(zip(sources, key)), "doc_count": cnt}
        b.update(_merge_composite_children(plan, members))
        buckets.append(b)
    out: Dict[str, Any] = {"buckets": buckets}
    if page:
        out["after_key"] = dict(zip(sources, page[-1][0]))
    return out


def _tuple_sort_key(key: tuple):
    return tuple((0, v) if isinstance(v, (int, float, bool))
                 else (1, str(v)) for v in key)


def _merge_composite_children(plan, members) -> Dict[str, Any]:
    if not plan.children:
        return {}
    out: Dict[str, Any] = {}
    for j, child in enumerate(plan.children):
        child_entries = []
        for d, p, flat in members:
            total_card = int(np.prod([max(len(k), 1)
                                      for k in d.plan.render["key_lists"]]))
            child_entries.append((d.children[j], p * total_card + flat))
        out[child.name] = _merge_node(child_entries)
    return out


def _merge_grid(entries: List[Tuple[Decoded, int]]) -> Dict[str, Any]:
    plan = entries[0][0].plan
    body = plan.render.get("body", {})
    totals: Dict[str, int] = {}
    for d, p in entries:
        counts = d.out.get("counts")
        if counts is None:
            continue
        keys = d.plan.render.get("keys", [])  # per-segment key table
        card = max(len(keys), 1)
        base = p * card
        arr = np.asarray(counts[base:base + card])  # sync-ok: host -- decoded partials are host arrays
        for i in np.nonzero(arr)[0]:
            if i < len(keys):
                totals[keys[i]] = totals.get(keys[i], 0) + int(arr[i])
    size = int(body.get("size", 10000))
    buckets = sorted(totals.items(), key=lambda kv: (-kv[1], kv[0]))[:size]
    return {"buckets": [{"key": k, "doc_count": c} for k, c in buckets]}


def _merge_significant_terms(entries: List[Tuple[Decoded, int]]
                             ) -> Dict[str, Any]:
    """JLH significance scoring (reference default heuristic:
    (fg% - bg%) * (fg% / bg%))."""
    plan = entries[0][0].plan
    # fg and bg accumulate by KEY across segments (per-segment dictionaries)
    fg_by_key: Dict[Any, int] = {}
    bg_by_key: Dict[Any, int] = {}
    bg_total = 0
    for d, p in entries:
        keys = d.plan.render.get("keys", [])
        bg = d.plan.render.get("bg", [])
        bg_total += max(d.plan.render.get("bg_total", 0), 0)
        card = max(len(keys), 1)
        counts = d.out.get("counts")
        for i, key in enumerate(keys):
            bg_by_key[key] = bg_by_key.get(key, 0) +                 (int(bg[i]) if i < len(bg) else 0)
            if counts is not None:
                fg_by_key[key] = fg_by_key.get(key, 0) +                     int(counts[p * card + i])
    bg_total = max(bg_total, 1)
    subset_size = max(sum(fg_by_key.values()), 1)
    body = plan.render.get("body", {})
    min_doc_count = int(body.get("min_doc_count", 3))
    size = int(body.get("size", 10))
    scored = []
    for key, fg_count in fg_by_key.items():
        if fg_count < min_doc_count:
            continue
        fg_pct = fg_count / subset_size
        bg_pct = max(bg_by_key.get(key, 0), 1) / bg_total
        if fg_pct <= bg_pct:
            continue
        score = (fg_pct - bg_pct) * (fg_pct / bg_pct)
        scored.append({"key": key, "doc_count": int(fg_count),
                       "score": float(score),
                       "bg_count": int(bg_by_key.get(key, 0))})
    scored.sort(key=lambda b: -b["score"])
    return {"doc_count": subset_size, "bg_count": bg_total,
            "buckets": scored[:size]}


def _merge_adjacency(entries: List[Tuple[Decoded, int]]) -> Dict[str, Any]:
    plan = entries[0][0].plan
    names = plan.render["names"]
    totals: Dict[str, int] = {}
    for d, p in entries:
        for i in range(len(names)):
            for j in range(i, len(names)):
                arr = d.out.get(f"c_{i}_{j}")
                if arr is None:
                    continue
                key = names[i] if i == j else f"{names[i]}&{names[j]}"
                totals[key] = totals.get(key, 0) + int(arr[p])
    buckets = [{"key": k, "doc_count": c}
               for k, c in sorted(totals.items()) if c > 0]
    return {"buckets": buckets}


def _merge_matrix_stats(entries: List[Tuple[Decoded, int]]) -> Dict[str, Any]:
    plan = entries[0][0].plan
    fields = plan.render["fields"]

    def total(key):
        return sum(float(d.out[key][p]) for d, p in entries
                   if key in d.out)

    out_fields = []
    moments = {}
    for f in fields:
        cnt = int(total(f"{f}::cnt"))
        if cnt == 0:
            continue
        s1 = total(f"{f}::sum")
        s2 = total(f"{f}::sum2")
        s3 = total(f"{f}::sum3")
        s4 = total(f"{f}::sum4")
        mean = s1 / cnt
        var = max(s2 / cnt - mean ** 2, 0.0)
        std = var ** 0.5
        # central moments from raw moments
        m3 = s3 / cnt - 3 * mean * s2 / cnt + 2 * mean ** 3
        m4 = (s4 / cnt - 4 * mean * s3 / cnt + 6 * mean ** 2 * s2 / cnt
              - 3 * mean ** 4)
        moments[f] = (cnt, mean, var)
        entry = {
            "name": f, "count": cnt, "mean": mean,
            "variance": var * cnt / max(cnt - 1, 1),  # sample variance
            "skewness": (m3 / std ** 3) if std > 0 else 0.0,
            "kurtosis": (m4 / var ** 2) if var > 0 else 0.0,
            "covariance": {}, "correlation": {},
        }
        out_fields.append(entry)
    by_name = {e["name"]: e for e in out_fields}
    for i, fa in enumerate(fields):
        for fb in fields[i + 1:]:
            key = f"{fa}*{fb}"
            if fa not in by_name or fb not in by_name:
                continue
            n = int(total(f"{key}::cnt"))
            if n == 0:
                continue
            sxy = total(f"{key}::sumxy")
            sx = total(f"{key}::sumx")
            sy = total(f"{key}::sumy")
            cov = sxy / n - (sx / n) * (sy / n)
            cov_sample = cov * n / max(n - 1, 1)
            _, _, var_a = moments[fa]
            _, _, var_b = moments[fb]
            corr = cov / ((var_a ** 0.5) * (var_b ** 0.5)) \
                if var_a > 0 and var_b > 0 else 0.0
            for a, b in ((fa, fb), (fb, fa)):
                by_name[a]["covariance"][b] = cov_sample
                by_name[a]["correlation"][b] = corr
    for e in out_fields:
        e["covariance"][e["name"]] = e["variance"]
        e["correlation"][e["name"]] = 1.0
    return {"doc_count": max((e["count"] for e in out_fields), default=0),
            "fields": out_fields}


def _merge_geo(entries: List[Tuple[Decoded, int]]) -> Dict[str, Any]:
    plan = entries[0][0].plan
    kind = plan.render.get("kind", "geo_bounds")
    cnt = sum(int(d.out["cnt"][p]) for d, p in entries if "cnt" in d.out)
    if cnt == 0:
        return {"doc_count": 0} if kind == "geo_centroid" else {}
    if kind == "geo_centroid":
        sum_lat = sum(float(d.out["sum_lat"][p]) for d, p in entries
                      if "sum_lat" in d.out)
        sum_lon = sum(float(d.out["sum_lon"][p]) for d, p in entries
                      if "sum_lon" in d.out)
        return {"location": {"lat": sum_lat / cnt, "lon": sum_lon / cnt},
                "count": cnt}
    agg = lambda key, fn, init: fn(  # noqa: E731
        [float(d.out[key][p]) for d, p in entries if key in d.out] or [init])
    return {"bounds": {
        "top_left": {"lat": agg("max_lat", max, 0.0),
                     "lon": agg("min_lon", min, 0.0)},
        "bottom_right": {"lat": agg("min_lat", min, 0.0),
                         "lon": agg("max_lon", max, 0.0)},
    }}
