"""Aggregation request parsing: REST "aggs" body → typed builder tree.

Reference: search/aggregations/AggregatorFactories.java parseAggregators and
the per-type Builder parsers. Bucket aggs may nest sub-aggregations under
"aggs"/"aggregations"; pipeline aggs reference sibling paths via buckets_path.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Any, Dict, List, Optional

from opensearch_tpu.common.errors import ParsingError

BUCKET_TYPES = {"terms", "histogram", "date_histogram", "range", "date_range",
                "filter", "filters", "global", "missing", "ip_range",
                "composite", "multi_terms", "significant_terms",
                "auto_date_histogram", "adjacency_matrix", "geohash_grid",
                "geotile_grid", "nested", "reverse_nested"}
METRIC_TYPES = {"min", "max", "sum", "avg", "value_count", "stats",
                "extended_stats", "cardinality", "percentiles",
                "percentile_ranks", "weighted_avg", "median_absolute_deviation",
                "top_hits", "geo_centroid", "scripted_metric", "matrix_stats",
                "geo_bounds"}
PIPELINE_TYPES = {"derivative", "cumulative_sum", "bucket_script",
                  "bucket_selector", "bucket_sort", "avg_bucket", "max_bucket",
                  "min_bucket", "sum_bucket", "stats_bucket",
                  "extended_stats_bucket", "percentiles_bucket", "serial_diff",
                  "moving_avg", "moving_fn"}


@dataclass
class AggNode:
    name: str
    type: str
    body: Dict[str, Any]
    children: List["AggNode"] = dc_field(default_factory=list)
    pipelines: List["AggNode"] = dc_field(default_factory=list)

    @property
    def field(self) -> Optional[str]:
        return self.body.get("field")


def parse_aggs(aggs_body: Optional[dict]) -> List[AggNode]:
    if not aggs_body:
        return []
    if not isinstance(aggs_body, dict):
        raise ParsingError("Found [aggs] but expected an object")
    out: List[AggNode] = []
    for name, spec in aggs_body.items():
        if not isinstance(spec, dict):
            raise ParsingError(f"aggregation [{name}] must be an object")
        sub_body = spec.get("aggs", spec.get("aggregations"))
        type_keys = [k for k in spec if k not in ("aggs", "aggregations", "meta")]
        if len(type_keys) != 1:
            raise ParsingError(
                f"Expected exactly one aggregation type for [{name}], "
                f"found {sorted(type_keys)}")
        agg_type = type_keys[0]
        if agg_type not in BUCKET_TYPES | METRIC_TYPES | PIPELINE_TYPES:
            raise ParsingError(f"Unknown aggregation type [{agg_type}]")
        node = AggNode(name=name, type=agg_type, body=spec[agg_type] or {})
        if sub_body:
            if agg_type in METRIC_TYPES:
                raise ParsingError(
                    f"Aggregator [{name}] of type [{agg_type}] cannot accept "
                    f"sub-aggregations")
            subs = parse_aggs(sub_body)
            node.children = [s for s in subs if s.type not in PIPELINE_TYPES]
            node.pipelines = [s for s in subs if s.type in PIPELINE_TYPES]
        out.append(node)
    return out
