"""Pipeline aggregations: run on the reduced bucket tree.

Reference: search/aggregations/pipeline/ (17 types) — parent pipelines
(derivative, cumulative_sum, moving_avg/fn, serial_diff, bucket_script/
selector/sort) transform a parent bucket agg's bucket list; sibling pipelines
(avg/max/min/sum/stats/extended_stats/percentiles_bucket) summarize a sibling
path into a single value. bucket_script uses a restricted arithmetic
expression evaluator instead of painless.
"""

from __future__ import annotations

import ast
import math
import operator
from typing import Any, Dict, List, Optional

from opensearch_tpu.common.errors import IllegalArgumentError, ParsingError
from opensearch_tpu.search.aggs.parse import AggNode, PIPELINE_TYPES


def resolve_bucket_path(bucket: Dict[str, Any], path: str) -> Optional[float]:
    """Resolve a buckets_path within one bucket: '_count', 'metric',
    'metric.property' (e.g. 'stats.avg')."""
    if path == "_count":
        return float(bucket.get("doc_count", 0))
    parts = path.split(".")
    node = bucket.get(parts[0])
    if node is None:
        return None
    if len(parts) == 1:
        if isinstance(node, dict):
            return node.get("value")
        return node
    val = node
    for p in parts[1:]:
        if not isinstance(val, dict):
            return None
        val = val.get(p)
    return val


# -------------------------------------------------- restricted script eval

_BINOPS = {ast.Add: operator.add, ast.Sub: operator.sub, ast.Mult: operator.mul,
           ast.Div: operator.truediv, ast.Mod: operator.mod,
           ast.Pow: operator.pow, ast.FloorDiv: operator.floordiv}
_UNARY = {ast.USub: operator.neg, ast.UAdd: operator.pos}
_CMPOPS = {ast.Gt: operator.gt, ast.GtE: operator.ge, ast.Lt: operator.lt,
           ast.LtE: operator.le, ast.Eq: operator.eq, ast.NotEq: operator.ne}
_FUNCS = {"abs": abs, "min": min, "max": max, "log": math.log,
          "log10": math.log10, "sqrt": math.sqrt, "floor": math.floor,
          "ceil": math.ceil, "round": round, "exp": math.exp}


def safe_eval(expr: str, variables: Dict[str, float]) -> Any:
    """Arithmetic-only expression evaluator (the bucket_script 'painless'
    subset). Supports params.x variables, arithmetic, comparisons, ternary."""
    try:
        tree = ast.parse(expr, mode="eval")
    except SyntaxError as e:
        raise ParsingError(f"invalid script [{expr}]: {e}")

    def ev(node):
        if isinstance(node, ast.Expression):
            return ev(node.body)
        if isinstance(node, ast.Constant):
            if isinstance(node.value, (int, float, bool)):
                return node.value
            raise ParsingError(f"unsupported literal in script [{expr}]")
        if isinstance(node, ast.Name):
            if node.id in variables:
                return variables[node.id]
            if node.id == "params":
                return variables
            raise ParsingError(f"unknown variable [{node.id}] in script")
        if isinstance(node, ast.Attribute):
            base = ev(node.value)
            if isinstance(base, dict) and node.attr in base:
                return base[node.attr]
            raise ParsingError(f"unknown variable [params.{node.attr}]")
        if isinstance(node, ast.BinOp) and type(node.op) in _BINOPS:
            return _BINOPS[type(node.op)](ev(node.left), ev(node.right))
        if isinstance(node, ast.UnaryOp) and type(node.op) in _UNARY:
            return _UNARY[type(node.op)](ev(node.operand))
        if isinstance(node, ast.Compare) and len(node.ops) == 1 \
                and type(node.ops[0]) in _CMPOPS:
            return _CMPOPS[type(node.ops[0])](ev(node.left),
                                              ev(node.comparators[0]))
        if isinstance(node, ast.IfExp):
            return ev(node.body) if ev(node.test) else ev(node.orelse)
        if isinstance(node, ast.BoolOp):
            vals = [ev(v) for v in node.values]
            return all(vals) if isinstance(node.op, ast.And) else any(vals)
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id in _FUNCS:
            return _FUNCS[node.func.id](*[ev(a) for a in node.args])
        raise ParsingError(f"unsupported construct in script [{expr}]")

    return ev(tree)


# ----------------------------------------------------------- application

def apply_pipelines(nodes: List[AggNode], aggs_result: Dict[str, Any]):
    """Mutates aggs_result in place: nested parent pipelines inside bucket
    aggs, then top-level sibling pipelines."""
    for node in nodes:
        if node.type in PIPELINE_TYPES:
            continue  # handled after non-pipeline siblings resolve
        result = aggs_result.get(node.name)
        if result is not None:
            _apply_nested(node, result)
    for node in nodes:
        if node.type in PIPELINE_TYPES:
            aggs_result[node.name] = _sibling_value(node, aggs_result)


def _bucket_list(result: Dict[str, Any]) -> Optional[List[Dict[str, Any]]]:
    buckets = result.get("buckets")
    if buckets is None:
        return None
    if isinstance(buckets, dict):
        return list(buckets.values())
    return buckets


def _apply_nested(node: AggNode, result: Dict[str, Any]):
    buckets = _bucket_list(result)
    if buckets is None:
        # single-bucket aggs (filter/global/missing) carry children inline
        for child in node.children:
            sub = result.get(child.name)
            if sub is not None:
                _apply_nested(child, sub)
        for p in node.pipelines:
            if p.type in _SIBLING:
                result[p.name] = _sibling_value(p, result)
        return
    for b in buckets:
        for child in node.children:
            sub = b.get(child.name)
            if sub is not None:
                _apply_nested(child, sub)
    for p in node.pipelines:
        if p.type in _SIBLING:
            result[p.name] = _sibling_value(p, result)
        else:
            _apply_parent_pipeline(p, node, result)


def _apply_parent_pipeline(p: AggNode, parent: AggNode, result: Dict[str, Any]):
    buckets = _bucket_list(result)
    if buckets is None:
        return
    path = p.body.get("buckets_path")
    gap_policy = p.body.get("gap_policy", "skip")

    if p.type == "bucket_script":
        paths = path or {}
        if not isinstance(paths, dict):
            raise ParsingError("[bucket_script] requires a buckets_path map")
        script = _script_source(p.body)
        for b in buckets:
            variables = {k: resolve_bucket_path(b, v) for k, v in paths.items()}
            if any(v is None for v in variables.values()):
                if gap_policy == "insert_zeros":
                    variables = {k: (0.0 if v is None else v)
                                 for k, v in variables.items()}
                else:
                    continue
            b[p.name] = {"value": safe_eval(script, variables)}
        return

    if p.type == "bucket_selector":
        paths = path or {}
        script = _script_source(p.body)
        keep = []
        for b in buckets:
            variables = {k: resolve_bucket_path(b, v) for k, v in paths.items()}
            if any(v is None for v in variables.values()):
                continue
            if safe_eval(script, variables):
                keep.append(b)
        _replace_buckets(result, keep)
        return

    if p.type == "bucket_sort":
        sort_specs = p.body.get("sort", [])
        frm = int(p.body.get("from", 0))
        size = p.body.get("size")
        ordered = list(buckets)
        for spec in reversed(sort_specs):
            if isinstance(spec, str):
                field, order = spec, "asc"
            else:
                field, opts = next(iter(spec.items()))
                order = opts.get("order", "asc") if isinstance(opts, dict) \
                    else str(opts)
            ordered.sort(key=lambda b: (resolve_bucket_path(b, field) is None,
                                        resolve_bucket_path(b, field) or 0),
                         reverse=(order == "desc"))
        ordered = ordered[frm:frm + int(size)] if size is not None \
            else ordered[frm:]
        _replace_buckets(result, ordered)
        return

    # sequence pipelines over a single metric path
    if not path:
        raise ParsingError(f"[{p.type}] requires [buckets_path]")
    values = [resolve_bucket_path(b, path) for b in buckets]

    if p.type == "derivative":
        prev = None
        for b, v in zip(buckets, values):
            if prev is not None and v is not None:
                b[p.name] = {"value": v - prev}
            prev = v if v is not None else prev
        return
    if p.type == "cumulative_sum":
        acc = 0.0
        for b, v in zip(buckets, values):
            acc += v or 0.0
            b[p.name] = {"value": acc}
        return
    if p.type == "serial_diff":
        lag = int(p.body.get("lag", 1))
        for i, b in enumerate(buckets):
            if i >= lag and values[i] is not None and values[i - lag] is not None:
                b[p.name] = {"value": values[i] - values[i - lag]}
        return
    if p.type in ("moving_avg", "moving_fn"):
        window = int(p.body.get("window", 5))
        shift = int(p.body.get("shift", 0))
        for i, b in enumerate(buckets):
            lo = max(0, i - window + shift)
            hi = max(0, i + shift)
            vals = [v for v in values[lo:hi] if v is not None]
            if not vals:
                continue
            if p.type == "moving_avg":
                b[p.name] = {"value": sum(vals) / len(vals)}
            else:
                script = _script_source(p.body)
                b[p.name] = {"value": safe_eval(
                    script, {"values_sum": sum(vals), "values_len": len(vals),
                             "values_min": min(vals), "values_max": max(vals)})}
        return
    raise IllegalArgumentError(f"unsupported pipeline aggregation [{p.type}]")


def _script_source(body: dict) -> str:
    script = body.get("script", "")
    if isinstance(script, dict):
        script = script.get("source", "")
    return str(script)


def _replace_buckets(result: Dict[str, Any], new_buckets):
    if isinstance(result.get("buckets"), dict):
        # keyed filters buckets — rebuild preserving keys is not meaningful
        return
    result["buckets"] = new_buckets


_SIBLING = {"avg_bucket", "max_bucket", "min_bucket", "sum_bucket",
            "stats_bucket", "extended_stats_bucket", "percentiles_bucket"}


def _sibling_value(p: AggNode, scope: Dict[str, Any]) -> Dict[str, Any]:
    path = p.body.get("buckets_path", "")
    if ">" not in path and p.type in _SIBLING:
        raise ParsingError(f"[{p.type}] buckets_path must reference a "
                           f"sibling bucket aggregation (agg>metric)")
    agg_name, _, metric_path = path.partition(">")
    sibling = scope.get(agg_name)
    if sibling is None:
        return {"value": None}
    buckets = _bucket_list(sibling) or []
    values = [resolve_bucket_path(b, metric_path or "_count") for b in buckets]
    values = [v for v in values if v is not None]
    if p.type == "avg_bucket":
        return {"value": (sum(values) / len(values)) if values else None}
    if p.type == "max_bucket":
        if not values:
            return {"value": None, "keys": []}
        best = max(values)
        keys = [str(b.get("key_as_string", b.get("key"))) for b, v in
                zip(buckets, [resolve_bucket_path(b, metric_path or "_count")
                              for b in buckets]) if v == best]
        return {"value": best, "keys": keys}
    if p.type == "min_bucket":
        if not values:
            return {"value": None, "keys": []}
        best = min(values)
        keys = [str(b.get("key_as_string", b.get("key"))) for b, v in
                zip(buckets, [resolve_bucket_path(b, metric_path or "_count")
                              for b in buckets]) if v == best]
        return {"value": best, "keys": keys}
    if p.type == "sum_bucket":
        return {"value": sum(values) if values else 0.0}
    if p.type in ("stats_bucket", "extended_stats_bucket"):
        if not values:
            return {"count": 0, "min": None, "max": None, "avg": None,
                    "sum": 0.0}
        out = {"count": len(values), "min": min(values), "max": max(values),
               "avg": sum(values) / len(values), "sum": sum(values)}
        if p.type == "extended_stats_bucket":
            mean = out["avg"]
            var = sum((v - mean) ** 2 for v in values) / len(values)
            out.update({"sum_of_squares": sum(v * v for v in values),
                        "variance": var, "std_deviation": math.sqrt(var)})
        return out
    if p.type == "percentiles_bucket":
        percents = p.body.get("percents", [1.0, 5.0, 25.0, 50.0, 75.0, 95.0,
                                           99.0])
        if not values:
            return {"values": {f"{float(q)}": None for q in percents}}
        import numpy as _np
        arr = _np.asarray(sorted(values))  # sync-ok: host -- coordinator reduce over host floats
        return {"values": {f"{float(q)}": float(_np.percentile(arr, q))
                           for q in percents}}
    raise IllegalArgumentError(f"unsupported pipeline aggregation [{p.type}]")
