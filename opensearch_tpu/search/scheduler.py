"""Async wave scheduler: coalesce concurrent users into shared device
waves.

ROADMAP item 1's centerpiece. The committed open-loop baseline
(BENCH_CONC_r01.json) quantifies the prize: 8 concurrent clients each
paying a full B=1 dispatch get a fraction of what the same box does
when independent requests ride ONE interned envelope — the
O(unique-templates) batched path PR 5 built and PR 9 turned into a
double-buffered wave pipeline. Every request the REST layer serves
inline burns a full dispatch; this module makes independent users
share one device round trip instead.

Architecture — the scheduler sits BETWEEN admission and the executor:

    REST _run_search / _msearch     (admission already passed; the
        |                            permit + quota token are HELD
        v                            across the coalesce window)
    WaveScheduler.execute[_many]    (bounded queue, blocking submit)
        |
    scheduler thread: adaptive micro-batch delay window
        | groups compatible sub-requests by target shard executor
        v (template/segment/shape-bucket grouping happens INSIDE the
           envelope — dsl.intern_query + compile_interned already key
           plan skeletons on exactly that tuple)
    SearchExecutor.multi_search(bodies, timelines=...)  — the existing
        wave pipeline (_run_wave_pipeline) dispatches shared waves and
        emits per-request coalesce/dispatch/collect/overlap lifecycle
        events through the timeline fan
        |
        v
    per-request demux: each queued request gets its own slice of the
    envelope's per-item responses (error items / timed-out partials
    ride the PR 6 per-item machinery) and its blocked thread wakes.

The adaptive window (`plan_window_ms`, mirrored by
tests/reference_impl.ref_window_ms) is p99-budget aware: it reuses the
admission controller's serial-queue model (`predict_queue_ms`, the
PR 11 shed predictor) priced with the LIVE rolling service estimate,
and never spends delay a queued request's `timeout=`/SLO budget cannot
afford. It is also pressure-aware: the live arrival-gap estimate
decides whether waiting can plausibly buy a companion at all — an
idle node dispatches immediately (zero added latency at low offered
load), a saturated node batches the backlog that forms naturally while
the previous wave executes.

Invariants (pinned by tests/test_scheduler.py + tools/chaos_sweep.py):
  - permits/quota tokens acquired at admission are HELD by the blocked
    request thread across the coalesce window and released in the REST
    layer's existing finally — the PR 11 counter invariant
    (admitted_total == released_total) extends to scheduler-queued
    requests, and a request the scheduler sheds at deadline (or
    rejects queue-full) gets its quota token refunded
    (`AdmissionController.refund_unserved`): it never executed;
  - scheduler-off is byte-identical: eligible bodies ride the SAME
    B=1 envelope inline (controller's allow_envelope delegation), and
    batching is score-bit-identical by the PR 5 parity suite — the
    differential test pins scheduler-on == scheduler-off across
    B ∈ {1, 32, 1024};
  - a deadline that expires INSIDE the window renders the reference
    timed-out partial shape (zero hits, `timed_out: true`), never an
    error — timeout is a budget decision;
  - cancellation drains: a queued request whose task was cancelled
    leaves the queue with the cancellation error at the next pump, and
    disabling the scheduler dispatches every queued request before the
    thread exits (no stranded waiter).

No-op discipline (gate-lint registry row; bench.py asserts the running
instance): `enabled = False` by default and `gate()` returns None —
the disabled query path costs one attribute load and a branch, and the
disabled scheduler owns no thread.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from opensearch_tpu.common.admission import predict_queue_ms
from opensearch_tpu.common.errors import (
    AdmissionRejectedError, OpenSearchTpuError)
from opensearch_tpu.telemetry.rolling import RollingEstimator

REASON_QUEUE_FULL = "scheduler_queue_full"

DEFAULT_WINDOW_MS = 2.0
DEFAULT_MAX_QUEUE = 1024
DEFAULT_MAX_BATCH = 1024


def plan_window_ms(budgets_ms: List[Optional[float]],
                   service_ms: Optional[float],
                   queue_depth: int,
                   arrival_gap_ms: Optional[float],
                   window_max_ms: float) -> float:
    """The adaptive micro-batch delay window, in milliseconds. Pure
    math — tests/reference_impl.ref_window_ms mirrors it.

    Two terms, ANDed:

    budget cap   the window may only spend latency every queued
                 request can afford: for each request with a budget
                 (its `timeout=` deadline remainder, else the node
                 SLO), headroom = budget − predicted queue time, where
                 the prediction is the PR 11 serial-queue model
                 `predict_queue_ms(service, depth)` on the live
                 rolling service estimate. The window is the MINIMUM
                 headroom, clamped to [0, window_max_ms]. Requests
                 without a budget afford the full window; an unknown
                 service estimate predicts 0 (never starve the window
                 blind — the budget itself still caps).

    pressure     waiting only pays if a companion is likely to arrive
                 within the cap: when the live arrival-gap estimate
                 (median enqueue-to-enqueue spacing) exceeds the cap,
                 the expected yield of waiting is zero requests, so
                 dispatch immediately — an idle or lightly-loaded node
                 adds NO latency. Under pressure (gap <= cap) the full
                 cap is spent; the backlog that forms while a wave
                 executes coalesces on top of it for free.
    """
    cap = float(window_max_ms)
    predicted = predict_queue_ms(service_ms, queue_depth)
    if predicted is None:
        predicted = 0.0
    for budget in budgets_ms:
        if budget is None:
            continue
        cap = min(cap, budget - predicted)
    cap = max(0.0, min(cap, float(window_max_ms)))
    if cap <= 0.0:
        return 0.0
    if arrival_gap_ms is None or arrival_gap_ms > cap:
        return 0.0
    return cap


class _RehydratedItemError(OpenSearchTpuError):
    """Re-raise a per-item envelope error object as the typed exception
    the inline (non-scheduler) path would have raised: same
    `to_xcontent` payload, same status — the REST error body stays
    byte-identical whether the request rode the scheduler or not."""

    def __init__(self, payload: dict, status: int):
        super().__init__(str(payload.get("reason", "")))
        self._payload = dict(payload)
        self.status = int(status)
        self.error_type = str(payload.get("type", "exception"))

    def to_xcontent(self) -> dict:
        return dict(self._payload)


class _SchedItem:
    """One queued submission: a single search (one body) or a whole
    msearch envelope's admitted bodies (the envelope coalesces as a
    unit — queue bookkeeping stays O(1) per envelope)."""

    __slots__ = ("target", "bodies", "deadline", "timeline", "tenant",
                 "task", "enq_t", "done", "responses", "error", "shed")

    def __init__(self, target, bodies, deadline, timeline, tenant, task,
                 enq_t):
        self.target = target
        self.bodies = bodies
        self.deadline = deadline
        self.timeline = timeline
        self.tenant = tenant
        self.task = task
        self.enq_t = enq_t
        self.done = threading.Event()
        self.responses: Optional[List[dict]] = None
        self.error: Optional[BaseException] = None
        self.shed = 0           # sub-requests shed at deadline (the
        # quota-refund count the REST layer settles)


def _timed_out_partial(enq_t: float) -> dict:
    """The reference per-request timeout shape for a sub-request whose
    deadline expired inside the coalesce window: a zero-hit partial
    with `timed_out: true` — a budget decision, never an error (the
    executor's `_timed_out_item` contract, anchored on enqueue so
    `took` covers the real wait)."""
    from opensearch_tpu.search.executor import _timed_out_item
    return _timed_out_item(enq_t)


class WaveScheduler:
    """The node's cross-request micro-batching layer. OFF by default;
    `gate()` returns None when disabled (one attribute load + branch on
    the hot path — the tracer/ledger/injector/flight-recorder
    discipline, gate-lint registered).

    `admission` (the node's AdmissionController) supplies the live
    service estimate the window math prices with and receives this
    queue's depth through `queue_depth_extra`, so the deadline-shed
    stage prices arrivals against the REAL scheduler queue.

    Threading: request threads block in `execute`/`execute_many` on a
    per-item Event while ONE scheduler thread windows, groups,
    dispatches and demultiplexes. `autostart=False` +
    `pump_once()` give tests a fully synchronous, seeded-deterministic
    harness — no thread, explicit clock."""

    # msearch envelopes at or under this many sub-requests ride the
    # coalescing queue (cross-envelope shared waves); larger envelopes
    # are ALREADY the batch the scheduler exists to build and dispatch
    # inline — queueing them would only add per-item bookkeeping
    msearch_coalesce_max = 64

    def __init__(self, admission=None, clock: Callable[[], float]
                 = time.monotonic, autostart: bool = True):
        self.enabled = False
        self.admission = admission
        self.window_max_ms = DEFAULT_WINDOW_MS
        self.max_queue = DEFAULT_MAX_QUEUE
        self.max_batch = DEFAULT_MAX_BATCH
        self.slo_ms: Optional[float] = None
        self._clock = clock
        self._autostart = autostart
        self._cv = threading.Condition(threading.Lock())
        self._queue: "deque[_SchedItem]" = deque()
        self._depth = 0             # queued sub-requests (bounded)
        self._thread: Optional[threading.Thread] = None
        self._running = False
        # live estimators: per-sub-request amortized service wall (own
        # stream — the admission shedder's, when present and warm, is
        # preferred for the window math so both layers price with ONE
        # model) and the enqueue-to-enqueue arrival gap. The WINDOW
        # prices with the median of the last few gaps (deque below),
        # not the rolling estimator: offered load shifts in
        # milliseconds and a minutes-half-life estimate left stale-low
        # by a burst would charge the window to serial traffic
        # (measured: a post-burst closed loop paid the full cap per
        # request); the rolling stream still feeds stats.
        self.service_est = RollingEstimator()
        self.arrival_gap_est = RollingEstimator()
        self._recent_gaps: "deque[float]" = deque(maxlen=16)
        self._last_enq: Optional[float] = None
        # stats (all read under _cv's lock in stats())
        self.submitted = 0          # sub-requests ever enqueued
        self.dispatches = 0         # shared dispatch calls
        self.coalesced_total = 0    # sub-requests in co_batched>1 waves
        self.solo_total = 0
        self.shed_deadline = 0
        self.rejected_full = 0
        self.cancelled = 0
        self.co_batched_max = 0
        self.last_window_ms = 0.0
        self.co_batched_est = RollingEstimator()
        self.window_est = RollingEstimator()
        self.queue_wait_est = RollingEstimator()

    # ------------------------------------------------------------- gating

    def gate(self) -> Optional["WaveScheduler"]:
        """The per-request gate: None when the scheduler is disabled —
        callers fall straight through to the inline execute path."""
        if not self.enabled:
            return None
        return self

    def queue_depth(self) -> int:
        """Queued sub-requests — the `queue_depth_extra` feed for the
        admission controller's deadline-shed pricing (a plain int read;
        staleness by one item is fine for a shed estimate)."""
        return self._depth

    @staticmethod
    def eligible(body: Optional[dict]) -> bool:
        """A body the batched envelope serves bit-identically to the
        inline path: the plain batchable shape (PR 5 interning family)
        or the hybrid envelope shape — everything else (scroll, sort,
        inner_hits, aggs-with-pipelines, ...) executes inline, so an
        exotic request can never head-of-line-block the wave queue."""
        from opensearch_tpu.search.executor import (
            _hybrid_msearch_batchable, _msearch_batchable)
        body = body or {}
        return _msearch_batchable(body) or _hybrid_msearch_batchable(body)

    # ---------------------------------------------------------- lifecycle

    def set_enabled(self, on: bool) -> None:
        """Enable starts the scheduler thread; disable stops it AFTER
        draining — every queued request is dispatched (windowless)
        before the thread exits, so no waiter strands."""
        with self._cv:
            if on and not self._running:
                self.enabled = True
                self._running = True
                if self._autostart:
                    self._thread = threading.Thread(
                        target=self._loop, name="wave-scheduler",
                        daemon=True)
                    self._thread.start()
                return
            if not on:
                self.enabled = False
                self._running = False
                self._cv.notify_all()
                thread = self._thread
                self._thread = None
        if not on and thread is not None:
            thread.join(timeout=30)

    # ------------------------------------------------------------- submit

    def execute(self, target, body: dict,
                deadline: Optional[float] = None, timeline=None,
                tenant: Optional[str] = None, task=None) \
            -> Tuple[dict, bool]:
        """Blocking single-search submit. Returns (response, shed) —
        `shed` True when the deadline expired inside the window and the
        response is the timed-out partial (the caller refunds the
        quota token: the request never executed). A per-item error
        object re-raises as the typed exception the inline path would
        have raised (byte-identical REST error body)."""
        responses, shed = self.execute_many(
            target, [body], deadline=deadline, timeline=timeline,
            tenant=tenant, task=task)
        res = responses[0]
        if isinstance(res, dict) and "error" in res and "status" in res \
                and not shed:
            raise _RehydratedItemError(res["error"], res["status"])
        return res, bool(shed)

    def execute_many(self, target, bodies: List[dict],
                     deadline: Optional[float] = None, timeline=None,
                     tenant: Optional[str] = None, task=None) \
            -> Tuple[List[dict], int]:
        """Blocking envelope submit: the bodies coalesce as a unit with
        whatever else the window collects for the same target. Returns
        (per-item responses, shed-count). Raises the queue-full 429
        when the bounded queue cannot take the envelope — the caller
        refunds and renders it through the PR 11 machinery."""
        n = len(bodies)
        now = self._clock()
        item = _SchedItem(target, bodies, deadline, timeline, tenant,
                          task, now)
        inline = False
        with self._cv:
            if not self._running:
                # disabled between the caller's gate() and here (or a
                # synchronous test harness): serve inline — never
                # hang. Dispatch happens OUTSIDE the lock below:
                # device work under _cv would block every concurrent
                # submitter and stats() reader for its duration.
                inline = True
            elif self._depth + n > self.max_queue:
                self.rejected_full += 1
                raise AdmissionRejectedError(
                    f"rejected execution of search: scheduler queue is "
                    f"full [{self._depth} + {n} > {self.max_queue}]",
                    reject_reason=REASON_QUEUE_FULL, tenant=tenant,
                    bytes_wanted=self._depth + n,
                    bytes_limit=self.max_queue,
                    retry_after_ms=self._retry_after_ms())
            else:
                if self._last_enq is not None:
                    gap = max((now - self._last_enq) * 1000.0, 0.0)
                    self.arrival_gap_est.observe(gap)
                    self._recent_gaps.append(gap)
                self._last_enq = now
                self.submitted += n
                self._queue.append(item)
                self._depth += n
                self._cv.notify_all()
        if inline:
            self._dispatch_group([item])
        item.done.wait()
        if item.error is not None:
            raise item.error
        return item.responses, item.shed

    def _retry_after_ms(self) -> float:
        """Queue-full Retry-After: the predicted time for the CURRENT
        queue to drain ahead of a retry — the PR 11 serial-queue
        estimate, not one item's service wall (a full 1024-deep queue
        advertising 'retry in 1ms' just re-rejects honest clients in a
        tight loop). Floored at 1ms like every admission header."""
        predicted = predict_queue_ms(self._service_estimate_ms(),
                                     self._depth)
        return max(predicted if predicted else 0.0, 1.0)

    # ----------------------------------------------------- window sizing

    def _service_estimate_ms(self) -> Optional[float]:
        """The per-request service estimate the window math prices
        with: the admission shedder's near-exclusive median when it has
        one (so scheduler and shed price with the SAME model), else
        this scheduler's own amortized-wall stream."""
        if self.admission is not None:
            q = self.admission.shedder.service_ms.quantile(0.5)
            if q:
                return q
        return self.service_est.quantile(0.5)

    def _gap_estimate_ms(self) -> Optional[float]:
        """Median of the last few enqueue gaps — adapts to an offered-
        load shift within one deque-full of arrivals. None until a
        handful of gaps exist (an unknown rate never opens the
        window)."""
        gaps = sorted(self._recent_gaps)
        if len(gaps) < 4:
            return None
        return gaps[len(gaps) // 2]

    def _window_ms(self) -> float:
        """Size the window for the CURRENT queue (called with _cv
        held): budgets from each queued item's deadline remainder (or
        the node SLO), depth = everything queued ahead."""
        now = self._clock()
        budgets: List[Optional[float]] = []
        for it in self._queue:
            if it.deadline is not None:
                budgets.append((it.deadline - now) * 1000.0)
            else:
                budgets.append(self.slo_ms)
        w = plan_window_ms(
            budgets, self._service_estimate_ms(), self._depth,
            self._gap_estimate_ms(), self.window_max_ms)
        self.last_window_ms = w
        return w

    # ----------------------------------------------------------- dispatch

    def _loop(self) -> None:
        while True:
            with self._cv:
                while self._running and not self._queue:
                    self._cv.wait(0.1)
                if not self._queue:
                    if not self._running:
                        return
                    continue
                window_ms = self._window_ms() if self._running else 0.0
                if window_ms > 0:
                    # hold the window open: collect arrivals until it
                    # closes or the batch is full. Anchored at the
                    # FIRST waiter's enqueue so a request never waits
                    # more than one full window.
                    end = self._queue[0].enq_t + window_ms / 1000.0
                    while self._running and self._depth < self.max_batch:
                        left = end - self._clock()
                        if left <= 0:
                            break
                        self._cv.wait(left)
                batch: List[_SchedItem] = []
                taken = 0
                while self._queue and taken < self.max_batch:
                    item = self._queue.popleft()
                    self._depth -= len(item.bodies)
                    taken += len(item.bodies)
                    batch.append(item)
            self._pump(batch)

    def pump_once(self) -> int:
        """Synchronous test harness: drain the queue and dispatch it on
        the calling thread (no window wait). Returns the number of
        sub-requests served."""
        with self._cv:
            batch = list(self._queue)
            self._queue.clear()
            served = self._depth
            self._depth = 0
        self._pump(batch)
        return served

    def _pump(self, batch: List[_SchedItem]) -> None:
        """Group a drained batch by target executor and dispatch each
        group as one shared envelope. Grouping preserves arrival order
        inside a group; finer (template, segment, shape-bucket)
        grouping is the envelope's own interning machinery."""
        if not batch:
            return
        groups: Dict[int, List[_SchedItem]] = {}
        for item in batch:
            groups.setdefault(id(item.target), []).append(item)
        for items in groups.values():
            self._dispatch_group(items)

    def _dispatch_group(self, items: List[_SchedItem]) -> None:
        """One shared wave dispatch: expire/cancel the dead, send the
        live bodies through the target's wave pipeline with the
        timeline fan, demux per-item responses, wake every waiter.
        EVERY item's Event is set on EVERY path — a failed dispatch
        wakes its waiters with the error, never strands them."""
        now = self._clock()
        live: List[_SchedItem] = []
        for item in items:
            if item.task is not None:
                try:
                    item.task.check_cancelled()
                except OpenSearchTpuError as e:
                    # cancellation drains the queue: the cancelled
                    # request leaves with its typed error at the next
                    # pump instead of burning a shared wave slot
                    self.cancelled += len(item.bodies)
                    item.error = e
                    item.done.set()
                    continue
            if item.deadline is not None and now > item.deadline:
                n = len(item.bodies)
                self.shed_deadline += n
                item.shed = n
                item.responses = [_timed_out_partial(item.enq_t)
                                  for _ in range(n)]
                if item.timeline is not None:
                    item.timeline.queue_wait((now - item.enq_t) * 1000.0)
                item.done.set()
                continue
            live.append(item)
        if not live:
            return
        bodies: List[dict] = []
        timelines: List[Any] = []
        group_deadline: Optional[float] = None
        saw_unbounded = False
        for item in live:
            wait_ms = (now - item.enq_t) * 1000.0
            self.queue_wait_est.observe(wait_ms)
            if item.timeline is not None:
                # the REAL queue_wait the lifecycle contract reserved
                # this field for (PR 10: "the field the wave scheduler
                # fills") — emitted from the scheduler thread, read
                # only after completion
                item.timeline.queue_wait(wait_ms)
            bodies.extend(item.bodies)
            timelines.extend(item.timeline for _ in item.bodies)
            if item.deadline is None:
                saw_unbounded = True
            elif group_deadline is None or item.deadline > group_deadline:
                group_deadline = item.deadline
        # the shared envelope runs under the LOOSEST member deadline (a
        # tight sibling is served a touch late rather than killing the
        # whole wave's work); any unbounded member unbounds the wave
        if saw_unbounded:
            group_deadline = None
        n = len(bodies)
        self.dispatches += 1
        self.co_batched_est.observe(float(n))
        self.window_est.observe(self.last_window_ms)
        if n > self.co_batched_max:
            self.co_batched_max = n
        if n > 1:
            self.coalesced_total += n
        else:
            self.solo_total += 1
        from opensearch_tpu.telemetry import TELEMETRY
        TELEMETRY.metrics.counter("scheduler.dispatches").inc()
        TELEMETRY.metrics.histogram("scheduler.co_batched").observe(n)
        # per-tenant byte attribution rides the ledger: when it is on,
        # the envelope fills phase_times with the wave's fetched bytes,
        # split below proportionally like the wall (None keeps the
        # disabled path at one attribute load + branch)
        pt = {} if TELEMETRY.ledger.enabled else None
        # per-item tenants ride along for the insights recorder's
        # per-shape tenant breakdown (ISSUE 15): the shared dispatch
        # runs on the scheduler thread, so the REST layer's thread-local
        # binding cannot reach it — the owning requests' tenants go per
        # item, aligned with `timelines` (None = recorder off, one
        # attribute load + branch)
        tenants = [item.tenant for item in live
                   for _ in item.bodies] \
            if TELEMETRY.insights.enabled else None
        t0 = time.monotonic()
        try:
            res = live[0].target.multi_search(
                bodies, deadline=group_deadline, timelines=timelines,
                phase_times=pt, tenants=tenants)
            responses = res["responses"]
        except BaseException as e:  # except-ok: waiter wakeup -- a dispatch failure delivers the error to every blocked request thread instead of stranding them on the Event
            for item in live:
                item.error = e
                item.done.set()
            return
        wall_ms = (time.monotonic() - t0) * 1000.0
        self.service_est.observe(wall_ms / max(n, 1))
        wave_bytes = int(pt.get("bytes_fetched", 0)) if pt else 0
        off = 0
        for item in live:
            item.responses = responses[off:off + len(item.bodies)]
            off += len(item.bodies)
            # per-tenant resource attribution (ISSUE 14): the shared
            # wave's device wall (and fetched bytes) split across its
            # co-batched owners by item count — each request's
            # `device_share_ms` lifecycle field plus the per-tenant
            # totals the admission `usage` block accumulates
            n_items = len(item.bodies)
            share_ms = wall_ms * n_items / n
            if item.timeline is not None:
                item.timeline.device_share(share_ms, wall_ms, n)
            if self.admission is not None:
                self.admission.note_usage(
                    item.tenant, share_ms,
                    d2h_bytes=wave_bytes * n_items // n,
                    items=n_items)
            if item.timeline is not None:
                # response assembled HERE: complete() turns the
                # ready→completed interval into the `handoff` phase —
                # under contention that is the waiter's measured
                # wakeup/GIL starvation, otherwise-invisible wall
                item.timeline.mark_ready()
            item.done.set()

    # ------------------------------------------------------------ settings

    @staticmethod
    def parse_settings(flat: Dict[str, Any]) -> Dict[str, Any]:
        """Parse + validate the scheduler keys out of a flat settings
        map without mutating anything — the REST layer dry-runs this
        before committing a cluster-settings update (the PR 11
        validate-then-commit contract)."""
        from opensearch_tpu.common.errors import SettingsError
        from opensearch_tpu.common.settings import _parse_bool

        def _num(key, cast=float):
            v = flat.get(key)
            if v is None:
                return None
            try:
                out = cast(v)
            except (TypeError, ValueError):
                raise SettingsError(
                    f"Failed to parse value [{v}] for setting [{key}]")
            if out < 0:
                raise SettingsError(
                    f"Failed to parse value [{v}] for setting [{key}]: "
                    f"must be >= 0")
            return out

        v = flat.get("search.scheduler.enabled")
        return {
            "enabled": None if v is None
            else _parse_bool(v, "search.scheduler.enabled"),
            "window_ms": _num("search.scheduler.window_ms"),
            "max_queue": _num("search.scheduler.max_queue", int),
            "max_batch": _num("search.scheduler.max_batch", int),
            "slo_ms": _num("search.scheduler.slo_ms"),
        }

    def apply_settings(self, flat: Dict[str, Any]) -> None:
        """Apply node/cluster settings (flat keys, dynamic — the REST
        cluster-settings path re-runs this on every update)."""
        p = self.parse_settings(flat)
        if p["window_ms"] is not None:
            self.window_max_ms = p["window_ms"]
        if p["max_queue"] is not None:
            self.max_queue = max(int(p["max_queue"]), 1)
        if p["max_batch"] is not None:
            self.max_batch = max(int(p["max_batch"]), 1)
        if p["slo_ms"] is not None:
            self.slo_ms = p["slo_ms"] if p["slo_ms"] > 0 else None
        if p["enabled"] is not None and p["enabled"] != self.enabled:
            self.set_enabled(p["enabled"])

    # --------------------------------------------------------------- stats

    def stats(self) -> dict:
        """The `scheduler` block on `_nodes/stats`: queue depth, live
        window size, coalesce ratio, per-wave co_batched histogram."""
        with self._cv:
            submitted = self.submitted
            coalesced = self.coalesced_total
            return {
                "enabled": self.enabled,
                "queue_depth": self._depth,
                "max_queue": self.max_queue,
                "max_batch": self.max_batch,
                "window_max_ms": self.window_max_ms,
                "last_window_ms": round(self.last_window_ms, 3),
                "slo_ms": self.slo_ms,
                "submitted": submitted,
                "dispatched_waves": self.dispatches,
                "coalesced": coalesced,
                "solo": self.solo_total,
                "coalesce_ratio": round(coalesced / submitted, 3)
                if submitted else 0.0,
                "shed_deadline": self.shed_deadline,
                "rejected_queue_full": self.rejected_full,
                "cancelled": self.cancelled,
                "co_batched": {**self.co_batched_est.summary(),
                               "max": self.co_batched_max},
                "window_ms": self.window_est.summary(),
                "queue_wait_ms": self.queue_wait_est.summary(),
                "service_ms": self.service_est.summary(),
            }
