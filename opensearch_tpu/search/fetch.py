"""Fetch-phase subphases: per-hit enrichment after the device query phase.

Re-design of the reference FetchPhase (search/fetch/FetchPhase.java:106) and
its sub-phases (search/fetch/subphase/): _source filtering, docvalue_fields,
highlighting (highlight/), and explain (ExplainPhase → Lucene
Explanation via BM25Similarity.explain). All of this is host-side work over
the hit page only — the device program already picked the top docs.
"""

from __future__ import annotations

import math
import re
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from opensearch_tpu.common.errors import IllegalArgumentError
from opensearch_tpu.index.segment import Segment, smallfloat_byte4_to_int
from opensearch_tpu.search import dsl
from opensearch_tpu.telemetry import TELEMETRY

DEFAULT_K1 = 1.2
DEFAULT_B = 0.75


# ----------------------------------------------------------- term extraction

def collect_field_terms(node, mapper) -> Dict[str, List[str]]:
    """Walk a parsed query tree collecting the analyzed terms per field —
    what the reference gets from Query.visit(QueryVisitor) for highlighting."""
    out: Dict[str, List[str]] = {}

    def add(field: str, terms: List[str]):
        if field:
            out.setdefault(field, []).extend(t for t in terms if t)

    def analyze(field: str, text: Any) -> List[str]:
        ft = mapper.get_field(field)
        if ft is None or text is None:
            return []
        if ft.is_text:
            analyzer = mapper.analysis.get(ft.search_analyzer or ft.analyzer)
            return [t for t, _ in analyzer.analyze(str(text))]
        return [str(text)]

    def walk(n):
        if n is None:
            return
        if isinstance(n, dsl.BoolQuery):
            for child in list(n.must) + list(n.should) + list(n.filter):
                walk(child)  # must_not terms don't highlight
            return
        if isinstance(n, (dsl.ConstantScoreQuery,)):
            walk(n.filter)
            return
        if isinstance(n, dsl.DisMaxQuery):
            for child in n.queries:
                walk(child)
            return
        if isinstance(n, dsl.BoostingQuery):
            walk(n.positive)
            return
        if isinstance(n, (dsl.MatchQuery, dsl.MatchPhraseQuery,
                          dsl.MatchBoolPrefixQuery)):
            add(n.field, analyze(n.field, n.query))
            return
        if isinstance(n, dsl.MultiMatchQuery):
            for f in mapper.expand_field_patterns(list(n.fields)):
                f = f.split("^")[0]
                add(f, analyze(f, n.query))
            return
        if isinstance(n, dsl.TermQuery):
            add(n.field, [str(n.value)])
            return
        if isinstance(n, dsl.TermsQuery):
            add(n.field, [str(v) for v in n.values])
            return
        if isinstance(n, dsl.PrefixQuery):
            # trailing-* marker: highlight_text prefix-matches these
            add(n.field, [str(n.value) + "*"])
            return
        if isinstance(n, dsl.FuzzyQuery):
            add(n.field, [str(n.value)])
            return
        if isinstance(n, (dsl.QueryStringQuery, dsl.SimpleQueryStringQuery)):
            # best effort: bare terms against default/explicit fields
            fields = [f.split("^")[0] for f in (n.fields or [])]
            if getattr(n, "default_field", None):
                fields.append(n.default_field)
            text = re.sub(r'[+\-()"~*?:\\]|AND|OR|NOT', " ", n.query)
            for token in text.split():
                if ":" in token:
                    f, v = token.split(":", 1)
                    add(f, analyze(f, v))
                else:
                    for f in fields:
                        add(f, analyze(f, token))
            return
        # leaf without highlightable terms (range/exists/knn/...)

    walk(node)
    return {f: list(dict.fromkeys(ts)) for f, ts in out.items()}


# -------------------------------------------------------------- highlighting

_TOKEN_RE = re.compile(r"\w+", re.UNICODE)


def highlight_text(text: str, terms: List[str], pre: str, post: str,
                   fragment_size: int, number_of_fragments: int,
                   analyzer) -> List[str]:
    """Unified-highlighter analog: analyze the stored text, mark offsets of
    matching terms, cut fragments around matches."""
    term_set = {t for t in terms if not t.endswith("*")}
    prefixes = tuple(t[:-1] for t in terms if t.endswith("*") and len(t) > 1)
    matches: List[Tuple[int, int]] = []
    for m in _TOKEN_RE.finditer(text):
        raw = m.group(0)
        analyzed = analyzer.analyze(raw) if analyzer else [(raw.lower(), 0)]
        if any(t in term_set or (prefixes and t.startswith(prefixes))
               for t, _ in analyzed):
            matches.append((m.start(), m.end()))
    if not matches:
        return []
    if number_of_fragments == 0:
        # whole-field highlighting
        return [_mark(text, matches, pre, post)]
    fragments: List[str] = []
    used_until = -1
    for start, end in matches:
        if start < used_until:
            continue
        frag_start = max(0, start - max(0, (fragment_size - (end - start)) // 2))
        # snap to a word boundary
        while frag_start > 0 and text[frag_start - 1].isalnum():
            frag_start -= 1
        frag_end = min(len(text), frag_start + fragment_size)
        while frag_end < len(text) and text[frag_end - 1].isalnum() \
                and not text[frag_end].isspace():
            frag_end += 1
        used_until = frag_end
        inside = [(s, e) for s, e in matches if s >= frag_start and e <= frag_end]
        fragments.append(_mark(text[frag_start:frag_end],
                               [(s - frag_start, e - frag_start)
                                for s, e in inside], pre, post))
        if len(fragments) >= number_of_fragments:
            break
    return fragments


def _mark(text: str, spans: List[Tuple[int, int]], pre: str, post: str) -> str:
    out = []
    last = 0
    for s, e in spans:
        out.append(text[last:s])
        out.append(pre + text[s:e] + post)
        last = e
    out.append(text[last:])
    return "".join(out)


def build_highlights(source: Optional[dict], hl_body: dict, field_terms,
                     mapper) -> dict:
    TELEMETRY.metrics.counter("fetch.highlight_hits").inc()
    if not source:
        return {}
    pre = (hl_body.get("pre_tags") or ["<em>"])[0]
    post = (hl_body.get("post_tags") or ["</em>"])[0]
    out = {}
    for field_spec, spec in (hl_body.get("fields") or {}).items():
        spec = spec or {}
        # wildcard highlight fields expand to the fields the query
        # actually matched (the reference's HighlightPhase field
        # resolution over wildcard patterns)
        if "*" in field_spec:
            import fnmatch as _fn
            targets = [f for f in field_terms
                       if _fn.fnmatchcase(f, field_spec)]
        else:
            targets = [field_spec]
        for field in targets:
            _highlight_one(source, field, spec, hl_body, field_terms,
                           mapper, pre, post, out)
    return out


def _highlight_one(source, field, spec, hl_body, field_terms, mapper,
                   pre, post, out):
        hq = spec.get("highlight_query") or hl_body.get("highlight_query")
        if hq is not None:
            # highlight with a DIFFERENT query's terms (the reference's
            # highlight_query override, HighlightBuilder#highlightQuery)
            try:
                field_terms = collect_field_terms(dsl.parse_query(hq),
                                                  mapper)
            except Exception:   # except-ok: highlighting is best-effort -- an unparseable highlight_query just yields no fragments
                field_terms = {}
        terms = field_terms.get(field, [])
        if not terms:
            return
        value = _source_value(source, field)
        if value is None and "." in field:
            # multi-fields (text.fvh) read their parent's source value
            value = _source_value(source, field.rsplit(".", 1)[0])
        if value is None:
            return
        ft = mapper.get_field(field)
        analyzer = None
        if ft is not None and ft.is_text:
            analyzer = mapper.analysis.get(ft.search_analyzer or ft.analyzer)
        frags = highlight_text(
            str(value), terms,
            pre=(spec.get("pre_tags") or [pre])[0],
            post=(spec.get("post_tags") or [post])[0],
            fragment_size=int(spec.get("fragment_size",
                                       hl_body.get("fragment_size", 100))),
            number_of_fragments=int(spec.get(
                "number_of_fragments",
                hl_body.get("number_of_fragments", 5))),
            analyzer=analyzer)
        if frags:
            out[field] = frags


def _source_value(source: dict, path: str):
    cur: Any = source
    for part in path.split("."):
        if isinstance(cur, dict) and part in cur:
            cur = cur[part]
        else:
            return None
    if isinstance(cur, list):
        return " ".join(str(v) for v in cur)
    return cur


# ------------------------------------------------------------------- explain

def explain_hit(seg: Segment, ord_: int, node, mapper, stats,
                score: float) -> dict:
    """BM25 explanation tree for one hit — mirrors the shape of Lucene's
    BM25Similarity.explain (weight(...) / idf / tf breakdown) for the term
    clauses; compound/other queries get a summary node."""
    details = []
    field_terms = collect_field_terms(node, mapper)
    for field, terms in field_terms.items():
        ft = mapper.get_field(field)
        if ft is None or not ft.is_text:
            continue
        norms = seg.norms.get(field)
        dl = float(smallfloat_byte4_to_int(int(norms[ord_]))) \
            if norms is not None else 1.0
        avgdl = stats.avgdl(field)
        doc_count, _ = stats.field_stats(field)
        for term in terms:
            tf = _term_freq(seg, field, term, ord_)
            if tf <= 0:
                continue
            df = stats.df(field, term)
            idf_v = math.log(1.0 + (doc_count - df + 0.5) / (df + 0.5))
            tf_factor = (tf * (DEFAULT_K1 + 1.0)
                         / (tf + DEFAULT_K1 * (1.0 - DEFAULT_B
                                               + DEFAULT_B * dl / avgdl)))
            details.append({
                "value": idf_v * tf_factor,
                "description": f"weight({field}:{term} in {ord_}) "
                               f"[BM25Similarity], result of:",
                "details": [
                    {"value": idf_v,
                     "description": f"idf, computed as log(1 + (N - n + 0.5) "
                                    f"/ (n + 0.5)) from n={df}, N={doc_count}",
                     "details": []},
                    {"value": tf_factor,
                     "description": f"tf, computed as freq * (k1 + 1) / "
                                    f"(freq + k1 * (1 - b + b * dl / avgdl)) "
                                    f"from freq={tf}, k1={DEFAULT_K1}, "
                                    f"b={DEFAULT_B}, dl={dl}, avgdl={avgdl}",
                     "details": []},
                ],
            })
    return {"value": score,
            "description": "sum of:" if details else "score(...), computed "
            "by the TPU query phase",
            "details": details}


def _term_freq(seg: Segment, field: str, term: str, ord_: int) -> float:
    meta = seg.get_term(field, term)
    if meta is None:
        return 0.0
    blocks = slice(meta.start_block, meta.start_block + meta.num_blocks)
    docs = seg.post_docs[blocks].reshape(-1)
    tfs = seg.post_tf[blocks].reshape(-1)
    hit = np.nonzero(docs == ord_)[0]
    return float(tfs[hit[0]]) if len(hit) else 0.0


# ----------------------------------------------------------- field retrieval

def _format_numeric_dv(vals, ft) -> list:
    """Response formatting for numeric docvalues — shared by the host
    column scan below and the result page's fused gather (the prefetched
    branch), so the two paths can never drift on types."""
    if ft is not None and ft.is_date:
        from opensearch_tpu.index.mapper import format_date_millis
        return [format_date_millis(int(v)) for v in vals]
    if ft is not None and (ft.is_numeric and ft.type in
                           ("integer", "long", "short", "byte")):
        return [int(v) for v in vals]
    return [float(v) for v in vals]


def docvalue_fields(seg: Segment, ord_: int, specs: List[Any],
                    mapper, prefetched: Optional[dict] = None) -> dict:
    """`prefetched`: the result page's fused docvalue gather for this hit
    ({field: [raw values]}, empty list = field missing on the doc) —
    those fields skip the per-leaf column scan below; fields the page
    could not fuse (multi-valued, keyword) fall through to it."""
    import time
    out = {}
    ledger = TELEMETRY.ledger
    scope = ledger.current()
    accounting = ledger.enabled or scope is not None
    for spec in specs or []:
        field = spec["field"] if isinstance(spec, dict) else spec
        if prefetched is not None and field in prefetched:
            vals = prefetched[field]
            if vals:
                out[field] = _format_numeric_dv(vals, mapper.get_field(field))
            continue
        t0 = time.monotonic() if accounting else 0.0
        col = seg.numeric_dv.get(field)
        if col is not None:
            mask = col.doc_ids == ord_
            vals = col.values[mask]
            if accounting:
                # per-leaf round-trip attribution (ISSUE 17 satellite 1):
                # this host-mirror scan stands in for a device column
                # fetch — one round trip per leaf on a remote device,
                # zero wire bytes here (byte conservation stays exact)
                ledger.note_round_trip(
                    "docvalues", (time.monotonic() - t0) * 1000,
                    scope=scope)
            if len(vals):
                out[field] = _format_numeric_dv(vals,
                                                mapper.get_field(field))
            continue
        ocol = seg.ordinal_dv.get(field)
        if ocol is not None:
            mask = ocol.doc_ids == ord_
            ords = ocol.ords[mask]
            if accounting:
                ledger.note_round_trip(
                    "docvalues", (time.monotonic() - t0) * 1000,
                    scope=scope)
            if len(ords):
                out[field] = [ocol.dictionary[o] for o in ords]
    return out


# --------------------------------------------------------------- inner hits
#
# Nested inner_hits (index/query/InnerHitBuilder + fetch/subphase/
# InnerHitsPhase): for each page hit, return the CHILD rows that matched
# the nested query, scored and paged. The child-level plan (the nested
# query's inner query compiled WITHOUT the root join) is evaluated once
# per (segment, query) on device; per-hit work is then a host-side slice
# of that dense result over the root's own child rows.

_INNER_JIT: Dict[Any, Any] = {}


def _eval_child_scores(plan, arrays):
    import time

    import jax
    import jax.numpy as jnp

    from opensearch_tpu.search.plan_eval import _eval_plan
    sig = ("inner_hits", plan.sig())
    fn = _INNER_JIT.get(sig)
    if fn is None:
        def run(seg, flat, _plan=plan):
            cursor = [0]
            return _eval_plan(_plan, seg, flat, cursor)
        fn = _INNER_JIT[sig] = jax.jit(run)  # shared-state-ok: benign double-jit race; dict slot write is GIL-atomic
    host_flat = plan.flatten_inputs([])
    ledger = TELEMETRY.ledger
    # scope: the request's LedgerScope, bound ambiently by the
    # controller's fetch phase — a traced/profiled request accounts
    # here even with the node-wide ledger off
    scope = ledger.current()
    accounting = ledger.enabled or scope is not None
    if accounting:
        ledger.record("upload.literals", "h2d",
                      sum(int(np.asarray(v).nbytes)
                          for d in host_flat for v in d.values()),
                      scope=scope)
    flat = jax.tree_util.tree_map(jnp.asarray, host_flat)
    t0 = time.monotonic() if accounting else 0.0
    # self-attributing region: the single-node controller binds ambient
    # around its fetch phase, but the cluster-distributed fetch
    # (cluster/service.py _on_shard_fetch) reaches here without it — the
    # sync site owns its own attribution marker so every caller is
    # covered (the sanitizer caught exactly this gap on the transport
    # path)
    with ledger.attributed(scope):
        scores, matches = jax.device_get(fn(arrays, flat))
        scores, matches = np.asarray(scores), np.asarray(matches)
    if accounting:
        # the fetch phase's one device gather (dense child scores/masks
        # for inner_hits) — the `docvalues` channel of the ledger
        nb = scores.nbytes + matches.nbytes
        ledger.record("docvalues", "d2h", nb, wave=ledger.new_wave(),
                      scope=scope)
        ledger.note_device_get((time.monotonic() - t0) * 1000, nbytes=nb,
                               scope=scope)
    return scores, matches


def collect_inner_hit_specs(node) -> List[Any]:
    """Every nested/has_child/has_parent query carrying an inner_hits
    spec in the tree."""
    from dataclasses import fields as dc_fields
    out: List[Any] = []

    def walk(n):
        if isinstance(n, (dsl.NestedQuery, dsl.HasChildQuery,
                          dsl.HasParentQuery)) and \
                n.inner_hits is not None:
            out.append(n)
        for f in dc_fields(n):
            sub = getattr(n, f.name, None)
            if isinstance(sub, dsl.QueryNode):
                walk(sub)
            elif isinstance(sub, (list, tuple)):
                for s in sub:
                    if isinstance(s, dsl.QueryNode):
                        walk(s)

    if node is not None:
        walk(node)
    names = [(n.inner_hits or {}).get(
        "name", n.path if isinstance(n, dsl.NestedQuery) else n.type)
        for n in out]
    for name in names:
        if names.count(name) > 1:
            raise IllegalArgumentError(
                f"[inner_hits] already contains an entry for key [{name}]")
    return out


def build_inner_hits(ex, seg_i: int, root_ord: int, nested_nodes,
                     cache: Dict) -> Dict[str, dict]:
    """inner_hits sections for one page hit. `cache` memoizes the per-
    (segment, nested node) child evaluation across the page's hits."""
    TELEMETRY.metrics.counter("fetch.inner_hits").inc()
    from opensearch_tpu.search.compile import Compiler
    seg = ex.reader.segments[seg_i]
    arrays, meta = ex.reader.device[seg_i]
    out: Dict[str, dict] = {}
    for node in nested_nodes:
        if isinstance(node, (dsl.HasChildQuery, dsl.HasParentQuery)):
            _join_inner_hits(ex, seg, seg_i, root_ord, node, cache, out)
            continue
        spec = node.inner_hits or {}
        name = spec.get("name", node.path)
        # every REQUESTED section appears, even with zero matching
        # children (the reference returns an empty hits array, not a
        # missing key — clients index hit["inner_hits"][name] directly)
        empty = {"hits": {"total": {"value": 0, "relation": "eq"},
                          "max_score": None, "hits": []}}
        try:
            pord = seg.nested_paths.index(node.path)
        except ValueError:
            out[name] = empty           # segment has no rows on this path
            continue
        key = (seg.uid, repr(node.query))   # repr = stable fingerprint
        got = cache.get(key)
        if got is None:
            compiler = Compiler(ex.reader.mapper, ex.reader.stats())
            plan = compiler.compile(node.query, seg, meta)
            if len(cache) > 256:
                cache.clear()
            got = cache[key] = _eval_child_scores(plan, arrays)
        scores, matches = got
        rows = np.nonzero((seg.parent_ptr == root_ord)
                          & (seg.path_ords == pord) & seg.live)[0]
        hit_rows = rows[matches[rows]] if len(rows) else rows
        if not len(hit_rows):
            out[name] = empty
            continue
        # offsets index the parent's source array in row order
        offset_of = {int(r): i for i, r in enumerate(rows)}
        order = np.argsort(-scores[hit_rows], kind="stable")
        size = int(spec.get("size", 3))
        from_ = int(spec.get("from", 0))
        page = [int(hit_rows[j]) for j in order][from_:from_ + size]
        src_parent = _source_value_raw(seg.sources[root_ord], node.path)
        hits = []
        for r in page:
            off = offset_of[r]
            child_src = (src_parent[off]
                         if isinstance(src_parent, list)
                         and off < len(src_parent) else src_parent)
            hits.append({
                "_index": ex.reader.index_name,
                "_id": seg.doc_ids[root_ord],
                "_nested": {"field": node.path, "offset": off},
                "_score": float(scores[r]),
                "_source": child_src,
            })
        out[name] = {"hits": {
            "total": {"value": int(len(hit_rows)), "relation": "eq"},
            "max_score": float(scores[hit_rows].max()),
            "hits": hits,
        }}
    return out


def _source_value_raw(source, path: str):
    """Navigate dotted paths WITHOUT flattening lists (inner hits need the
    raw nested array to index by offset)."""
    cur = source
    for part in path.split("."):
        if isinstance(cur, dict) and part in cur:
            cur = cur[part]
        else:
            return None
    return cur


def _join_inner_hits(ex, seg, seg_i: int, root_ord: int, node, cache,
                     out: Dict[str, dict]):
    """has_child/has_parent inner_hits (parent-join InnerHitContextBuilder):
    children/parents are ROOT documents related through the join field's
    hidden parent-id column, joined host-side across the shard's segments
    (the reference joins via global ordinals)."""
    from opensearch_tpu.search.compile import Compiler
    spec = node.inner_hits or {}
    name = spec.get("name", node.type)
    empty = {"hits": {"total": {"value": 0, "relation": "eq"},
                      "max_score": None, "hits": []}}
    ckey = ("join_ctx", ex.reader.index_name)
    ctx = cache.get(ckey)
    if ctx is None:
        compiler = Compiler(ex.reader.mapper, ex.reader.stats())
        info = compiler._join_info()
        ctx = cache[ckey] = {"compiler": compiler, "info": info}
    compiler, info = ctx["compiler"], ctx["info"]
    if info is None:
        out[name] = empty
        return
    join, _relations = info

    def seg_ctx(s):
        key = ("join_cols", s.uid)
        got = cache.get(key)
        if got is None:
            got = cache[key] = compiler._join_columns(s, join)
        return got

    def match_mask(s):
        key = ("join_match", s.uid, repr(node.query))
        got = cache.get(key)
        if got is None:
            got = cache[key] = compiler._host_match(s, node.query)
        return got

    def children_by_parent():
        """parent_id → [(segment, ord)] of matching live children —
        computed ONCE per (shard, query) and reused across the page."""
        key = ("join_children", repr(node.query), node.type)
        got = cache.get(key)
        if got is None:
            got = {}
            for s in ex.reader.segments:
                rel, par = seg_ctx(s)
                mask = match_mask(s)
                cand = np.nonzero(mask & s.live[:s.num_docs])[0] \
                    if len(mask) else []
                for d in cand:
                    d = int(d)
                    if rel[d] == node.type and par[d] is not None:
                        got.setdefault(par[d], []).append((s, d))
            cache[key] = got
        return got

    doc_id = seg.doc_ids[root_ord]
    hits = []
    total = 0
    if isinstance(node, dsl.HasChildQuery):
        # this hit is the PARENT: gather its matching children
        size = int(spec.get("size", 3))
        from_ = int(spec.get("from", 0))
        kids = children_by_parent().get(doc_id, [])
        total = len(kids)
        hits = [{"_index": ex.reader.index_name, "_id": s2.doc_ids[d],
                 "_score": 1.0, "_source": s2.sources[d]}
                for s2, d in kids[from_:from_ + size]]
    else:
        # this hit is the CHILD: resolve its single parent
        size = int(spec.get("size", 3))
        from_ = int(spec.get("from", 0))
        rel, par = seg_ctx(seg)
        parent_id = par[root_ord]
        if parent_id is not None:
            for s in ex.reader.segments:
                srel, _ = seg_ctx(s)
                ord_ = s.ord_of(parent_id)
                if ord_ is not None and srel[ord_] == node.type \
                        and match_mask(s)[ord_]:
                    total = 1
                    hits = [{"_index": ex.reader.index_name,
                             "_id": parent_id, "_score": 1.0,
                             "_source": s.sources[ord_]}]
                    break
        hits = hits[from_:from_ + size]   # paging applies here too
    if not total:
        out[name] = empty
        return
    out[name] = {"hits": {"total": {"value": total, "relation": "eq"},
                          "max_score": 1.0, "hits": hits}}
