"""Shard-level search execution: the TPU QueryPhase + FetchPhase.

Reference flow being re-designed (SURVEY.md §3.2): SearchService.executeQueryPhase
(search/SearchService.java:529) builds a collector chain and runs Lucene's
BulkScorer leaf-by-leaf; FetchPhase (search/fetch/FetchPhase.java:106) then
loads _source for the top hits. Here the whole query phase for a segment is ONE
jitted XLA program: evaluate the plan tree → dense (scores, matches) → masked
top-k + total-hit count on device; the host merges per-segment candidates
(stable score-desc/doc-asc, Lucene's tie-break) and runs the fetch phase from
the host-side _source store.

Field sort: the device selects per-segment top-k by segment-local value rank
(correct within a segment); the host then re-keys candidates with the real
values (exact f64 / dictionary strings) for the cross-segment merge, since
ranks from different segments are not comparable. Docs missing the sort field
get a sentinel key so they are fetched and sorted last, per the reference's
missing:_last default.

Compiled executables are cached by (plan signature, segment meta, k) — the
analog of Lucene's per-(query,reader) Weight caching, but at XLA level.
"""

from __future__ import annotations

import functools
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from opensearch_tpu.common.errors import IllegalArgumentError, QueryShardError
from opensearch_tpu.index.mapper import MapperService
from opensearch_tpu.index.segment import Segment, pad_bucket
from opensearch_tpu.ops.bm25 import (
    ordinal_terms_match, range_match_on_ranks, score_text_clause)
from opensearch_tpu.ops.device_segment import (
    DeviceSegmentMeta, refresh_live, upload_segment)
from opensearch_tpu.ops.topk import NEG_INF
from opensearch_tpu.search import dsl
from opensearch_tpu.search.compile import Compiler, Plan, ShardStats
from opensearch_tpu.search.plan_eval import _eval_plan
from opensearch_tpu.search.aggs.engine import compile_aggs, eval_aggs
from opensearch_tpu.search.aggs.parse import parse_aggs
from opensearch_tpu.search.aggs.reduce import decode_outputs, reduce_aggs

# sort key for eligible docs that lack the sort field: far below any real
# rank key, far above NEG_INF (which marks ineligible docs) → fetched last
MISSING_KEY = np.float32(-1e30)


# --------------------------------------------------------------- shard reader

class ShardReader:
    """Holds a shard's sealed segments + their device images.

    Reference: the Engine.Searcher / ReaderContext pair pinned by
    search/SearchService.java:585 createContext.
    """

    def __init__(self, mapper: MapperService, segments: Optional[List[Segment]] = None,
                 index_name: str = "_index"):
        self.mapper = mapper
        self.index_name = index_name
        self.segments: List[Segment] = []
        self.device: List[Tuple[Dict, DeviceSegmentMeta]] = []
        for seg in (segments or []):
            self.add_segment(seg)

    def add_segment(self, seg: Segment):
        arrays, meta = upload_segment(seg)
        self.segments.append(seg)
        self.device.append((arrays, meta))

    def remove_segment(self, seg_id: str):
        for i, seg in enumerate(self.segments):
            if seg.seg_id == seg_id:
                del self.segments[i]
                del self.device[i]
                return

    def notify_deletes(self, seg: Segment):
        for i, s in enumerate(self.segments):
            if s is seg:
                arrays, meta = self.device[i]
                self.device[i] = (refresh_live(arrays, seg), meta)

    @property
    def num_docs(self) -> int:
        return sum(s.live_doc_count for s in self.segments)

    def stats(self) -> ShardStats:
        return ShardStats(self.segments)


# ------------------------------------------------------------------ execution

_JIT_CACHE: Dict[Any, Any] = {}


def _runner(plan_sig, plan: Plan, meta: DeviceSegmentMeta, k: int, sort_mode: str,
            agg_plans=()):
    key = (plan_sig, meta, k, sort_mode, tuple(a.sig() for a in agg_plans))
    fn = _JIT_CACHE.get(key)
    if fn is not None:
        return fn

    def run(seg, flat_inputs, sort_key_arr, min_score):
        cursor = [0]
        scores, matches = _eval_plan(plan, seg, flat_inputs, cursor)
        d_pad = seg["live"].shape[0]
        in_seg = jnp.arange(d_pad, dtype=jnp.int32) < meta.num_docs
        eligible = matches & seg["live"] & in_seg & (scores >= min_score)
        total = jnp.sum(eligible.astype(jnp.int32))
        keys = scores if sort_mode == "score" else sort_key_arr
        masked = jnp.where(eligible, keys, NEG_INF)
        k_eff = min(k, d_pad)
        top_keys, top_idx = jax.lax.top_k(masked, k_eff)
        top_scores = scores[top_idx]
        agg_outs = []
        if agg_plans:
            root_eff = jnp.zeros(d_pad, jnp.int32)
            eval_aggs(list(agg_plans), seg, flat_inputs, cursor, eligible,
                      root_eff, 1, agg_outs)
        return top_keys, top_scores, top_idx.astype(jnp.int32), total, agg_outs

    fn = jax.jit(run)
    _JIT_CACHE[key] = fn
    return fn


def _build_sort_key(arrays, primary_sort) -> jnp.ndarray:
    """Dense per-doc f32 key for the device's per-segment top-k selection
    (segment-local value ranks; higher sorts first; missing → MISSING_KEY)."""
    d_pad = arrays["live"].shape[0]
    if primary_sort is None:
        return jnp.zeros(d_pad, jnp.float32)
    field, order = primary_sort
    col = arrays["numeric"].get(field)
    if col is not None:
        if order == "asc":
            key = -col["min_rank"].astype(jnp.float32)
        else:
            key = col["max_rank"].astype(jnp.float32)
        return jnp.where(col["exists"], key, MISSING_KEY)
    col = arrays["ordinal"].get(field)
    if col is not None:
        pair_valid = col["doc_ids"] >= 0
        idx = jnp.where(pair_valid, col["doc_ids"], d_pad)
        if order == "asc":
            dense = jnp.full(d_pad, 2 ** 30, jnp.int32).at[idx].min(
                jnp.where(pair_valid, col["ords"], 2 ** 30), mode="drop")
            key = -dense.astype(jnp.float32)
        else:
            dense = jnp.full(d_pad, -1, jnp.int32).at[idx].max(
                jnp.where(pair_valid, col["ords"], -1), mode="drop")
            key = dense.astype(jnp.float32)
        return jnp.where(col["exists"], key, MISSING_KEY)
    return jnp.full(d_pad, MISSING_KEY, jnp.float32)


class _Candidate:
    __slots__ = ("score", "seg_i", "ord", "sort_values")

    def __init__(self, score, seg_i, ord_, sort_values):
        self.score = score
        self.seg_i = seg_i
        self.ord = ord_
        self.sort_values = sort_values  # list parallel to sort specs; None = missing


def _compare_candidates(specs):
    """Multi-key comparator with missing-last semantics (reference default)."""
    def cmp(a: _Candidate, b: _Candidate) -> int:
        for i, (field, order) in enumerate(specs):
            va, vb = a.sort_values[i], b.sort_values[i]
            if va is None and vb is None:
                continue
            if va is None:
                return 1   # missing sorts last
            if vb is None:
                return -1
            if va != vb:
                lt = va < vb
                if order == "desc":
                    lt = not lt
                return -1 if lt else 1
        if a.seg_i != b.seg_i:
            return -1 if a.seg_i < b.seg_i else 1
        return -1 if a.ord < b.ord else 1
    return functools.cmp_to_key(cmp)


class SearchExecutor:
    """Executes a parsed search request against one shard (query + fetch)."""

    def __init__(self, reader: ShardReader):
        self.reader = reader

    def search(self, body: Optional[dict] = None) -> dict:
        body = body or {}
        start = time.monotonic()
        size = int(body.get("size", 10))
        from_ = int(body.get("from", 0))
        if size < 0 or from_ < 0:
            raise IllegalArgumentError("[from] and [size] must be non-negative")
        node = dsl.parse_query(body.get("query"))
        min_score = float(body["min_score"]) if body.get("min_score") is not None \
            else NEG_INF

        sort_specs = _parse_sort(body.get("sort"))
        score_sorted = sort_specs[0][0] == "_score"
        primary = None if score_sorted else sort_specs[0]
        wants_score = score_sorted or any(f == "_score" for f, _ in sort_specs) \
            or bool(body.get("track_scores", False))

        stats = self.reader.stats()
        compiler = Compiler(self.reader.mapper, stats)
        agg_nodes = parse_aggs(body.get("aggs") or body.get("aggregations"))
        from opensearch_tpu.search.aggs.parse import PIPELINE_TYPES
        device_agg_nodes = [n for n in agg_nodes
                            if n.type not in PIPELINE_TYPES]
        k = max(from_ + size, 10)
        k_fetch = min(k + 128, 1 << 16)  # over-fetch for ties & cross-seg merge

        candidates: List[_Candidate] = []
        per_segment_decoded = []
        total = 0
        for seg_i, (seg, (arrays, meta)) in enumerate(
                zip(self.reader.segments, self.reader.device)):
            if seg.num_docs == 0:
                continue
            plan = compiler.compile(node, seg, meta)
            agg_plans = compile_aggs(device_agg_nodes, self.reader.mapper, seg,
                                     meta, compiler) if agg_nodes else []
            sort_key = _build_sort_key(arrays, primary)
            fn = _runner(plan.sig(), plan, meta,
                         min(k_fetch, pad_bucket(max(seg.num_docs, 1))),
                         "score" if score_sorted else "field",
                         tuple(agg_plans))
            flat = plan.flatten_inputs([])
            for ap in agg_plans:
                ap.flatten_inputs(flat)
            flat = jax.tree_util.tree_map(jnp.asarray, flat)
            top_keys, top_scores, top_idx, seg_total, agg_outs = fn(
                arrays, flat, sort_key, jnp.float32(min_score))
            if agg_nodes:
                agg_outs = jax.tree_util.tree_map(np.asarray, agg_outs)
                per_segment_decoded.append(decode_outputs(agg_plans, agg_outs))
            top_keys = np.asarray(top_keys)
            top_scores = np.asarray(top_scores)
            top_idx = np.asarray(top_idx)
            total += int(seg_total)
            for key_val, score, ord_ in zip(top_keys, top_scores, top_idx):
                if key_val == NEG_INF:
                    continue  # ineligible / padding
                sort_values = [
                    float(score) if f == "_score" else _sort_value(seg, f, o, int(ord_))
                    for f, o in sort_specs]
                candidates.append(_Candidate(float(score), seg_i, int(ord_),
                                             sort_values))

        candidates.sort(key=_compare_candidates(sort_specs))
        page = candidates[from_:from_ + size]

        max_score = None
        if score_sorted or wants_score:
            for c in candidates:
                if max_score is None or c.score > max_score:
                    max_score = c.score

        hits = []
        for c in page:
            seg = self.reader.segments[c.seg_i]
            hit = {
                "_index": self.reader.index_name,
                "_id": seg.doc_ids[c.ord],
                "_score": c.score if wants_score else None,
            }
            src = _filter_source(seg.sources[c.ord], body.get("_source", True))
            if src is not None:
                hit["_source"] = src
            if not score_sorted:
                hit["sort"] = c.sort_values
            hits.append(hit)

        took_ms = int((time.monotonic() - start) * 1000)
        resp = {
            "took": took_ms,
            "timed_out": False,
            "_shards": {"total": 1, "successful": 1, "skipped": 0, "failed": 0},
            "hits": {
                "total": {"value": total, "relation": "eq"},
                "max_score": max_score,
                "hits": hits,
            },
        }
        if agg_nodes:
            from opensearch_tpu.search.aggs.pipeline import apply_pipelines
            aggregations = reduce_aggs(per_segment_decoded)
            apply_pipelines(agg_nodes, aggregations)
            resp["aggregations"] = aggregations
        return resp

    def count(self, body: Optional[dict] = None) -> int:
        body = dict(body or {})
        body["size"] = 0
        body.pop("from", None)
        return self.search(body)["hits"]["total"]["value"]


def _parse_sort(sort_body) -> List[Tuple[str, str]]:
    """Normalize the sort body to [(field | '_score', order), ...].
    Default (None / empty / '_score') is score-descending."""
    if sort_body is None:
        return [("_score", "desc")]
    specs = sort_body if isinstance(sort_body, list) else [sort_body]
    out: List[Tuple[str, str]] = []
    for spec in specs:
        if isinstance(spec, str):
            if spec == "_score":
                out.append(("_score", "desc"))
            elif spec == "_doc":
                continue  # doc order is the built-in final tie-break
            else:
                out.append((spec, "asc"))
        elif isinstance(spec, dict):
            field, opts = next(iter(spec.items()))
            if field == "_score":
                order = opts.get("order", "desc") if isinstance(opts, dict) \
                    else str(opts)
                out.append(("_score", order))
            else:
                order = opts.get("order", "asc") if isinstance(opts, dict) \
                    else str(opts)
                out.append((field, order))
    if not out:
        return [("_score", "desc")]
    return out


def _sort_value(seg: Segment, field: str, order: str, ord_: int):
    """Real (host, exact) sort value for the cross-segment merge + response."""
    col = seg.numeric_dv.get(field)
    if col is not None:
        vals = col.values[col.doc_ids == ord_]
        if len(vals) == 0:
            return None
        v = float(vals.min() if order == "asc" else vals.max())
        return int(v) if v.is_integer() else v
    ocol = seg.ordinal_dv.get(field)
    if ocol is not None:
        ords = ocol.ords[ocol.doc_ids == ord_]
        if len(ords) == 0:
            return None
        o = int(ords.min() if order == "asc" else ords.max())
        return ocol.dictionary[o]
    return None


def _filter_source(source: Optional[dict], source_spec) -> Optional[dict]:
    """_source filtering per the reference's FetchSourceContext: an include
    pattern selects its whole subtree; excludes override includes."""
    if source is None or source_spec is True or source_spec is None:
        return source
    if source_spec is False:
        return None
    import fnmatch as _fn

    if isinstance(source_spec, str):
        includes, excludes = [source_spec], []
    elif isinstance(source_spec, list):
        includes, excludes = list(source_spec), []
    elif isinstance(source_spec, dict):
        includes = source_spec.get("includes", source_spec.get("include", []))
        excludes = source_spec.get("excludes", source_spec.get("exclude", []))
        if isinstance(includes, str):
            includes = [includes]
        if isinstance(excludes, str):
            excludes = [excludes]
    else:
        return source

    def matches_any(path: str, patterns) -> bool:
        # a pattern matches the leaf itself or any ancestor object path
        parts = path.split(".")
        prefixes = [".".join(parts[:i + 1]) for i in range(len(parts))]
        return any(_fn.fnmatchcase(prefix, p)
                   for prefix in prefixes for p in patterns)

    def walk(obj, path=""):
        if not isinstance(obj, dict):
            return obj
        out = {}
        for k, v in obj.items():
            full = f"{path}{k}"
            if isinstance(v, dict):
                sub = walk(v, f"{full}.")
                if sub:
                    out[k] = sub
                continue
            if matches_any(full, includes) if includes else True:
                if not matches_any(full, excludes):
                    out[k] = v
        return out

    return walk(source)
