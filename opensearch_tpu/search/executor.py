"""Shard-level search execution: the TPU QueryPhase + FetchPhase.

Reference flow being re-designed (SURVEY.md §3.2): SearchService.executeQueryPhase
(search/SearchService.java:529) builds a collector chain and runs Lucene's
BulkScorer leaf-by-leaf; FetchPhase (search/fetch/FetchPhase.java:106) then
loads _source for the top hits. Here the whole query phase for a segment is ONE
jitted XLA program: evaluate the plan tree → dense (scores, matches) → masked
top-k + total-hit count on device; the host merges per-segment candidates
(stable score-desc/doc-asc, Lucene's tie-break) and runs the fetch phase from
the host-side _source store.

Field sort: the device selects per-segment top-k by segment-local value rank
(correct within a segment); the host then re-keys candidates with the real
values (exact f64 / dictionary strings) for the cross-segment merge, since
ranks from different segments are not comparable. Docs missing the sort field
get a sentinel key so they are fetched and sorted last, per the reference's
missing:_last default.

Compiled executables are cached by (plan signature, segment meta, k) — the
analog of Lucene's per-(query,reader) Weight caching, but at XLA level.
"""

from __future__ import annotations

import functools
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from opensearch_tpu.common.errors import IllegalArgumentError, QueryShardError
from opensearch_tpu.index.mapper import MapperService
from opensearch_tpu.index.segment import Segment, pad_bucket
from opensearch_tpu.ops.bm25 import (
    ordinal_terms_match, range_match_on_ranks, score_text_clause)
from opensearch_tpu.ops.device_segment import (
    DeviceSegmentMeta, refresh_live, upload_segment)
from opensearch_tpu.ops.topk import NEG_INF
from opensearch_tpu.search import dsl
from opensearch_tpu.search.compile import Compiler, Plan, ShardStats
from opensearch_tpu.search.plan_eval import _eval_plan
from opensearch_tpu.search.aggs.engine import compile_aggs, eval_aggs
from opensearch_tpu.search.aggs.parse import parse_aggs
from opensearch_tpu.search.aggs.reduce import decode_outputs, reduce_aggs

# sort key for eligible docs that lack the sort field: far below any real
# rank key, far above NEG_INF (which marks ineligible docs) → fetched last
MISSING_KEY = np.float32(-1e30)


# --------------------------------------------------------------- shard reader

class ShardReader:
    """Holds a shard's sealed segments + their device images.

    Reference: the Engine.Searcher / ReaderContext pair pinned by
    search/SearchService.java:585 createContext.
    """

    def __init__(self, mapper: MapperService, segments: Optional[List[Segment]] = None,
                 index_name: str = "_index"):
        self.mapper = mapper
        self.index_name = index_name
        self.segments: List[Segment] = []
        self.device: List[Tuple[Dict, DeviceSegmentMeta]] = []
        for seg in (segments or []):
            self.add_segment(seg)

    def add_segment(self, seg: Segment):
        arrays, meta = upload_segment(seg)
        self.segments.append(seg)
        self.device.append((arrays, meta))

    def remove_segment(self, seg_id: str):
        for i, seg in enumerate(self.segments):
            if seg.seg_id == seg_id:
                del self.segments[i]
                del self.device[i]
                return

    def notify_deletes(self, seg: Segment):
        for i, s in enumerate(self.segments):
            if s is seg:
                arrays, meta = self.device[i]
                self.device[i] = (refresh_live(arrays, seg), meta)

    def update_segment(self, seg: Segment):
        """Adopt a possibly-replaced segment object with the same id
        (recovery/segment-replication installs clone_for_copy objects):
        shared immutable columns keep their device image, only the live
        mask re-uploads; a genuinely different segment re-uploads fully."""
        for i, s in enumerate(self.segments):
            if s.seg_id != seg.seg_id:
                continue
            if s is seg or s.post_docs is seg.post_docs:
                self.segments[i] = seg
                arrays, meta = self.device[i]
                self.device[i] = (refresh_live(arrays, seg), meta)
            else:
                self.segments[i] = seg
                self.device[i] = upload_segment(seg)
            return
        self.add_segment(seg)

    @property
    def num_docs(self) -> int:
        return sum(s.live_doc_count for s in self.segments)

    def stats(self) -> ShardStats:
        return ShardStats(self.segments)


class PinnedReader:
    """Point-in-time snapshot of a ShardReader: segments are immutable, so
    pinning is just holding references to the current segment list + device
    images (reference: ReaderContext / PitReaderContext keeping the Lucene
    searcher open across requests, search/internal/PitReaderContext.java)."""

    def __init__(self, reader: ShardReader):
        self.mapper = reader.mapper
        self.index_name = reader.index_name
        self.segments = list(reader.segments)
        self.device = list(reader.device)
        self._stats = ShardStats(self.segments)

    @property
    def num_docs(self) -> int:
        return sum(s.live_doc_count for s in self.segments)

    def stats(self) -> ShardStats:
        return self._stats


# ------------------------------------------------------------------ execution

_JIT_CACHE: Dict[Any, Any] = {}


def build_query_phase(plan: Plan, meta: DeviceSegmentMeta, k: int,
                      sort_mode: str, agg_plans=()):
    """The single-segment query phase as a pure jittable function — the TPU
    program that replaces one ContextIndexSearcher.searchLeaf pass
    (search/internal/ContextIndexSearcher.java:260). Exposed unjitted so the
    graft entry can hand it to the driver's compile check."""

    def run(seg, flat_inputs, sort_key_arr, min_score):
        cursor = [0]
        scores, matches = _eval_plan(plan, seg, flat_inputs, cursor)
        d_pad = seg["live"].shape[0]
        in_seg = jnp.arange(d_pad, dtype=jnp.int32) < meta.num_docs
        eligible = matches & seg["live"] & in_seg & (scores >= min_score)
        total = jnp.sum(eligible.astype(jnp.int32))
        keys = scores if sort_mode == "score" else sort_key_arr
        masked = jnp.where(eligible, keys, NEG_INF)
        k_eff = min(k, d_pad)
        top_keys, top_idx = jax.lax.top_k(masked, k_eff)
        top_scores = scores[top_idx]
        agg_outs = []
        if agg_plans:
            root_eff = jnp.zeros(d_pad, jnp.int32)
            eval_aggs(list(agg_plans), seg, flat_inputs, cursor, eligible,
                      root_eff, 1, agg_outs)
        return top_keys, top_scores, top_idx.astype(jnp.int32), total, agg_outs

    return run


def build_batched_query_phase(plan: Plan, meta: DeviceSegmentMeta, k: int):
    """B same-shaped queries against one segment as ONE device program.

    The TPU answer to per-query launch latency: where the reference executes
    queries one at a time per shard (SearchService.executeQueryPhase), here a
    whole _msearch batch vmaps over a leading query axis — gathers, BM25 and
    top-k all batch cleanly, so one host↔device round trip serves B queries.
    Score-sorted, agg-free queries only (the common high-QPS shape)."""

    def one(seg, flat_inputs, min_score):
        cursor = [0]
        scores, matches = _eval_plan(plan, seg, flat_inputs, cursor)
        in_seg = jnp.arange(seg["live"].shape[0], dtype=jnp.int32) < meta.num_docs
        eligible = matches & seg["live"] & in_seg & (scores >= min_score)
        total = jnp.sum(eligible.astype(jnp.int32))
        masked = jnp.where(eligible, scores, NEG_INF)
        k_eff = min(k, seg["live"].shape[0])
        top_scores, top_idx = jax.lax.top_k(masked, k_eff)
        # pack into ONE f32 row [k | k | 1] (ints bitcast) so the host fetches
        # a single array — each fetch is a full round trip on remote devices
        return jnp.concatenate([
            top_scores,
            jax.lax.bitcast_convert_type(top_idx.astype(jnp.int32),
                                         jnp.float32),
            jax.lax.bitcast_convert_type(total[None].astype(jnp.int32),
                                         jnp.float32)])

    def run(seg, batched_flat, min_scores):
        return jax.vmap(one, in_axes=(None, 0, 0))(seg, batched_flat,
                                                   min_scores)

    return run


def unpack_batched_result(packed: np.ndarray, k_eff: int):
    """Inverse of the packed [B, 2k+1] row layout from
    build_batched_query_phase."""
    scores = packed[:, :k_eff]
    idx = packed[:, k_eff:2 * k_eff].view(np.int32)
    totals = packed[:, 2 * k_eff:].view(np.int32)[:, 0]
    return scores, idx, totals


def _batched_runner(plan_sig, plan: Plan, meta: DeviceSegmentMeta, k: int,
                    batch: int):
    key = ("batched", plan_sig, meta, k, batch)
    fn = _JIT_CACHE.get(key)
    if fn is None:
        fn = jax.jit(build_batched_query_phase(plan, meta, k))
        _JIT_CACHE[key] = fn
    return fn


def _runner(plan_sig, plan: Plan, meta: DeviceSegmentMeta, k: int, sort_mode: str,
            agg_plans=()):
    key = (plan_sig, meta, k, sort_mode, tuple(a.sig() for a in agg_plans))
    fn = _JIT_CACHE.get(key)
    if fn is not None:
        return fn
    fn = jax.jit(build_query_phase(plan, meta, k, sort_mode, agg_plans))
    _JIT_CACHE[key] = fn
    return fn


def _build_sort_key(arrays, primary_sort) -> jnp.ndarray:
    """Dense per-doc f32 key for the device's per-segment top-k selection
    (segment-local value ranks; higher sorts first; missing → MISSING_KEY)."""
    d_pad = arrays["live"].shape[0]
    if primary_sort is None:
        return jnp.zeros(d_pad, jnp.float32)
    field, order = primary_sort
    col = arrays["numeric"].get(field)
    if col is not None:
        if order == "asc":
            key = -col["min_rank"].astype(jnp.float32)
        else:
            key = col["max_rank"].astype(jnp.float32)
        return jnp.where(col["exists"], key, MISSING_KEY)
    col = arrays["ordinal"].get(field)
    if col is not None:
        pair_valid = col["doc_ids"] >= 0
        idx = jnp.where(pair_valid, col["doc_ids"], d_pad)
        if order == "asc":
            dense = jnp.full(d_pad, 2 ** 30, jnp.int32).at[idx].min(
                jnp.where(pair_valid, col["ords"], 2 ** 30), mode="drop")
            key = -dense.astype(jnp.float32)
        else:
            dense = jnp.full(d_pad, -1, jnp.int32).at[idx].max(
                jnp.where(pair_valid, col["ords"], -1), mode="drop")
            key = dense.astype(jnp.float32)
        return jnp.where(col["exists"], key, MISSING_KEY)
    return jnp.full(d_pad, MISSING_KEY, jnp.float32)


class _Candidate:
    __slots__ = ("score", "seg_i", "ord", "sort_values", "shard_i",
                 "collapse_value")

    def __init__(self, score, seg_i, ord_, sort_values, shard_i=0):
        self.score = score
        self.seg_i = seg_i
        self.ord = ord_
        self.sort_values = sort_values  # list parallel to sort specs; None = missing
        self.shard_i = shard_i          # coordinator-side shard index


def _compare_candidates(specs):
    """Multi-key comparator with missing-last semantics (reference default).
    Final tie-break (shard, segment, doc) asc — mergeTopDocs order
    (action/search/SearchPhaseController.java:228)."""
    def cmp(a: _Candidate, b: _Candidate) -> int:
        for i, (field, order) in enumerate(specs):
            va, vb = a.sort_values[i], b.sort_values[i]
            if va is None and vb is None:
                continue
            if va is None:
                return 1   # missing sorts last
            if vb is None:
                return -1
            if va != vb:
                lt = va < vb
                if order == "desc":
                    lt = not lt
                return -1 if lt else 1
        if a.shard_i != b.shard_i:
            return -1 if a.shard_i < b.shard_i else 1
        if a.seg_i != b.seg_i:
            return -1 if a.seg_i < b.seg_i else 1
        return -1 if a.ord < b.ord else 1
    return functools.cmp_to_key(cmp)


class SearchExecutor:
    """Executes a parsed search request against one shard (query + fetch)."""

    def __init__(self, reader: ShardReader):
        self.reader = reader

    def search(self, body: Optional[dict] = None) -> dict:
        from opensearch_tpu.search.controller import execute_search
        return execute_search([self], body)

    def execute_query_phase(self, body: dict, k: int,
                            extra_filter: Optional[dict] = None):
        """Per-shard query phase (SearchService.executeQueryPhase analog):
        returns (candidates, per-segment decoded agg partials, total hits)
        for the coordinator to merge. `k` = from+size requested globally.
        `extra_filter` is an alias filter applied as a non-scoring clause
        (reference: QueryShardContext filter from AliasFilter)."""
        body = body or {}
        node = dsl.parse_query(body.get("query"))
        if extra_filter is not None:
            node = dsl.BoolQuery(must=[node],
                                 filter=[dsl.parse_query(extra_filter)])
        min_score = float(body["min_score"]) if body.get("min_score") is not None \
            else NEG_INF

        sort_specs = _parse_sort(body.get("sort"))
        score_sorted = sort_specs[0][0] == "_score"
        primary = None if score_sorted else sort_specs[0]

        stats = self.reader.stats()
        compiler = Compiler(self.reader.mapper, stats)
        agg_nodes = parse_aggs(body.get("aggs") or body.get("aggregations"))
        from opensearch_tpu.search.aggs.parse import PIPELINE_TYPES
        device_agg_nodes = [n for n in agg_nodes
                            if n.type not in PIPELINE_TYPES]
        k_fetch = min(k + 128, 1 << 16)  # over-fetch for ties & cross-seg merge

        # phase 1: dispatch every segment's program without forcing — jax
        # dispatch is async, so device work overlaps; phase 2 collects ALL
        # results in ONE device_get (one transfer round trip total — on a
        # tunneled device the round trip dominates device compute)
        launched = []
        for seg_i, (seg, (arrays, meta)) in enumerate(
                zip(self.reader.segments, self.reader.device)):
            if seg.num_docs == 0:
                continue
            plan = compiler.compile(node, seg, meta)
            agg_plans = compile_aggs(device_agg_nodes, self.reader.mapper, seg,
                                     meta, compiler) if agg_nodes else []
            sort_key = _build_sort_key(arrays, primary)
            fn = _runner(plan.sig(), plan, meta,
                         min(k_fetch, pad_bucket(max(seg.num_docs, 1))),
                         "score" if score_sorted else "field",
                         tuple(agg_plans))
            flat = plan.flatten_inputs([])
            for ap in agg_plans:
                ap.flatten_inputs(flat)
            flat = jax.tree_util.tree_map(jnp.asarray, flat)
            launched.append((seg_i, seg, agg_plans,
                             fn(arrays, flat, sort_key,
                                jnp.float32(min_score))))

        fetched = jax.device_get([out for _, _, _, out in launched])

        candidates: List[_Candidate] = []
        per_segment_decoded = []
        total = 0
        for (seg_i, seg, agg_plans, _), outs in zip(launched, fetched):
            top_keys, top_scores, top_idx, seg_total, agg_outs = outs
            if agg_nodes:
                per_segment_decoded.append(decode_outputs(agg_plans, agg_outs))
            total += int(seg_total)
            for key_val, score, ord_ in zip(top_keys, top_scores, top_idx):
                if key_val == NEG_INF:
                    continue  # ineligible / padding
                sort_values = [
                    float(score) if f == "_score" else _sort_value(seg, f, o, int(ord_))
                    for f, o in sort_specs]
                candidates.append(_Candidate(float(score), seg_i, int(ord_),
                                             sort_values))

        return candidates, per_segment_decoded, total

    def _hit_dict(self, seg_i: int, ord_: int, score: Optional[float],
                  body: dict) -> dict:
        """One search hit (fetch phase for a single doc) — shared by search()
        and multi_search()."""
        seg = self.reader.segments[seg_i]
        hit = {"_index": self.reader.index_name,
               "_id": seg.doc_ids[ord_],
               "_score": score}
        src = _filter_source(seg.sources[ord_], body.get("_source", True))
        if src is not None:
            hit["_source"] = src
        return hit

    def multi_search(self, bodies: List[dict]) -> dict:
        """_msearch: execute many search bodies, batching same-shaped
        score-sorted queries into single vmapped device programs per segment
        (reference: action/search/TransportMultiSearchAction fans bodies out
        concurrently; here concurrency is a batch axis on the MXU/VPU)."""
        start = time.monotonic()
        responses: List[Optional[dict]] = [None] * len(bodies)

        batchable: List[Tuple[int, dict, Any, int, int, float]] = []
        for i, body in enumerate(bodies):
            body = body or {}
            simple = (not (body.get("aggs") or body.get("aggregations"))
                      and body.get("sort") in (None, "_score", ["_score"])
                      and not body.get("search_after"))
            if not simple:
                responses[i] = self.search(body)
                continue
            try:
                node = dsl.parse_query(body.get("query"))
            except Exception:
                responses[i] = self.search(body)  # surface the error uniformly
                continue
            size = int(body.get("size", 10))
            from_ = int(body.get("from", 0))
            if size < 0 or from_ < 0:
                raise IllegalArgumentError(
                    "[from] and [size] must be non-negative")
            min_score = float(body["min_score"]) \
                if body.get("min_score") is not None else NEG_INF
            batchable.append((i, body, node, size, from_, min_score))

        # group by plan STRUCTURE (shape-free): the cross-query shape envelope
        # (pad_stack_trees) grows every query's inputs to the group max, so
        # queries whose terms landed in different postings buckets still share
        # one vmapped program — one device round trip per group
        from opensearch_tpu.parallel.distributed import (
            _tree_shapes, pad_stack_trees, plan_struct)

        groups: Dict[Any, List[int]] = {}
        compiled: Dict[int, List[Plan]] = {}
        stats = self.reader.stats()
        compiler = Compiler(self.reader.mapper, stats)
        for entry in batchable:
            i, body, node, size, from_, min_score = entry
            plans = []
            for seg, (arrays, meta) in zip(self.reader.segments,
                                           self.reader.device):
                if seg.num_docs == 0:
                    plans.append(None)
                    continue
                plans.append(compiler.compile(node, seg, meta))
            compiled[i] = plans
            # no tie overfetch needed: per-segment top-k by score with
            # doc-asc tie-break (lax.top_k picks the lowest index) merges to
            # the exact global page for score-sorted queries
            k = max(from_ + size, 10)
            if all(p is None or p.kind == "match_none" for p in plans):
                # no term matched any segment: answer host-side, zero
                # device work (the can-match pre-filter analog)
                responses[i] = {
                    "took": int((time.monotonic() - start) * 1000),
                    "timed_out": False,
                    "_shards": {"total": 1, "successful": 1, "skipped": 0,
                                "failed": 0},
                    "hits": {"total": {"value": 0, "relation": "eq"},
                             "max_score": None, "hits": []},
                }
                continue
            struct = tuple(plan_struct(p) if p is not None else None
                           for p in plans)
            groups.setdefault((struct, min(k, 1 << 16)), []).append(i)

        entry_by_i = {e[0]: e for e in batchable}
        # phase 1: dispatch every group × segment program without blocking —
        # jax dispatch is async, so device work and tunnel transfers overlap
        pending = []
        for (struct, k_fetch), idxs in groups.items():
            for seg_i, (seg, (arrays, meta)) in enumerate(
                    zip(self.reader.segments, self.reader.device)):
                if seg.num_docs == 0:
                    continue
                flats = [compiled[i][seg_i].flatten_inputs([]) for i in idxs]
                batched = jax.tree_util.tree_map(
                    jnp.asarray, pad_stack_trees(flats))
                min_scores = jnp.asarray(np.asarray(
                    [entry_by_i[i][5] for i in idxs], dtype=np.float32))
                k_seg = min(k_fetch, pad_bucket(max(seg.num_docs, 1)))
                plan0 = compiled[idxs[0]][seg_i]
                fn = _batched_runner(
                    (plan_struct(plan0), _tree_shapes(batched)),
                    plan0, meta, k_seg, len(idxs))
                pending.append((idxs, seg_i, k_seg,
                                fn(arrays, batched, min_scores)))

        # phase 2: collect (vectorized — no per-candidate python objects);
        # ONE device_get for every group×segment result = one transfer
        # round trip for the whole msearch batch
        grouped = [i for idxs in groups.values() for i in idxs]
        per_query_segs: Dict[int, List[Tuple[int, np.ndarray, np.ndarray]]] = \
            {i: [] for i in grouped}
        per_query_total: Dict[int, int] = {i: 0 for i in grouped}
        fetched = jax.device_get([packed for _, _, _, packed in pending])
        for (idxs, seg_i, k_seg, _), packed in zip(pending, fetched):
            scores_b, idx_b, total_b = unpack_batched_result(
                np.asarray(packed), k_seg)
            for row, i in enumerate(idxs):
                per_query_total[i] += int(total_b[row])
                per_query_segs[i].append((seg_i, scores_b[row], idx_b[row]))

        for i, seg_results in per_query_segs.items():
            _, body, _, size, from_, _ = entry_by_i[i]
            if seg_results:
                all_scores = np.concatenate([s for _, s, _ in seg_results])
                all_ords = np.concatenate([o for _, _, o in seg_results])
                all_segs = np.concatenate(
                    [np.full(len(s), si, np.int32) for si, s, _ in seg_results])
                valid = all_scores > NEG_INF
                all_scores, all_ords, all_segs = (
                    all_scores[valid], all_ords[valid], all_segs[valid])
                # score desc, then seg asc, then doc asc — mergeTopDocs order
                order = np.lexsort((all_ords, all_segs, -all_scores))
                page = order[from_:from_ + size]
                max_score = float(all_scores.max()) if len(all_scores) else None
            else:
                page = np.array([], dtype=np.int64)
                all_scores = all_ords = all_segs = np.array([])
                max_score = None
            hits = [self._hit_dict(int(all_segs[j]), int(all_ords[j]),
                                   float(all_scores[j]), body)
                    for j in page]
            responses[i] = {
                "took": int((time.monotonic() - start) * 1000),
                "timed_out": False,
                "_shards": {"total": 1, "successful": 1, "skipped": 0,
                            "failed": 0},
                "hits": {
                    "total": {"value": per_query_total[i],
                              "relation": "eq"},
                    "max_score": max_score,
                    "hits": hits,
                },
            }

        return {"took": int((time.monotonic() - start) * 1000),
                "responses": responses}

    def count(self, body: Optional[dict] = None) -> int:
        body = dict(body or {})
        body["size"] = 0
        body.pop("from", None)
        return self.search(body)["hits"]["total"]["value"]


def _parse_sort(sort_body) -> List[Tuple[str, str]]:
    """Normalize the sort body to [(field | '_score', order), ...].
    Default (None / empty / '_score') is score-descending."""
    if sort_body is None:
        return [("_score", "desc")]
    specs = sort_body if isinstance(sort_body, list) else [sort_body]
    out: List[Tuple[str, str]] = []
    for spec in specs:
        if isinstance(spec, str):
            if spec == "_score":
                out.append(("_score", "desc"))
            elif spec == "_doc":
                continue  # doc order is the built-in final tie-break
            else:
                out.append((spec, "asc"))
        elif isinstance(spec, dict):
            field, opts = next(iter(spec.items()))
            if field == "_score":
                order = opts.get("order", "desc") if isinstance(opts, dict) \
                    else str(opts)
                out.append(("_score", order))
            else:
                order = opts.get("order", "asc") if isinstance(opts, dict) \
                    else str(opts)
                out.append((field, order))
    if not out:
        return [("_score", "desc")]
    return out


def _sort_value(seg: Segment, field: str, order: str, ord_: int):
    """Real (host, exact) sort value for the cross-segment merge + response."""
    col = seg.numeric_dv.get(field)
    if col is not None:
        vals = col.values[col.doc_ids == ord_]
        if len(vals) == 0:
            return None
        v = float(vals.min() if order == "asc" else vals.max())
        return int(v) if v.is_integer() else v
    ocol = seg.ordinal_dv.get(field)
    if ocol is not None:
        ords = ocol.ords[ocol.doc_ids == ord_]
        if len(ords) == 0:
            return None
        o = int(ords.min() if order == "asc" else ords.max())
        return ocol.dictionary[o]
    return None


def _filter_source(source: Optional[dict], source_spec) -> Optional[dict]:
    """_source filtering per the reference's FetchSourceContext: an include
    pattern selects its whole subtree; excludes override includes."""
    if source is None or source_spec is True or source_spec is None:
        return source
    if source_spec is False:
        return None
    import fnmatch as _fn

    if isinstance(source_spec, str):
        includes, excludes = [source_spec], []
    elif isinstance(source_spec, list):
        includes, excludes = list(source_spec), []
    elif isinstance(source_spec, dict):
        includes = source_spec.get("includes", source_spec.get("include", []))
        excludes = source_spec.get("excludes", source_spec.get("exclude", []))
        if isinstance(includes, str):
            includes = [includes]
        if isinstance(excludes, str):
            excludes = [excludes]
    else:
        return source

    def matches_any(path: str, patterns) -> bool:
        # a pattern matches the leaf itself or any ancestor object path
        parts = path.split(".")
        prefixes = [".".join(parts[:i + 1]) for i in range(len(parts))]
        return any(_fn.fnmatchcase(prefix, p)
                   for prefix in prefixes for p in patterns)

    def walk(obj, path=""):
        if not isinstance(obj, dict):
            return obj
        out = {}
        for k, v in obj.items():
            full = f"{path}{k}"
            if isinstance(v, dict):
                sub = walk(v, f"{full}.")
                if sub:
                    out[k] = sub
                continue
            if matches_any(full, includes) if includes else True:
                if not matches_any(full, excludes):
                    out[k] = v
        return out

    return walk(source)
