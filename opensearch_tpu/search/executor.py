"""Shard-level search execution: the TPU QueryPhase + FetchPhase.

Reference flow being re-designed (SURVEY.md §3.2): SearchService.executeQueryPhase
(search/SearchService.java:529) builds a collector chain and runs Lucene's
BulkScorer leaf-by-leaf; FetchPhase (search/fetch/FetchPhase.java:106) then
loads _source for the top hits. Here the whole query phase for a segment is ONE
jitted XLA program: evaluate the plan tree → dense (scores, matches) → masked
top-k + total-hit count on device; the host merges per-segment candidates
(stable score-desc/doc-asc, Lucene's tie-break) and runs the fetch phase from
the host-side _source store.

Field sort: the device selects per-segment top-k by segment-local value rank
(correct within a segment); the host then re-keys candidates with the real
values (exact f64 / dictionary strings) for the cross-segment merge, since
ranks from different segments are not comparable. Docs missing the sort field
get a sentinel key so they are fetched and sorted last, per the reference's
missing:_last default.

Compiled executables are cached by (plan signature, segment meta, k) — the
analog of Lucene's per-(query,reader) Weight caching, but at XLA level.
"""

from __future__ import annotations

import functools
import json
import os
import queue
import threading
import time
import weakref
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from opensearch_tpu.common import faults, retry
from opensearch_tpu.common.admission import WAVE_BREAKER
from opensearch_tpu.common.errors import (
    IllegalArgumentError, OpenSearchTpuError, QueryShardError)
from opensearch_tpu.index.mapper import MapperService
from opensearch_tpu.index.segment import Segment, pad_bucket
from opensearch_tpu.ops import bm25 as _bm25
from opensearch_tpu.ops.bm25 import (
    blockmax_keep_mask, ordinal_terms_match, range_match_on_ranks,
    score_text_clause)
from opensearch_tpu.ops import device_segment as _devseg
from opensearch_tpu.ops.device_segment import (
    DeviceSegmentMeta, refresh_live, tree_nbytes, upload_segment)
from opensearch_tpu.ops.topk import (NEG_INF, f32_sortable, single_valued,
                                     value_merge_key)
from opensearch_tpu.search import dsl
from opensearch_tpu.search.compile import (Compiler, Plan, ShardStats,
                                           _PartialBundle, carry_memo,
                                           struct_fingerprint)
from opensearch_tpu.search.plan_eval import _eval_plan
from opensearch_tpu.search.aggs.engine import compile_aggs, eval_aggs
from opensearch_tpu.search.aggs.parse import parse_aggs
from opensearch_tpu.search.aggs.reduce import decode_outputs, reduce_aggs
from opensearch_tpu.telemetry import TELEMETRY
from opensearch_tpu.telemetry.ledger import LedgerScope

# sort key for eligible docs that lack the sort field: far below any real
# rank key, far above NEG_INF (which marks ineligible docs) → fetched last
MISSING_KEY = np.float32(-1e30)

# Single-round-trip result pages (ISSUE 17): cross-segment top-k merge,
# on-device sort-key extraction and the fused docvalue gather assemble a
# wave's whole response body from ONE device_get instead of the legacy
# multi-channel host merge + per-leaf column reads. OFF by default
# (faults-style module flag, registered in tools/lint/gate_lint.py);
# wired from the static node setting `search.result_page.enabled`
# (node.py) — flipping it mid-flight would split the ledger's
# round-trip accounting across two regimes. With the flag False the
# general path keeps the legacy collect byte-for-byte.
RESULT_PAGE = False

# transfer ledger + device-memory accounting (telemetry/ledger.py):
# module-level handles — the guards on the query path are one attribute
# load, the tracer/fault-injector no-op discipline
_LEDGER = TELEMETRY.ledger
_DEVMEM = TELEMETRY.device_memory
_FLIGHT = TELEMETRY.flight
_CHURN = TELEMETRY.churn
# query insights (telemetry/insights.py, ISSUE 15): per-shape cost
# attribution — the envelope notes every completed sub-request at wave
# merge, joined to its interned template signature / structural hash
_INSIGHTS = TELEMETRY.insights


def _item_shape(node, body: dict) -> Tuple[str, str]:
    """(shape id, kind) for one envelope item: the interned template's
    signature when the item interned (`node` is the QueryTemplate the
    parse loop resolved — no second intern walk), else the structural
    hash of the raw query body."""
    from opensearch_tpu.telemetry.insights import (
        structural_shape, template_shape)
    if isinstance(node, dsl.QueryTemplate):
        return template_shape(node.sig), "template"
    return structural_shape(body.get("query")), "hash"


def _live_sig(seg) -> bytes:
    """Packed live-mask bytes — the skip key delta publish compares to
    decide whether a refresh must re-ship a segment's liveness bitmap
    at all (ISSUE 16 tentpole d). One packbits over num_docs bools per
    segment per refresh, write-path only."""
    return np.packbits(np.asarray(seg.live, dtype=bool)).tobytes()  # sync-ok: host -- seg.live is the engine's host-side bitmap


def _shape_sig(tree, prefix="") -> tuple:
    """Flattened (path, shape, dtype) signature of a device pytree — the
    shape-bucket identity that decides XLA executable reuse (plan
    signatures embed input shapes, so two segments with identical device
    array shapes share every compiled executable). Power-of-two padding
    (ops/device_segment.py) makes collisions the COMMON case by design;
    the churn ledger's recompile/warmup-hit verdict keys on this."""
    if isinstance(tree, dict):
        out = []
        for k in sorted(tree):
            out.extend(_shape_sig(tree[k], f"{prefix}{k}."))
        return tuple(out)
    return ((prefix, tuple(getattr(tree, "shape", ())),
             str(getattr(tree, "dtype", ""))),)

# live ShardReaders, sampled by the corpus-columns memory gauge: weak
# refs so a dropped reader (closed index, finished test) leaves the
# gauge without an unregistration hook
_LIVE_READERS: "weakref.WeakSet" = weakref.WeakSet()


def _corpus_memory_stats() -> dict:
    readers = list(_LIVE_READERS)
    return {"live_bytes": sum(r.device_bytes for r in readers),
            "segments": sum(len(r.segments) for r in readers),
            "readers": len(readers)}


def _agg_const_memory_stats() -> dict:
    """Fused-agg executable constants (aggs/engine.py stashes the byte
    map on each segment): summed over LIVE readers' segments only, so
    index deletes, shard closes and clone replacements all leave the
    gauge by construction."""
    tables = [getattr(seg, "_agg_const_bytes", None)
              for r in list(_LIVE_READERS) for seg in r.segments]
    return {"live_bytes": sum(sum(t.values()) for t in tables if t),
            "entries": sum(len(t) for t in tables if t)}


_DEVMEM.add_provider("corpus_columns", _corpus_memory_stats)
_DEVMEM.add_provider("agg_constants", _agg_const_memory_stats)


# --------------------------------------------------------------- shard reader

class ShardReader:
    """Holds a shard's sealed segments + their device images.

    Reference: the Engine.Searcher / ReaderContext pair pinned by
    search/SearchService.java:585 createContext.

    Concurrent-publish contract (ISSUE 13, refresh/merge while queries
    fly): `segments` and `device` are views over ONE atomically-swapped
    `_published` pair — every mutation builds fresh lists and publishes
    them in a single attribute store, so a search thread can never see
    segment i paired with another segment's device arrays. Readers that
    need the pair must take `snapshot()` ONCE (one attribute read) and
    zip the result; reading the two properties separately can straddle
    a publish. Writers (the refreshing/merging thread) serialize on
    `_publish_lock`; readers stay lock-free."""

    def __init__(self, mapper: MapperService, segments: Optional[List[Segment]] = None,
                 index_name: str = "_index"):
        self.mapper = mapper
        self.index_name = index_name
        # (segments, device) published as one tuple — see class doc
        self._published: Tuple[List[Segment],
                               List[Tuple[Dict, DeviceSegmentMeta]]] = \
            ([], [])
        self._publish_lock = threading.Lock()
        self._stats_cache: Optional[ShardStats] = None
        self._seg_bytes: Dict[str, int] = {}    # seg_id → device bytes
        # segment-keyed memo carry (ISSUE 16 tentpole b, gate-lint row):
        # OFF by default — a publish drops the whole ShardStats memo
        # exactly as before; ON, _build_stats copies still-valid interned
        # entries into the fresh memo (see compile.carry_memo)
        self.memo_carry = False
        # the retiring ShardStats a publish displaced — carry_memo's
        # source (publish sites null _stats_cache, so without this
        # stash the carry would never see the old memo)
        self._carry_prev: Optional[ShardStats] = None
        # novel device shape fingerprints accumulated by uploads since
        # the last take_novel_shapes() — the precompiler's trigger feed.
        # Swapped wholesale on take; a racing append into the retiring
        # list can drop a fingerprint, which only delays (never breaks)
        # precompilation — the warmup replay covers the whole registry.
        self._novel_shapes: List[str] = []
        # last-uploaded packed live mask per seg_id, kept only while
        # delta publish is on: lets a refresh skip the per-segment
        # live-mask re-upload when no delete touched the mask
        self._live_sigs: Dict[str, bytes] = {}
        # staged-publish barrier (ISSUE 16 tentpole a, barrier mode):
        # while a publisher holds the stage, mutations build `_staged`
        # instead of `_published`; only a thread inside staged_visible()
        # (the precompile replay) sees the staged pair — every serving
        # thread keeps reading the old published pair until commit, so
        # queries never observe a segment set whose executables were
        # not compiled yet.
        self._staged: Optional[Tuple[List[Segment],
                                     List[Tuple[Dict,
                                                DeviceSegmentMeta]]]] = None
        self._staged_stats: Optional[ShardStats] = None
        self._staging = False
        self._stage_tls = threading.local()
        self._stage_lock = threading.Lock()
        _LIVE_READERS.add(self)
        for seg in (segments or []):
            self.add_segment(seg)

    @property
    def segments(self) -> List[Segment]:
        return self._published[0]

    @property
    def device(self) -> List[Tuple[Dict, DeviceSegmentMeta]]:
        return self._published[1]

    def snapshot(self) -> Tuple[List[Segment],
                                List[Tuple[Dict, DeviceSegmentMeta]]]:
        """One consistent (segments, device) pair — the per-request
        anchor every query/fetch phase must zip from. On the barrier
        replay thread (staged_visible) the staged pair IS the pair."""
        if getattr(self._stage_tls, "on", False) and \
                self._staged is not None:
            return self._staged
        return self._published

    @property
    def device_bytes(self) -> int:
        """Live device bytes held by this reader's segment images —
        the corpus-columns slice of the device-memory stats."""
        return sum(self._seg_bytes.values())

    # ------------------------------------------------- staged publish

    def _cur_pair_locked(self):
        """The pair mutations build on: the staged pair while a barrier
        publish is open, the published pair otherwise. Caller holds
        _publish_lock."""
        return self._staged if self._staging else self._published

    def _set_pair_locked(self, pair) -> None:
        """Install a mutated pair: into the stage while a barrier
        publish is open (the live published pair — and its stats cache
        — keep serving untouched), directly into _published otherwise.
        Caller holds _publish_lock."""
        if self._staging:
            self._staged = pair
            self._staged_stats = None
        else:
            self._published = pair
            self._retire_stats_locked()

    def begin_staged_publish(self) -> None:
        """Open a barrier publish: subsequent mutations land in a
        staged copy of the published pair, invisible to serving threads
        until commit_staged_publish(). Single-publisher: a concurrent
        refresh/merge blocks here until the holder commits."""
        self._stage_lock.acquire()
        with self._publish_lock:
            self._staged = self._published
            self._staged_stats = self._stats_cache
            self._staging = True

    def commit_staged_publish(self) -> None:
        """Atomically publish the staged pair (with whatever stats the
        precompile replay built against it — its memo already holds the
        carried + freshly-compiled bundles) and release the stage."""
        try:
            with self._publish_lock:
                pair, stats = self._staged, self._staged_stats
                self._staging = False
                self._staged = None
                self._staged_stats = None
                if pair is not None and pair is not self._published:
                    self._retire_stats_locked()
                    self._published = pair
                    self._stats_cache = stats
        finally:
            self._stage_lock.release()

    @contextmanager
    def staged_visible(self):
        """Make the staged pair THIS thread's snapshot source — the
        precompile replay runs its warm searches under this, compiling
        against the exact pair the commit will publish."""
        prev = getattr(self._stage_tls, "on", False)
        self._stage_tls.on = True
        try:
            yield
        finally:
            self._stage_tls.on = prev

    def add_segment(self, seg: Segment):
        # delta publish (ISSUE 16 tentpole d): gated inside
        # publish_segment — disabled it IS upload_segment and
        # xfer == resident bytes, byte-for-byte the legacy accounting
        arrays, meta, xfer = _devseg.publish_segment(seg)
        nb = tree_nbytes(arrays)
        with self._publish_lock:
            segs, dev = self._cur_pair_locked()
            self._set_pair_locked((segs + [seg], dev + [(arrays, meta)]))
            self._seg_bytes[seg.seg_id] = nb
        if _devseg.DELTA_PUBLISH:
            self._live_sigs[seg.seg_id] = _live_sig(seg)
        if _LEDGER.enabled:
            _LEDGER.record("upload.corpus", "h2d", xfer)
        # churn attribution (ISSUE 13): the seen-shape set is fed on
        # EVERY upload (the verdict is only honest if pre-enable uploads
        # count); the per-event scope records only while a refresh/merge
        # holds one bound. The signature is the TRUE executable-reuse
        # identity: meta.compile_key() (the constants traced programs
        # close over) + every device array's (path, shape, dtype).
        fp = struct_fingerprint((meta.compile_key(), _shape_sig(arrays)))
        known = _CHURN.observe_shape(fp)
        if not known:
            ns = self._novel_shapes
            ns.append(fp)
            if len(ns) > 64:        # bounded when nothing drains it
                del ns[:len(ns) - 64]
        cs = _CHURN.current()
        if cs is not None:
            cs.note_upload(seg.seg_id, xfer, known)

    def remove_segment(self, seg_id: str):
        with self._publish_lock:
            segs, dev = self._cur_pair_locked()
            for i, seg in enumerate(segs):
                if seg.seg_id == seg_id:
                    self._set_pair_locked((segs[:i] + segs[i + 1:],
                                           dev[:i] + dev[i + 1:]))
                    self._seg_bytes.pop(seg_id, None)
                    self._live_sigs.pop(seg_id, None)
                    return

    def notify_deletes(self, seg: Segment):
        live_nbytes = None
        with self._publish_lock:
            segs, dev = self._cur_pair_locked()
            for i, s in enumerate(segs):
                if s is seg:
                    arrays, meta = dev[i]
                    pair = (segs,
                            dev[:i] + [(refresh_live(arrays, seg), meta)]
                            + dev[i + 1:])
                    # segments list unchanged → the stats cache (and its
                    # memo) stays valid; only the device pair re-publishes
                    if self._staging:
                        self._staged = pair
                    else:
                        self._published = pair
                    live_nbytes = int(arrays["live"].nbytes)
                    break
        if live_nbytes is not None:
            if _devseg.DELTA_PUBLISH:
                self._live_sigs[seg.seg_id] = _live_sig(seg)
            if _LEDGER.enabled:
                # only the liveness bitmap re-uploads
                _LEDGER.record("upload.corpus", "h2d", live_nbytes)
            cs = _CHURN.current()
            if cs is not None:
                cs.note_live_mask(live_nbytes)

    def update_segment(self, seg: Segment):
        """Adopt a possibly-replaced segment object with the same id
        (recovery/segment-replication installs clone_for_copy objects):
        shared immutable columns keep their device image, only the live
        mask re-uploads; a genuinely different segment re-uploads fully."""
        segs = (self._staged if self._staging else self._published)[0]
        for i, s in enumerate(segs):
            if s.seg_id != seg.seg_id:
                continue
            if s is seg or s.post_docs is seg.post_docs:
                if _devseg.DELTA_PUBLISH and s is seg:
                    # delta publish (ISSUE 16 tentpole d): the reader
                    # already holds this exact object — when the live
                    # mask is byte-identical to the last uploaded one,
                    # the refresh ships NOTHING for this segment (the
                    # legacy path re-uploads every segment's mask every
                    # refresh). The published pair and stats cache stay
                    # untouched: nothing changed.
                    sig = _live_sig(seg)
                    if self._live_sigs.get(seg.seg_id) == sig:
                        return
                live_nbytes = None
                with self._publish_lock:
                    segs, dev = self._cur_pair_locked()
                    for j, sj in enumerate(segs):
                        if sj.seg_id == seg.seg_id:
                            arrays, meta = dev[j]
                            self._set_pair_locked((
                                segs[:j] + [seg] + segs[j + 1:],
                                dev[:j]
                                + [(refresh_live(arrays, seg), meta)]
                                + dev[j + 1:]))
                            live_nbytes = int(arrays["live"].nbytes)
                            break
                if live_nbytes is not None:
                    if _devseg.DELTA_PUBLISH:
                        self._live_sigs[seg.seg_id] = _live_sig(seg)
                    if _LEDGER.enabled:
                        _LEDGER.record("upload.corpus", "h2d",
                                       live_nbytes)
                    cs = _CHURN.current()
                    if cs is not None:
                        cs.note_live_mask(live_nbytes)
            else:
                arrays, meta, xfer = _devseg.publish_segment(seg)
                nb = tree_nbytes(arrays)
                with self._publish_lock:
                    segs, dev = self._cur_pair_locked()
                    for j, sj in enumerate(segs):
                        if sj.seg_id == seg.seg_id:
                            self._set_pair_locked((
                                segs[:j] + [seg] + segs[j + 1:],
                                dev[:j] + [(arrays, meta)]
                                + dev[j + 1:]))
                            self._seg_bytes[seg.seg_id] = nb
                            break
                if _devseg.DELTA_PUBLISH:
                    self._live_sigs[seg.seg_id] = _live_sig(seg)
                if _LEDGER.enabled:
                    _LEDGER.record("upload.corpus", "h2d", xfer)
                fp = struct_fingerprint((meta.compile_key(),
                                         _shape_sig(arrays)))
                known = _CHURN.observe_shape(fp)
                if not known:
                    self._novel_shapes.append(fp)
                cs = _CHURN.current()
                if cs is not None:
                    cs.note_upload(seg.seg_id, xfer, known)
            return
        self.add_segment(seg)

    @property
    def num_docs(self) -> int:
        return sum(s.live_doc_count for s in self.segments)

    def stats(self) -> ShardStats:
        # cached while the segment list is stable: ShardStats carries the
        # per-term idf memo, so reuse across requests is the win (deletes
        # don't move doc_freq until merge, same as Lucene)
        return self.stats_snapshot()[0]

    def stats_snapshot(self) -> Tuple[ShardStats, List[Segment],
                                      List[Tuple[Dict,
                                                 DeviceSegmentMeta]]]:
        """The per-request anchor under concurrent publish: a
        (ShardStats, segments, device) triple that is mutually
        consistent — the stats (and its interned-plan memo) were built
        for exactly the returned segment list, and the device list is
        its pair. Retries if a refresh publishes mid-build (rare; the
        loop converges as soon as one read sees a stable pair)."""
        if getattr(self._stage_tls, "on", False):
            # barrier-publish replay thread: snapshot the STAGED pair —
            # the stats built here (memo carry + compiled bundles)
            # become the published cache at commit
            with self._publish_lock:
                pair = self._staged
                stats = self._staged_stats
            if pair is not None:
                if stats is None or stats.segments != pair[0]:
                    stats = self._build_stats(pair[0])
                    self._staged_stats = stats
                return stats, pair[0], pair[1]
        while True:
            pub = self._published
            stats = self._stats_cache
            if stats is None or stats.segments != pub[0]:
                stats = self._build_stats(pub[0])
                self._stats_cache = stats
            if self._published is pub:
                return stats, pub[0], pub[1]

    def _retire_stats_locked(self) -> None:
        """Invalidate the stats cache on publish; with memo carry on,
        stash the retiring stats so the next build can copy still-valid
        interned entries out of its memo. Caller holds _publish_lock."""
        if self.memo_carry and self._stats_cache is not None:
            self._carry_prev = self._stats_cache
        self._stats_cache = None

    def _build_stats(self, segments: List[Segment]) -> ShardStats:
        """Build the ShardStats for a published segment list. With memo
        carry ON (ISSUE 16 tentpole b) the retiring cache's still-valid
        interned entries copy into the fresh memo instead of dropping
        wholesale — see compile.carry_memo for the per-family rules.
        The carry copies into a FRESH RotatingMemo (never reuses the
        old object): an in-flight query holding the old snapshot keeps
        writing old-list-aligned bundles into the OLD memo, harmlessly."""
        stats = ShardStats(segments)
        stats.built_mapper_version = getattr(self.mapper, "version", 0)
        old = self._stats_cache
        if old is None:
            old = self._carry_prev
        if self.memo_carry and old is not None and \
                getattr(old, "built_mapper_version", None) == \
                stats.built_mapper_version:
            stats.carry_report = carry_memo(old, stats)
            self._carry_prev = None
        return stats

    def rebuild_stats(self) -> ShardStats:
        """Eagerly (re)build + cache the stats for the CURRENT published
        pair — called by the refreshing thread right after a publish so
        the carry pass runs OFF the serving path: serving threads find a
        warm cache instead of paying the rebuild under a query."""
        return self.stats_snapshot()[0]

    def take_novel_shapes(self) -> List[str]:
        """Drain the novel device-shape fingerprints uploads accumulated
        since the last take — the precompiler's per-publish trigger feed
        (ISSUE 16 tentpole a)."""
        shapes, self._novel_shapes = self._novel_shapes, []
        return shapes


class PinnedReader:
    """Point-in-time snapshot of a ShardReader: segments are immutable, so
    pinning is just holding references to the current segment list + device
    images (reference: ReaderContext / PitReaderContext keeping the Lucene
    searcher open across requests, search/internal/PitReaderContext.java)."""

    def __init__(self, reader: ShardReader):
        self.mapper = reader.mapper
        self.index_name = reader.index_name
        # one snapshot() read: a consistent pair even while a
        # concurrent refresh publishes
        segments, device = reader.snapshot()
        self.segments = list(segments)
        self.device = list(device)
        self._stats = ShardStats(self.segments)

    @property
    def num_docs(self) -> int:
        return sum(s.live_doc_count for s in self.segments)

    def stats(self) -> ShardStats:
        return self._stats

    def snapshot(self):
        """A pinned reader IS a snapshot: the pair never changes."""
        return self.segments, self.device

    def stats_snapshot(self):
        return self._stats, self.segments, self.device


# ------------------------------------------------------------------ execution

_JIT_CACHE: Dict[Any, Any] = {}

# executable cache size for the device-memory stats: XLA does not expose
# per-executable HBM bytes portably, so this class reports counts (the
# raw backend bytes land in the `hbm` block when available)
_DEVMEM.add_provider(
    "compiled_executables",
    lambda: {"entries": len(_JIT_CACHE)})


# per-THREAD compile accounting + the first-call compile timer moved to
# telemetry/kernels.py (ISSUE 19) so the ops-layer jit sites (knn
# k-means, delta-publish expanders) share one census wrapper without an
# import cycle; the executor names stay as aliases — warmup.py and the
# ingest-serving tests import them from here
from opensearch_tpu.telemetry.kernels import (  # noqa: E402
    THREAD_COMPILES as _THREAD_COMPILES, note_compile as _note_compile,
    offpath_compiles, timed_first_call as _timed_first_call)

# kernel profiler handle (ISSUE 19): census registration is always-on
# (compile-time only); the sampled dispatch timer rides the gate
_KERNELS = TELEMETRY.kernels


def _plan_family(plan: Plan, agg_plans=()) -> str:
    """Kernel-family label for one compiled plan tree (the census/
    timing vocabulary, telemetry/kernels.py): vector leaves win (their
    kernels dominate the program), then the agg envelope, then the
    dense BM25 kernel build_query_phase lowers to."""
    def walk(p):
        if p.kind == "knn":
            return "knn"
        if p.kind == "maxsim":
            comp = p.static[2] if len(p.static) > 2 else None
            return "maxsim_adc" if comp == "pq" else "maxsim"
        for c in p.children:
            f = walk(c)
            if f is not None:
                return f
        return None
    fam = walk(plan)
    if fam is not None:
        return fam
    return "agg_env" if agg_plans else "bm25_dense"


def _layout_batch(layout) -> int:
    """Batch rows of a packed envelope layout (every stacked leaf shares
    the padded batch axis)."""
    for _off, shape, _dt in layout:
        if shape:
            return int(shape[0])
    return 0


def _env_shape(layout, k: int, meta) -> str:
    """Shape-bucket string for an envelope executable: padded batch,
    top-k and the segment's padded doc axis — the axes the compile key
    buckets on."""
    return f"b{_layout_batch(layout)}/k{k}/d{meta.d_pad}"


def _plan_cost(plan: Plan, meta, batch: int = 1):
    """Analytic (flops, bytes) fallback for the census when the backend
    exposes no cost model: the scan formulas (telemetry/scan.py) give
    the bytes the kernel touches; flops are estimated at 2 ops per f32
    lane (one multiply-add) — coarse, but roofline-stable, and marked
    `cost_source: analytic` so readers know the provenance."""
    from opensearch_tpu.telemetry.scan import (
        DENSE_LANE_BYTES, POSTING_BLOCK_BYTES, plan_scan_blocks,
        plan_scan_extra)
    per_row = (plan_scan_blocks(plan) * POSTING_BLOCK_BYTES
               + meta.d_pad * DENSE_LANE_BYTES + plan_scan_extra(plan))
    nbytes = float(per_row * max(1, batch))
    return nbytes / 4.0 * 2.0, nbytes

# msearch phase accounting (?profile analog for the batch path): per-batch
# milliseconds land in the always-on telemetry metrics registry as
# per-phase histograms — visible on _nodes/stats, `bench.py --telemetry`
# and tools/profile_host.py (replaces the old module-global accumulator)
MSEARCH_PHASE_NAMES = ("parse", "compile_group", "stack_pack_dispatch",
                       "device_get", "respond")
_PHASE_HISTS = {name: TELEMETRY.metrics.histogram(f"msearch.phase.{name}_ms")
                for name in MSEARCH_PHASE_NAMES}

# query-template interning (ISSUE 5): repeated-structure msearch batches
# skip parse+compile via the per-reader (template, literals) bundle memo.
# The env switch exists for A/B parity testing (tests/
# test_template_interning.py), not as a serving configuration.
TEMPLATE_INTERNING = os.environ.get(
    "OPENSEARCH_TPU_DISABLE_INTERNING") != "1"
_BUNDLE_HITS = TELEMETRY.metrics.counter("msearch.template.bundle_hits")
_BUNDLE_MISSES = TELEMETRY.metrics.counter("msearch.template.bundle_misses")
_INTERN_FALLBACKS = TELEMETRY.metrics.counter("msearch.template.fallbacks")

# ------------------------------------------------------ wave-pipeline engine
#
# Overlapped multi-wave dispatch (ROADMAP item 1): a large msearch batch
# splits into power-of-two-bucketed waves so wave N+1's host work
# (intern/stack/pack/upload) and async dispatch run while wave N's
# device_get is in flight on a collector thread. Round 7 measured
# two-wave pipelining as a wash; PR 5 since cut the host cost 2.6× and
# the round-9 ledger proved the wall is the dispatch-sync, not byte
# volume — the overlap now pays (PROFILE.md round 10). Wave sizes stay
# power-of-two buckets so the warmup registry's (plan-struct,
# shape-bucket, b_pad) signatures are reused across wave splits.

# bench --waves / tests override; 0/None = the auto policy below.
# OPENSEARCH_TPU_MSEARCH_WAVES seeds it for whole-process A/B runs.
try:
    FORCED_WAVES: Optional[int] = int(os.environ.get(
        "OPENSEARCH_TPU_MSEARCH_WAVES", "0")) or None
except ValueError:
    FORCED_WAVES = None

# below 2× this many batchable items a split cannot win: each extra wave
# is an extra device_get round trip, and the host work it could hide is
# O(items in the NEXT wave)
MSEARCH_MIN_WAVE_ITEMS = 128
MSEARCH_MAX_WAVES = 4
# bounded in-flight window (double buffering): at most this many waves
# dispatched-but-uncollected, so device memory holds at most two waves
# of input envelopes + result pages at any instant
MSEARCH_INFLIGHT_WINDOW = 2


# lazily probed once: overlap only pays where the collect wall is IDLE
# host time (a real accelerator / the tunnel). On the CPU fallback the
# "device" compute runs on the same cores as the host prepare, so
# pipelining just contends — measured at parity-to-worse (PROFILE.md
# round 10 re-confirms round 7's CPU number). None = not probed yet.
_OVERLAP_CAPABLE: Optional[bool] = None


def _overlap_capable() -> bool:
    global _OVERLAP_CAPABLE
    if _OVERLAP_CAPABLE is None:
        try:
            _OVERLAP_CAPABLE = jax.devices()[0].platform != "cpu"
        except Exception:  # except-ok: backend probe must never fail a search; unprobeable backends serve single-wave
            _OVERLAP_CAPABLE = False
    return _OVERLAP_CAPABLE


def _effective_waves(n_batchable: int) -> int:
    """Wave-count policy for an envelope of `n_batchable` items:
    FORCED_WAVES (bench --waves / env / tests) always wins; otherwise
    split only when every wave keeps MSEARCH_MIN_WAVE_ITEMS rows and
    the backend can actually overlap (see _overlap_capable)."""
    if FORCED_WAVES:
        return max(int(FORCED_WAVES), 1)
    if n_batchable < 2 * MSEARCH_MIN_WAVE_ITEMS or not _overlap_capable():
        return 1
    return min(MSEARCH_MAX_WAVES, n_batchable // MSEARCH_MIN_WAVE_ITEMS)


def _wave_sizes(n: int, n_waves: int) -> List[int]:
    """Split n items into power-of-two-bucketed wave sizes (the last
    wave takes the remainder; pad_bucket inside each wave's groups keeps
    its executables on reused shape buckets)."""
    if n_waves <= 1 or n <= 1:
        return [n]
    per = pad_bucket(-(-n // n_waves), minimum=1)
    sizes: List[int] = []
    left = n
    while left > 0:
        sizes.append(min(per, left))
        left -= per
    return sizes


def _release_wave_gauges(state: Optional[dict]) -> None:
    """Zero a wave state's `wave_buffer_bytes` marker and release the
    device-memory gauge. Idempotent (the marker is the guard), and the
    ONLY way any path releases it — finish halves at their fetch
    completion, _collect_wave's finally, and the pipeline's backstop
    all funnel here, so the release semantics live in one place."""
    if not state:
        return
    leaked = state.get("wave_buffer_bytes", 0)
    if leaked:
        state["wave_buffer_bytes"] = 0
        _DEVMEM.adjust("wave_buffers", -leaked)


class _StagingPool:
    """Double-buffered host staging for packed input envelopes.

    `jnp.asarray` on the CPU backend is ZERO-COPY (the device array
    aliases the host buffer), so a staging buffer may only be reused
    once its wave's device_get has completed — the one point where the
    dispatched program has provably finished reading its inputs. The
    pipeline acquires at pack time (main thread) and releases from the
    collector after the wave's collect (collector thread), hence the
    lock. Exact-size free lists: steady-state waves repeat identical
    envelope sizes, so after the first in-flight window fills, packing
    allocates nothing per wave. (True XLA buffer donation was measured
    unusable here: the int32 input envelope never shape/dtype-matches
    the f32 result rows, so donate_argnums degrades to a no-op with a
    per-dispatch warning — see README "Wave pipeline".)"""

    MAX_PER_SIZE = 4            # ≥ in-flight window, double-buffered
    MAX_BYTES = 64 << 20

    def __init__(self):
        self._lock = threading.Lock()
        self._free: Dict[int, List[np.ndarray]] = {}
        self._bytes = 0

    def acquire(self, n: int) -> np.ndarray:
        with self._lock:
            bufs = self._free.get(n)
            if bufs:
                buf = bufs.pop()
                self._bytes -= buf.nbytes
                return buf
        return np.empty(n, np.int32)

    def release(self, buf: np.ndarray) -> None:
        with self._lock:
            bufs = self._free.setdefault(int(buf.shape[0]), [])
            if len(bufs) < self.MAX_PER_SIZE and \
                    self._bytes + buf.nbytes <= self.MAX_BYTES:
                bufs.append(buf)
                self._bytes += buf.nbytes


class _MsearchWave:
    """One wave of the msearch pipeline: its item indices, the payload
    the prepare half consumes, and the dispatch/collect bookkeeping the
    overlap attribution is computed from."""

    __slots__ = ("kind", "items", "payload", "state", "scope", "ph",
                 "raise_errors", "window", "prep_t0", "prep_t1",
                 "collect_t0", "collect_t1", "error", "index",
                 "timeline", "breaker_probe")

    def __init__(self, kind: str, items: List[int], payload,
                 raise_errors: bool = False):
        self.kind = kind            # "plain" | "hybrid"
        self.items = items          # sub-request indices this wave owns
        self.payload = payload      # batchable entries / hybrid items
        self.state: Optional[dict] = None
        self.scope = None           # wave-local LedgerScope (or None)
        self.ph = dict.fromkeys(MSEARCH_PHASE_NAMES, 0.0)
        self.raise_errors = raise_errors
        self.window = None          # in-flight window semaphore slot
        self.prep_t0 = self.prep_t1 = 0.0
        self.collect_t0 = self.collect_t1 = 0.0
        self.error: Optional[Exception] = None
        self.index = 0              # envelope-local wave id (0-based)
        self.timeline = None        # request Timeline (or None) — rides
        # the wave record across the collector-thread boundary so the
        # collect event lands on the owning request's lifecycle
        self.breaker_probe = False  # this wave is the device-memory
        # breaker's single half-open probe (common/admission.py)


class _TimelineFan:
    """Fan one wave's lifecycle events out to every owning request's
    timeline. When the wave scheduler (search/scheduler.py) packs
    sub-requests from DIFFERENT requests into one shared wave, the
    coalesce/dispatch/collect/overlap events must land on each
    request's own lifecycle — `co_batched` then counts CROSS-REQUEST
    siblings, the number the scheduler is judged by. Appends are
    GIL-atomic and each timeline is read only after its own request
    completes, the same contract the collector thread already rides."""

    __slots__ = ("timelines",)

    def __init__(self, timelines):
        self.timelines = timelines

    def event(self, name: str, **fields) -> None:
        for tl in self.timelines:
            tl.event(name, **fields)


def _distinct_timelines(timelines, items=None):
    """The identity-distinct non-None timelines of `timelines`
    (optionally restricted to positions `items`), insertion-ordered —
    one request's timeline appears once however many of its
    sub-requests share the wave."""
    seen: Dict[int, Any] = {}
    for i in (items if items is not None else range(len(timelines))):
        tl = timelines[i]
        if tl is not None and id(tl) not in seen:
            seen[id(tl)] = tl
    return list(seen.values())


class _WaveCollector:
    """Collector thread for the overlapped pipeline: pulls dispatched
    waves off the queue and runs their device_get + response assembly
    while the main thread prepares the next wave. The in-flight window
    is a semaphore acquired BEFORE the next wave's prepare
    (acquire_slot) and released when a wave's collect completes, so at
    most `window` waves are device-resident at any instant."""

    def __init__(self, collect_fn, window: int):
        self._collect = collect_fn
        # the window is enforced BEFORE prepare (acquire_slot), not at
        # submit: a wave is device-resident from its dispatch inside
        # prepare, so bounding the queue alone would let window+1 waves
        # of envelopes + result pages sit on the device
        self._window = threading.Semaphore(max(window, 1))
        self._q: "queue.Queue" = queue.Queue()
        self._thread = threading.Thread(
            target=self._loop, name="msearch-wave-collector", daemon=True)
        self._thread.start()

    def acquire_slot(self) -> threading.Semaphore:
        """Block until an in-flight slot frees (a prior wave's collect
        completed); the returned semaphore is released by that wave's
        _collect_wave finally."""
        self._window.acquire()
        return self._window

    def submit(self, wave: _MsearchWave) -> None:
        self._q.put(wave)

    def drain(self) -> None:
        """Flush and join — called on EVERY pipeline exit path, so a
        cancellation or mid-flight error still collects the dispatched
        waves and releases their buffers."""
        self._q.put(None)
        self._thread.join()

    def _loop(self) -> None:
        while True:
            wave = self._q.get()
            if wave is None:
                return
            # scope rides the wave record across the thread boundary;
            # the collect callback re-binds it (sync-lint's collector-
            # thread pattern) and attributes its own device_get region
            self._collect(wave)


def _base_response(took_ms: int, total: int, max_score, hits: list) -> dict:
    """The msearch envelope's response skeleton — shared by the batched
    respond path, the match-none short-circuit and the request-cache
    renderer so all three stay byte-identical."""
    return {
        "took": took_ms,
        "timed_out": False,
        "_shards": {"total": 1, "successful": 1, "skipped": 0,
                    "failed": 0},
        "hits": {"total": {"value": total, "relation": "eq"},
                 "max_score": max_score, "hits": hits},
    }


def _item_error(e: OpenSearchTpuError) -> dict:
    """Per-item msearch error object (reference:
    TransportMultiSearchAction wraps each failed sub-request instead of
    failing siblings)."""
    return {"error": e.to_xcontent(), "status": e.status}


def _timed_out_item(start: float) -> dict:
    """A sub-request the envelope's deadline expired before launching:
    rendered as a zero-hit partial response with timed_out: true (the
    reference's per-request timeout shape), never an error object —
    timeout is a budget decision, not a failure."""
    resp = _base_response(int((time.monotonic() - start) * 1000), 0,
                          None, [])
    resp["timed_out"] = True
    return resp


# ------------------------------------------------------- transfer accounting
#
# Channel decomposition of the device_get result layouts: bytes come from
# array nbytes (metadata — no device sync), padding from the difference
# against the actually-transferred buffer, so per-channel bytes always
# sum to the transferred total (tests/test_transfer_ledger.py pins the
# conservation property).

def _ledger_unbatched_collect(scope, fetched, ms: float) -> None:
    """One general-path collect: per segment (top_keys, top_scores,
    top_idx, total, agg_outs) tuples fetched in one round trip."""
    sort_b = score_b = id_b = tot_b = agg_b = 0
    for outs in fetched:
        top_keys, top_scores, top_idx, seg_total, agg_outs = outs
        sort_b += int(np.asarray(top_keys).nbytes)
        score_b += int(np.asarray(top_scores).nbytes)
        id_b += int(np.asarray(top_idx).nbytes)
        tot_b += int(np.asarray(seg_total).nbytes)
        if agg_outs:
            agg_b += sum(int(np.asarray(v).nbytes)
                         for v in jax.tree_util.tree_leaves(agg_outs))
    wave = _LEDGER.new_wave()
    for channel, b in (("sort_keys", sort_b), ("scores", score_b),
                       ("topk_ids", id_b), ("totals", tot_b),
                       ("agg_buffers", agg_b)):
        if b:
            _LEDGER.record(channel, "d2h", b, wave=wave, scope=scope)
    _LEDGER.note_device_get(
        ms, nbytes=sort_b + score_b + id_b + tot_b + agg_b, scope=scope)


def _ledger_page_collect(scope, page_np, agg_fetched, ms: float) -> None:
    """One result-page collect (RESULT_PAGE on): the packed int32 page
    plus the per-segment agg buffers, fetched together in EXACTLY one
    round trip — the whole wave lands in the `result_page` channel,
    byte-exact against the transferred total (the conservation
    invariant holds because the channel bytes ARE the fetched nbytes)."""
    nb = int(np.asarray(page_np).nbytes)
    nb += sum(int(np.asarray(v).nbytes)
              for v in jax.tree_util.tree_leaves(agg_fetched))
    wave = _LEDGER.new_wave()
    _LEDGER.record("result_page", "d2h", nb, wave=wave, scope=scope)
    _LEDGER.note_device_get(ms, nbytes=nb, scope=scope)


def _ledger_packed_rows(scope, pending, fetched, actual_bytes: int,
                        ms: float, round_trips: int) -> None:
    """One msearch-envelope wave: [B, 2k+1+W] packed rows per program —
    k scores, k ids, 1 total, W agg-partial floats per row. Real
    channels count only the group's REAL rows (len(idxs)); batch-pad
    rows and combined-fetch column padding both land in `padding` via
    the remainder, so channel bytes sum exactly to the transferred
    total while the decomposition reports payload, not pad."""
    score_b = id_b = tot_b = agg_b = pruned_b = 0
    for (idxs, _seg_i, k_seg, _out, _ol, bm), packed in zip(pending,
                                                            fetched):
        if packed is None:
            continue
        rows = min(len(idxs), packed.shape[0])
        width = packed.shape[1]
        score_b += rows * k_seg * 4
        id_b += rows * k_seg * 4
        tot_b += rows * 4
        if bm:
            # blockmax rows carry one trailing pruned-count lane
            pruned_b += rows * 4
            width -= 1
        agg_b += rows * max(width - 2 * k_seg - 1, 0) * 4
    wave = _LEDGER.new_wave()
    pad_b = max(actual_bytes
                - (score_b + id_b + tot_b + agg_b + pruned_b), 0)
    for channel, b in (("scores", score_b), ("topk_ids", id_b),
                       ("totals", tot_b), ("agg_buffers", agg_b),
                       ("pruned_counts", pruned_b),
                       ("padding", pad_b)):
        if b:
            _LEDGER.record(channel, "d2h", b, wave=wave,
                           round_trips=round_trips, scope=scope)
    _LEDGER.note_device_get(ms, nbytes=actual_bytes, scope=scope,
                            round_trips=round_trips)


def _ledger_hybrid_rows(scope, programs, ms: float) -> None:
    """One hybrid-envelope wave: per program (rows, real_rows, k_seg,
    n_sub) of [rows, n_sub·(2k+4)+1] fused rows — per-sub scores/ids
    plus the (count, min, max, sum_sq) bounds block and the union
    total. Batch-pad rows (rows > real_rows) go to the `padding`
    channel, same as the plain packed path, so the per-channel
    decomposition reports real payload, not pad."""
    score_b = id_b = bounds_b = tot_b = pad_b = 0
    for rows, real_rows, k_seg, n_sub in programs:
        score_b += real_rows * k_seg * n_sub * 4
        id_b += real_rows * k_seg * n_sub * 4
        bounds_b += real_rows * n_sub * 4 * 4
        tot_b += real_rows * 4
        pad_b += (rows - real_rows) * (n_sub * (2 * k_seg + 4) + 1) * 4
    wave = _LEDGER.new_wave()
    for channel, b in (("scores", score_b), ("topk_ids", id_b),
                       ("score_bounds", bounds_b), ("totals", tot_b),
                       ("padding", pad_b)):
        if b:
            _LEDGER.record(channel, "d2h", b, wave=wave, scope=scope)
    _LEDGER.note_device_get(
        ms, nbytes=score_b + id_b + bounds_b + tot_b + pad_b, scope=scope)


def _cache_get_isolated(rc, key):
    """Request-cache read with fault-site + transient-retry wrapping; a
    persistently failing cache degrades to a MISS (recompute), never a
    failed query. The disabled-injector path is the bare cache call —
    the in-memory cache itself has no transient failure modes."""
    if not faults.ENABLED:
        return rc.REQUEST_CACHE.get(key)

    def op():
        faults.fire("request_cache.get")
        return rc.REQUEST_CACHE.get(key)
    try:
        return retry.call_with_retry(op, label="request_cache.get")
    except Exception:   # except-ok: cache-IO isolation -- any failure class degrades to a MISS, never a failed query
        return rc.REQUEST_CACHE._MISS


def _cache_put_isolated(rc, key, value) -> None:
    """Request-cache write with the same wrapping; a failed put is
    dropped (the entry just isn't cached)."""
    if not faults.ENABLED:
        rc.REQUEST_CACHE.put(key, value)
        return

    def op():
        faults.fire("request_cache.put")
        rc.REQUEST_CACHE.put(key, value)
    try:
        retry.call_with_retry(op, label="request_cache.put")
    except Exception:   # except-ok: cache-IO isolation -- a failed put just drops the entry
        pass


# a single interned-plan bundle larger than this never enters the memo:
# its flattened inputs would crowd out a whole generation of normal-sized
# working-set entries for one outlier query shape
_BUNDLE_MEMO_MAX_ENTRY_BYTES = 16 << 20


def _bundle_nbytes(flats) -> int:
    """Approximate host bytes retained by a memoized bundle: the flattened
    per-segment input arrays dominate (plans/signatures are tuples)."""
    if not flats:
        return 0
    return sum(getattr(v, "nbytes", 0) for f in flats if f
               for d in f for v in d.values())


def _item_error_untyped(e: Exception) -> dict:
    """Per-item wrapper for exceptions with no OpenSearchTpuError typing:
    reported as the 500-class failure it is (not relabeled 400 — a raw
    TypeError may just as well be an internal bug as a client error)."""
    return {"error": {"type": "exception",
                      "reason": f"{type(e).__name__}: {e}"},
            "status": 500}


def _run_item_isolated(responses, i: int, raise_item_errors: bool,
                       fn) -> None:
    """Execute one sub-request's work under the per-item failure contract
    (reference TransportMultiSearchAction wraps EVERY per-item exception,
    never the envelope): typed errors render with their own status,
    untyped ones honestly as a 500-class item; fn's non-None return value
    becomes the item's response. raise_item_errors (the B=1 _search
    delegation) propagates instead — error objects are an _msearch-only
    shape."""
    try:
        r = fn()
        if r is not None:
            responses[i] = r
    except OpenSearchTpuError as e:
        if raise_item_errors:
            raise
        responses[i] = _item_error(e)
    except Exception as e:  # except-ok: per-item isolation -- untyped failures render 500-class error items, never fail siblings
        if raise_item_errors:
            raise
        responses[i] = _item_error_untyped(e)


_request_cache_mod = None


def _request_cache():
    """Lazily bound indices.request_cache module: the indices package
    __init__ imports a chain that leads back here (index.shard ->
    executor), so a top-level import would be circular — and a fresh
    function-level import per msearch sub-request is pure sys.modules
    lookup cost on the hot parse loop."""
    global _request_cache_mod
    if _request_cache_mod is None:
        from opensearch_tpu.indices import request_cache
        _request_cache_mod = request_cache
    return _request_cache_mod


def _req_int(body: dict, key: str, default: int) -> int:
    try:
        return int(body.get(key, default))
    except (TypeError, ValueError):
        raise IllegalArgumentError(
            f"Failed to parse int parameter [{key}] with value "
            f"[{body.get(key)!r}]")


def _req_min_score(body: dict):
    raw = body.get("min_score")
    if raw is None:
        return NEG_INF
    try:
        return float(raw)
    except (TypeError, ValueError):
        raise IllegalArgumentError(
            f"Failed to parse float parameter [min_score] with value "
            f"[{raw!r}]")


def build_query_phase(plan: Plan, meta: DeviceSegmentMeta, k: int,
                      sort_mode: str, agg_plans=()):
    """The single-segment query phase as a pure jittable function — the TPU
    program that replaces one ContextIndexSearcher.searchLeaf pass
    (search/internal/ContextIndexSearcher.java:260). Exposed unjitted so the
    graft entry can hand it to the driver's compile check."""

    def run(seg, flat_inputs, sort_key_arr, min_score):
        cursor = [0]
        scores, matches = _eval_plan(plan, seg, flat_inputs, cursor)
        d_pad = seg["live"].shape[0]
        in_seg = jnp.arange(d_pad, dtype=jnp.int32) < meta.num_docs
        # root: only top-level rows are returnable hits — nested child rows
        # participate in scoring solely through the `nested` plan's join
        # (Queries.newNonNestedFilter analog)
        eligible = matches & seg["live"] & seg["root"] & in_seg \
            & (scores >= min_score)
        total = jnp.sum(eligible.astype(jnp.int32))
        keys = scores if sort_mode == "score" else sort_key_arr
        masked = jnp.where(eligible, keys, NEG_INF)
        k_eff = min(k, d_pad)
        top_keys, top_idx = jax.lax.top_k(masked, k_eff)
        top_scores = scores[top_idx]
        agg_outs = []
        if agg_plans:
            eval_aggs(list(agg_plans), seg, flat_inputs, cursor, eligible,
                      agg_outs)
        return top_keys, top_scores, top_idx.astype(jnp.int32), total, agg_outs

    return run


# ---------------------------------------------------- packed input envelope
#
# Round-3 profile (PROFILE.md): on the tunneled device the per-leaf
# jnp.asarray uploads dominated the msearch batch (~1.4s of a ~1.0s-compute
# run — one transfer round trip per leaf). The envelope packs every stacked
# input leaf of a group into ONE int32 buffer host-side; the jitted program
# slices/bitcasts the leaves back out with a static layout, so a whole
# group costs exactly one host→device transfer regardless of leaf count.

def pack_leaves(leaves: List[np.ndarray], pool: Optional[_StagingPool] = None):
    """Concatenate i32/f32/bool leaves into one int32 buffer + layout.
    `pool` (the wave pipeline's staging pool) reuses a released buffer
    of the exact size instead of allocating — steady-state waves pack
    into recycled memory."""
    total = 0
    metas = []
    for leaf in leaves:
        n = int(np.prod(leaf.shape)) if leaf.ndim else 1
        metas.append((total, tuple(leaf.shape), str(leaf.dtype)))
        total += n
    buf = pool.acquire(max(total, 1)) if pool is not None \
        else np.empty(max(total, 1), np.int32)
    for leaf, (off, shape, dtype) in zip(leaves, metas):
        n = int(np.prod(shape)) if shape else 1
        flat = np.ascontiguousarray(leaf).reshape(-1)
        if leaf.dtype == np.float32:
            flat = flat.view(np.int32)
        elif leaf.dtype == np.bool_:
            flat = flat.astype(np.int32)
        elif leaf.dtype != np.int32:
            raise ValueError(f"unsupported envelope dtype [{leaf.dtype}]")
        buf[off:off + n] = flat
    return buf, tuple(metas)


def unpack_leaves(buf, layout):
    """Device-side inverse of pack_leaves (static layout → traced slices)."""
    out = []
    for off, shape, dtype in layout:
        n = int(np.prod(shape)) if shape else 1
        piece = jax.lax.slice(buf, (off,), (off + n,))
        if dtype == "float32":
            piece = jax.lax.bitcast_convert_type(piece, jnp.float32)
        elif dtype == "bool":
            piece = piece.astype(jnp.bool_)
        out.append(piece.reshape(shape))
    return out


def _fill_value(name: str, dtype) -> Any:
    from opensearch_tpu.parallel.distributed import _PAD_FILL
    return _PAD_FILL.get(name, False if dtype == np.bool_ else 0)


def stack_flat_inputs(flats: List[List[Dict[str, np.ndarray]]],
                      with_const: bool = False):
    """Fast batch-stack of per-query flat input trees: grows every leaf to
    the per-position max shape (same envelope semantics as
    parallel.distributed.pad_stack_trees) but via preallocated fills
    instead of per-query np.pad — the host-side hot path of msearch.

    with_const: leaves named in aggs.engine.CONST_INPUT_KEYS (content-
    hashed into the group signature, so identical across the batch) are
    NOT stacked — one copy is packed and the runner maps them with
    in_axes=None, keeping table lookups unbatched so the GEMM agg path's
    one-hot matrices stay shared across the query batch. Returns
    (stacked, treedef, axes) with axes the per-leaf vmap axis list."""
    from opensearch_tpu.search.aggs.engine import CONST_INPUT_KEYS
    b = len(flats)
    treedef = jax.tree_util.tree_structure(flats[0])
    names = [kp[-1].key if hasattr(kp[-1], "key") else ""
             for kp, _ in jax.tree_util.tree_flatten_with_path(flats[0])[0]]
    per_query = [jax.tree_util.tree_leaves(f) for f in flats]
    n_leaves = len(per_query[0])
    stacked = []
    axes: List[Optional[int]] = []
    for li in range(n_leaves):
        if with_const and names[li] in CONST_INPUT_KEYS:
            stacked.append(np.asarray(per_query[0][li]))  # sync-ok: host -- flattened plan inputs are host arrays pre-upload
            axes.append(None)
            continue
        arrs = [np.asarray(q[li]) for q in per_query]  # sync-ok: host -- flattened plan inputs are host arrays pre-upload
        a0 = arrs[0]
        shape = tuple(max(a.shape[d] for a in arrs)
                      for d in range(a0.ndim))
        if all(a.shape == shape for a in arrs):
            out = np.stack(arrs)
        else:
            out = np.full((b, *shape), _fill_value(names[li], a0.dtype),
                          dtype=a0.dtype)
            for qi, a in enumerate(arrs):
                out[(qi, *map(slice, a.shape))] = a
        stacked.append(out)
        axes.append(0)
    return stacked, treedef, axes


def _pack_row(top_scores, top_idx, total):
    """ONE f32 row [k | k | 1] (ints bitcast) so the host fetches a single
    array — each fetch is a full round trip on remote devices."""
    return jnp.concatenate([
        top_scores,
        jax.lax.bitcast_convert_type(top_idx.astype(jnp.int32),
                                     jnp.float32),
        jax.lax.bitcast_convert_type(total[None].astype(jnp.int32),
                                     jnp.float32)])


def _topk_or_empty(masked, k_eff: int):
    """lax.top_k, except k=0 (size=0 agg/count queries) skips the
    selection networks entirely — the dominant device cost for a
    hits-free query."""
    if k_eff == 0:
        return (jnp.zeros((0,), jnp.float32), jnp.zeros((0,), jnp.int32))
    return jax.lax.top_k(masked, k_eff)


# candidate-buffer kernel only pays off while the sorted buffer stays far
# below the dense [d_pad] width; above this lane count the dense
# scatter+top_k path wins (bitonic sort is O(N log^2 N))
CANDIDATE_MAX_LANES = 1 << 14

# the candidate-buffer kernel's exact-windowed segment sum needs the
# distinct-term count bounded; beyond this the dense kernel serves
CANDIDATE_MAX_TERMS = 16


def _candidate_kernel_fits(kind: str, n_terms: int, qb_lanes: int) -> bool:
    """THE candidate-vs-dense decision, shared by _envelope_runner
    (which kernel compiles) and _envelope_kernel (what the scan heat
    map records) so the telemetry's kernel-mix column can never drift
    from the kernel that actually dispatches."""
    return kind == "text" and n_terms <= CANDIDATE_MAX_TERMS \
        and 0 < qb_lanes <= CANDIDATE_MAX_LANES


def build_candidate_query_phase(plan: Plan, meta: DeviceSegmentMeta, k: int,
                                layout, treedef, bm: bool = False):
    """B text queries against one segment, scored in a COMPACT candidate
    buffer instead of a dense per-doc vector.

    The round-3 verdict's block-max/WAND analog: a text clause's matches
    are exactly the union of its terms' postings lanes, so instead of
    scatter-adding into a [d_pad]-wide score vector and top_k-ing 131K
    lanes per query (the round-3 kernel), the gathered [QB·128] lanes are
    sorted by doc id, duplicate docs are segment-summed with a
    cumsum-at-run-ends trick, and top-k runs over the small buffer. HBM
    traffic per query drops from O(d_pad) to O(QB·128).

    Correctness notes: BM25 partials are >= 0 (idf >= 0, boosts
    non-negative), which the monotone-cumsum run-total trick relies on;
    per-term postings list each doc once, so a doc's run length equals its
    distinct matched terms (min_hits / operator=and semantics); top_k on
    ties picks the lowest lane = lowest doc id, matching the dense
    kernel's doc-ascending tie-break."""

    constant = plan.static[0]
    n_terms = plan.static[1] if len(plan.static) > 1 else 1

    def one(seg, flat_inputs, min_score):
        my = flat_inputs[0]
        lane_real = my["ids"] >= 0                    # [QB]
        if bm:
            # block-max phase A (ISSUE 20): per-block upper bounds vs the
            # slice-derived competitive threshold. Non-competitive blocks
            # are redirected to the shared row 0 by the safe_ids gather
            # below, so they ship no postings; the mask is DATA — every
            # shape stays static (retrace-lint clean)
            keep, pruned = blockmax_keep_mask(
                seg, my, my["k1"], n_terms, k, min_score)
            lane_real = lane_real & keep
        else:
            pruned = jnp.int32(0)
        safe_ids = jnp.where(lane_real, my["ids"], 0)
        docs = seg["post_docs"][safe_ids]             # [QB, 128]
        tfs = seg["post_tf"][safe_ids]
        valid = docs >= 0
        safe_docs = jnp.where(valid, docs, 0)
        norm_bytes = seg["norms"][my["row"]][safe_docs]
        dl = seg["length_table"][norm_bytes]
        b = my["b"]
        k1 = my["k1"]
        denom = tfs + k1 * (1.0 - b + b * dl / my["avgdl"])
        partial = my["w"][:, None] * tfs * (k1 + 1.0) / denom
        real = valid & lane_real[:, None]

        n = docs.shape[0] * docs.shape[1]
        big = jnp.int32(2 ** 30)
        doc_key = jnp.where(real, docs, big).reshape(n)
        part = jnp.where(real, partial, 0.0).reshape(n)
        hit = jnp.where(real, 1, 0).astype(jnp.int32).reshape(n)

        sdoc, spart, shit = jax.lax.sort([doc_key, part, hit], num_keys=1)
        is_end = jnp.concatenate([sdoc[:-1] != sdoc[1:],
                                  jnp.ones((1,), bool)])
        # exact windowed segment-sum: a doc's lanes are adjacent after the
        # sort and number at most n_terms (each term lists a doc once), so
        # summing a fixed backward window at the run's END lane is exact —
        # no cumsum-difference cancellation, and the left-to-right order of
        # the (stable) sort keeps float summation deterministic
        run_score = spart
        run_hits = shit
        for j in range(1, n_terms):
            prev_doc = jnp.concatenate([jnp.full((j,), -2, sdoc.dtype),
                                        sdoc[:-j]])
            same = prev_doc == sdoc
            prev_part = jnp.concatenate([jnp.zeros((j,), spart.dtype),
                                         spart[:-j]])
            prev_hit = jnp.concatenate([jnp.zeros((j,), shit.dtype),
                                        shit[:-j]])
            run_score = run_score + jnp.where(same, prev_part, 0.0)
            run_hits = run_hits + jnp.where(same, prev_hit, 0)
        matches = run_hits >= my["min_hits"]
        score = jnp.full(n, my["boost"]) if constant else run_score
        valid_end = is_end & (sdoc < big)
        safe_end_docs = jnp.where(valid_end, sdoc, 0)
        eligible = valid_end & matches & seg["live"][safe_end_docs] \
            & seg["root"][safe_end_docs] & (score >= min_score)
        total = jnp.sum(eligible.astype(jnp.int32))
        masked = jnp.where(eligible, score, NEG_INF)
        k_eff = min(k, n)
        top_scores, top_lane = jax.lax.top_k(masked, k_eff)
        top_docs = sdoc[top_lane]
        if k_eff < k:
            top_scores = jnp.concatenate(
                [top_scores, jnp.full(k - k_eff, NEG_INF)])
            top_docs = jnp.concatenate(
                [top_docs, jnp.zeros(k - k_eff, jnp.int32)])
        row = _pack_row(top_scores, top_docs, total)
        if bm:
            # phase-A popcount rides the SAME packed row the host already
            # fetches — pruned-block accounting costs no extra round trip
            row = jnp.concatenate([row, jax.lax.bitcast_convert_type(
                pruned[None].astype(jnp.int32), jnp.float32)])
        return row

    def run(seg, packed_buf):
        leaves = unpack_leaves(packed_buf, layout)
        batched_flat = jax.tree_util.tree_unflatten(treedef, leaves[:-1])
        return jax.vmap(one, in_axes=(None, 0, 0))(seg, batched_flat,
                                                   leaves[-1])

    return run


def _blockmax_admitted(plan, k: int) -> bool:
    """STATIC admission for the two-phase block-max kernel, shared by
    _envelope_runner (which kernel compiles) and the prepare/finish
    halves (whether a pruned-count lane exists in the packed row) so
    the row layout can never drift from the compiled program. A plan
    qualifies when it was compiled with the gate ON (it carries the
    phase-A `tid` input — the memo key includes the gate state), is a
    plain non-constant text clause on the candidate kernel, touches
    enough blocks to be worth a slice pass, and the slice can actually
    cover k (theta needs a k-th exact score)."""
    if plan is None or plan.kind != "text" or plan.static[0] \
            or "tid" not in plan.inputs:
        return False   # constant-score: no competitive threshold exists
    n_blocks = plan.inputs["ids"].shape[-1]
    return (n_blocks >= _bm25.BLOCKMAX_MIN_BLOCKS
            and 0 < k <= _bm25.BLOCKMAX_SLICE_BLOCKS * 128
            and _envelope_kernel(plan) == "candidate")


def build_batched_query_phase(plan: Plan, meta: DeviceSegmentMeta, k: int,
                              layout, treedef):
    """B same-shaped queries against one segment as ONE device program.

    The TPU answer to per-query launch latency: where the reference executes
    queries one at a time per shard (SearchService.executeQueryPhase), here a
    whole _msearch batch vmaps over a leading query axis — gathers, BM25 and
    top-k all batch cleanly, so one host↔device round trip serves B queries.
    Score-sorted, agg-free queries only (the common high-QPS shape)."""

    def one(seg, flat_inputs, min_score):
        cursor = [0]
        scores, matches = _eval_plan(plan, seg, flat_inputs, cursor)
        in_seg = jnp.arange(seg["live"].shape[0], dtype=jnp.int32) < meta.num_docs
        eligible = matches & seg["live"] & seg["root"] & in_seg \
            & (scores >= min_score)
        total = jnp.sum(eligible.astype(jnp.int32))
        masked = jnp.where(eligible, scores, NEG_INF)
        k_eff = min(k, seg["live"].shape[0])
        top_scores, top_idx = _topk_or_empty(masked, k_eff)
        return _pack_row(top_scores, top_idx, total)

    def run(seg, packed_buf):
        leaves = unpack_leaves(packed_buf, layout)
        batched_flat = jax.tree_util.tree_unflatten(treedef, leaves[:-1])
        return jax.vmap(one, in_axes=(None, 0, 0))(seg, batched_flat,
                                                   leaves[-1])

    return run


def _flatten_agg_out(out: Dict[str, Any]) -> List[Any]:
    """Deterministic (sorted-key) leaf order for one eval_aggs output dict —
    the device-side packer and the host-side unpacker must agree."""
    return [out[k] for k in sorted(out)]


def build_batched_agg_query_phase(plan: Plan, meta: DeviceSegmentMeta,
                                  k: int, layout, treedef, axes, agg_plans):
    """B same-shaped queries WITH aggregations as ONE device program.

    Extends build_batched_query_phase with the agg collection pass
    (eval_aggs) per query row; every agg partial array is bitcast to f32
    and concatenated onto the packed hit row, so a whole group of agg
    queries still fetches as ONE [B, 2k+1+W] array = one transfer round
    trip (reference executes aggs per query per shard:
    search/aggregations/AggregationPhase.java preProcess/execute)."""

    def one(seg, flat_inputs, min_score):
        cursor = [0]
        scores, matches = _eval_plan(plan, seg, flat_inputs, cursor)
        d_pad = seg["live"].shape[0]
        in_seg = jnp.arange(d_pad, dtype=jnp.int32) < meta.num_docs
        eligible = matches & seg["live"] & seg["root"] & in_seg \
            & (scores >= min_score)
        total = jnp.sum(eligible.astype(jnp.int32))
        masked = jnp.where(eligible, scores, NEG_INF)
        k_eff = min(k, d_pad)
        top_scores, top_idx = _topk_or_empty(masked, k_eff)
        agg_outs: List[dict] = []
        eval_aggs(list(agg_plans), seg, flat_inputs, cursor, eligible,
                  agg_outs)
        pieces = [_pack_row(top_scores, top_idx, total)]
        for out in agg_outs:
            for v in _flatten_agg_out(out):
                v = v.reshape(-1)
                if v.dtype != jnp.float32:
                    v = jax.lax.bitcast_convert_type(
                        v.astype(jnp.int32), jnp.float32)
                pieces.append(v)
        return jnp.concatenate(pieces)

    def run(seg, packed_buf):
        leaves = unpack_leaves(packed_buf, layout)
        batched_flat = jax.tree_util.tree_unflatten(treedef, leaves[:-1])
        axes_tree = jax.tree_util.tree_unflatten(treedef, list(axes[:-1]))
        return jax.vmap(one, in_axes=(None, axes_tree, 0))(
            seg, batched_flat, leaves[-1])

    return run


def _agg_out_layout(plan: Plan, meta: DeviceSegmentMeta, agg_plans,
                    arrays, example_flat, min_score_example):
    """Host-side layout of one query's agg partials: for each eval_aggs
    output dict, its sorted keys with shapes and dtypes. Computed by
    abstract evaluation (jax.eval_shape) — no device work."""

    def probe(seg, flat_inputs, min_score):
        cursor = [0]
        scores, matches = _eval_plan(plan, seg, flat_inputs, cursor)
        d_pad = seg["live"].shape[0]
        eligible = matches & seg["live"] & (scores >= min_score)
        agg_outs: List[dict] = []
        eval_aggs(list(agg_plans), seg, flat_inputs, cursor, eligible,
                  agg_outs)
        return agg_outs

    shapes = jax.eval_shape(probe, arrays, example_flat, min_score_example)
    out_layout = []
    width = 0
    for out in shapes:
        entry = []
        for key in sorted(out):
            s = out[key]
            n = int(np.prod(s.shape)) if s.shape else 1
            entry.append((key, tuple(s.shape), str(s.dtype)))
            width += n
        out_layout.append(tuple(entry))
    return tuple(out_layout), width


def _decode_agg_row(row: np.ndarray, out_layout) -> List[dict]:
    """Invert the device-side f32 packing for one query row (the agg tail
    of a [2k+1+W] packed row) back into eval_aggs-ordered output dicts."""
    outs = []
    off = 0
    for entry in out_layout:
        d = {}
        for key, shape, dtype in entry:
            n = int(np.prod(shape)) if shape else 1
            piece = row[off:off + n]
            off += n
            if dtype == "float32":
                arr = piece
            elif dtype == "bool":
                arr = piece.view(np.int32).astype(np.bool_)
            else:
                arr = piece.view(np.int32)
                if dtype != "int32":
                    arr = arr.astype(dtype)
            d[key] = arr.reshape(shape)
        outs.append(d)
    return outs


def _agg_envelope_runner(plan_sig, plan: Plan, meta: DeviceSegmentMeta,
                         k: int, layout, treedef, axes, agg_sig, agg_plans,
                         arrays, example_flat):
    """Jitted group program for agg-bearing batches + the host layout of
    each row's agg tail. Always the dense kernel: eval_aggs consumes the
    dense eligible mask the candidate-buffer kernel never materializes."""
    key = ("aggenv", plan_sig, agg_sig, meta.compile_key(), k, layout,
           treedef, axes)
    hit = _JIT_CACHE.get(key)
    if hit is None:
        out_layout, width = _agg_out_layout(
            plan, meta, agg_plans, arrays, example_flat, np.float32(0))
        fn = jax.jit(build_batched_agg_query_phase(
            plan, meta, k, layout, treedef, axes, agg_plans))
        _JIT_CACHE[key] = (fn, out_layout, width)  # shared-state-ok: benign double-jit race; dict slot write is GIL-atomic
        wrapped = _timed_first_call(
            fn, family="agg_env", shape=_env_shape(layout, k, meta),
            key=key, cost=_plan_cost(plan, meta, _layout_batch(layout)))
        return (wrapped, out_layout, width)
    kp = _KERNELS.gate()
    if kp is not None:
        return (kp.timed(hit[0], "agg_env", _env_shape(layout, k, meta)),
                hit[1], hit[2])
    return hit


@functools.partial(jax.jit, static_argnums=())
def _concat_rows(outs):
    """Column-pad + row-concat all group outputs into ONE device array, so
    a whole msearch batch is fetched in a single transfer (on a tunneled
    device every fetch is a full round trip — the round-3 profile showed
    3 sequential fetches costing ~200-400ms against ~0.3ms of compute)."""
    width = max(o.shape[1] for o in outs)
    return jnp.concatenate(
        [jnp.pad(o, ((0, 0), (0, width - o.shape[1]))) for o in outs],
        axis=0)


def unpack_batched_result(packed: np.ndarray, k_eff: int):
    """Inverse of the packed [B, 2k+1] row layout from
    build_batched_query_phase."""
    scores = packed[:, :k_eff]
    idx = packed[:, k_eff:2 * k_eff].view(np.int32)
    totals = packed[:, 2 * k_eff:].view(np.int32)[:, 0]
    return scores, idx, totals


def _envelope_runner(plan_sig, plan: Plan, meta: DeviceSegmentMeta, k: int,
                     layout, treedef):
    """Jitted group program over a packed input envelope: the candidate-
    buffer kernel for plain text clauses within the lane budget, the dense
    kernel otherwise."""
    # meta.compile_key() (seg_id excluded): a refreshed segment whose
    # shapes land in an already-compiled bucket REUSES the executable
    # instead of paying a per-segment XLA recompile — the churn
    # ledger's warmup_hit verdict is true by construction (ISSUE 13)
    key = ("env", plan_sig, meta.compile_key(), k, layout, treedef)
    fn = _JIT_CACHE.get(key)
    if fn is None:
        qb128 = 0
        n_terms = plan.static[1] if plan.kind == "text" \
            and len(plan.static) > 1 else 1 << 30
        for off, shape, dtype in layout:
            if len(shape) == 2:         # first [B, QB] leaf
                qb128 = shape[1] * 128
                break
        cand = _candidate_kernel_fits(plan.kind, n_terms, qb128)
        if cand:
            # blockmax admission is a pure function of facts already in
            # the JIT key: the plan's input tree (treedef gains tid/
            # bscale only when compiled with the gate on), the layout's
            # lane count, and k — no extra key component needed
            fn = jax.jit(build_candidate_query_phase(
                plan, meta, k, layout, treedef,
                bm=_blockmax_admitted(plan, k)))
        else:
            fn = jax.jit(build_batched_query_phase(plan, meta, k,
                                                   layout, treedef))
        _JIT_CACHE[key] = fn  # shared-state-ok: benign double-jit race; dict slot write is GIL-atomic
        fam = "bm25_candidate" if cand else _plan_family(plan)
        return _timed_first_call(
            fn, family=fam, shape=_env_shape(layout, k, meta), key=key,
            cost=_plan_cost(plan, meta, _layout_batch(layout)))
    kp = _KERNELS.gate()
    if kp is not None:
        fam = "bm25_candidate" \
            if _envelope_kernel(plan) == "candidate" else _plan_family(plan)
        return kp.timed(fn, fam, _env_shape(layout, k, meta))
    return fn


def _envelope_kernel(plan: Plan) -> str:
    """The kernel class _envelope_runner picks for one item's plan —
    `candidate` (candidate-buffer kernel) or `dense` — via the SAME
    `_candidate_kernel_fits` predicate the runner compiles with, so
    the scan heat map's kernel mix matches what dispatches. The lane
    count comes from the plan's `ids` input, which IS the packed
    layout's [B, QB] leaf width (the compiler pre-buckets shapes)."""
    n_terms = plan.static[1] if plan.kind == "text" \
        and len(plan.static) > 1 else 1 << 30
    ids = plan.inputs.get("ids")
    qb128 = ids.shape[-1] * 128 if ids is not None else 0
    return "candidate" \
        if _candidate_kernel_fits(plan.kind, n_terms, qb128) else "dense"


def _scan_accumulate_item(device, plans, seg_rows, per_query) -> None:
    """Always-on scan accounting for ONE msearch item (ISSUE 14),
    accumulated LOCALLY (plain dict adds on the wave's own state — no
    lock, no estimator): per compiled segment plan, posting-block
    bytes from the plan statics and — only when the dense kernel runs
    — the O(d_pad) dense-lane bytes the candidate-buffer kernel exists
    to avoid. `SCAN.note_batch` lands the whole wave in one flush."""
    from opensearch_tpu.telemetry.scan import (
        DENSE_LANE_BYTES, POSTING_BLOCK_BYTES, plan_scan_blocks,
        plan_scan_extra)
    q_posting = q_dense = 0
    noted = False
    for plan, (_, meta) in zip(plans, device):
        if plan is None or plan.kind == "match_none":
            continue
        posting = plan_scan_blocks(plan) * POSTING_BLOCK_BYTES
        kernel = _envelope_kernel(plan)
        dense = 0 if kernel == "candidate" \
            else meta.d_pad * DENSE_LANE_BYTES
        # rank_vectors token-matrix / PQ-code bytes (maxsim kernels)
        # fold into the dense class — they are O(d_pad) HBM traffic
        dense += plan_scan_extra(plan)
        row = seg_rows.get(meta.seg_id)
        if row is None:
            row = seg_rows[meta.seg_id] = [0, 0, 0, {}]
        row[0] += 1
        row[1] += posting
        row[2] += dense
        row[3][kernel] = row[3].get(kernel, 0) + 1
        q_posting += posting
        q_dense += dense
        noted = True
    if noted:
        per_query.append((q_posting, q_dense))


def _runner(plan_sig, plan: Plan, meta: DeviceSegmentMeta, k: int, sort_mode: str,
            agg_plans=()):
    key = (plan_sig, meta.compile_key(), k, sort_mode,
           tuple(a.sig() for a in agg_plans))
    fn = _JIT_CACHE.get(key)
    if fn is not None:
        kp = _KERNELS.gate()
        if kp is not None:
            return kp.timed(fn, _plan_family(plan, agg_plans),
                            f"k{k}/d{meta.d_pad}/{sort_mode}")
        return fn
    fn = jax.jit(build_query_phase(plan, meta, k, sort_mode, agg_plans))
    _JIT_CACHE[key] = fn  # shared-state-ok: benign double-jit race; dict slot write is GIL-atomic
    return _timed_first_call(
        fn, family=_plan_family(plan, agg_plans),
        shape=f"k{k}/d{meta.d_pad}/{sort_mode}", key=key,
        cost=_plan_cost(plan, meta))


def build_hybrid_query_phase(plans, meta: DeviceSegmentMeta, k: int):
    """The FUSED hybrid query phase for one segment: every sub-query of a
    `hybrid` clause evaluates inside ONE jitted program (one plan-signature
    executable, one dispatch, one fetch) instead of N sequential searches.

    Per sub-query the program emits its own top-k channel PLUS the score
    bounds the normalization-processor needs at reduce time:
      [k scores | k doc ords | count | min | max | sum-of-squares]
    and one trailing union total (a doc matching any sub-query counts once).
    Bounds are computed ON DEVICE over the sub-query's selected top-k
    window — the exact candidate set that reaches the coordinator — so the
    merge can reconstruct GLOBAL min/max (min-of-mins / max-of-maxs) and
    the global L2 norm (sum of per-shard sums) without a second pass over
    candidate lists, mirroring the reference's per-shard TopDocs bounds
    (neural-search NormalizationProcessorWorkflow over CompoundTopDocs)."""

    n_sub = len(plans)

    def run(seg, flat_inputs, min_score):
        cursor = [0]
        d_pad = seg["live"].shape[0]
        in_seg = jnp.arange(d_pad, dtype=jnp.int32) < meta.num_docs
        base = seg["live"] & seg["root"] & in_seg
        union = jnp.zeros(d_pad, jnp.bool_)
        pieces = []
        k_eff = min(k, d_pad)
        for i in range(n_sub):
            scores, matches = _eval_plan(plans[i], seg, flat_inputs, cursor)
            eligible = matches & base & (scores >= min_score)
            union = union | eligible
            masked = jnp.where(eligible, scores, NEG_INF)
            top_scores, top_idx = jax.lax.top_k(masked, k_eff)
            valid = top_scores > NEG_INF
            cnt = jnp.sum(valid.astype(jnp.int32))
            mn = jnp.min(jnp.where(valid, top_scores, jnp.inf))
            mx = jnp.max(jnp.where(valid, top_scores, -jnp.inf))
            vs = jnp.where(valid, top_scores, 0.0)
            ssq = jnp.sum(vs * vs)
            pieces.append(jnp.concatenate([
                top_scores,
                jax.lax.bitcast_convert_type(top_idx.astype(jnp.int32),
                                             jnp.float32),
                jax.lax.bitcast_convert_type(cnt[None], jnp.float32),
                mn[None], mx[None], ssq[None]]))
        total = jnp.sum(union.astype(jnp.int32))
        pieces.append(jax.lax.bitcast_convert_type(total[None],
                                                   jnp.float32))
        return jnp.concatenate(pieces)

    return run


def build_batched_hybrid_query_phase(plans, meta: DeviceSegmentMeta,
                                     k: int, layout, treedef):
    """B same-shaped hybrid queries against one segment as ONE device
    program: the fused multi-sub-query phase vmapped over the msearch
    envelope's packed batch axis — a whole dashboard of hybrid queries
    costs one upload, one program, one fetch."""
    one = build_hybrid_query_phase(plans, meta, k)

    def run(seg, packed_buf):
        leaves = unpack_leaves(packed_buf, layout)
        batched_flat = jax.tree_util.tree_unflatten(treedef, leaves[:-1])
        return jax.vmap(one, in_axes=(None, 0, 0))(seg, batched_flat,
                                                   leaves[-1])

    return run


def _batched_hybrid_runner(plans, meta: DeviceSegmentMeta, k: int,
                           layout, treedef):
    key = ("hybenv", tuple(p.sig() for p in plans), meta.compile_key(),
           k, layout, treedef)
    fn = _JIT_CACHE.get(key)
    if fn is None:
        fn = jax.jit(build_batched_hybrid_query_phase(plans, meta, k,
                                                      layout, treedef))
        _JIT_CACHE[key] = fn  # shared-state-ok: benign double-jit race; dict slot write is GIL-atomic
        cost = [_plan_cost(p, meta, _layout_batch(layout))
                for p in plans]
        return _timed_first_call(
            fn, family="hybrid_env", shape=_env_shape(layout, k, meta),
            key=key, cost=(sum(c[0] for c in cost),
                           sum(c[1] for c in cost)))
    kp = _KERNELS.gate()
    if kp is not None:
        return kp.timed(fn, "hybrid_env", _env_shape(layout, k, meta))
    return fn


def _decode_hybrid_row(row: np.ndarray, k_seg: int, n_sub: int):
    """Invert one segment's fused hybrid row: per-sub (scores, ords,
    count, min, max, sum_sq) channels + the trailing union total."""
    out = []
    off = 0
    for _ in range(n_sub):
        scores = row[off:off + k_seg]
        ords = row[off + k_seg:off + 2 * k_seg].view(np.int32)
        off += 2 * k_seg
        cnt = int(row[off:off + 1].view(np.int32)[0])
        mn, mx, ssq = (float(row[off + 1]), float(row[off + 2]),
                       float(row[off + 3]))
        off += 4
        out.append((scores, ords, cnt, mn, mx, ssq))
    total = int(row[off:off + 1].view(np.int32)[0])
    return out, total


# body keys the batched hybrid envelope fully renders (weights/techniques
# come from the pipeline spec, not the body)
_HYBRID_BATCHABLE_KEYS = frozenset({"query", "size", "from", "min_score",
                                    "_source", "track_total_hits"})


def _hybrid_msearch_batchable(body: dict) -> bool:
    return (_contains_hybrid(body.get("query"))
            and set(body) <= _HYBRID_BATCHABLE_KEYS)


class HybridShardResult:
    """One shard's fused hybrid query phase output: per-sub-query candidate
    lists + per-sub-query (min, max, sum_sq, count) bounds + union total."""
    __slots__ = ("per_sub", "bounds", "total")

    def __init__(self, per_sub, bounds, total):
        self.per_sub = per_sub      # [sub][(score, seg_i, ord), ...]
        self.bounds = bounds        # [sub](min, max, sum_sq, count)
        self.total = total


def _empty_hybrid_result(n_sub: int) -> HybridShardResult:
    return HybridShardResult(
        [[] for _ in range(n_sub)],
        [[float("inf"), float("-inf"), 0.0, 0] for _ in range(n_sub)], 0)


def _accumulate_hybrid_row(result: HybridShardResult, row: np.ndarray,
                           seg_i: int, k_seg: int, n_sub: int) -> None:
    channels, total = _decode_hybrid_row(row, k_seg, n_sub)
    for i, (scores, ords, cnt, mn, mx, ssq) in enumerate(channels):
        # top_k is score-desc with padding last: the first cnt lanes are
        # exactly the valid candidates
        for s, o in zip(scores[:cnt], ords[:cnt]):
            result.per_sub[i].append((float(s), seg_i, int(o)))
        if cnt:
            b = result.bounds[i]
            b[0] = min(b[0], mn)
            b[1] = max(b[1], mx)
            b[2] += ssq
            b[3] += cnt
    result.total += total


def _build_sort_key(arrays, primary_sort) -> jnp.ndarray:
    """Dense per-doc f32 key for the device's per-segment top-k selection
    (segment-local value ranks; higher sorts first; missing → MISSING_KEY)."""
    d_pad = arrays["live"].shape[0]
    if primary_sort is None:
        return jnp.zeros(d_pad, jnp.float32)
    field, order = primary_sort
    col = arrays["numeric"].get(field)
    if col is not None:
        if order == "asc":
            key = -col["min_rank"].astype(jnp.float32)
        else:
            key = col["max_rank"].astype(jnp.float32)
        return jnp.where(col["exists"], key, MISSING_KEY)
    col = arrays["ordinal"].get(field)
    if col is not None:
        pair_valid = col["doc_ids"] >= 0
        idx = jnp.where(pair_valid, col["doc_ids"], d_pad)
        if order == "asc":
            dense = jnp.full(d_pad, 2 ** 30, jnp.int32).at[idx].min(
                jnp.where(pair_valid, col["ords"], 2 ** 30), mode="drop")
            key = -dense.astype(jnp.float32)
        else:
            dense = jnp.full(d_pad, -1, jnp.int32).at[idx].max(
                jnp.where(pair_valid, col["ords"], -1), mode="drop")
            key = dense.astype(jnp.float32)
        return jnp.where(col["exists"], key, MISSING_KEY)
    return jnp.full(d_pad, MISSING_KEY, jnp.float32)


# ------------------------------------------------------ result page (ISSUE 17)
#
# The single-round-trip result page: a SECOND jitted program per wave
# that (a) re-keys every segment's per-segment winners with cross-
# segment-comparable decoded values and lax.top_k's them into ONE
# global candidate page, (b) gathers the winners' sort-key ranks inside
# the same program (the host's exact-value re-scan disappears — decode
# is an O(1) unique[rank] lookup per winner), and (c) gathers each
# fused docvalue field's rank + exists lane for the winners, so the
# fetch phase's per-hit column reads disappear too. Everything lands in
# one packed int32 buffer (f32 lanes bitcast, the pack_leaves idiom)
# fetched together with the agg partials in ONE device_get.

def _page_sort_mode(body: dict, sort_specs, mapper):
    """Static page admission: ("score",) / ("field", name, order) when
    the request's result assembly can ride the on-device merge, None for
    the legacy host merge. Collapse/rescore post-process the candidate
    POOL and need the full per-segment over-fetch (the page's global cut
    would under-fill them — same reason search/spmd.py excludes them);
    multi-key and keyword sorts keep the host path (ordinal ranks are
    not comparable across segments)."""
    if body.get("collapse") or body.get("rescore"):
        return None
    if len(sort_specs) != 1:
        return None
    field, order = sort_specs[0]
    if field == "_score":
        return ("score",)
    ft = mapper.get_field(field)
    if ft is None or not (ft.is_numeric or ft.is_date or ft.is_bool):
        return None
    return ("field", field, order)


def _page_dv_fields(body: dict, mapper) -> tuple:
    """The docvalue_fields specs a result page can fuse: numeric-typed
    fields (decode is rank -> host unique[], exact f64 — dates included,
    unlike the f32-compared SORT key). Keyword fields keep the host
    dictionary scan; per-SEGMENT multi-valued columns fall back in
    _page_segment_admit."""
    out = []
    for spec in body.get("docvalue_fields") or []:
        field = spec["field"] if isinstance(spec, dict) else spec
        ft = mapper.get_field(field)
        if ft is not None and (ft.is_numeric or ft.is_date or ft.is_bool) \
                and field not in out:
            out.append(field)
    return tuple(out)


def _page_segment_admit(seg, arrays, meta, mode, dv_fields):
    """Per-segment page admission + the device/host column refs one
    segment contributes. None disqualifies the whole request (a sort
    column whose values are not exactly f32-representable — selection
    on device would diverge from the host's exact keys). Per dv field:
    `col` (device gather + host unique[] decode), `absent` (no column —
    decode to no-values), or `host` (multi-valued: the fetch phase's
    host scan, with its own per-leaf round-trip accounting)."""
    out = {"d_pad": meta.d_pad, "sort_col": None, "sort_host": None,
           "dv_state": {}}
    if mode[0] == "field":
        field = mode[1]
        host = seg.numeric_dv.get(field)
        if host is not None and not f32_sortable(host):
            return None
        out["sort_col"] = arrays["numeric"].get(field)
        out["sort_host"] = host
    for f in dv_fields:
        host = seg.numeric_dv.get(f)
        dev = arrays["numeric"].get(f)
        if host is None and f not in seg.ordinal_dv:
            out["dv_state"][f] = ("absent", None, None)
        elif host is not None and dev is not None and single_valued(host):
            out["dv_state"][f] = ("col", dev, host)
        else:
            out["dv_state"][f] = ("host", None, None)
    return out


def _page_merger(sig, mode, k_page: int, stride: int, seg_statics,
                 dv_fields):
    """The cached jitted page-merge program (one executable per layout
    signature, the same _JIT_CACHE + compile-event discipline as
    _runner). Takes every segment's (keys, scores, idx, total) plus the
    device column refs and returns ONE packed int32 page."""
    fn = _JIT_CACHE.get(sig)
    if fn is not None:
        kp = _KERNELS.gate()
        if kp is not None:
            return kp.timed(fn, "page_merger",
                            f"k{k_page}/s{stride}/n{len(seg_statics)}")
        return fn
    field_mode = mode[0] == "field"
    order = mode[2] if field_mode else None

    def run(rows):
        keys, scores, gids = [], [], []
        sranks, sexists = [], []
        dv_lanes = {f: ([], []) for f in dv_fields}
        for pos, ((k_i, d_pad, _has_sort, dv_states), row) in enumerate(
                zip(seg_statics, rows)):
            ti = row["idx"]
            valid = row["keys"] != NEG_INF
            if field_mode:
                # re-key this segment's winners with decoded VALUES:
                # per-segment selection by rank is order-correct inside
                # the segment, but ranks are not comparable across
                # segments — the value key is (ops/topk.py)
                col = row.get("sort_col")
                vkey = value_merge_key(col, order, d_pad)
                keys.append(jnp.where(valid, vkey[ti], NEG_INF))
                if col is None:
                    sranks.append(jnp.zeros(ti.shape[0], jnp.int32))
                    sexists.append(jnp.zeros(ti.shape[0], jnp.int32))
                else:
                    ra = col["min_rank"] if order == "asc" \
                        else col["max_rank"]
                    sranks.append(ra[ti])
                    sexists.append(col["exists"][ti].astype(jnp.int32))
            else:
                keys.append(row["keys"])
            scores.append(row["scores"])
            gids.append(jnp.int32(pos * stride) + ti)
            for f, state in zip(dv_fields, dv_states):
                r_l, e_l = dv_lanes[f]
                if state == "col":
                    col = row["dv"][f]
                    r_l.append(col["min_rank"][ti])
                    e_l.append(col["exists"][ti].astype(jnp.int32))
                else:
                    r_l.append(jnp.zeros(ti.shape[0], jnp.int32))
                    e_l.append(jnp.zeros(ti.shape[0], jnp.int32))
        mk, mi = jax.lax.top_k(jnp.concatenate(keys), k_page)
        parts = [jax.lax.bitcast_convert_type(mk, jnp.int32),
                 jax.lax.bitcast_convert_type(
                     jnp.concatenate(scores)[mi], jnp.int32),
                 jnp.concatenate(gids)[mi]]
        if field_mode:
            parts.append(jnp.concatenate(sranks)[mi])
            parts.append(jnp.concatenate(sexists)[mi])
        for f in dv_fields:
            r_l, e_l = dv_lanes[f]
            parts.append(jnp.concatenate(r_l)[mi])
            parts.append(jnp.concatenate(e_l)[mi])
        parts.append(jnp.stack([row["total"] for row in rows])
                     .astype(jnp.int32).reshape(-1))
        return jnp.concatenate(parts)

    fn = jax.jit(run)
    _JIT_CACHE[sig] = fn  # shared-state-ok: benign double-jit race; dict slot write is GIL-atomic
    return _timed_first_call(
        fn, family="page_merger",
        shape=f"k{k_page}/s{stride}/n{len(seg_statics)}", key=sig)


class _Candidate:
    __slots__ = ("score", "seg_i", "ord", "sort_values", "shard_i",
                 "collapse_value", "dv_page")

    def __init__(self, score, seg_i, ord_, sort_values, shard_i=0):
        self.score = score
        self.seg_i = seg_i
        self.ord = ord_
        self.sort_values = sort_values  # list parallel to sort specs; None = missing
        self.shard_i = shard_i          # coordinator-side shard index
        # result-page prefetch (ISSUE 17): {field: [raw values]} decoded
        # from the fused docvalue lanes; None = no page rode this
        # candidate (fetch falls back to the per-leaf host scan)
        self.dv_page = None


def _compare_candidates(specs):
    """Multi-key comparator with missing-last semantics (reference default).
    Final tie-break (shard, segment, doc) asc — mergeTopDocs order
    (action/search/SearchPhaseController.java:228)."""
    def cmp(a: _Candidate, b: _Candidate) -> int:
        for i, (field, order) in enumerate(specs):
            va, vb = a.sort_values[i], b.sort_values[i]
            if va is None and vb is None:
                continue
            if va is None:
                return 1   # missing sorts last
            if vb is None:
                return -1
            if va != vb:
                lt = va < vb
                if order == "desc":
                    lt = not lt
                return -1 if lt else 1
        if a.shard_i != b.shard_i:
            return -1 if a.shard_i < b.shard_i else 1
        if a.seg_i != b.seg_i:
            return -1 if a.seg_i < b.seg_i else 1
        return -1 if a.ord < b.ord else 1
    return functools.cmp_to_key(cmp)


# request keys the batched envelope path fully renders; anything else
# (highlight, collapse, rescore, ...) takes the general path
_BATCHABLE_KEYS = frozenset({"query", "size", "from", "min_score", "sort",
                             "_source", "aggs", "aggregations"})


def _contains_hybrid(query_spec) -> bool:
    """Top-level hybrid clause detection on the RAW body (pre-parse): the
    batched envelope and the general host loop both hand hybrid off to the
    fused hybrid query phase (searchpipeline/hybrid.py drives it)."""
    return isinstance(query_spec, dict) and "hybrid" in query_spec


def _contains_inner_hits(obj) -> bool:
    if isinstance(obj, dict):
        return "inner_hits" in obj or any(_contains_inner_hits(v)
                                          for v in obj.values())
    if isinstance(obj, list):
        return any(_contains_inner_hits(v) for v in obj)
    return False


def _msearch_batchable(body: dict) -> bool:
    return (set(body) <= _BATCHABLE_KEYS
            and body.get("sort") in (None, "_score", ["_score"])
            # inner_hits need the full fetch sub-phase pipeline, which
            # the batched envelope's _hit_dict does not run
            and not _contains_inner_hits(body.get("query"))
            # hybrid executes through its own fused multi-sub-query
            # program with per-sub-query score channels — the envelope's
            # single (scores, matches) row can't carry them
            and not _contains_hybrid(body.get("query")))


class SearchExecutor:
    """Executes a parsed search request against one shard (query + fetch)."""

    def __init__(self, reader: ShardReader):
        self.reader = reader
        # index.max_result_window (set by the owning IndexService; the
        # default matches the reference)
        self.max_result_window = 10000
        # wave-pipeline staging: recycled host envelope buffers, released
        # only after the owning wave's collect (zero-copy-safe reuse)
        self._staging = _StagingPool()

    def search(self, body: Optional[dict] = None,
               _direct: bool = False) -> dict:
        from opensearch_tpu.search.controller import execute_search
        body = body or {}
        if not _direct and _msearch_batchable(body):
            # single searches share the batched envelope kernel (B=1): one
            # program, one upload, and bit-identical scores with _msearch;
            # errors raise (the per-item error wrapping is _msearch-only)
            return self.multi_search(
                [body], _raise_item_errors=True)["responses"][0]
        return execute_search([self], body)

    def execute_query_phase(self, body: dict, k: int,
                            extra_filter: Optional[dict] = None,
                            stats_override=None, trace=None,
                            ledger_scope=None):
        """Per-shard query phase (SearchService.executeQueryPhase analog):
        returns (candidates, per-segment decoded agg partials, total hits)
        for the coordinator to merge. `k` = from+size requested globally.
        `extra_filter` is an alias filter applied as a non-scoring clause
        (reference: QueryShardContext filter from AliasFilter). `trace`
        (a telemetry Span or None) collects device-dispatch attribution:
        compile/dispatch/collect ns, bytes_to_device, XLA compile events.

        size=0 requests are served through the shard request cache
        (IndicesRequestCache analog — indices/request_cache.py); the key
        includes the segment identities, so refreshes/deletes miss."""
        body = body or {}
        # DFS requests never cache (the reference excludes
        # dfs_query_then_fetch from IndicesRequestCache): the global stats
        # live outside the shard's own segments, so a per-shard key can't
        # see them change
        if body.get("search_type") == "dfs_query_then_fetch" \
                or "_dfs" in body:
            return self._query_phase_uncached(body, k, extra_filter,
                                              stats_override, trace,
                                              ledger_scope)
        rc = _request_cache()
        if rc.cacheable(body):
            base = rc.cache_key(self.reader.segments, body, k,
                                extra_filter)
            key = ("shard", base) if base is not None else None
            if key is not None:
                hit = _cache_get_isolated(rc, key)
                if hit is not rc.REQUEST_CACHE._MISS:
                    if trace is not None:
                        trace.set_attribute("request_cache", "hit")
                    cts, decoded, total = hit
                    return ([_Candidate(s, g, o, sv)
                             for s, g, o, sv in cts], decoded, total)
                if trace is not None:
                    trace.set_attribute("request_cache", "miss")
                cands, decoded, total = self._query_phase_uncached(
                    body, k, extra_filter, stats_override, trace,
                    ledger_scope)
                # store candidates as plain tuples: callers mutate
                # _Candidate.shard_i, which must not leak between hits
                _cache_put_isolated(
                    rc, key, ([(c.score, c.seg_i, c.ord, c.sort_values)
                               for c in cands], decoded, total))
                return cands, decoded, total
        return self._query_phase_uncached(body, k, extra_filter,
                                          stats_override, trace,
                                          ledger_scope)

    def _query_phase_uncached(self, body: dict, k: int,
                              extra_filter: Optional[dict] = None,
                              stats_override=None, trace=None,
                              ledger_scope=None):
        node = dsl.parse_query(body.get("query"))
        if extra_filter is not None:
            node = dsl.BoolQuery(must=[node],
                                 filter=[dsl.parse_query(extra_filter)])
        slice_spec = body.get("slice")
        if slice_spec is not None:
            sid = int(slice_spec.get("id", 0))
            smax = int(slice_spec.get("max", 0))
            if smax < 2:
                raise IllegalArgumentError("[slice] max must be >= 2")
            if not 0 <= sid < smax:
                raise IllegalArgumentError(
                    f"[slice] id must be in [0, {smax})")
            node = dsl.BoolQuery(must=[node],
                                 filter=[dsl.SliceQuery(id=sid, max=smax)])
        min_score = float(body["min_score"]) if body.get("min_score") is not None \
            else NEG_INF

        sort_specs = _parse_sort(body.get("sort"))
        score_sorted = sort_specs[0][0] == "_score"
        primary = None if score_sorted else sort_specs[0]

        # DFS query-then-fetch: score with the coordinator-merged global
        # statistics instead of shard-local ones (StaticStats)
        if stats_override is not None:
            stats = stats_override
            segments, device = self.reader.snapshot()
        else:
            # one consistent (stats, segments, device) anchor: a
            # concurrent refresh publishing mid-request must not let
            # this request pair segment i with another segment's arrays
            stats, segments, device = self.reader.stats_snapshot()
        compiler = Compiler(self.reader.mapper, stats)
        agg_nodes = parse_aggs(body.get("aggs") or body.get("aggregations"))
        from opensearch_tpu.search.aggs.parse import PIPELINE_TYPES
        device_agg_nodes = [n for n in agg_nodes
                            if n.type not in PIPELINE_TYPES]
        k_fetch = min(k + 128, 1 << 16)  # over-fetch for ties & cross-seg merge

        # single-round-trip result page (RESULT_PAGE, ISSUE 17): static
        # admission here, per-segment admission in the dispatch loop;
        # page_rows collapses to None the moment any segment (or later
        # the gid-packing range) disqualifies — the legacy host merge is
        # always the fallback and stays byte-identical when gated off
        page_mode = _page_sort_mode(body, sort_specs, self.reader.mapper) \
            if RESULT_PAGE else None
        page_dv = _page_dv_fields(body, self.reader.mapper) \
            if page_mode is not None else ()
        page_rows = [] if page_mode is not None else None

        # phase 1: dispatch every segment's program without forcing — jax
        # dispatch is async, so device work overlaps; phase 2 collects ALL
        # results in ONE device_get (one transfer round trip total — on a
        # tunneled device the round trip dominates device compute)
        rec = trace is not None and getattr(trace, "recording", False)
        # per-shard transfer accounting (None = ledger off AND request not
        # traced/profiled — the zero-overhead path)
        scope = _LEDGER.scope(trace)
        if rec:
            # request-scoped compile attribution via the thread-local
            # accumulator (_note_compile) — global-counter deltas would
            # charge this span with CONCURRENT requests' compiles
            _THREAD_COMPILES.active = True
            _THREAD_COMPILES.count = 0
            _THREAD_COMPILES.ms = 0.0
            plan_compile_ns = dispatch_ns = 0
        launched = []
        from opensearch_tpu.telemetry.scan import (
            DENSE_LANE_BYTES, POSTING_BLOCK_BYTES, SCAN,
            plan_scan_blocks, plan_scan_extra)
        scan_shard = str(getattr(self.reader, "shard_id", 0))
        q_posting = q_dense = 0
        # kernel-family attribution (ISSUE 19): resolved from the first
        # compiled plan only when a consumer wants it — the insights
        # per-shape breakdown, the profiler, or a recording trace (the
        # Profile API's per-shard `kernels` entry)
        q_family = None
        _want_family = rec or _INSIGHTS.enabled or _KERNELS.enabled
        from opensearch_tpu.indices.query_cache import FilterCacheContext
        for seg_i, (seg, (arrays, meta)) in enumerate(
                zip(segments, device)):
            if seg.num_docs == 0:
                continue
            if rec:
                t0 = time.perf_counter_ns()
            compiler.filter_ctx = FilterCacheContext(seg, arrays)
            plan = compiler.compile(node, seg, meta)
            compiler.filter_ctx = None
            agg_plans = compile_aggs(device_agg_nodes, self.reader.mapper, seg,
                                     meta, compiler) if agg_nodes else []
            if rec:
                plan_compile_ns += time.perf_counter_ns() - t0
            if _want_family and q_family is None:
                q_family = _plan_family(plan, agg_plans)
            # always-on scan accounting (telemetry/scan.py, ISSUE 14):
            # this path runs the DENSE kernel (build_query_phase) —
            # posting blocks gathered per the plan statics plus the
            # O(d_pad) dense lanes, attributed per (shard, segment)
            posting = plan_scan_blocks(plan) * POSTING_BLOCK_BYTES
            dense = meta.d_pad * DENSE_LANE_BYTES + plan_scan_extra(plan)
            SCAN.note_segment(self.reader.index_name, scan_shard,
                              meta.seg_id, posting, dense, "dense")
            q_posting += posting
            q_dense += dense
            if page_rows is not None:
                prow = _page_segment_admit(seg, arrays, meta, page_mode,
                                           page_dv)
                if prow is None:
                    page_rows = None
                else:
                    page_rows.append(prow)
            sort_key = _build_sort_key(arrays, primary)
            fn = _runner(plan.sig(), plan, meta,
                         min(k_fetch, pad_bucket(max(seg.num_docs, 1))),
                         "score" if score_sorted else "field",
                         tuple(agg_plans))
            flat = plan.flatten_inputs([])
            for ap in agg_plans:
                ap.flatten_inputs(flat)
            if scope is not None:
                _LEDGER.record(
                    "upload.literals", "h2d",
                    sum(int(np.asarray(v).nbytes)
                        for d in flat for v in d.values()),
                    scope=scope)
            if rec:
                t0 = time.perf_counter_ns()
            flat = jax.tree_util.tree_map(jnp.asarray, flat)

            def _dispatch(fn=fn, arrays=arrays, flat=flat,
                          sort_key=sort_key):
                # fault site + bounded transient retry around the device
                # call: a transient dispatch blip costs a retry, not the
                # shard (the jitted fn is pure — re-dispatch is safe)
                if faults.ENABLED:
                    faults.fire("query.dispatch")
                return fn(arrays, flat, sort_key, jnp.float32(min_score))
            launched.append((seg_i, seg, agg_plans,
                             retry.call_with_retry(
                                 _dispatch, label="query.dispatch",
                                 trace=trace)))
            if rec:
                dispatch_ns += time.perf_counter_ns() - t0

        if launched:
            SCAN.note_query(q_posting, q_dense)
            ins = _INSIGHTS.gate()
            if ins is not None:
                # the per-request scan join (ISSUE 15): the SAME bytes
                # the heat map just counted, accumulated thread-locally
                # for the controller's per-shape note at request end
                ins.add_scan(q_posting, q_dense)
                if q_family is not None:
                    # kernel-family join (ISSUE 19): same thread-local
                    # carry, read back by _note_controller_insights
                    ins.add_family(q_family)

        page_args = None
        if page_rows is not None and launched:
            page_args = self._page_build(launched, page_rows, page_mode,
                                         page_dv, k_fetch, body)

        def _collect():
            if faults.ENABLED:
                faults.fire("fetch.gather")
            if page_args is not None:
                # dispatch the page merger, then fetch the packed page
                # TOGETHER with the agg partials: one device_get, one
                # round trip for the wave's entire result assembly
                fn, rows_arg, _lay = page_args
                return jax.device_get(
                    (fn(rows_arg), [o[3][4] for o in launched]))
            return jax.device_get([out for _, _, _, out in launched])

        t0c = time.monotonic() if scope is not None else 0.0
        with _LEDGER.attributed(scope):
            if rec:
                try:
                    with trace.child("device_collect",
                                     segments=len(launched)):
                        fetched = retry.call_with_retry(
                            _collect, label="fetch.gather", trace=trace)
                finally:
                    _THREAD_COMPILES.active = False
            else:
                fetched = retry.call_with_retry(_collect,
                                                label="fetch.gather")
        if scope is not None:
            if page_args is not None:
                _ledger_page_collect(scope, fetched[0], fetched[1],
                                     (time.monotonic() - t0c) * 1000)
            else:
                _ledger_unbatched_collect(scope, fetched,
                                          (time.monotonic() - t0c) * 1000)
            if rec:
                xla_compiles = _THREAD_COMPILES.count
                trace.set_attribute("plan_compile_ns", plan_compile_ns)
                trace.set_attribute("device_dispatch_ns", dispatch_ns)
                trace.set_attribute("bytes_to_device", scope.h2d_bytes)
                trace.set_attribute("bytes_fetched", scope.d2h_bytes)
                trace.set_attribute("transfers", scope.to_list())
                if q_family is not None:
                    # Profile API per-shard kernel attribution (ISSUE
                    # 19): the shard's device wall against the family
                    # that owns its program (+ the page merger when the
                    # single-round-trip page assembled the response)
                    fams = [q_family]
                    if page_args is not None:
                        fams.append("page_merger")
                    trace.set_attribute("kernels", [
                        {"family": f,
                         "device_ms": round(
                             scope.device_get_ms / len(fams), 3)}
                        for f in fams])
                trace.set_attribute("compiled", xla_compiles > 0)
                if xla_compiles:
                    trace.set_attribute("xla_compiles", xla_compiles)
                    trace.set_attribute("compile_ms",
                                        round(_THREAD_COMPILES.ms, 3))

        def _absorb():
            # absorb runs LAST: the legacy path's re-key round trip
            # (below) must reach the caller's request scope too
            if scope is not None and ledger_scope is not None \
                    and ledger_scope is not scope:
                ledger_scope.absorb(scope)

        if page_args is not None:
            out = self._decode_page(fetched, page_args, launched,
                                    agg_nodes)
            _absorb()
            return out

        candidates: List[_Candidate] = []
        per_segment_decoded = []
        total = 0
        t0r = time.monotonic() if scope is not None else 0.0
        for (seg_i, seg, agg_plans, _), outs in zip(launched, fetched):
            top_keys, top_scores, top_idx, seg_total, agg_outs = outs
            if agg_nodes:
                per_segment_decoded.append(decode_outputs(agg_plans, agg_outs))
            total += int(seg_total)
            for key_val, score, ord_ in zip(top_keys, top_scores, top_idx):
                if key_val == NEG_INF:
                    continue  # ineligible / padding
                sort_values = [
                    float(score) if f == "_score" else _sort_value(seg, f, o, int(ord_))
                    for f, o in sort_specs]
                candidates.append(_Candidate(float(score), seg_i, int(ord_),
                                             sort_values))
        if scope is not None and primary is not None and candidates:
            # round-trip attribution fix (ISSUE 17 satellite 1): the
            # exact-value re-key above reads the sort column once per
            # winner — served by the host mirror here (zero wire bytes,
            # so byte conservation against the measured device_get
            # holds) but a full gather round trip on a remote device.
            # The result page (RESULT_PAGE) extracts these keys inside
            # the merge program and never pays it.
            _LEDGER.note_round_trip("sort_keys",
                                    (time.monotonic() - t0r) * 1000,
                                    scope=scope)
        _absorb()
        return candidates, per_segment_decoded, total

    def _page_build(self, launched, page_rows, page_mode, page_dv,
                    k_fetch: int, body: dict):
        """Assemble the page merger's (jitted fn, device args, layout)
        for one wave, or None when the gid packing cannot cover the
        launched segments in int32 (the legacy collect takes over)."""
        stride = max(r["d_pad"] for r in page_rows)
        if len(launched) * stride >= (1 << 31):
            return None
        seg_statics, rows_arg = [], []
        lanes = 0
        for (seg_i, seg, agg_plans, out), prow in zip(launched, page_rows):
            top_keys, top_scores, top_idx = out[0], out[1], out[2]
            k_i = int(top_keys.shape[0])
            lanes += k_i
            dv_states = tuple(prow["dv_state"][f][0] for f in page_dv)
            seg_statics.append((k_i, prow["d_pad"],
                                prow["sort_col"] is not None, dv_states))
            arg = {"keys": top_keys, "scores": top_scores, "idx": top_idx,
                   "total": out[3]}
            if prow["sort_col"] is not None:
                arg["sort_col"] = prow["sort_col"]
            dv_cols = {f: prow["dv_state"][f][1] for f in page_dv
                       if prow["dv_state"][f][0] == "col"}
            if dv_cols:
                arg["dv"] = dv_cols
            rows_arg.append(arg)
        k_page = min(k_fetch, lanes)
        mode_sig = page_mode if page_mode[0] == "score" \
            else (page_mode[0], page_mode[1], page_mode[2])
        sig = ("page", mode_sig, k_page, stride, tuple(seg_statics),
               page_dv)
        fn = _page_merger(sig, page_mode, k_page, stride,
                          tuple(seg_statics), page_dv)
        # page-shaped executables enter the warmup registry: a node
        # restart (search/warmup.py warm_all) or a publish-triggered
        # precompile replay (Precompiler) re-runs the body and — with
        # the node's RESULT_PAGE gate on — reproduces exactly this
        # merger executable off the serving path
        from opensearch_tpu.search.warmup import WARMUP
        WARMUP.record(self.reader.index_name, body, 1, sig)
        lay = {"mode": page_mode, "k_page": k_page, "stride": stride,
               "dv_fields": page_dv, "rows_meta": page_rows}
        return fn, rows_arg, lay

    def _decode_page(self, fetched, page_args, launched, agg_nodes):
        """Host decode of one packed result page: candidates with exact
        sort values (rank -> host unique[], f64 — no f32 precision ever
        reaches a response) and the fused docvalue prefetch attached per
        candidate, plus per-segment totals and decoded agg partials."""
        packed, agg_fetched = fetched
        _fn, _rows, lay = page_args
        # already host-resident: the one device_get in _collect() moved
        # (and _ledger_page_collect accounted) every byte of the page
        buf = np.asarray(packed)  # sync-ok: result_page
        k_page, stride = lay["k_page"], lay["stride"]
        off = 0

        def take(n):
            nonlocal off
            part = buf[off:off + n]
            off += n
            return part

        mk = take(k_page).view(np.float32)
        msc = take(k_page).view(np.float32)
        mg = take(k_page)
        field_mode = lay["mode"][0] == "field"
        srank = sexists = None
        if field_mode:
            field, order = lay["mode"][1], lay["mode"][2]
            srank, sexists = take(k_page), take(k_page)
        dv_cols = [(f, take(k_page), take(k_page))
                   for f in lay["dv_fields"]]
        totals = take(len(launched))
        total = int(totals.sum())
        per_segment_decoded = []
        if agg_nodes:
            for (_seg_i, _seg, agg_plans, _), agg_outs in zip(
                    launched, agg_fetched):
                per_segment_decoded.append(
                    decode_outputs(agg_plans, agg_outs))
        candidates: List[_Candidate] = []
        for j in range(k_page):
            if mk[j] == NEG_INF:
                continue  # ineligible / padding
            pos, ord_ = divmod(int(mg[j]), stride)
            seg_i, seg = launched[pos][0], launched[pos][1]
            score = float(msc[j])
            if field_mode:
                if sexists[j]:
                    # exact f64 decode (host unique[]): the f32 merge key
                    # selected, the host table answers — same contract as
                    # _sort_value's vals.min()/max()
                    host = lay["rows_meta"][pos]["sort_host"]
                    v = float(host.unique[int(srank[j])])
                    sv = [int(v) if v.is_integer() else v]
                else:
                    sv = [None]
            else:
                sv = [score]
            cand = _Candidate(score, seg_i, ord_, sv)
            if dv_cols:
                prow = lay["rows_meta"][pos]
                dvm = {}
                for f, ranks, exists in dv_cols:
                    state, _dev, host = prow["dv_state"][f]
                    if state == "host":
                        continue  # fetch-phase host scan (own accounting)
                    if state == "col" and exists[j]:
                        dvm[f] = [float(host.unique[int(ranks[j])])]
                    else:
                        dvm[f] = []
                cand.dv_page = dvm
            candidates.append(cand)
        return candidates, per_segment_decoded, total

    def execute_hybrid_query_phase(self, body: dict, k: int,
                                   extra_filter: Optional[dict] = None,
                                   ledger_scope=None
                                   ) -> "HybridShardResult":
        """Per-shard fused hybrid query phase: ALL sub-queries of the
        hybrid clause run as ONE device program per segment (dispatched
        async across segments, collected with one device_get), returning
        per-sub-query candidates + score bounds for the coordinator's
        normalization merge (searchpipeline/hybrid.py). `ledger_scope`
        (telemetry/ledger.py) accumulates this shard's transfer
        attribution for the caller's span / slow log."""
        node = dsl.parse_query(body.get("query"))
        if not isinstance(node, dsl.HybridQuery):
            raise IllegalArgumentError(
                "execute_hybrid_query_phase requires a top-level [hybrid] "
                "query")
        min_score = float(body["min_score"]) \
            if body.get("min_score") is not None else NEG_INF
        n_sub = len(node.queries)
        sub_nodes: List[dsl.QueryNode] = []
        for sub in node.queries:
            if extra_filter is not None:
                sub = dsl.BoolQuery(must=[sub],
                                    filter=[dsl.parse_query(extra_filter)])
            sub_nodes.append(sub)
        stats, segments, device = self.reader.stats_snapshot()
        compiler = Compiler(self.reader.mapper, stats)
        # per-sub-query candidate window = from+size, the reference's
        # per-shard TopDocs size for hybrid sub-queries (no tie overfetch:
        # no cursor path rides hybrid, and the window depth directly sets
        # both the top_k cost and the normalization pool)
        k_fetch = min(k, 1 << 16)

        from opensearch_tpu.indices.query_cache import FilterCacheContext
        from opensearch_tpu.search.warmup import WARMUP
        scope = ledger_scope if ledger_scope is not None \
            else _LEDGER.scope()
        launched = []
        struct_parts: List[Any] = []
        shape_parts: List[Any] = []
        for seg_i, (seg, (arrays, meta)) in enumerate(
                zip(segments, device)):
            if seg.num_docs == 0:
                struct_parts.append(None)
                shape_parts.append(None)
                continue
            compiler.filter_ctx = FilterCacheContext(seg, arrays)
            plans = [compiler.compile(q, seg, meta) for q in sub_nodes]
            compiler.filter_ctx = None
            k_seg = min(k_fetch, pad_bucket(max(seg.num_docs, 1)))
            flat: List[Dict[str, np.ndarray]] = []
            for p in plans:
                p.flatten_inputs(flat)
            struct_parts.append(tuple(p.sig() for p in plans))
            shape_parts.append(tuple((k2, v.shape, v.dtype.num)
                                     for d in flat for k2, v in d.items()))
            # the B=1 envelope program: the SAME executable family as the
            # batched _msearch hybrid path (identical layout/treedef), so
            # single searches and batches share warmed executables
            stacked, treedef, _axes = stack_flat_inputs([flat])
            stacked.append(np.asarray([min_score], dtype=np.float32))
            buf, layout = pack_leaves(stacked)
            fn = _batched_hybrid_runner(plans, meta, k_seg, layout,
                                        treedef)

            def _dispatch(fn=fn, arrays=arrays, buf=buf):
                if faults.ENABLED:
                    faults.fire("query.dispatch")
                return fn(arrays, jnp.asarray(buf))
            launched.append((seg_i, k_seg, retry.call_with_retry(
                _dispatch, label="query.dispatch")))
            if scope is not None:
                # after the dispatch: a failed one must not count h2d
                # bytes that never crossed
                _LEDGER.record("upload.literals", "h2d", buf.nbytes,
                               scope=scope)
        if extra_filter is None:
            # register the fused executable's (plan-struct, shape-bucket)
            # signature so index-open / node-start warmup AOT-compiles the
            # hybrid program off the query path — replaying the recorded
            # body through multi_search reproduces exactly this B=1 group
            # (alias-filtered variants are skipped: the recorded body
            # alone cannot reproduce their plans)
            WARMUP.record(self.reader.index_name, body, 1,
                          ("hybenv", tuple(struct_parts),
                           tuple(shape_parts), k_fetch, 1))

        result = _empty_hybrid_result(n_sub)
        if launched:
            def _collect():
                if faults.ENABLED:
                    faults.fire("fetch.gather")
                return jax.device_get([out for _, _, out in launched])
            t0c = time.monotonic() if scope is not None else 0.0
            with _LEDGER.attributed(scope):
                fetched = retry.call_with_retry(_collect,
                                                label="fetch.gather")
            if scope is not None:
                _ledger_hybrid_rows(
                    scope, [(1, 1, k_seg, n_sub)
                            for _seg_i, k_seg, _ in launched],
                    (time.monotonic() - t0c) * 1000)
            for (seg_i, k_seg, _), rows in zip(launched, fetched):
                _accumulate_hybrid_row(result, np.asarray(rows)[0], seg_i,
                                       k_seg, n_sub)
        result.bounds = [tuple(b) for b in result.bounds]
        return result

    def _hit_dict(self, seg_i: int, ord_: int, score: Optional[float],
                  body: dict, segments=None) -> dict:
        """One search hit (fetch phase for a single doc) — shared by search()
        and multi_search(). `segments` is the query phase's snapshot
        list: under a concurrent refresh, `seg_i` must resolve against
        the list the candidates were produced over, not today's."""
        seg = (segments if segments is not None
               else self.reader.segments)[seg_i]
        hit = {"_index": self.reader.index_name,
               "_id": seg.doc_ids[ord_],
               "_score": score}
        src = _filter_source(seg.sources[ord_], body.get("_source", True))
        if src is not None:
            hit["_source"] = src
        return hit

    def multi_search(self, bodies: List[dict],
                     _bypass_request_cache: bool = False,
                     _raise_item_errors: bool = False,
                     task=None, deadline: Optional[float] = None,
                     trace=None,
                     phase_times: Optional[dict] = None,
                     waves: Optional[int] = None,
                     timelines: Optional[list] = None,
                     tenants: Optional[list] = None) -> dict:
        """_msearch: execute many search bodies, batching same-shaped
        score-sorted queries into single vmapped device programs per segment
        (reference: action/search/TransportMultiSearchAction fans bodies out
        concurrently; here concurrency is a batch axis on the MXU/VPU).

        A malformed sub-request (negative/non-numeric size/from/min_score,
        unparseable query, too-deep pagination) renders as a PER-ITEM
        error object — siblings execute normally, matching the
        reference's per-item failure contract.

        _bypass_request_cache: executable warmup replays must reach the
        device even when an identical body was just served (search/warmup
        — a cache hit would compile nothing).
        _raise_item_errors: the B=1 delegation from search() wants the
        exception, not an error item.
        task / deadline: cancellation + timeout checkpoints at wave
        boundaries — cancellation kills the whole envelope (the task IS
        the msearch request, reference TransportMultiSearchAction task)
        after draining in-flight waves, a passed deadline stops
        launching new waves and renders the unlaunched items as
        zero-hit `timed_out: true` partials while already-dispatched
        waves' results survive.
        waves: explicit wave count for the overlapped pipeline (None =
        the _effective_waves policy; warmup replays pass 1 so the
        recorded (plan-struct, shape-bucket, b_pad) signatures
        reproduce exactly).
        trace / phase_times: the envelope's transfer attribution —
        bytes_to_device/bytes_fetched/transfers land on the span when it
        records, device_get/bytes_fetched in phase_times for the
        caller's slow log (both only when the ledger or tracing is on;
        see telemetry/ledger.py's no-op discipline).

        Request lifecycle (telemetry/lifecycle.py): when the flight
        recorder is on and no timeline is bound (direct callers —
        bench, warmup, tests), this wrapper owns one for the envelope
        and completes it on EVERY exit, error paths included (a
        cancelled/faulted envelope must still be capture-eligible);
        REST/controller-owned requests pass straight through to the
        impl, which rides the bound timeline.
        timelines: per-body request timelines from the wave scheduler's
        batch-of-batches entry (search/scheduler.py) — wave events fan
        out to each owning request's lifecycle and the envelope itself
        owns NO timeline (the foreign requests' own wrappers complete
        theirs).
        tenants: per-body tenant ids from the scheduler (aligned with
        `timelines`) — the insights recorder's per-shape tenant
        breakdown reads them per item on coalesced waves; inline paths
        ride the thread-local binding instead."""
        if timelines is not None or not _FLIGHT.enabled \
                or _FLIGHT.current() is not None:
            return self._multi_search_impl(
                bodies, _bypass_request_cache, _raise_item_errors, task,
                deadline, trace, phase_times, waves, timelines, tenants)
        tl = _FLIGHT.timeline()
        if tl is None:      # disabled race: behave as the gate said
            return self._multi_search_impl(
                bodies, _bypass_request_cache, _raise_item_errors, task,
                deadline, trace, phase_times, waves, tenants=tenants)
        tl.event("admit")
        prev = _FLIGHT.bind(tl)
        status = "error"
        try:
            res = self._multi_search_impl(
                bodies, _bypass_request_cache, _raise_item_errors, task,
                deadline, trace, phase_times, waves, tenants=tenants)
            status = "ok"
            return res
        finally:
            _FLIGHT.unbind(prev)
            tl.event("respond")
            _FLIGHT.complete(tl, status=status, span=trace)

    def _multi_search_impl(self, bodies: List[dict],
                           _bypass_request_cache: bool = False,
                           _raise_item_errors: bool = False,
                           task=None, deadline: Optional[float] = None,
                           trace=None,
                           phase_times: Optional[dict] = None,
                           waves: Optional[int] = None,
                           timelines: Optional[list] = None,
                           tenants: Optional[list] = None) -> dict:
        TELEMETRY.metrics.counter("msearch.requests").inc()
        TELEMETRY.metrics.counter("msearch.bodies").inc(len(bodies))
        scope = _LEDGER.scope(trace)
        # the request's lifecycle timeline, bound by whoever owns it
        # (REST / controller / the multi_search wrapper above).
        # Disabled: one attribute load + branch.
        tl = _FLIGHT.current() if _FLIGHT.enabled else None
        if tl is not None:
            tl.route()      # arrive→envelope-entry gap becomes `route`
        # scheduler-coalesced envelopes carry the owning requests' own
        # timelines instead: each request's pre-envelope gap (admission
        # glue minus its recorded queue_wait) becomes ITS `route`
        fan_tls = _distinct_timelines(timelines) if timelines else None
        if fan_tls:
            for _ftl in fan_tls:
                _ftl.route()
        start = time.monotonic()
        if task is not None:
            task.check_cancelled()
        ph = dict.fromkeys(MSEARCH_PHASE_NAMES, 0.0)
        _t = time.monotonic()
        responses: List[Optional[dict]] = [None] * len(bodies)

        resp_cache_keys: Dict[int, Any] = {}
        batchable: List[Tuple[int, dict, Any, int, int, float]] = []
        hybrid_items: List[Tuple[int, dict]] = []
        for i, body in enumerate(bodies):
            if task is not None and i % 16 == 0:
                # general-path items execute inline here, so the parse
                # loop is itself a sequence of safe points
                task.check_cancelled()
            if deadline is not None and time.monotonic() > deadline:
                responses[i] = _timed_out_item(start)
                continue
            _run_item_isolated(
                responses, i, _raise_item_errors,
                lambda: self._msearch_parse_one(
                    i, body or {}, responses, batchable, hybrid_items,
                    resp_cache_keys, _bypass_request_cache, start,
                    # the per-item tenant rides into the cache-hit note:
                    # on a scheduler-coalesced envelope this loop runs
                    # on the scheduler thread, where the REST layer's
                    # thread-local binding never reached
                    tenant=tenants[i] if tenants is not None else None))

        ph["parse"] += time.monotonic() - _t
        # Overlapped multi-wave dispatch: the batchable list splits into
        # power-of-two-bucketed waves; wave N+1's host work and async
        # dispatch run while wave N's device_get is in flight on the
        # collector thread (bounded in-flight window). Hybrid items ride
        # the same engine as their own wave, and a single-wave envelope
        # (B=1, small batches) degenerates to the inline flow — no
        # thread. (Round 7 measured two-wave pipelining as a wash; the
        # host cost that made it one has since dropped 2.6× (PR 5) and
        # the round-9 ledger proved the wall is the dispatch-sync, not
        # byte volume — see PROFILE.md round 10 for the re-measurement.)
        wave_list: List[_MsearchWave] = []
        if hybrid_items:
            wave_list.append(_MsearchWave(
                "hybrid", [i for i, _b in hybrid_items], hybrid_items,
                raise_errors=_raise_item_errors))
        if batchable:
            n_waves = _effective_waves(len(batchable)) if waves is None \
                else max(int(waves), 1)
            off = 0
            for size in _wave_sizes(len(batchable), n_waves):
                chunk = batchable[off:off + size]
                off += size
                wave_list.append(_MsearchWave(
                    "plain", [e[0] for e in chunk], chunk,
                    raise_errors=_raise_item_errors))
        if wave_list:
            # mixed hybrid+plain envelopes have >1 waves structurally;
            # whether they OVERLAP still follows the wave-count policy
            # (explicit waves>1 / FORCED_WAVES win, else the backend
            # probe) — on the unforced CPU fallback they run
            # inline-sequentially, exactly the old flow
            explicit = waves if waves is not None else FORCED_WAVES
            allow_pipeline = (int(explicit) > 1 if explicit is not None
                              else _overlap_capable())
            self._run_wave_pipeline(
                wave_list, responses, start, ph, task=task,
                deadline=deadline, scope=scope,
                resp_cache_keys=resp_cache_keys,
                allow_pipeline=allow_pipeline, timeline=tl,
                item_timelines=timelines, item_tenants=tenants)
        # parse always runs; the wave phases only get a sample when a
        # batched wave actually executed — otherwise every all-general or
        # all-hybrid envelope would log spurious 0-ms device_get/respond
        # samples and drag the telemetry percentiles toward zero
        _PHASE_HISTS["parse"].observe(ph["parse"] * 1000)
        if batchable:
            for name, sec in ph.items():
                if name != "parse":
                    _PHASE_HISTS[name].observe(sec * 1000)
        TELEMETRY.metrics.histogram("msearch.batch_ms").observe(
            (time.monotonic() - start) * 1000)
        if scope is not None:
            # the envelope's transfer attribution (the shared
            # LedgerScope.publish contract): fixes the spuriously-zero
            # bytes_to_device on envelope/hybrid-served spans (the old
            # accounting lived only in the general path's single-branch
            # sum)
            scope.publish(trace, phase_times)
        if tl is not None:
            # the envelope's phase decomposition lands on the request's
            # lifecycle (parse/compile_group/stack_pack_dispatch/
            # device_get/respond are disjoint, so a captured slow
            # envelope explains its own took — tools/tail_report.py).
            # `coordinate` is the controller's `render` catch-all
            # analog: everything inside the envelope the five phase
            # timers don't bracket (wave splitting, scope/gauge
            # bookkeeping, collector handoff) — without it a slow
            # envelope under GIL contention leaves its glue time
            # unattributed. max(0): pipelined waves' phases overlap
            # wall-clock, so their sum can exceed the envelope wall.
            ph_ms = {name: sec * 1000.0 for name, sec in ph.items()}
            glue = (time.monotonic() - start) * 1000.0 \
                - sum(ph_ms.values())
            if glue > 0:
                ph_ms["coordinate"] = glue
            tl.merge_phases(ph_ms)
            tl.mark_ready()
        if fan_tls:
            # each coalesced request WAITED for the whole shared
            # envelope, so the envelope's phase decomposition explains
            # each request's wall: merge it into every owner (their own
            # threads mark_ready/complete after demux)
            ph_ms = {name: sec * 1000.0 for name, sec in ph.items()}
            for _ftl in fan_tls:
                _ftl.merge_phases(ph_ms)
        return {"took": int((time.monotonic() - start) * 1000),
                "responses": responses}

    def _run_wave_pipeline(self, wave_list: List[_MsearchWave], responses,
                           start: float, ph: dict, task=None,
                           deadline: Optional[float] = None, scope=None,
                           resp_cache_keys: Optional[dict] = None,
                           allow_pipeline: bool = True,
                           timeline=None,
                           item_timelines: Optional[list] = None,
                           item_tenants: Optional[list] = None) -> None:
        """Drive the wave engine: prepare + async-dispatch each wave on
        THIS thread, collect on the collector thread (bounded in-flight
        window), and merge per-wave phase times, ledger scopes and
        overlap attribution once everything drained.

        The PR 6 checkpoints live at the wave boundaries: a cancellation
        raises here after in-flight waves drain (their buffers release,
        the device-memory gauge returns to baseline); a passed deadline
        renders the unlaunched waves' items as zero-hit timed-out
        partials while dispatched waves still finish and their results
        survive. len(wave_list) == 1 is the degenerate W=1 pipeline —
        fully inline, no thread — which the B=1 single-search delegation
        and hybrid-only envelopes ride. `allow_pipeline` carries the
        wave-count policy's verdict: a mixed hybrid+plain envelope has
        >1 waves structurally, but must still run inline-sequentially
        where the policy says overlap cannot pay (the CPU fallback,
        unforced)."""
        pipelined = len(wave_list) > 1 and allow_pipeline
        collector = _WaveCollector(
            lambda w: self._collect_wave(w, responses, start),
            MSEARCH_INFLIGHT_WINDOW) if pipelined else None
        dispatched: List[_MsearchWave] = []
        try:
            for wave_idx, wave in enumerate(wave_list):
                wave.index = wave_idx
                wave.timeline = timeline
                if timeline is None and item_timelines is not None:
                    # scheduler-coalesced wave: fan its events out to
                    # every owning request's timeline (one per request,
                    # however many of its items share the wave)
                    fanned = _distinct_timelines(item_timelines,
                                                 wave.items)
                    if fanned:
                        wave.timeline = _TimelineFan(fanned)
                if task is not None:
                    task.check_cancelled()
                if deadline is not None and time.monotonic() > deadline:
                    for i in wave.items:
                        if responses[i] is None:
                            responses[i] = _timed_out_item(start)
                    continue
                breaker = WAVE_BREAKER.gate()
                if breaker is not None:
                    # device-memory breaker (common/admission.py): a
                    # node whose in-flight wave buffers are over budget
                    # sheds this WAVE as per-item 429s through the PR 6
                    # per-item machinery — never a 5xx. Checked BEFORE
                    # prepare so a shed wave allocates nothing; the
                    # half-open probe's collect outcome reports back in
                    # the merge loop below.
                    berr, wave.breaker_probe = breaker.pre_wave(
                        _DEVMEM.live_bytes("wave_buffers"))
                    if berr is not None:
                        if wave.raise_errors:
                            raise berr
                        item = _item_error(berr)
                        for i in wave.items:
                            if responses[i] is None:
                                responses[i] = dict(item)
                        continue
                if wave.timeline is not None:
                    # coalesce: which wave this request's items ride and
                    # with how many co-batched siblings — fanned to
                    # every owning request on a scheduler-coalesced
                    # wave, where co_batched counts CROSS-REQUEST
                    # companions
                    wave.timeline.event("coalesce", wave=wave_idx,
                                        co_batched=len(wave.items),
                                        kind=wave.kind)
                if collector is not None:
                    # bounded in-flight window: block until a slot frees
                    # BEFORE compiling/dispatching the next wave
                    wave.window = collector.acquire_slot()
                wave.scope = LedgerScope() if scope is not None else None
                wave.prep_t0 = time.monotonic()
                if wave.kind == "hybrid":
                    wave.state = self._msearch_hybrid_prepare(
                        wave.payload, responses, start,
                        wave.raise_errors, scope=wave.scope)
                else:
                    wave.state = self._msearch_prepare(
                        wave.payload, responses, start, wave.ph,
                        wave.raise_errors, deadline=deadline,
                        scope=wave.scope)
                    wave.state["resp_cache_keys"] = resp_cache_keys or {}
                wave.prep_t1 = time.monotonic()
                # the in-flight gauges rise HERE (not inside prepare) so
                # an exception out of prepare can never strand them; the
                # collect path and the finally below are the two release
                # points — no exit path leaks
                _DEVMEM.adjust("wave_buffers",
                               wave.state.get("wave_buffer_bytes", 0))
                _LEDGER.note_wave_inflight(+1)
                if wave.timeline is not None:
                    wave.timeline.event("dispatch", wave=wave_idx,
                                        inflight=_LEDGER
                                        .inflight_waves())
                dispatched.append(wave)
                if collector is None:
                    if task is not None:
                        task.check_cancelled()
                    self._collect_wave(wave, responses, start)
                else:
                    collector.submit(wave)
        finally:
            if collector is not None:
                collector.drain()
            # backstop for waves whose collect never ran or died before
            # its release points (e.g. the inline path's pre-collect
            # cancellation checkpoint fired between dispatch and
            # collect): after drain() every submitted wave has been
            # collected, so an unset collect_t1 means THIS wave still
            # owns its inflight-gauge slot and its buffers
            for wave in dispatched:
                _release_wave_gauges(wave.state)
                if not wave.collect_t1:
                    _LEDGER.note_wave_inflight(-1)
            # device-memory breaker probe verdicts — in the finally so
            # no exit path (cancellation, raised wave error, crashed
            # prepare) can strand the breaker half-open with a probe
            # outstanding: a clean collect closes it, anything else
            # re-opens it
            _dispatched_ids = {id(w) for w in dispatched}
            for w in wave_list:
                if w.breaker_probe:
                    WAVE_BREAKER.on_result(
                        id(w) in _dispatched_ids and w.error is None
                        and bool(w.collect_t1))
        # merge per-wave accounting on this thread (single writer):
        # phase times sum, wave scopes absorb into the request scope,
        # and each wave's measured overlap — its prepare/dispatch time
        # that ran while an earlier wave's device_get was in flight —
        # lands in the ledger as a first-class number
        collects: List[Tuple[float, float]] = []
        pipeline_error: Optional[Exception] = None
        for wave in dispatched:
            for name, sec in wave.ph.items():
                ph[name] += sec
            if wave.scope is not None:
                wave.scope.waves += 1
            if pipelined and collects:
                # this wave's prepare/dispatch time during which an
                # earlier wave's device_get was in flight — the
                # pipeline's measured win (first wave has nothing to
                # overlap with, so it records no event)
                overlap_s = sum(
                    max(0.0, min(c1, wave.prep_t1)
                        - max(c0, wave.prep_t0))
                    for c0, c1 in collects)
                _LEDGER.note_overlap(overlap_s * 1000.0,
                                     scope=wave.scope)
                if wave.timeline is not None:
                    # per-wave overlap as a lifecycle event: what
                    # tools/trace_report.py's pipeline table reads
                    wave.timeline.event("overlap", wave=wave.index,
                                        ms=round(overlap_s * 1000.0, 3))
            if wave.collect_t1:
                collects.append((wave.collect_t0, wave.collect_t1))
            if wave.scope is not None and scope is not None:
                scope.absorb(wave.scope)
            if wave.error is not None and wave.raise_errors \
                    and pipeline_error is None:
                pipeline_error = wave.error
        if pipeline_error is not None:
            raise pipeline_error
        self._note_wave_insights(dispatched, responses, timeline,
                                 item_timelines, item_tenants)

    def _note_wave_insights(self, dispatched: List[_MsearchWave],
                            responses, timeline,
                            item_timelines: Optional[list],
                            item_tenants: Optional[list]) -> None:
        """Per-item insights notes + timeline shape annotation at wave
        merge (ISSUE 15): runs on the dispatching thread AFTER the
        collector drained, so every wave's responses, phase walls and
        ledger scope are final (single writer — no lock beyond the
        recorder's own). Shared wave costs split across the wave's live
        grouped items exactly as the scheduler's `device_share_ms`
        split: the device_get wall divides evenly, ledger byte/round-
        trip integers divide with the remainder landing on the first
        live item so per-shape totals conserve EXACTLY against the
        global ledger. Scan bytes were attributed per item at prepare
        (including items a mid-envelope deadline later expired — the
        heat map counted their compile-time scan, so the per-shape join
        must too). Runs only when prepare built shape meta: insights or
        flight recorder enabled."""
        ins = _INSIGHTS.gate()
        for wave in dispatched:
            meta = (wave.state or {}).get("insights")
            if not meta:
                continue
            dead = (wave.state or {}).get("dead") or set()
            co = len(wave.items)
            live = [i for i in meta
                    if meta[i]["grouped"] and i not in dead
                    and isinstance(responses[i], dict)
                    and "error" not in responses[i]]
            if not live:
                # every grouped item errored or deadline-expired, but
                # the wave's uploads may already have crossed (the
                # ledger counted them): split over ALL grouped items so
                # the per-shape byte totals still conserve exactly
                # against the global ledger
                live = [i for i in sorted(meta) if meta[i]["grouped"]]
            n_live = len(live)
            live_set = set(live)
            # the wave's shared device wall: the finish half's measured
            # device_get (seconds in wave.ph), the ledger's attributed
            # wall for hybrid waves, else the collect duration
            dev_ms = wave.ph.get("device_get", 0.0) * 1000.0
            if not dev_ms and wave.scope is not None:
                dev_ms = wave.scope.device_get_ms
            if not dev_ms and wave.collect_t1:
                dev_ms = (wave.collect_t1 - wave.collect_t0) * 1000.0
            h2d = wave.scope.h2d_bytes if wave.scope is not None else 0
            d2h = wave.scope.d2h_bytes if wave.scope is not None else 0
            rts = wave.scope.round_trips if wave.scope is not None else 0
            dev_share = dev_ms / n_live if n_live else 0.0
            h2d_q, h2d_r = divmod(h2d, n_live) if n_live else (0, 0)
            d2h_q, d2h_r = divmod(d2h, n_live) if n_live else (0, 0)
            rt_q, rt_r = divmod(rts, n_live) if n_live else (0, 0)
            rem_pending = n_live > 0
            for i in sorted(meta):
                m = meta[i]
                resp = responses[i]
                if not isinstance(resp, dict):
                    continue        # never answered (catastrophic wave)
                in_split = i in live_set
                eh, ed, er = (h2d_q, d2h_q, rt_q) if in_split \
                    else (0, 0, 0)
                if in_split and rem_pending:
                    eh, ed, er = eh + h2d_r, ed + d2h_r, er + rt_r
                    rem_pending = False
                tl_i = item_timelines[i] \
                    if item_timelines is not None else timeline
                if tl_i is not None and \
                        getattr(tl_i, "shape", "") is None:
                    # the tail-capture shape annotation ("which shape
                    # owns the p99" — tools/tail_report.py): first
                    # resolved item wins for a multi-item envelope's
                    # single owned timeline; scheduler-coalesced waves
                    # stamp each owner with its OWN item's shape
                    tl_i.shape = m["label"]
                if ins is None:
                    continue
                status = "error" if "error" in resp else "ok"
                item_dev = dev_share if in_split else 0.0
                ins.note(
                    m["label"], kind=m["kind"],
                    took_ms=float(resp.get("took", 0))
                    if status == "ok" else 0.0,
                    device_ms=item_dev,
                    posting_bytes=m["posting"],
                    dense_bytes=m["dense"],
                    pruned_bytes=m.get("pruned", 0),
                    h2d_bytes=eh, d2h_bytes=ed, round_trips=er,
                    co_batched=co,
                    # kernel-family breakdown (ISSUE 19): the item's
                    # device-wall share against the family its group
                    # program dispatched — the per-shape dominant-kernel
                    # join GET /_insights/top_queries surfaces
                    kernels={m["family"]: item_dev}
                    if item_dev and m.get("family") else None,
                    # warm=None (hybrid) = no bundle verdict exists:
                    # count neither compiled nor warm
                    compiled=m["warm"] is False,
                    warm_hit=bool(m["warm"]),
                    status=status,
                    tenant=item_tenants[i]
                    if item_tenants is not None
                    else ins.current_tenant())

    def _collect_wave(self, wave: _MsearchWave, responses,
                      start: float) -> None:
        """Wave half 2, on the collector thread (or inline for W=1):
        device_get + response assembly. `wave.scope` is the LedgerScope
        handed across the queue/thread boundary — the finish halves
        open their own LEDGER.attributed regions on THIS thread, so the
        sanitizer contract holds with the collector active. An escaping
        exception is captured per wave: the owning wave's unanswered
        items render as error objects, sibling waves are untouched."""
        scope = wave.scope
        wave.collect_t0 = time.monotonic()
        try:
            if wave.kind == "hybrid":
                self._msearch_hybrid_finish(wave.state, responses, start,
                                            scope=scope)
            else:
                self._msearch_finish(wave.state, responses, start,
                                     wave.ph, scope=scope)
        except Exception as e:  # except-ok: per-wave isolation -- a collect failure downgrades only this wave's items, never siblings or the envelope
            wave.error = e
        finally:
            wave.collect_t1 = time.monotonic()
            if wave.timeline is not None:
                # collect lands on the owning request's lifecycle from
                # THIS thread (appends are GIL-atomic; the timeline is
                # only read after the pipeline drains)
                wave.timeline.event(
                    "collect", wave=wave.index,
                    ms=round((wave.collect_t1 - wave.collect_t0) * 1000,
                             3),
                    device_get_ms=round(wave.scope.device_get_ms, 3)
                    if wave.scope is not None else None)
            state = wave.state or {}
            _release_wave_gauges(state)
            # collect done ⇒ the device program finished reading its
            # (zero-copy-aliased) input envelope: staging is reusable
            for buf in state.pop("staging", ()):
                self._staging.release(buf)
            _LEDGER.note_wave_inflight(-1)
            if wave.window is not None:
                wave.window.release()
        if wave.error is not None and not wave.raise_errors:
            err = _item_error(wave.error) \
                if isinstance(wave.error, OpenSearchTpuError) \
                else _item_error_untyped(wave.error)
            for i in wave.items:
                if responses[i] is None:
                    responses[i] = dict(err)

    def _msearch_parse_one(self, i: int, body: dict, responses, batchable,
                           hybrid_items, resp_cache_keys,
                           bypass_request_cache: bool,
                           start: float,
                           tenant: Optional[str] = None) -> None:
        """One sub-request of the parse loop: route to the general path /
        hybrid envelope / request cache, or intern + validate it into the
        batchable list. Raises OpenSearchTpuError for malformed items —
        multi_search converts that to a per-item error object."""
        if not _msearch_batchable(body):
            if _hybrid_msearch_batchable(body):
                # hybrid bodies batch through their own envelope: one
                # vmapped fused multi-sub-query program per
                # (plan-struct, shape) group
                hybrid_items.append((i, body))
            else:
                responses[i] = self.search(body, _direct=True)
            return
        # template interning: structural signature + stripped literals
        # (dsl.intern_query); None = a shape only the full parser handles
        tpl = dsl.intern_query(body.get("query")) if TEMPLATE_INTERNING \
            else None
        rc = _request_cache()
        if rc.cacheable(body, query_now_safe=tpl is not None) \
                and not bypass_request_cache:
            # shard request cache at QUERY-PHASE granularity: the
            # cached value is (total, decoded partials, agg nodes) —
            # live objects the renderers only read — and the response
            # is rebuilt per hit, so caller mutations of a returned
            # response can't leak back in (the old design serialized
            # the whole response to JSON for that guarantee, which
            # cost a full dumps per MISS on the respond hot path).
            # A refresh/delete rotates segment uids/live counts out
            # of the key
            base = rc.cache_key(self.reader.segments, body, 0, None,
                                query_key=tpl.key if tpl is not None
                                else None)
            if base is not None:
                key = ("msearch", base)
                hit = _cache_get_isolated(rc, key)
                if hit is not rc.REQUEST_CACHE._MISS:
                    responses[i] = self._render_cached_msearch(hit, start)
                    ins = _INSIGHTS.gate()
                    if ins is not None:
                        # a cache-served sub-request is still a
                        # completed request of its shape: count it
                        # (zero device/scan bytes — the scan counters
                        # don't see cache hits either, so per-shape
                        # totals stay byte-exact vs the heat map)
                        label, kind = _item_shape(tpl, body)
                        ins.note(label, kind=kind,
                                 took_ms=float(
                                     responses[i].get("took", 0)),
                                 cached=True,
                                 tenant=tenant if tenant is not None
                                 else ins.current_tenant())
                    return
                resp_cache_keys[i] = key
        if tpl is None:
            _INTERN_FALLBACKS.inc()
            try:
                node: Any = dsl.parse_query(body.get("query"))
            except OpenSearchTpuError:
                raise
            except Exception:  # except-ok: per-item isolation -- the general path renders the proper error object for this item
                # surface the error uniformly via the general path
                responses[i] = self.search(body, _direct=True)
                return
        else:
            node = tpl
        size = _req_int(body, "size", 10)
        from_ = _req_int(body, "from", 0)
        if size < 0 or from_ < 0:
            raise IllegalArgumentError(
                "[from] parameter cannot be negative" if from_ < 0
                else "[size] parameter cannot be negative")
        if from_ + size > self.max_result_window:
            raise IllegalArgumentError(
                f"Result window is too large, from + size must be "
                f"less than or equal to: [{self.max_result_window}] "
                f"but was [{from_ + size}]. See the scroll api for a "
                f"more efficient way to request large data sets. This "
                f"limit can be set by changing the "
                f"[index.max_result_window] index level setting.")
        min_score = _req_min_score(body)
        batchable.append((i, body, node, size, from_, min_score))

    def _msearch_hybrid_prepare(self, items: List[Tuple[int, dict]],
                                responses, start: float,
                                raise_item_errors: bool = False,
                                scope=None) -> dict:
        """Hybrid wave half 1 (compile + group + stack + pack +
        DISPATCH, async): same-structure hybrid bodies become ONE
        vmapped fused program per (plan-struct, shape, k) group per
        segment — per-query launch cost amortizes exactly like the
        plain msearch envelope. Returns the state
        _msearch_hybrid_finish consumes. Responses use the DEFAULT
        normalization spec (pipeline-specific specs ride the REST path,
        where _run_search executes per query with the resolved
        processor chain)."""
        from opensearch_tpu.searchpipeline import hybrid as hyb
        # one consistent anchor for the hybrid wave (see _msearch_prepare)
        stats, segments, device = self.reader.stats_snapshot()
        compiler = Compiler(self.reader.mapper, stats)
        prepared: Dict[int, tuple] = {}
        groups: Dict[Any, List[int]] = {}
        # per-item shape meta (ISSUE 15): hybrid bodies are never
        # internable, so their shape class is the structural hash
        ins_items: Optional[Dict[int, dict]] = {} \
            if (_INSIGHTS.enabled or _FLIGHT.enabled) else None
        for i, body in items:
            try:
                min_score = _req_min_score(body)
                node = dsl.parse_query(body.get("query"))
                n_sub = len(node.queries)
                _s, _f, k = hyb.validate_hybrid_request(
                    body, n_sub, hyb.DEFAULT_SPEC, [self])
                k_fetch = min(k, 1 << 16)  # same window as the 1-query path
                plans_per_seg: List[Optional[list]] = []
                flats_per_seg: List[Optional[list]] = []
                for seg, (arrays, meta) in zip(segments, device):
                    if seg.num_docs == 0:
                        plans_per_seg.append(None)
                        flats_per_seg.append(None)
                        continue
                    plans = [compiler.compile(q, seg, meta)
                             for q in node.queries]
                    flat: List[Dict[str, np.ndarray]] = []
                    for p in plans:
                        p.flatten_inputs(flat)
                    plans_per_seg.append(plans)
                    flats_per_seg.append(flat)
            except OpenSearchTpuError as e:
                # already a well-typed request error (bad min_score,
                # invalid hybrid spec): render per item directly
                if raise_item_errors:
                    raise
                responses[i] = _item_error(e)
                continue
            except Exception:  # except-ok: per-item isolation -- a malformed hybrid body fails through the general path's renderer, not siblings
                # surface errors through the general path's renderer —
                # per item, so a malformed hybrid body can't fail siblings
                _run_item_isolated(responses, i, raise_item_errors,
                                   lambda: self.search(body, _direct=True))
                continue
            prepared[i] = (body, n_sub, min_score, plans_per_seg,
                           flats_per_seg)
            if ins_items is not None:
                from opensearch_tpu.telemetry.insights import \
                    structural_shape
                # warm=None: the hybrid path has no per-item bundle
                # memo, so a warm-vs-compiled verdict would be a guess —
                # the note pass counts NEITHER rather than reporting
                # compiled=True for every warm repeat
                ins_items[i] = {
                    "label": structural_shape(body.get("query")),
                    "kind": "hash", "posting": 0, "dense": 0,
                    "grouped": True, "warm": None, "interned": False,
                    "family": "hybrid_env"}
            struct = tuple(
                tuple(p.sig() for p in plans) if plans is not None
                else None for plans in plans_per_seg)
            shape_sig = tuple(
                None if f is None else tuple(
                    (k2, v.shape, v.dtype.num)
                    for d in f for k2, v in d.items())
                for f in flats_per_seg)
            groups.setdefault((struct, shape_sig, k_fetch), []).append(i)

        from opensearch_tpu.search.warmup import WARMUP
        pending = []
        dead: set = set()
        staging: List[np.ndarray] = []
        wave_buffer_bytes = 0
        for (struct, shape_sig, k_fetch), idxs in groups.items():
            b_pad = pad_bucket(len(idxs), minimum=1)
            pad_rows = b_pad - len(idxs)
            WARMUP.record(self.reader.index_name, prepared[idxs[0]][0],
                          b_pad, ("hybenv", struct, shape_sig, k_fetch,
                                  b_pad))
            min_scores = np.asarray(
                [prepared[i][2] for i in idxs] + [np.inf] * pad_rows,
                dtype=np.float32)
            for seg_i, (seg, (arrays, meta)) in enumerate(
                    zip(segments, device)):
                if seg.num_docs == 0:
                    continue
                group_flats = [prepared[i][4][seg_i] for i in idxs]
                group_flats += [group_flats[0]] * pad_rows
                stacked, treedef, axes = stack_flat_inputs(group_flats)
                stacked.append(min_scores)
                buf, layout = pack_leaves(stacked, pool=self._staging)
                k_seg = min(k_fetch, pad_bucket(max(seg.num_docs, 1)))
                plans0 = prepared[idxs[0]][3][seg_i]
                try:
                    fn = _batched_hybrid_runner(plans0, meta, k_seg,
                                                layout, treedef)

                    def _dispatch(fn=fn, arrays=arrays, buf=buf):
                        if faults.ENABLED:
                            faults.fire("query.dispatch")
                        return fn(arrays, jnp.asarray(buf))
                    out = retry.call_with_retry(_dispatch,
                                                label="msearch.dispatch")
                except Exception as e:  # except-ok: per-item isolation -- a failed hybrid group dispatch downgrades its items to error objects
                    if raise_item_errors:
                        raise
                    err = _item_error(e) \
                        if isinstance(e, OpenSearchTpuError) \
                        else _item_error_untyped(e)
                    for i in idxs:
                        responses[i] = dict(err)
                        dead.add(i)
                    break
                if scope is not None:
                    # after the dispatch: a failed one must not count
                    # h2d bytes that never crossed
                    _LEDGER.record("upload.literals", "h2d", buf.nbytes,
                                   scope=scope)
                staging.append(buf)
                wave_buffer_bytes += buf.nbytes
                pending.append((idxs, seg_i, k_seg, len(plans0), out))
        return {"prepared": prepared, "pending": pending, "dead": dead,
                "raise_item_errors": raise_item_errors,
                "staging": staging,
                "insights": ins_items,
                "wave_buffer_bytes": wave_buffer_bytes}

    def _msearch_hybrid_finish(self, state: dict, responses,
                               start: float, scope=None) -> None:
        """Hybrid wave half 2: ONE device_get for the wave's fused
        rows (run on the collector thread when the pipeline overlaps),
        then accumulate per-sub-query channels and render through the
        normalization merge."""
        prepared, pending, dead = (state["prepared"], state["pending"],
                                   state["dead"])
        raise_item_errors = state["raise_item_errors"]
        results = {i: _empty_hybrid_result(prepared[i][1])
                   for i in prepared}
        if pending:
            def _collect():
                if faults.ENABLED:
                    faults.fire("fetch.gather")
                return jax.device_get(
                    [packed for _, _, _, _, packed in pending])
            t0c = time.monotonic() if scope is not None else 0.0
            try:
                with _LEDGER.attributed(scope):
                    fetched = retry.call_with_retry(_collect,
                                                    label="fetch.gather")
                if scope is not None:
                    _ledger_hybrid_rows(
                        scope,
                        [(packed.shape[0], len(idxs), k_seg, n_sub)
                         for idxs, _s, k_seg, n_sub, packed in pending],
                        (time.monotonic() - t0c) * 1000)
            except Exception as e:  # except-ok: per-item isolation -- any device-fault class downgrades the wave's items to error objects, never the envelope
                if raise_item_errors:
                    raise
                err = _item_error(e) if isinstance(e, OpenSearchTpuError) \
                    else _item_error_untyped(e)
                for idxs, _s, _k, _n, _p in pending:
                    for i in idxs:
                        responses[i] = dict(err)
                        dead.add(i)
                fetched = []
                pending = []
            _release_wave_gauges(state)
            for (idxs, seg_i, k_seg, n_sub, _), packed in zip(pending,
                                                              fetched):
                packed = np.asarray(packed)
                for row_i, i in enumerate(idxs):
                    _accumulate_hybrid_row(results[i], packed[row_i],
                                           seg_i, k_seg, n_sub)
        _release_wave_gauges(state)
        from opensearch_tpu.searchpipeline import hybrid as hyb
        for i, result in results.items():
            if i in dead:
                continue
            body, n_sub = prepared[i][0], prepared[i][1]
            result.bounds = [tuple(b) for b in result.bounds]
            responses[i] = hyb.merge_and_render(
                [self], body, [result], hyb.DEFAULT_SPEC, start, n_sub)

    def _compile_msearch_bundle(self, compiler: Compiler, stats, tpl,
                                node, body: dict, agg_spec,
                                agg_json: Optional[str] = None,
                                snapshot=None,
                                force_full: bool = False) -> tuple:
        """Compile ONE sub-request's per-segment plans + flattened inputs
        + grouping signatures. When `tpl` (a dsl.QueryTemplate) is given,
        plans bind through the (template, segment) skeleton cache
        (Compiler.compile_interned); the returned bundle is what the
        per-(template, literals) memo stores, so a repeated body skips
        this function entirely."""
        from opensearch_tpu.parallel.distributed import plan_struct
        from opensearch_tpu.search.aggs.parse import PIPELINE_TYPES
        agg_nodes = parse_aggs(agg_spec)
        device_agg_nodes = [n for n in agg_nodes
                            if n.type not in PIPELINE_TYPES]
        # agg plans are (agg spec, segment)-static — memoized on the
        # reader stats like compiled text plans, so a dashboard workload
        # of repeated agg shapes skips the per-query bucket-table
        # recomputation (the Weight-cache analog)
        if agg_nodes and agg_json is None:
            agg_json = json.dumps(agg_spec, sort_keys=True, default=str)
        plans: List[Optional[Plan]] = []
        agg_plans_per_seg: List[list] = []
        segments, device = (snapshot if snapshot is not None
                            else self.reader.snapshot())
        for seg, (arrays, meta) in zip(segments, device):
            if seg.num_docs == 0:
                plans.append(None)
                agg_plans_per_seg.append([])
                continue
            plan = None
            if tpl is not None:
                plan = compiler.compile_interned(tpl, seg, meta)
            if plan is None:
                if node is None:
                    node = dsl.parse_query(body.get("query"))
                plan = compiler.compile(node, seg, meta)
            plans.append(plan)
            if not agg_nodes:
                agg_plans_per_seg.append([])
                continue
            memo_key = ("aggc", seg.uid, agg_json)
            aplans = stats.memo.get(memo_key)
            if aplans is None:
                aplans = compile_aggs(device_agg_nodes, self.reader.mapper,
                                      seg, meta, compiler)
                stats.memo[memo_key] = aplans
            agg_plans_per_seg.append(aplans)
        all_none = all(p is None or p.kind == "match_none" for p in plans)
        if all_none and not force_full:
            # force_full (the _PartialBundle tail-extension path) needs
            # real struct/flats even for an all-none tail slice — the
            # short-circuit form cannot concatenate positionally
            return (plans, None, None, None, None, agg_plans_per_seg,
                    agg_nodes, True)
        struct = tuple(plan_struct(p) if p is not None else None
                       for p in plans)
        flats: List[Optional[list]] = []
        for p, aplans in zip(plans, agg_plans_per_seg):
            if p is None:
                flats.append(None)
                continue
            flat = p.flatten_inputs([])
            for ap in aplans:
                ap.flatten_inputs(flat)
            flats.append(flat)
        shape_sig = tuple(
            None if f is None else tuple(
                (k2, v.shape, v.dtype.num)
                for d in f for k2, v in d.items())
            for f in flats)
        agg_sig = tuple(tuple(ap.sig() for ap in aplans)
                        for aplans in agg_plans_per_seg) \
            if agg_nodes else None
        return (plans, flats, struct, shape_sig, agg_sig,
                agg_plans_per_seg, agg_nodes, all_none)

    def _extend_msearch_bundle(self, compiler: Compiler, stats, tpl,
                               body: dict, agg_spec,
                               agg_json: Optional[str],
                               partial: _PartialBundle,
                               snapshot) -> tuple:
        """Complete a carried _PartialBundle (pure-append publish,
        ISSUE 16 tentpole b): compile ONLY the appended tail segments
        and concatenate the per-segment positional lists — a warm query
        after a 32-doc refresh pays one tail-segment compile instead of
        a whole-bundle rebuild. Returns the full 8-tuple for this
        snapshot's segment list."""
        segments, device = snapshot
        n = partial.n_segs
        (plans, flats, struct, shape_sig, agg_sig, agg_plans,
         agg_nodes, _all_none) = partial.bundle
        if n >= len(segments):
            return partial.bundle
        tail = self._compile_msearch_bundle(
            compiler, stats, tpl, None, body, agg_spec, agg_json,
            snapshot=(segments[n:], device[n:]), force_full=True)
        (t_plans, t_flats, t_struct, t_shape, t_agg_sig, t_aggs,
         _t_nodes, _t_all_none) = tail
        return (plans + t_plans, flats + t_flats, struct + t_struct,
                shape_sig + t_shape,
                (agg_sig + t_agg_sig) if agg_sig is not None else None,
                agg_plans + t_aggs, agg_nodes, False)

    def _msearch_prepare(self, batchable, responses, start, ph,
                         raise_item_errors: bool = False,
                         deadline: Optional[float] = None, scope=None):
        """Wave half 1: compile + group + stack + pack + DISPATCH (async).
        Returns the state _msearch_finish consumes.

        Template interning makes this phase O(unique (template, literals)
        pairs): interned bodies memoize their whole compiled bundle
        (plans, flattened inputs, grouping signatures) on the reader
        stats, so a warm repeated batch reduces to one memo lookup per
        query — zero plan compiles, zero DSL walks.

        Grouping is by plan STRUCTURE + per-segment input SHAPES: shapes
        are already power-of-two bucketed by the compiler, so shape-keyed
        groups stay few while making each group's stack a plain np.stack
        (no padding growth) and its kernel choice (candidate vs dense)
        uniform — one packed upload + one device program per group. The
        shape signature uses dtype.num (numpy's dtype.__str__ is slow on
        this path) and relies on deterministic dict insertion order."""
        _t = time.monotonic()
        groups: Dict[Any, List[int]] = {}
        # always-on scan accounting (telemetry/scan.py, ISSUE 14):
        # per-wave LOCAL accumulators, flushed in ONE note_batch call
        # below — the disabled-lock discipline the <2% gate demands
        _scan_rows: Dict[Any, list] = {}
        _scan_per_query: List = []
        # per-item STATIC posting bytes, kept for the finish half's
        # pruned-overlay flush (effective = static - pruned per query)
        _scan_posting_by_i: Dict[int, int] = {}
        # per-item shape meta (ISSUE 15): shape id + scan bytes + bundle
        # verdict, read back by the wave-merge note pass. Built when the
        # insights recorder wants cost rows OR the flight recorder wants
        # the shape annotation on captured timelines; both gates off =
        # one attribute load + branch, nothing allocates.
        ins_items: Optional[Dict[int, dict]] = {} \
            if (_INSIGHTS.enabled or _FLIGHT.enabled) else None
        compiled: Dict[int, List[Optional[Plan]]] = {}
        flats_by_i: Dict[int, List[Optional[list]]] = {}
        agg_by_i: Dict[int, List[list]] = {}      # i -> per-seg AggPlans
        agg_nodes_by_i: Dict[int, list] = {}      # i -> parsed AggNodes
        # one consistent anchor for the whole wave (prepare -> dispatch
        # -> finish): a concurrent refresh publishing mid-wave must not
        # re-pair seg_i between the compiled flats and the device arrays
        stats, segments, device = self.reader.stats_snapshot()
        compiler = Compiler(self.reader.mapper, stats)
        mapper_version = getattr(self.reader.mapper, "version", 0)

        def _general_fallback(i, body):
            # an agg/query shape the batch program can't express (or a
            # user error): the general path raises it properly — rendered
            # per item so one bad body can't fail siblings
            _run_item_isolated(responses, i, raise_item_errors,
                               lambda: self.search(body, _direct=True))

        for entry in batchable:
            i, body, node, size, from_, min_score = entry
            tpl = node if isinstance(node, dsl.QueryTemplate) else None
            agg_spec = body.get("aggs") or body.get("aggregations")
            bundle = bkey = agg_json = None
            if tpl is not None:
                try:
                    agg_json = (json.dumps(agg_spec, sort_keys=True,
                                           default=str) if agg_spec
                                else None)
                except Exception:  # except-ok: per-item isolation -- e.g. mixed-type agg keys; the general path owns the typed error
                    # e.g. mixed-type agg keys breaking sort_keys: the
                    # general path owns the proper error, per item
                    _general_fallback(i, body)
                    continue
                # gate in the key: bundles hold compiled plans, and a
                # blockmax flip changes plan inputs (tid/bscale) — a
                # stale-gate bundle would prune (or not) the wrong way
                bkey = ("qenv", mapper_version, tpl.sig, tpl.literals,
                        agg_json, _bm25.BLOCKMAX)
                bundle = stats.memo.get(bkey)
                if isinstance(bundle, _PartialBundle):
                    # pure-append carry (ISSUE 16): compile only the
                    # appended tail segments, re-store the completed
                    # bundle (two threads racing here duplicate one
                    # tail compile, harmlessly — last store wins)
                    try:
                        bundle = self._extend_msearch_bundle(
                            compiler, stats, tpl, body, agg_spec,
                            agg_json, bundle, (segments, device))
                    except Exception:  # except-ok: per-item isolation -- tail-compile failure falls back to the general path per item
                        _general_fallback(i, body)
                        continue
                    cost = _bundle_nbytes(bundle[1])
                    if cost <= _BUNDLE_MEMO_MAX_ENTRY_BYTES:
                        stats.memo.set(bkey, bundle, cost=cost)
            bundle_hit = bundle is not None
            if bundle is None:
                if tpl is not None:
                    _BUNDLE_MISSES.inc()
                try:
                    bundle = self._compile_msearch_bundle(
                        compiler, stats, tpl,
                        None if tpl is not None else node, body, agg_spec,
                        agg_json, snapshot=(segments, device))
                except Exception:  # except-ok: per-item isolation -- compile failure falls back to the general path per item
                    _general_fallback(i, body)
                    continue
                if bkey is not None:
                    # bundles hold flattened device inputs — charge their
                    # bytes against the memo's byte budget, and keep
                    # outliers (a single huge high-cardinality filter)
                    # out entirely rather than letting one entry evict a
                    # whole generation's working set
                    cost = _bundle_nbytes(bundle[1])
                    if cost <= _BUNDLE_MEMO_MAX_ENTRY_BYTES:
                        stats.memo.set(bkey, bundle, cost=cost)
            else:
                _BUNDLE_HITS.inc()
            (plans, flats, struct, shape_sig, agg_sig, agg_plans_per_seg,
             agg_nodes, all_none) = bundle
            # no tie overfetch needed: per-segment top-k by score with
            # doc-asc tie-break (lax.top_k picks the lowest index) merges
            # to the exact global page for score-sorted queries; size=0
            # (agg/count-only) requests skip hit selection entirely
            k = 0 if from_ + size == 0 else max(from_ + size, 10)
            if all_none:
                if agg_nodes:
                    # empty-match WITH aggs still owes fully-shaped empty
                    # agg structures — the general path builds those
                    _general_fallback(i, body)
                else:
                    # no term matched any segment: answer host-side, zero
                    # device work (the can-match pre-filter analog)
                    responses[i] = _base_response(
                        int((time.monotonic() - start) * 1000), 0, None,
                        [])
                    if ins_items is not None:
                        label, kind = _item_shape(node, body)
                        ins_items[i] = {
                            "label": label, "kind": kind, "posting": 0,
                            "dense": 0, "grouped": False,
                            "warm": bundle_hit,
                            "interned": tpl is not None}
                continue
            compiled[i] = plans
            flats_by_i[i] = flats
            if agg_nodes:
                agg_by_i[i] = agg_plans_per_seg
                agg_nodes_by_i[i] = agg_nodes
            groups.setdefault((struct, agg_sig, shape_sig,
                               min(k, 1 << 16)), []).append(i)
            # per-item posting/dense bytes from the compiled plans —
            # the kernel split mirrors _envelope_runner's decision
            # (candidate-buffer for plain text clauses within the lane
            # budget, dense otherwise), so the heat map's kernel mix
            # reflects what actually dispatches. One attribute read
            # per warm (memoized) plan, no per-lane work, no lock.
            n_scan0 = len(_scan_per_query)
            _scan_accumulate_item(device, plans, _scan_rows,
                                  _scan_per_query)
            _scan_posting_by_i[i] = _scan_per_query[-1][0] \
                if len(_scan_per_query) > n_scan0 else 0
            if ins_items is not None:
                # the per-item scan join (ISSUE 15): the SAME tuple the
                # always-on heat map just accumulated, so per-shape
                # totals conserve byte-exactly against telemetry.scan
                sp, sd = _scan_per_query[-1] \
                    if len(_scan_per_query) > n_scan0 else (0, 0)
                label, kind = _item_shape(node, body)
                plan0 = next((p for p in plans if p is not None), None)
                fam = None
                if plan0 is not None:
                    # kernel family for the insights breakdown (ISSUE
                    # 19): agg-bearing items dispatch the agg envelope;
                    # plain items the candidate/dense kernel the runner
                    # will pick (same predicate)
                    fam = "agg_env" if agg_nodes else (
                        "bm25_candidate"
                        if _envelope_kernel(plan0) == "candidate"
                        else _plan_family(plan0))
                ins_items[i] = {"label": label, "kind": kind,
                                "posting": sp, "dense": sd,
                                "grouped": True, "warm": bundle_hit,
                                "interned": tpl is not None,
                                "family": fam}

        from opensearch_tpu.telemetry.scan import SCAN
        SCAN.note_batch(self.reader.index_name,
                        str(getattr(self.reader, "shard_id", 0)),
                        _scan_rows, _scan_per_query)
        entry_by_i = {e[0]: e for e in batchable}
        ph["compile_group"] += time.monotonic() - _t
        _t = time.monotonic()
        from opensearch_tpu.parallel.distributed import plan_struct
        # dispatch every group × segment program without blocking — jax
        # dispatch is async, so device work and tunnel transfers overlap.
        # The batch axis is padded to a power-of-two bucket (dummy rows
        # get min_score=+inf, matching nothing) so executables are reused
        # across varying msearch batch sizes.
        from opensearch_tpu.search.warmup import WARMUP
        pending = []
        wave_buffer_bytes = 0   # in-flight packed uploads, released by
        # _msearch_finish once the wave's results are fetched
        staging: List[np.ndarray] = []  # pooled envelope buffers, back
        # to the pool once this wave's collect completes (zero-copy-safe)
        dead: set = set()       # items already answered (error/timeout):
        # _msearch_finish must not overwrite their responses
        for (struct, agg_sig, shape_sig, k_fetch), idxs in groups.items():
            if deadline is not None and time.monotonic() > deadline:
                # budget spent between waves: unlaunched groups render as
                # zero-hit timed-out partials, launched ones still finish
                for i in idxs:
                    if responses[i] is None:
                        responses[i] = _timed_out_item(start)
                    dead.add(i)
                continue
            b_pad = pad_bucket(len(idxs), minimum=1)
            pad_rows = b_pad - len(idxs)
            # register this (plan-struct, shape-bucket) combination so an
            # index-open / node-start warmup can AOT-compile its
            # executable off the query path (a representative body replayed
            # b_pad times reproduces exactly this group program)
            WARMUP.record(self.reader.index_name, entry_by_i[idxs[0]][1],
                          b_pad, (struct, agg_sig, shape_sig, k_fetch,
                                  b_pad))
            min_scores = np.asarray(
                [entry_by_i[i][5] for i in idxs]
                + [np.inf] * pad_rows, dtype=np.float32)
            for seg_i, (seg, (arrays, meta)) in enumerate(
                    zip(segments, device)):
                if seg.num_docs == 0:
                    continue
                group_flats = [flats_by_i[i][seg_i] for i in idxs]
                group_flats += [group_flats[0]] * pad_rows
                stacked, treedef, axes = stack_flat_inputs(
                    group_flats, with_const=agg_sig is not None)
                stacked.append(min_scores)
                axes.append(0)
                buf, layout = pack_leaves(stacked, pool=self._staging)
                k_seg = min(k_fetch, pad_bucket(max(seg.num_docs, 1)))
                plan0 = compiled[idxs[0]][seg_i]
                try:
                    if agg_sig is not None:
                        fn, out_layout, agg_w = _agg_envelope_runner(
                            plan_struct(plan0), plan0, meta, k_seg,
                            layout, treedef, tuple(axes), agg_sig[seg_i],
                            agg_by_i[idxs[0]][seg_i], arrays,
                            group_flats[0])
                    else:
                        fn = _envelope_runner(plan_struct(plan0), plan0,
                                              meta, k_seg, layout,
                                              treedef)
                        out_layout = None

                    def _dispatch(fn=fn, arrays=arrays, buf=buf):
                        if faults.ENABLED:
                            faults.fire("query.dispatch")
                        return fn(arrays, jnp.asarray(buf))
                    out = retry.call_with_retry(_dispatch,
                                                label="msearch.dispatch")
                except Exception as e:  # except-ok: per-item isolation -- a runtime device fault downgrades only this group's items
                    # a runtime device fault downgrades ONLY this group's
                    # items to per-item error objects (extending the
                    # malformed-item machinery to runtime faults) — the
                    # envelope and sibling groups are untouched
                    if raise_item_errors:
                        raise
                    err = _item_error(e) \
                        if isinstance(e, OpenSearchTpuError) \
                        else _item_error_untyped(e)
                    for i in idxs:
                        responses[i] = dict(err)
                        dead.add(i)
                    break       # no point dispatching more segments
                if scope is not None:
                    # record AFTER the dispatch succeeded: a failed
                    # dispatch must not count h2d bytes that never
                    # crossed (conservation). Const agg tables
                    # (in_axes=None leaves) are a distinct channel: one
                    # copy serves the whole batch, so their bytes scale
                    # with groups, not with B.
                    const_b = sum(int(a.nbytes)
                                  for a, ax in zip(stacked, axes)
                                  if ax is None) \
                        if agg_sig is not None else 0
                    if const_b:
                        _LEDGER.record("upload.agg_constants", "h2d",
                                       const_b, scope=scope)
                    _LEDGER.record("upload.literals", "h2d",
                                   buf.nbytes - const_b, scope=scope)
                # the in-flight gauge is ALWAYS fed (an int add here; the
                # device-memory classes are live like corpus_columns,
                # not ledger-gated) but NOT adjusted here: multi_search
                # raises it once from the returned total, so an
                # exception out of this loop can never strand bytes
                wave_buffer_bytes += buf.nbytes
                staging.append(buf)
                # bm: whether this program's packed rows carry the extra
                # pruned-count lane — MUST mirror _envelope_runner's
                # admission (same predicate on the same plan/k)
                pending.append((idxs, seg_i, k_seg, out, out_layout,
                                agg_sig is None
                                and _blockmax_admitted(plan0, k_seg)))
        ph["stack_pack_dispatch"] += time.monotonic() - _t
        return {"groups": groups, "entry_by_i": entry_by_i,
                "pending": pending, "agg_by_i": agg_by_i,
                "agg_nodes_by_i": agg_nodes_by_i, "dead": dead,
                "staging": staging,
                "wave_buffer_bytes": wave_buffer_bytes,
                # per-item shape meta for the insights note pass
                "insights": ins_items,
                "scan_posting": _scan_posting_by_i,
                # the wave's (segments, device) anchor: finish resolves
                # seg_i hits against THIS list, never a later publish
                "segments": segments}

    def _msearch_finish(self, state, responses, start, ph, scope=None):
        """Wave half 2: ONE device_get for the wave's outputs (concatenated
        on device = one transfer round trip), then COLUMNAR response
        assembly: per query the hit page is sliced from the fetched
        [B, k] score/ord arrays and converted once (`.tolist()` — Python
        floats/ints in bulk instead of a np-scalar cast per hit), doc ids
        and sources resolve through hoisted per-segment lists, and every
        response shares the `_base_response` skeleton. Replaces the
        per-query per-hit `_hit_dict` call chain that dominated the old
        respond phase."""
        _t = time.monotonic()
        groups, entry_by_i, pending = (state["groups"], state["entry_by_i"],
                                       state["pending"])
        agg_by_i = state.get("agg_by_i") or {}
        agg_nodes_by_i = state.get("agg_nodes_by_i") or {}
        dead = state.get("dead") or set()
        grouped = [i for idxs in groups.values() for i in idxs]
        per_query_segs: Dict[int, List[Tuple[int, np.ndarray, np.ndarray]]] = \
            {i: [] for i in grouped}
        per_query_total: Dict[int, int] = {i: 0 for i in grouped}
        per_query_decoded: Dict[int, list] = {i: [] for i in agg_by_i}
        if not pending:
            _release_wave_gauges(state)
            return

        # [actually transferred d2h bytes, round trips] — filled by the
        # fetch closures so the ledger attributes REAL buffer sizes
        # (combined-fetch padding included) and true round-trip counts
        fetch_stats = [0, 0]

        def _fetch_all():
            if faults.ENABLED:
                faults.fire("fetch.gather")
            if len(pending) > 1:
                combined = np.asarray(jax.device_get(_concat_rows(
                    tuple(p[3] for p in pending))))
                fetch_stats[0] = combined.nbytes
                fetch_stats[1] = 1
                out = []
                row = 0
                for p in pending:
                    rows, width = p[3].shape
                    out.append(combined[row:row + rows, :width])
                    row += rows
                return out
            out = jax.device_get([p[3] for p in pending])
            fetch_stats[0] = sum(int(np.asarray(a).nbytes) for a in out)
            fetch_stats[1] = 1
            return out

        with _LEDGER.attributed(scope):
            try:
                fetched = retry.call_with_retry(_fetch_all,
                                                label="fetch.gather")
            except Exception:   # except-ok: combined-gather isolation -- any failure class degrades to per-program fetches below
                # the combined gather failed as a unit: fall back to one
                # fetch per dispatched program, so a single bad program
                # downgrades only ITS items to error objects
                fetched = []
                fetch_stats[0] = fetch_stats[1] = 0
                for idxs, _seg_i, _k_seg, packed, _ol, _bm in pending:
                    def _one(packed=packed):
                        if faults.ENABLED:
                            faults.fire("fetch.gather")
                        return np.asarray(jax.device_get(packed))
                    try:
                        got = retry.call_with_retry(_one,
                                                    label="fetch.gather")
                        fetched.append(got)
                        fetch_stats[0] += got.nbytes
                        fetch_stats[1] += 1
                    except Exception as e:  # except-ok: per-item isolation -- a bad program downgrades only ITS items to error objects
                        fetched.append(None)
                        err = _item_error(e) \
                            if isinstance(e, OpenSearchTpuError) \
                            else _item_error_untyped(e)
                        for i in idxs:
                            responses[i] = dict(err)
                            dead.add(i)
        collect_s = time.monotonic() - _t
        ph["device_get"] += collect_s; _t = time.monotonic()
        _release_wave_gauges(state)
        if scope is not None:
            _ledger_packed_rows(scope, pending, fetched, fetch_stats[0],
                                collect_s * 1000, max(fetch_stats[1], 1))
        # block-max pruning overlay (ISSUE 20): phase-A popcounts decoded
        # from the packed rows' trailing lane, flushed once per wave
        per_query_pruned: Dict[int, int] = {}
        seg_pruned_bytes: Dict[str, int] = {}
        bm_items: set = set()
        wave_segments = state.get("segments")
        for (idxs, seg_i, k_seg, _, out_layout, bm), packed in zip(pending,
                                                                   fetched):
            if packed is None:
                continue            # this program's items are dead
            packed = np.asarray(packed)
            scores_b, idx_b, total_b = unpack_batched_result(
                packed[:, :2 * k_seg + 1], k_seg)
            totals = total_b.tolist()
            if bm:
                from opensearch_tpu.telemetry.scan import \
                    POSTING_BLOCK_BYTES
                pruned_b = packed[:, 2 * k_seg + 1].copy().view(np.int32)
                pruned_rows = pruned_b.tolist()
                seg_total = 0
                for row, i in enumerate(idxs):
                    blocks = int(pruned_rows[row])
                    per_query_pruned[i] = per_query_pruned.get(i, 0) \
                        + blocks * POSTING_BLOCK_BYTES
                    seg_total += blocks
                    bm_items.add(i)
                if wave_segments is not None and seg_total:
                    sid = wave_segments[seg_i].seg_id
                    seg_pruned_bytes[sid] = seg_pruned_bytes.get(sid, 0) \
                        + seg_total * POSTING_BLOCK_BYTES
            for row, i in enumerate(idxs):
                per_query_total[i] += totals[row]
                per_query_segs[i].append((seg_i, scores_b[row], idx_b[row]))
                if out_layout is not None:
                    outs = _decode_agg_row(packed[row, 2 * k_seg + 1:],
                                           out_layout)
                    per_query_decoded[i].append(
                        decode_outputs(agg_by_i[i][seg_i], outs))
        if bm_items:
            from opensearch_tpu.telemetry.scan import SCAN
            scan_posting = state.get("scan_posting") or {}
            ins_meta = state.get("insights")
            pq = []
            for i in sorted(bm_items):
                pruned = per_query_pruned.get(i, 0)
                pq.append((scan_posting.get(i, 0), pruned))
                if ins_meta is not None and i in ins_meta:
                    # ride the existing insights join so the per-shape
                    # effective bytes conserve against telemetry.scan
                    ins_meta[i]["pruned"] = pruned
            SCAN.note_pruned_batch(
                self.reader.index_name,
                str(getattr(self.reader, "shard_id", 0)),
                seg_pruned_bytes, pq)

        took_ms = int((time.monotonic() - start) * 1000)
        segments = state.get("segments")
        if segments is None:
            segments = self.reader.segments
        index_name = self.reader.index_name
        resp_cache_keys = state.get("resp_cache_keys", {})
        for i, seg_results in per_query_segs.items():
            if i in dead:
                continue        # already answered (error/timeout item)
            entry = entry_by_i[i]
            body, size, from_ = entry[1], entry[3], entry[4]
            page_segs: Optional[list] = None
            if seg_results:
                if len(seg_results) == 1:
                    # the device's top_k is already score-desc with
                    # doc-asc tie-break (candidate lanes are doc-sorted;
                    # ties pick the lowest lane) and padding (NEG_INF)
                    # sorts last — the single-segment page is a slice of
                    # the valid prefix
                    one_seg_i, scores, ords = seg_results[0]
                    n_valid = int((scores > NEG_INF).sum())
                    hi = min(from_ + size, n_valid)
                    page_scores = scores[from_:hi].tolist()
                    page_ords = ords[from_:hi].tolist()
                    max_score = float(scores[0]) if n_valid else None
                else:
                    all_scores = np.concatenate(
                        [s for _, s, _ in seg_results])
                    all_ords = np.concatenate(
                        [o for _, _, o in seg_results])
                    all_segs = np.concatenate(
                        [np.full(len(s), si, np.int32)
                         for si, s, _ in seg_results])
                    valid = all_scores > NEG_INF
                    all_scores, all_ords, all_segs = (
                        all_scores[valid], all_ords[valid],
                        all_segs[valid])
                    # score desc, seg asc, doc asc — mergeTopDocs order
                    order = np.lexsort((all_ords, all_segs, -all_scores))
                    page = order[from_:from_ + size]
                    page_scores = all_scores[page].tolist()
                    page_ords = all_ords[page].tolist()
                    page_segs = all_segs[page].tolist()
                    max_score = float(all_scores.max()) \
                        if len(all_scores) else None
            else:
                page_scores = page_ords = []
                max_score = None
            source_spec = body.get("_source", True)
            if source_spec is True or source_spec is None:
                hits = []
                if page_segs is None:
                    if page_ords:
                        seg = segments[one_seg_i]
                        ids, srcs = seg.doc_ids, seg.sources
                        for o, s in zip(page_ords, page_scores):
                            h = {"_index": index_name, "_id": ids[o],
                                 "_score": s}
                            src = srcs[o]
                            if src is not None:
                                h["_source"] = src
                            hits.append(h)
                else:
                    for g, o, s in zip(page_segs, page_ords, page_scores):
                        seg = segments[g]
                        h = {"_index": index_name, "_id": seg.doc_ids[o],
                             "_score": s}
                        src = seg.sources[o]
                        if src is not None:
                            h["_source"] = src
                        hits.append(h)
            else:
                # filtered _source: the general per-hit fetch path
                segs_for_page = page_segs if page_segs is not None \
                    else [one_seg_i] * len(page_ords)
                hits = [self._hit_dict(g, o, s, body, segments=segments)
                        for g, o, s in zip(segs_for_page, page_ords,
                                           page_scores)]
            responses[i] = _base_response(took_ms, per_query_total[i],
                                          max_score, hits)
            if per_query_pruned.get(i):
                # pruned blocks never reach the hit-count scatter, so the
                # total is a lower bound — same "gte" semantics Lucene
                # BMW reports under track_total_hits. The top-k page
                # itself stays byte-identical (rank-exact pruning).
                responses[i]["hits"]["total"]["relation"] = "gte"
            if i in agg_by_i:
                from opensearch_tpu.search.aggs.pipeline import \
                    apply_pipelines
                aggregations = reduce_aggs(per_query_decoded[i])
                apply_pipelines(agg_nodes_by_i[i], aggregations)
                responses[i]["aggregations"] = aggregations
            key = resp_cache_keys.get(i)
            if key is not None:
                # cached at query-phase granularity (totals + decoded agg
                # partials); the response dict handed to the caller is
                # NOT stored — _render_cached_msearch rebuilds one per hit
                _cache_put_isolated(
                    _request_cache(), key,
                    (per_query_total[i], per_query_decoded.get(i),
                     agg_nodes_by_i.get(i)))
        ph["respond"] += time.monotonic() - _t

    def _render_cached_msearch(self, cached, start: float) -> dict:
        """Build a fresh response from a cached (total, decoded partials,
        agg nodes) entry — size=0 only (the cacheable() gate), so there is
        no hits page to rebuild."""
        total, decoded, agg_nodes = cached
        resp = _base_response(int((time.monotonic() - start) * 1000),
                              total, None, [])
        if decoded is not None and agg_nodes is not None:
            from opensearch_tpu.search.aggs.pipeline import apply_pipelines
            aggregations = reduce_aggs(decoded)
            apply_pipelines(agg_nodes, aggregations)
            resp["aggregations"] = aggregations
        return resp

    def count(self, body: Optional[dict] = None) -> int:
        body = dict(body or {})
        body["size"] = 0
        body.pop("from", None)
        return self.search(body)["hits"]["total"]["value"]


def _parse_sort(sort_body) -> List[Tuple[str, str]]:
    """Normalize the sort body to [(field | '_score', order), ...].
    Default (None / empty / '_score') is score-descending."""
    if sort_body is None:
        return [("_score", "desc")]
    specs = sort_body if isinstance(sort_body, list) else [sort_body]
    out: List[Tuple[str, str]] = []
    for spec in specs:
        if isinstance(spec, str):
            if spec == "_score":
                out.append(("_score", "desc"))
            elif spec == "_doc":
                continue  # doc order is the built-in final tie-break
            else:
                out.append((spec, "asc"))
        elif isinstance(spec, dict):
            field, opts = next(iter(spec.items()))
            if field == "_score":
                order = opts.get("order", "desc") if isinstance(opts, dict) \
                    else str(opts)
                out.append(("_score", order))
            else:
                order = opts.get("order", "asc") if isinstance(opts, dict) \
                    else str(opts)
                out.append((field, order))
    if not out:
        return [("_score", "desc")]
    return out


def _sort_value(seg: Segment, field: str, order: str, ord_: int):
    """Real (host, exact) sort value for the cross-segment merge + response."""
    col = seg.numeric_dv.get(field)
    if col is not None:
        vals = col.values[col.doc_ids == ord_]
        if len(vals) == 0:
            return None
        v = float(vals.min() if order == "asc" else vals.max())
        return int(v) if v.is_integer() else v
    ocol = seg.ordinal_dv.get(field)
    if ocol is not None:
        ords = ocol.ords[ocol.doc_ids == ord_]
        if len(ords) == 0:
            return None
        o = int(ords.min() if order == "asc" else ords.max())
        return ocol.dictionary[o]
    return None


def _filter_source(source: Optional[dict], source_spec) -> Optional[dict]:
    """_source filtering per the reference's FetchSourceContext: an include
    pattern selects its whole subtree; excludes override includes."""
    if source is None or source_spec is True or source_spec is None:
        return source
    if source_spec is False:
        return None
    import fnmatch as _fn

    if isinstance(source_spec, str):
        includes, excludes = [source_spec], []
    elif isinstance(source_spec, list):
        includes, excludes = list(source_spec), []
    elif isinstance(source_spec, dict):
        includes = source_spec.get("includes", source_spec.get("include", []))
        excludes = source_spec.get("excludes", source_spec.get("exclude", []))
        if isinstance(includes, str):
            includes = [includes]
        if isinstance(excludes, str):
            excludes = [excludes]
    else:
        return source

    def matches_any(path: str, patterns) -> bool:
        # a pattern matches the leaf itself or any ancestor object path
        parts = path.split(".")
        prefixes = [".".join(parts[:i + 1]) for i in range(len(parts))]
        return any(_fn.fnmatchcase(prefix, p)
                   for prefix in prefixes for p in patterns)

    def walk(obj, path=""):
        if not isinstance(obj, dict):
            return obj
        out = {}
        for k, v in obj.items():
            full = f"{path}{k}"
            if isinstance(v, dict):
                sub = walk(v, f"{full}.")
                if sub:
                    out[k] = sub
                continue
            if matches_any(full, includes) if includes else True:
                if not matches_any(full, excludes):
                    out[k] = v
        return out

    return walk(source)
