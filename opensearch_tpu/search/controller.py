"""Coordinator-side search reduce: the SearchPhaseController analog.

Reference (SURVEY.md §3.2 coordinator half): TransportSearchAction fans the
query phase out to one copy of every shard, QueryPhaseResultConsumer
incrementally reduces (mergeTopDocs SearchPhaseController.java:228 +
InternalAggregations.topLevelReduce :453), then the fetch phase loads _source
only for the global top hits. Here each shard executes its jitted query phase
(device work across shards overlaps because jax dispatch is async), and the
host merges candidates with the reference's exact tie-break
(sort keys, then shard/segment/doc order) and reduces agg partials once.
"""

from __future__ import annotations

import time
from typing import List, Optional

from opensearch_tpu.common.errors import IllegalArgumentError
from opensearch_tpu.search.aggs.parse import PIPELINE_TYPES, parse_aggs
from opensearch_tpu.search.aggs.pipeline import apply_pipelines
from opensearch_tpu.search.aggs.reduce import reduce_aggs
from opensearch_tpu.search.executor import (
    _compare_candidates, _parse_sort)


def execute_search(executors: List, body: Optional[dict],
                   total_shards: Optional[int] = None,
                   failed_shards: int = 0,
                   extra_filters: Optional[List[Optional[dict]]] = None) -> dict:
    """Run the full query-then-fetch flow over shard executors and render
    the search response. `executors` are per-shard SearchExecutors;
    `extra_filters` (aligned with executors) carry per-index alias filters."""
    body = body or {}
    start = time.monotonic()
    size = int(body.get("size", 10))
    from_ = int(body.get("from", 0))
    if size < 0 or from_ < 0:
        raise IllegalArgumentError("[from] and [size] must be non-negative")

    sort_specs = _parse_sort(body.get("sort"))
    score_sorted = sort_specs[0][0] == "_score"
    wants_score = score_sorted or any(f == "_score" for f, _ in sort_specs) \
        or bool(body.get("track_scores", False))
    agg_nodes = parse_aggs(body.get("aggs") or body.get("aggregations"))

    k = max(from_ + size, 10)
    candidates = []
    decoded_partials = []
    total = 0
    for shard_i, ex in enumerate(executors):
        extra = extra_filters[shard_i] if extra_filters else None
        cands, decoded, shard_total = ex.execute_query_phase(body, k,
                                                             extra_filter=extra)
        for c in cands:
            c.shard_i = shard_i
        candidates.extend(cands)
        decoded_partials.extend(decoded)
        total += shard_total

    candidates.sort(key=_compare_candidates(sort_specs))
    page = candidates[from_:from_ + size]

    max_score = None
    if wants_score:
        for c in candidates:
            if max_score is None or c.score > max_score:
                max_score = c.score

    hits = []
    for c in page:
        ex = executors[c.shard_i]
        hit = ex._hit_dict(c.seg_i, c.ord,
                           c.score if wants_score else None, body)
        if not score_sorted:
            hit["sort"] = c.sort_values
        hits.append(hit)

    n_shards = total_shards if total_shards is not None else len(executors)
    resp = {
        "took": int((time.monotonic() - start) * 1000),
        "timed_out": False,
        "_shards": {"total": n_shards,
                    "successful": n_shards - failed_shards,
                    "skipped": 0, "failed": failed_shards},
        "hits": {
            "total": {"value": total, "relation": "eq"},
            "max_score": max_score,
            "hits": hits,
        },
    }
    if agg_nodes:
        aggregations = reduce_aggs(decoded_partials)
        apply_pipelines(agg_nodes, aggregations)
        resp["aggregations"] = aggregations
    return resp
