"""Coordinator-side search reduce: the SearchPhaseController analog.

Reference (SURVEY.md §3.2 coordinator half): TransportSearchAction fans the
query phase out to one copy of every shard, QueryPhaseResultConsumer
incrementally reduces (mergeTopDocs SearchPhaseController.java:228 +
InternalAggregations.topLevelReduce :453), then the fetch phase loads _source
only for the global top hits. Here each shard executes its jitted query phase
(device work across shards overlaps because jax dispatch is async), and the
host merges candidates with the reference's exact tie-break
(sort keys, then shard/segment/doc order) and reduces agg partials once.

Also implemented here (reference analogs in parentheses):
  - search_after / internal scroll cursors (SearchAfterBuilder,
    scroll keep-alive contexts) with a host-driven k-doubling retry when the
    cursor reaches past the device top-k window;
  - track_total_hits true/false/threshold (TotalHitCountCollector);
  - field collapse (CollapsingTopDocsCollector);
  - rescore (QueryRescorer) re-ranking the top window with a second query;
  - fetch sub-phases per page hit (FetchPhase.java:106 → highlight, explain,
    docvalue_fields in search/fetch.py).
"""

from __future__ import annotations

import time
from typing import Any, List, Optional, Tuple

from opensearch_tpu.common import faults
from opensearch_tpu.common.errors import (
    IllegalArgumentError, OpenSearchTpuError, ParsingError,
    SearchPhaseExecutionError, TaskCancelledError, shard_failure_entry)
from opensearch_tpu.search import dsl
from opensearch_tpu.search.aggs.parse import PIPELINE_TYPES, parse_aggs
from opensearch_tpu.search.aggs.pipeline import apply_pipelines
from opensearch_tpu.search.aggs.reduce import reduce_aggs
from opensearch_tpu.search.executor import (
    _compare_candidates, _parse_sort, _sort_value)


def _cmp_values(a: Any, b: Any, order: str) -> int:
    """Compare two sort values in page order (-1: a first)."""
    if a is None and b is None:
        return 0
    if a is None:
        return 1
    if b is None:
        return -1
    try:
        lt = a < b
        gt = b < a
    except TypeError:
        a, b = str(a), str(b)
        lt, gt = a < b, b < a
    if not lt and not gt:
        return 0
    if order == "desc":
        return -1 if gt else 1
    return -1 if lt else 1


def _after_cursor(candidates, sort_specs, after_values,
                  tiebreak: Optional[Tuple[int, int, int]] = None):
    """Drop candidates at or before the cursor position. `after_values`
    aligns with sort_specs; `tiebreak` is the internal (shard, seg, ord) of
    the last returned hit for fully-tied scroll continuation."""
    if len(after_values) != len(sort_specs):
        raise IllegalArgumentError(
            f"search_after has {len(after_values)} value(s) but sort has "
            f"{len(sort_specs)} field(s)")
    out = []
    for c in candidates:
        rel = 0
        for i, ((field, order), av) in enumerate(zip(sort_specs,
                                                     after_values)):
            cv = c.score if field == "_score" else c.sort_values[i]
            rel = _cmp_values(cv, av, order)
            if rel != 0:
                break
        if rel > 0:
            out.append(c)
        elif rel == 0 and tiebreak is not None and \
                (c.shard_i, c.seg_i, c.ord) > tiebreak:
            out.append(c)
    return out


def _apply_collapse(candidates, executors, collapse_field: str):
    """Keep the best candidate per collapse-field value (first in sort
    order); None-valued docs collapse into one group per the reference's
    CollapsingTopDocsCollector null policy (each null is its own group)."""
    seen = set()
    out = []
    for c in candidates:
        ex = executors[c.shard_i]
        seg = ex.reader.segments[c.seg_i]
        val = _sort_value(seg, collapse_field, "asc", c.ord)
        if val is None:
            out.append(c)
            continue
        if val in seen:
            continue
        seen.add(val)
        out.append(c)
        c.collapse_value = val
    return out


def _apply_rescore(executors, rescore_body, candidates, extra_filters):
    """QueryRescorer: re-rank the top window_size hits by combining the
    original score with a secondary query's score. Runs the rescore query
    as its own device pass per shard (k capped — see below) and combines
    host-side."""
    entries = rescore_body if isinstance(rescore_body, list) else [rescore_body]
    for entry in entries:
        window = int(entry.get("window_size", 10))
        spec = entry.get("query")
        if not spec or "rescore_query" not in spec:
            raise IllegalArgumentError("rescore malformed: missing rescore_query")
        qw = float(spec.get("query_weight", 1.0))
        rqw = float(spec.get("rescore_query_weight", 1.0))
        mode = spec.get("score_mode", "total")
        window_cands = candidates[:window]
        shard_ids = {c.shard_i for c in window_cands}
        # device pass must cover every window doc: k scales with the window
        # (docs the rescore query doesn't match at all contribute 0)
        k = max(512, window * 8)
        score_map = {}
        for shard_i in shard_ids:
            extra = extra_filters[shard_i] if extra_filters else None
            cands, _, _ = executors[shard_i].execute_query_phase(
                {"query": spec["rescore_query"]}, k, extra_filter=extra)
            for c in cands:
                score_map[(shard_i, c.seg_i, c.ord)] = c.score
        for c in window_cands:
            rs = score_map.get((c.shard_i, c.seg_i, c.ord))
            if rs is None:
                c.score = c.score * qw
                continue
            combined = {
                "total": c.score * qw + rs * rqw,
                "multiply": c.score * qw * (rs * rqw),
                "avg": (c.score * qw + rs * rqw) / 2.0,
                "max": max(c.score * qw, rs * rqw),
                "min": min(c.score * qw, rs * rqw),
            }.get(mode)
            if combined is None:
                raise IllegalArgumentError(
                    f"[rescore] illegal score_mode [{mode}]")
            c.score = combined
        window_cands.sort(key=lambda c: (-c.score, c.shard_i, c.seg_i, c.ord))
        candidates[:window] = window_cands
    return candidates


# the top-level keys SearchSourceBuilder's parser accepts — anything else
# is a parsing error (400), e.g. a query clause pasted at the top level
SEARCH_BODY_KEYS = frozenset({
    "query", "from", "size", "sort", "aggs", "aggregations", "_source",
    "fields", "stored_fields", "docvalue_fields", "script_fields",
    "track_total_hits", "track_scores", "min_score", "search_after",
    "highlight", "suggest", "rescore", "collapse", "post_filter",
    "explain", "version", "seq_no_primary_term", "slice", "pit",
    "profile", "timeout", "terminate_after", "indices_boost",
    "runtime_mappings", "search_type", "scroll", "scroll_id", "ext",
    "min_compatible_shard_node", "knn", "stats",
    "allow_partial_search_results",
    "_dfs",                       # internal: DFS-merged statistics
})


def _parse_deadline(body: dict) -> Optional[float]:
    """body['timeout'] ('10ms'/'1s'/bare-int millis) → monotonic
    deadline, or None. The long-ignored param now gates phase launches."""
    raw = body.get("timeout")
    if raw is None:
        return None
    from opensearch_tpu.common.settings import parse_time_value
    from opensearch_tpu.common.errors import SettingsError
    try:
        timeout_s = parse_time_value(raw, "timeout")
    except (SettingsError, TypeError, ValueError):
        raise IllegalArgumentError(
            f"failed to parse [timeout] with value [{raw!r}]")
    if timeout_s <= 0:
        return None                 # -1 / 0 disable, reference semantics
    return time.monotonic() + timeout_s


def _resolve_allow_partial(body: dict, default: Optional[bool]) -> bool:
    """allow_partial_search_results: body key > caller kwarg (REST param /
    cluster setting `search.default_allow_partial_results`) > true (the
    reference default)."""
    raw = body.get("allow_partial_search_results")
    if raw is None:
        return True if default is None else bool(default)
    if isinstance(raw, str):
        return raw.strip().lower() != "false"
    return bool(raw)


def _validate_search_body_keys(body: dict) -> None:
    for key in body:
        if key not in SEARCH_BODY_KEYS:
            raise ParsingError(f"unknown key [{key}] in the search body")


class _PhaseTimer:
    """Times one search phase. The ns total ALWAYS lands in the request's
    phase dict (metrics histograms and the slow log read it — a couple of
    perf_counter_ns calls per phase, paid whether or not tracing is on);
    a child span opens only when the trace records (node tracing enabled
    or a profile request), so the disabled path allocates nothing."""

    __slots__ = ("name", "phases", "span", "t0", "duration_ns")

    def __init__(self, trace, phases: dict, name: str, **attrs):
        self.name = name
        self.phases = phases
        self.span = trace.child(name, **attrs) if trace.recording else None
        self.duration_ns = 0
        self.t0 = time.perf_counter_ns()

    def set_attribute(self, key, value):
        if self.span is not None:
            self.span.set_attribute(key, value)

    def __enter__(self) -> "_PhaseTimer":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.duration_ns = time.perf_counter_ns() - self.t0
        self.phases[self.name] = self.phases.get(self.name, 0) \
            + self.duration_ns
        if self.span is not None:
            self.span.end(error=exc if exc_type is not None else None)
        return False


def _note_controller_insights(query_spec, took_ms, req_scope) -> None:
    """Per-shape cost note for controller-served requests (ISSUE 15):
    the general host loop, the SPMD path and the fused hybrid branch —
    everything the msearch envelope does NOT note itself. Shape id from
    the interned template signature (fallback: structural hash); scan
    bytes joined through the recorder's thread-local accumulator (the
    query phase / SPMD path feed it the SAME bytes the heat map
    counts); transfer bytes/round trips from the request's LedgerScope
    when the ledger is on. Also stamps the shape onto the bound
    lifecycle timeline so tail captures group by shape class — that
    annotation rides the flight recorder's own gate, not insights'.
    Both gates off = two attribute loads and branches."""
    from opensearch_tpu.telemetry import TELEMETRY
    ins = TELEMETRY.insights.gate()
    tl = TELEMETRY.flight.current() if TELEMETRY.flight.enabled else None
    if ins is None and tl is None:
        return
    from opensearch_tpu.telemetry.insights import query_shape
    label, kind = query_shape(query_spec)
    if tl is not None and tl.shape is None:
        tl.shape = label
    if ins is None:
        return
    sp, sd, spr = ins.take_scan()
    dev_ms = req_scope.device_get_ms if req_scope is not None else 0.0
    # kernel-family join (ISSUE 19): the families the query phase
    # recorded on this thread, each charged an even share of the
    # request's device wall — the per-shape dominant-kernel breakdown
    fams = ins.take_families()
    kernels = {f: dev_ms / len(fams) for f in fams} \
        if fams and dev_ms else None
    ins.note(
        label, kind=kind, took_ms=float(took_ms),
        device_ms=dev_ms,
        posting_bytes=sp, dense_bytes=sd, pruned_bytes=spr,
        h2d_bytes=req_scope.h2d_bytes if req_scope is not None else 0,
        d2h_bytes=req_scope.d2h_bytes if req_scope is not None else 0,
        round_trips=req_scope.round_trips
        if req_scope is not None else 0,
        co_batched=1, tenant=ins.current_tenant(), kernels=kernels)


def _publish_scope(scope, span, phase_times: Optional[dict]) -> None:
    """Attach a request's transfer accounting (telemetry/ledger.py
    LedgerScope) to its span and to the caller's phase_times dict, where
    the slow log reads the `device_get`/`bytes_fetched` fields. The
    field set lives on LedgerScope.publish — shared with the msearch
    envelope's own publication."""
    if scope is not None:
        scope.publish(span, phase_times)


def execute_search(executors: List, body: Optional[dict],
                   total_shards: Optional[int] = None,
                   failed_shards: int = 0,
                   extra_filters: Optional[List[Optional[dict]]] = None,
                   cursor_tiebreak: Optional[Tuple[int, int, int]] = None,
                   task=None, allow_envelope: bool = False,
                   phase_processors: Optional[dict] = None,
                   trace=None,
                   phase_times: Optional[dict] = None,
                   allow_partial: Optional[bool] = None) -> dict:
    """Lifecycle wrapper around `_execute_search_impl` (which carries
    the full contract docstring): when the flight recorder
    (telemetry/lifecycle.py) is enabled and no request timeline is
    bound yet — direct callers like IndexService.search, scroll,
    reindex, tests — this opens one (admit at entry, respond at exit,
    complete through the recorder's capture gate). REST-served requests
    already carry a bound timeline with real admission events; the
    wrapper passes straight through to keep one owner per request. The
    disabled path is one attribute load and a branch."""
    from opensearch_tpu.telemetry import TELEMETRY
    flight = TELEMETRY.flight
    tl = flight.timeline() \
        if flight.enabled and flight.current() is None else None
    if tl is None:
        return _execute_search_impl(
            executors, body, total_shards, failed_shards, extra_filters,
            cursor_tiebreak, task, allow_envelope, phase_processors,
            trace, phase_times, allow_partial)
    tl.event("admit")
    prev = flight.bind(tl)
    status = "error"
    try:
        res = _execute_search_impl(
            executors, body, total_shards, failed_shards, extra_filters,
            cursor_tiebreak, task, allow_envelope, phase_processors,
            trace, phase_times, allow_partial)
        status = "ok"
        return res
    finally:
        flight.unbind(prev)
        tl.event("respond")
        flight.complete(tl, status=status, span=trace)


def _execute_search_impl(executors: List, body: Optional[dict],
                         total_shards: Optional[int] = None,
                         failed_shards: int = 0,
                         extra_filters: Optional[List[Optional[dict]]]
                         = None,
                         cursor_tiebreak: Optional[Tuple[int, int, int]]
                         = None,
                         task=None, allow_envelope: bool = False,
                         phase_processors: Optional[dict] = None,
                         trace=None,
                         phase_times: Optional[dict] = None,
                         allow_partial: Optional[bool] = None) -> dict:
    """Run the full query-then-fetch flow over shard executors and render
    the search response. `executors` are per-shard SearchExecutors;
    `extra_filters` (aligned with executors) carry per-index alias filters;
    `cursor_tiebreak` is the internal scroll cursor position; `task` (when
    given) is checked for cancellation between shard launches — the safe
    points between device programs (CancellableBulkScorer analog).
    `allow_envelope` (top-level serving entry points only — REST _search,
    IndexService.search) lets a single-shard plain request delegate to the
    msearch envelope; scroll/reindex/CCS callers need this path's page
    cursor and shard accounting, and the envelope's own fallback re-enters
    here and must not loop. `phase_processors` is the resolved search
    pipeline's normalization-processor spec for hybrid queries (None =
    defaults). `trace` is the request's root telemetry span (None = not
    traced) — child spans cover parse, can_match, per-shard query with
    device-dispatch attribution, reduce and fetch, and close on every
    exit path. `phase_times` (pass a dict) is filled with per-phase
    milliseconds for the caller's slow log.

    Partial-failure contract (reference: AbstractSearchAsyncAction's
    per-shard onShardFailure accounting): a runtime exception in ONE
    shard's can-match / query / fetch phase costs that shard's slice of
    the response, not the envelope — failures render as reference-shaped
    `_shards.failures[]` entries. `allow_partial` (body key
    `allow_partial_search_results` > this kwarg > true) decides whether
    a partially-failed request returns 200 or raises
    SearchPhaseExecutionError; all shards failing always raises. The
    `timeout` body param is enforced at phase boundaries (between shard
    launches, before fetch): past-deadline requests stop launching new
    shard phases and render `timed_out: true` with whatever accumulated.
    Cancellation (`task`) is checked at the same safe points."""
    from opensearch_tpu.telemetry import NOOP_SPAN, TELEMETRY
    if trace is None:
        trace = NOOP_SPAN
    if TELEMETRY.flight.enabled:
        # lifecycle: whatever wall accumulated between the request's
        # arrival (REST entry / wrapper) and this engine entry becomes
        # the `route` phase — pipeline resolution, plumbing, and the
        # GIL starvation a contended node inflicts right here
        _tl_route = TELEMETRY.flight.current()
        if _tl_route is not None:
            _tl_route.route()
    body = body or {}
    _validate_search_body_keys(body)
    # per-request transfer accounting (telemetry/ledger.py): None unless
    # the ledger is enabled or this request traces/profiles — the
    # zero-overhead default. Feeds the span's bytes_to_device/
    # bytes_fetched, the Profile API's transfers[] and the slow log's
    # bytes_fetched/device_get fields on EVERY dispatch path (general
    # host loop, envelope, hybrid) — the attribution used to exist only
    # in the general path's single-branch sum.
    req_scope = TELEMETRY.ledger.scope(trace)
    if TELEMETRY.insights.enabled:
        # clear stale thread-local scan residue (an earlier errored
        # request on this thread must not leak bytes into this one's
        # per-shape join)
        TELEMETRY.insights.take_scan()
    query_spec = body.get("query")
    if isinstance(query_spec, dict) and "hybrid" in query_spec:
        # hybrid dense+sparse clause: its sub-queries keep SEPARATE score
        # channels through a fused per-shard program and merge via the
        # search pipeline's normalization-processor at reduce
        # (searchpipeline/hybrid.py) — the single-score paths below
        # cannot represent it
        if cursor_tiebreak is not None:
            raise IllegalArgumentError(
                "[scroll] is not supported with a [hybrid] query")
        from opensearch_tpu.searchpipeline.hybrid import \
            execute_hybrid_search
        trace.set_attribute("query_type", "hybrid")
        with trace.child("query", path="hybrid_fused") as hq:
            res = execute_hybrid_search(
                executors, body, phase_spec=phase_processors,
                extra_filters=extra_filters, total_shards=total_shards,
                failed_shards=failed_shards, task=task,
                allow_partial=_resolve_allow_partial(body, allow_partial),
                ledger_scope=req_scope)
        _publish_scope(req_scope, hq, phase_times)
        if TELEMETRY.flight.enabled:
            tl = TELEMETRY.flight.current()
            if tl is not None and req_scope is not None:
                tl.merge_phases({"device_get": req_scope.device_get_ms})
        _note_controller_insights(query_spec, res.get("took", 0),
                                  req_scope)
        return res
    if (allow_envelope and len(executors) == 1 and total_shards is None
            and failed_shards == 0 and cursor_tiebreak is None
            and not (extra_filters and extra_filters[0])):
        from opensearch_tpu.search.executor import _msearch_batchable
        if _msearch_batchable(body):
            # single-shard plain score-sorted request: serve through the
            # B=1 msearch envelope — the same executable family as
            # dashboard batches (bit-identical scores), so the warmup
            # registry's (plan-struct, shape-bucket) coverage extends to
            # REST _search singles, not just _msearch
            with trace.child("query", path="envelope") as eq:
                # straight into the envelope (search() would re-check
                # _msearch_batchable); errors raise — the per-item error
                # objects are an _msearch-only contract. The envelope
                # sets its own transfer attribution on the child span
                # and fills phase_times for the slow log. The request's
                # `timeout=` rides along: the wave engine enforces it at
                # its wave boundaries (a B=1 envelope is the degenerate
                # single wave), rendering the timed-out shape instead of
                # silently ignoring the budget on this path.
                return executors[0].multi_search(
                    [body], _raise_item_errors=True, task=task,
                    deadline=_parse_deadline(body),
                    trace=eq, phase_times=phase_times)["responses"][0]
    start = time.monotonic()
    start_ns = time.perf_counter_ns()
    deadline = _parse_deadline(body)
    allow_partial_results = _resolve_allow_partial(body, allow_partial)
    timed_out_box = [False]
    shard_failures: List[dict] = []     # reference-shaped failures[]
    failed_shard_ids: set = set()       # dedupe: one entry per shard

    def _deadline_passed() -> bool:
        if deadline is not None and time.monotonic() > deadline:
            timed_out_box[0] = True
            return True
        return False

    def _record_failure(shard_i: int, exc: BaseException) -> None:
        if shard_i in failed_shard_ids:
            return
        failed_shard_ids.add(shard_i)
        idx = executors[shard_i].reader.index_name \
            if 0 <= shard_i < len(executors) else "_unknown"
        shard_failures.append(shard_failure_entry(shard_i, idx, exc))
        TELEMETRY.metrics.counter("search.shard_failures").inc()
    profiling = bool(body.get("profile", False))
    if profiling and not trace.recording:
        # the profile API builds from request-scoped spans even when
        # node-wide tracing is off; a forced trace records locally but is
        # never retained in the tracer's ring buffer
        trace = TELEMETRY.tracer.start_trace("search", force=True)
        if req_scope is None:
            # the scope gate ran before the forced trace existed: profile
            # requests always account transfers (ledger.scope() treats a
            # recording trace as opt-in)
            req_scope = TELEMETRY.ledger.scope(trace)
    phases: dict = {}            # phase name -> accumulated ns
    profile_shards: List[dict] = []
    with _PhaseTimer(trace, phases, "parse"):
        size = int(body.get("size", 10))
        from_ = int(body.get("from", 0))
        if size < 0 or from_ < 0:
            raise IllegalArgumentError("[from] parameter cannot be negative" if from_ < 0
                    else "[size] parameter cannot be negative")
        # index.max_result_window (SearchService#validateSearchSource): deep
        # from+size pagination must use scroll/search_after-with-paging
        window = min((getattr(ex, "max_result_window", 10000)
                      for ex in executors), default=10000)
        if from_ + size > window and cursor_tiebreak is None:
            raise IllegalArgumentError(
                f"Result window is too large, from + size must be less than "
                f"or equal to: [{window}] but was [{from_ + size}]. See the "
                f"scroll api for a more efficient way to request large data "
                f"sets. This limit can be set by changing the "
                f"[index.max_result_window] index level setting.")

        sort_specs = _parse_sort(body.get("sort"))
        score_sorted = sort_specs[0][0] == "_score"
        wants_score = score_sorted \
            or any(f == "_score" for f, _ in sort_specs) \
            or bool(body.get("track_scores", False))
        agg_nodes = parse_aggs(body.get("aggs") or body.get("aggregations"))
        after_values = body.get("search_after")
        if after_values is not None and from_ > 0:
            raise IllegalArgumentError(
                "`from` parameter must be set to 0 when `search_after` is "
                "used")
        collapse_field = (body.get("collapse") or {}).get("field")
        track_total = body.get("track_total_hits", True)

        k = max(from_ + size, 10)
        max_k = 1 << 16

        # DFS query-then-fetch (DfsQueryPhase + aggregateDfs): collect every
        # shard's term statistics for the query, merge, and pin the merged
        # stats on every shard's compile so scores are globally comparable
        dfs_overrides: Optional[List] = None
        if body.get("search_type") == "dfs_query_then_fetch" and executors:
            from opensearch_tpu.common.errors import ParsingError
            from opensearch_tpu.search.compile import (
                StaticStats, collect_query_term_stats, merge_dfs_stats)
            try:
                qnode = dsl.parse_query(body.get("query"))
            except ParsingError:
                qnode = None         # the normal path raises it properly
            if qnode is not None:
                # any OTHER failure here is a real bug and must surface — a
                # silent fallback to shard-local stats would hand the user
                # non-comparable scores they explicitly asked to avoid
                parts = [collect_query_term_stats(qnode, ex.reader.mapper,
                                                  ex.reader.stats())
                         for ex in executors]
                fields, term_df = merge_dfs_stats(parts)
                dfs_overrides = [StaticStats(ex.reader.stats(), fields,
                                             term_df)
                                 for ex in executors]

    # can-match pre-filter (CanMatchPreFilterSearchPhase): shards whose
    # segment min/max metadata proves emptiness never compile or launch a
    # device program. Computed lazily — the SPMD program batches every
    # (shard, segment) row in one launch and never consults the flags —
    # and cached across k-growth retries. When every shard would skip, one
    # still executes so the response (empty agg structures, totals) is
    # fully shaped, exactly like the reference phase.
    from opensearch_tpu.search.canmatch import shard_can_match
    flags_box: List = [None]
    skipped_box = [0]
    pruned_box = [0]    # SPMD block-max pruned bytes: total -> "gte"

    def can_match_flags():
        if flags_box[0] is None:
            with _PhaseTimer(trace, phases, "can_match") as cm:
                flags = []
                for ex in executors:
                    # a can-match failure degrades to "don't skip": the
                    # pre-filter is an optimization, so its faults must
                    # cost an extra shard execution, never correctness
                    try:
                        if faults.ENABLED:
                            faults.fire("canmatch.shard")
                        flags.append(shard_can_match(ex, body))
                    except Exception:   # except-ok: canmatch isolation -- any failure class degrades to don't-skip, never a failed query
                        flags.append(True)
                if flags and not any(flags):
                    flags[0] = True
                cm.set_attribute("skipped",
                                 len(executors) - sum(flags))
            flags_box[0] = flags
        return flags_box[0]

    def run_query_phase(k_eff):
        candidates = []
        decoded_partials = []
        total = 0
        profile_shards.clear()
        shard_failures.clear()      # k-growth retries re-run the phase
        failed_shard_ids.clear()
        pruned_box[0] = 0           # last phase run decides the relation
        # SPMD path: with multiple (shard, segment) rows and enough mesh
        # devices, the query phase is ONE shard_map program with on-chip
        # all_gather/psum merge instead of a host loop (search/spmd.py).
        # Routing (rows + eligibility, incl. the cold module import) is
        # accounted under can_match — it's the same shard-routing
        # decision family
        with _PhaseTimer(trace, phases, "can_match", op="spmd_route"):
            from opensearch_tpu.search import spmd
            rows = spmd.spmd_rows(executors)
            # the fused all-shard SPMD program has no per-shard
            # boundaries: a deadline can't be checked mid-program and a
            # fault can't cost one shard's slice — deadline'd requests
            # and fault-injection runs take the per-shard host loop,
            # which has both checkpoints
            spmd_ok = deadline is None and not faults.ENABLED \
                and spmd.eligible(executors, body, rows, sort_specs)
        if spmd_ok:
            with _PhaseTimer(trace, phases, "query", path="spmd",
                             rows=len(rows)) as qt:
                try:
                    # the SPMD path attributes its transfers to the
                    # thread-ambient ledger scope (upload.literals /
                    # spmd.results in parallel/distributed.py read
                    # ledger.current()); binding the request scope here
                    # routes them onto THIS request — the per-shape
                    # transfer join (ISSUE 15) and the Profile/slow-log
                    # byte fields on SPMD-served requests both need it.
                    # Safe: the SPMD query phase is single-request.
                    if req_scope is not None:
                        with TELEMETRY.ledger.ambient(req_scope):
                            out = spmd.spmd_query_phase(
                                executors, body, k_eff, extra_filters,
                                rows)
                    else:
                        out = spmd.spmd_query_phase(
                            executors, body, k_eff, extra_filters, rows)
                except TaskCancelledError:
                    raise
                except Exception:   # except-ok: SPMD isolation -- any failure class degrades to the per-shard host loop
                    # the fused all-shard program failed as a unit:
                    # degrade to the per-shard host loop below, where
                    # failure isolation is per shard
                    out = None
            if out is not None:
                candidates, decoded_partials, total, spmd_pruned = out
                # block-max pruning made `total` a lower bound: the
                # response's hits.total.relation degrades to "gte"
                pruned_box[0] = spmd_pruned
                with _PhaseTimer(trace, phases, "reduce"):
                    candidates.sort(key=_compare_candidates(sort_specs))
                if profiling:
                    entry = {
                        "id": f"[{executors[0].reader.index_name}][spmd]",
                        "_query_ns": qt.duration_ns,
                        "searches": [{"query": [{
                            "type": "SpmdQueryPhase",
                            "description": str(body.get("query")),
                            "time_in_nanos": qt.duration_ns,
                            "breakdown": {"rows": len(rows),
                                          "segments": len(rows)},
                        }], "rewrite_time": 0, "collector": []}],
                        "aggregations": [],
                    }
                    # per-device attribution (ISSUE 14): when the
                    # device ledger captured this query, the shard
                    # entry carries the per-chip phase breakdown —
                    # upload / partial(device, wall) / collective
                    # merge / result pull + straggler skew
                    devscope = TELEMETRY.ledger.devices.take_last()
                    if devscope is not None:
                        entry["devices"] = devscope.to_dict()
                    profile_shards.append(entry)
                return candidates, decoded_partials, total
        flags = can_match_flags()
        skipped_box[0] = len(executors) - sum(flags)
        for shard_i, ex in enumerate(executors):
            if not flags[shard_i]:
                continue                # provably empty: skipped shard
            if task is not None:
                task.check_cancelled()
            if _deadline_passed():
                # budget spent: stop launching new shard phases; what
                # accumulated so far renders with timed_out: true
                break
            extra = extra_filters[shard_i] if extra_filters else None
            try:
                with _PhaseTimer(trace, phases, "query",
                                 shard=shard_i) as qt:
                    if faults.ENABLED:
                        faults.fire("query.shard")
                    cands, decoded, shard_total = ex.execute_query_phase(
                        body, k_eff, extra_filter=extra,
                        stats_override=dfs_overrides[shard_i]
                        if dfs_overrides else None,
                        trace=qt.span, ledger_scope=req_scope)
                    qt.set_attribute("candidates", len(cands))
            except TaskCancelledError:
                raise                   # cancellation is not a failure
            except OpenSearchTpuError as e:
                if e.status < 500:
                    # a 4xx is a deterministic request defect (parse /
                    # validation), not a shard fault: every shard would
                    # fail identically, so the request keeps its 4xx
                    # contract instead of degrading to a partial
                    raise
                _record_failure(shard_i, e)
                continue
            except Exception as e:  # except-ok: per-shard isolation -- failures land in _shards.failures[], not the request
                # one shard's query fault costs that shard's slice of
                # the response, not the request
                _record_failure(shard_i, e)
                continue
            for c in cands:
                c.shard_i = shard_i
            candidates.extend(cands)
            decoded_partials.extend(decoded)
            total += shard_total
            if profiling:
                # device-dispatch attribution (compile/dispatch/collect
                # ns, bytes_to_device, compiled) rides the span the
                # executor annotated
                breakdown = {"segments": len(ex.reader.segments)}
                if qt.span is not None:
                    breakdown.update(
                        {k2: v for k2, v in qt.span.attributes.items()
                         if k2 not in ("shard", "candidates")})
                # the per-transfer list is a first-class profile field,
                # not a breakdown scalar: transfers[] per shard is the
                # ledger's contract with the Profile API
                shard_transfers = breakdown.pop("transfers", [])
                # per-shard kernel attribution (ISSUE 19): the kernel
                # families the shard's program dispatched, with their
                # device-wall shares — same first-class treatment
                shard_kernels = breakdown.pop("kernels", [])
                profile_shards.append({
                    "id": f"[{ex.reader.index_name}][{shard_i}]",
                    "_query_ns": qt.duration_ns,
                    "searches": [{"query": [{
                        "type": "TpuQueryPhase",
                        "description": str(body.get("query")),
                        "time_in_nanos": qt.duration_ns,
                        "breakdown": breakdown,
                    }], "rewrite_time": 0, "collector": []}],
                    "aggregations": [],
                    "transfers": shard_transfers,
                    "kernels": shard_kernels,
                })
        with _PhaseTimer(trace, phases, "reduce"):
            candidates.sort(key=_compare_candidates(sort_specs))
        return candidates, decoded_partials, total

    candidates, decoded_partials, total = run_query_phase(k)
    raw_count = len(candidates)
    if after_values is not None:
        cursor_values = after_values
        with _PhaseTimer(trace, phases, "reduce"):
            filtered = _after_cursor(candidates, sort_specs, cursor_values,
                                     tiebreak=cursor_tiebreak)
        # the cursor may reach past the device top-k window: grow k until
        # the page is full or every match is on host (reference avoids this
        # by filtering inside the collector; here the host drives a retry)
        while len(filtered) < from_ + size and raw_count >= k and k < max_k \
                and k < total:
            k = min(max_k, k * 4)
            candidates, decoded_partials, total = run_query_phase(k)
            raw_count = len(candidates)
            with _PhaseTimer(trace, phases, "reduce"):
                filtered = _after_cursor(candidates, sort_specs,
                                         cursor_values,
                                         tiebreak=cursor_tiebreak)
        candidates = filtered

    if body.get("rescore") and score_sorted:
        with _PhaseTimer(trace, phases, "reduce", op="rescore"):
            candidates = _apply_rescore(executors, body["rescore"],
                                        candidates, extra_filters)
    if collapse_field:
        with _PhaseTimer(trace, phases, "reduce", op="collapse"):
            candidates = _apply_collapse(candidates, executors,
                                         collapse_field)

    with _PhaseTimer(trace, phases, "reduce", op="page"):
        page = candidates[from_:from_ + size]
        max_score = None
        if wants_score:
            for c in candidates:
                if max_score is None or c.score > max_score:
                    max_score = c.score

    _deadline_passed()      # the fetch-boundary timeout checkpoint:
    # accumulated hits still render (building the page from host-side
    # sources is cheap), but the response says timed_out
    if task is not None:
        task.check_cancelled()
    with _PhaseTimer(trace, phases, "fetch") as ft, \
            TELEMETRY.ledger.ambient(req_scope):
        # ambient binding: the fetch sub-phases (inner-hit docvalue
        # gathers in search/fetch.py) sit too deep to plumb the scope
        # through — they read it back via ledger.current()
        query_node = dsl.parse_query(body.get("query"))
        from opensearch_tpu.search import fetch as fetch_phase
        page_inner_specs = fetch_phase.collect_inner_hit_specs(query_node)
        page_inner_cache: dict = {}
        built = []      # (shard_i, hit): a mid-page shard failure must
        # drop the WHOLE shard's slice, including hits already built —
        # per-shard accounting (one failures[] entry per shard) with
        # per-candidate survivorship would double-count for clients that
        # retry failed shards
        for c in page:
            if c.shard_i in failed_shard_ids:
                continue
            ex = executors[c.shard_i]
            try:
                if faults.ENABLED:
                    faults.fire("fetch.gather")
                hit = _build_hit(ex, c, body,
                                 c.score if wants_score else None,
                                 query_node, sort_specs, score_sorted,
                                 inner_specs=page_inner_specs,
                                 inner_cache=page_inner_cache)
            except OpenSearchTpuError as e:
                if e.status < 500:
                    raise       # deterministic request defect: keep 4xx
                _record_failure(c.shard_i, e)
                continue
            except Exception as e:  # except-ok: per-shard isolation -- a fetch fault drops the shard's page hits, siblings render
                # a fetch fault fails the shard: its page hits drop as a
                # unit; siblings' hits still render
                _record_failure(c.shard_i, e)
                continue
            built.append((c.shard_i, hit))
        hits = [h for shard_i, h in built
                if shard_i not in failed_shard_ids]
        ft.set_attribute("hits", len(hits))

    n_shards = total_shards if total_shards is not None else len(executors)
    hits_block: dict = {"max_score": max_score, "hits": hits}
    # block-max pruning (ISSUE 20): pruned blocks' docs were never
    # counted, so `total` is a lower bound — "eq" degrades to "gte"
    # (the contract Lucene's BMW collector keeps via track_total_hits)
    exact_rel = "eq" if not pruned_box[0] else "gte"
    if track_total is False:
        pass  # total omitted entirely
    elif track_total is True:
        hits_block = {"total": {"value": total, "relation": exact_rel},
                      **hits_block}
    else:
        threshold = int(track_total)
        if total > threshold:
            hits_block = {"total": {"value": threshold, "relation": "gte"},
                          **hits_block}
        else:
            hits_block = {"total": {"value": total, "relation": exact_rel},
                          **hits_block}

    n_failed = failed_shards + len(shard_failures)
    attempted = sum(can_match_flags()) if flags_box[0] is not None \
        else len(executors)
    if shard_failures and len(failed_shard_ids) >= max(attempted, 1):
        # every shard that executed failed: no partial result exists to
        # degrade to (reference: "all shards failed" regardless of
        # allow_partial_search_results)
        raise SearchPhaseExecutionError(
            "all shards failed", phase="query", grouped=True,
            failed_shards=list(shard_failures))
    if shard_failures and not allow_partial_results:
        raise SearchPhaseExecutionError(
            "Partial shards failure", phase="query", grouped=True,
            failed_shards=list(shard_failures))
    shards_block: dict = {"total": n_shards,
                          "successful": max(n_shards - n_failed, 0),
                          "skipped": skipped_box[0], "failed": n_failed}
    if shard_failures:
        shards_block["failures"] = list(shard_failures)
    resp = {
        "took": 0,      # placeholder: set below AFTER agg reduce/suggest
        "timed_out": timed_out_box[0],
        "_shards": shards_block,
        "hits": hits_block,
    }
    if agg_nodes:
        with _PhaseTimer(trace, phases, "reduce", op="aggs"):
            try:
                if faults.ENABLED:
                    faults.fire("reduce.aggs")
                aggregations = reduce_aggs(decoded_partials)
                apply_pipelines(agg_nodes, aggregations)
            except OpenSearchTpuError:
                raise               # already a clean typed error
            except Exception as e:  # except-ok: wraps into typed SearchPhaseExecutionError -- never a raw 500
                # coordinator-level reduce has no per-shard slice to
                # degrade to — surface a clean typed error, never a
                # corrupt/partial agg tree
                raise SearchPhaseExecutionError(
                    f"failed to reduce aggregations: "
                    f"{type(e).__name__}: {e}", phase="reduce")
        resp["aggregations"] = aggregations
    if body.get("suggest"):
        from opensearch_tpu.search.suggest import execute_suggest
        with _PhaseTimer(trace, phases, "suggest"):
            resp["suggest"] = execute_suggest(executors, body["suggest"])
    # everything between the earlier timers and this point (hits/total
    # block shaping, the resp literal) is response rendering — attribute
    # it so the per-phase breakdown accounts for the whole request
    phases["render"] = phases.get("render", 0) \
        + (time.perf_counter_ns() - start_ns) - sum(phases.values())
    took_f = (time.monotonic() - start) * 1000
    resp["took"] = int(took_f)
    m = TELEMETRY.metrics
    m.counter("search.queries").inc()
    m.histogram("search.took_ms").observe(took_f)
    for phase_name, ns in phases.items():
        m.histogram(f"search.phase.{phase_name}_ms").observe(ns / 1e6)
    if phase_times is not None:
        phase_times.update(
            {phase_name: ns / 1e6 for phase_name, ns in phases.items()})
    # root-span + slow-log transfer attribution for the general host-loop
    # path (the envelope and hybrid paths publish their own above)
    _publish_scope(req_scope, trace, phase_times)
    if TELEMETRY.flight.enabled:
        # lifecycle phase decomposition (telemetry/lifecycle.py): the
        # request's timeline carries the same per-phase wall the metrics
        # histograms record, so a captured slow request explains its own
        # took. device_get is the ledger's sub-attribution of `query`
        # (tools/tail_report.py knows not to double-count it).
        tl = TELEMETRY.flight.current()
        if tl is not None:
            tl.merge_phases({name: ns / 1e6
                             for name, ns in phases.items()})
            if req_scope is not None:
                tl.merge_phases({"device_get": req_scope.device_get_ms})
            tl.mark_ready()
    # per-shape cost attribution (ISSUE 15) for the general/SPMD path —
    # after took/phases are final, before render-only bookkeeping
    _note_controller_insights(query_spec, took_f, req_scope)
    if profiling:
        # per-shard per-phase breakdown: coordinator phases (parse,
        # can_match, reduce, fetch, render) are shared across shards,
        # `query` is the shard's own device work — so each shard's phase
        # sum stays ≤ the request total (and ≈ it for a single shard)
        total_ns = time.perf_counter_ns() - start_ns
        for entry in profile_shards:
            q_ns = entry.pop("_query_ns", 0)
            entry["searches"][0]["rewrite_time"] = phases.get("parse", 0)
            entry["phases"] = {
                "parse": phases.get("parse", 0),
                "can_match": phases.get("can_match", 0),
                "query": q_ns,
                "reduce": phases.get("reduce", 0),
                "fetch": phases.get("fetch", 0),
                "render": phases.get("render", 0),
            }
        resp["profile"] = {"shards": profile_shards,
                           "total_ns": total_ns,
                           "phases_ns": dict(phases)}
        if req_scope is not None:
            # request-level transfer totals: the per-shard transfers[]
            # above decompose these (telemetry/ledger.py)
            resp["profile"]["bytes_to_device"] = req_scope.h2d_bytes
            resp["profile"]["bytes_fetched"] = req_scope.d2h_bytes
            resp["profile"]["device_get_ms"] = round(
                req_scope.device_get_ms, 3)
    if page:
        last = page[-1]
        resp["_page_cursor"] = {
            "values": [last.score if f == "_score" else last.sort_values[i]
                       for i, (f, _) in enumerate(sort_specs)],
            "tiebreak": (last.shard_i, last.seg_i, last.ord),
        }
    return resp


_SCRIPT_SERVICE = None


def _default_script_service():
    """Inline-script service for fetch-phase script_fields (stored-script
    lookup goes through the node's service at the REST layer)."""
    global _SCRIPT_SERVICE
    if _SCRIPT_SERVICE is None:
        from opensearch_tpu.script.service import ScriptService
        _SCRIPT_SERVICE = ScriptService()
    return _SCRIPT_SERVICE


def _build_hit(ex, c, body, score, query_node, sort_specs,
               score_sorted, inner_specs=None, inner_cache=None) -> dict:
    from opensearch_tpu.search import fetch as fetch_phase

    hit = ex._hit_dict(c.seg_i, c.ord, score, body)
    if not score_sorted or body.get("search_after") is not None:
        hit["sort"] = c.sort_values
    seg = ex.reader.segments[c.seg_i]
    mapper = ex.reader.mapper
    if body.get("highlight"):
        field_terms = fetch_phase.collect_field_terms(query_node, mapper)
        hl = fetch_phase.build_highlights(hit.get("_source"),
                                          body["highlight"], field_terms,
                                          mapper)
        if hl:
            hit["highlight"] = hl
    if body.get("explain"):
        hit["_explanation"] = fetch_phase.explain_hit(
            seg, c.ord, query_node, mapper, ex.reader.stats(),
            score if score is not None else c.score)
    if body.get("docvalue_fields"):
        fields = fetch_phase.docvalue_fields(
            seg, c.ord, body["docvalue_fields"], mapper,
            prefetched=getattr(c, "dv_page", None))
        if fields:
            hit["fields"] = fields
    if body.get("script_fields"):
        from opensearch_tpu.script.painless import collect_doc_fields
        from opensearch_tpu.script.service import doc_view
        svc = _default_script_service()
        for name, spec in body["script_fields"].items():
            fs = svc.compile((spec or {}).get("script"), "field")
            dv = doc_view(seg, c.ord, collect_doc_fields(fs.stmts) or None)
            value = fs.execute(dv, seg.sources[c.ord])
            hit.setdefault("fields", {})[name] = \
                value if isinstance(value, list) else [value]
    if body.get("version"):
        # doc_meta carries the persisted (version, seq_no, primary_term)
        meta = getattr(seg, "doc_meta", {}).get(hit["_id"])
        hit["_version"] = meta[0] if meta else 1
    nested_specs = inner_specs if inner_specs is not None \
        else fetch_phase.collect_inner_hit_specs(query_node)
    if nested_specs:
        # request-scoped eval cache: never shared across requests (stats
        # and segments may move between them)
        cache = inner_cache if inner_cache is not None else {}
        hit["inner_hits"] = fetch_phase.build_inner_hits(
            ex, c.seg_i, c.ord, nested_specs, cache)
    return hit
