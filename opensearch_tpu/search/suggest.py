"""Suggesters: term, phrase, completion.

Re-design of search/suggest/ (TermSuggester with DirectSpellChecker edit
distance + doc-freq ranking, PhraseSuggester's per-token best correction,
CompletionSuggester's prefix automaton). The vocabulary lives in the
segment term dictionaries / ordinal dictionaries, so candidate generation
is a host-side scan over sorted terms — small relative to the query phase,
and identical in contract to the reference's suggest API.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from opensearch_tpu.common.errors import IllegalArgumentError


def edit_distance(a: str, b: str, cap: int = 3) -> int:
    """Damerau-Levenshtein (the reference's LuceneLevenshteinDistance is the
    same family), capped for early exit."""
    if abs(len(a) - len(b)) > cap:
        return cap + 1
    prev2: Optional[List[int]] = None
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        cur = [i] + [0] * len(b)
        for j, cb in enumerate(b, 1):
            cost = 0 if ca == cb else 1
            cur[j] = min(prev[j] + 1, cur[j - 1] + 1, prev[j - 1] + cost)
            if i > 1 and j > 1 and ca == b[j - 2] and a[i - 2] == cb:
                cur[j] = min(cur[j], prev2[j - 2] + 1)
        if min(cur) > cap:
            return cap + 1
        prev2, prev = prev, cur
    return prev[len(b)]


def _field_vocab(executors, field: str) -> Dict[str, int]:
    """term → doc_freq across every segment of every target shard."""
    vocab: Dict[str, int] = {}
    for ex in executors:
        for seg in ex.reader.segments:
            for term in seg.terms_for_field(field):
                meta = seg.get_term(field, term)
                if meta is not None:
                    vocab[term] = vocab.get(term, 0) + meta.doc_freq
            ocol = seg.ordinal_dv.get(field)
            if ocol is not None:
                import numpy as np
                counts = np.bincount(ocol.ords,
                                     minlength=len(ocol.dictionary))
                for term, c in zip(ocol.dictionary, counts):
                    vocab[term] = vocab.get(term, 0) + int(c)
    return vocab


def _term_candidates(token: str, vocab: Dict[str, int], max_edits: int,
                     prefix_length: int, size: int,
                     include_exact: bool) -> List[dict]:
    out = []
    for term, freq in vocab.items():
        if prefix_length and not term.startswith(token[:prefix_length]):
            continue
        if term == token and not include_exact:
            continue
        dist = edit_distance(token, term, cap=max_edits)
        if dist > max_edits:
            continue
        score = 1.0 - dist / max(len(token), len(term), 1)
        out.append({"text": term, "score": round(score, 6), "freq": freq})
    out.sort(key=lambda c: (-c["score"], -c["freq"], c["text"]))
    return out[:size]


def term_suggest(executors, name: str, spec: dict) -> List[dict]:
    text = spec.get("text")
    body = spec.get("term") or {}
    field = body.get("field")
    if text is None or field is None:
        raise IllegalArgumentError(
            f"suggester [{name}] requires [text] and [term.field]")
    max_edits = int(body.get("max_edits", 2))
    prefix_length = int(body.get("prefix_length", 1))
    size = int(body.get("size", 5))
    suggest_mode = body.get("suggest_mode", "missing")
    vocab = _field_vocab(executors, field)
    results = []
    offset = 0
    for token in str(text).lower().split():
        exists = token in vocab
        if suggest_mode == "missing" and exists:
            options = []
        else:
            options = _term_candidates(token, vocab, max_edits,
                                       prefix_length, size,
                                       include_exact=False)
            if suggest_mode == "popular" and exists:
                options = [o for o in options
                           if o["freq"] > vocab[token]]
        results.append({"text": token, "offset": offset,
                        "length": len(token), "options": options})
        offset += len(token) + 1
    return results


def phrase_suggest(executors, name: str, spec: dict) -> List[dict]:
    text = spec.get("text")
    body = spec.get("phrase") or {}
    field = body.get("field")
    if text is None or field is None:
        raise IllegalArgumentError(
            f"suggester [{name}] requires [text] and [phrase.field]")
    max_errors = float(body.get("max_errors", 1.0))
    size = int(body.get("size", 5))
    vocab = _field_vocab(executors, field)
    tokens = str(text).lower().split()
    per_token: List[List[Tuple[str, float]]] = []
    n_corrections = 0
    for token in tokens:
        if token in vocab:
            per_token.append([(token, 1.0)])
            continue
        cands = _term_candidates(token, vocab, 2, 1, 3, include_exact=True)
        if cands:
            n_corrections += 1
            per_token.append([(c["text"], c["score"]) for c in cands])
        else:
            per_token.append([(token, 0.1)])
    allowed_errors = max_errors if max_errors >= 1 else \
        max_errors * len(tokens)
    options: List[dict] = []
    if 0 < n_corrections <= allowed_errors or n_corrections == 0:
        # beam over the top candidate combinations (best-first, width=size)
        beams: List[Tuple[float, List[str]]] = [(1.0, [])]
        for cands in per_token:
            beams = sorted(
                ((score * cscore, words + [cword])
                 for score, words in beams for cword, cscore in cands),
                key=lambda b: -b[0])[:size]
        for score, words in beams:
            phrase = " ".join(words)
            if phrase != " ".join(tokens):
                options.append({"text": phrase,
                                "score": round(score, 6)})
    return [{"text": str(text), "offset": 0, "length": len(str(text)),
             "options": options[:size]}]


def completion_suggest(executors, name: str, spec: dict) -> List[dict]:
    prefix = spec.get("prefix", spec.get("text"))
    body = spec.get("completion") or {}
    field = body.get("field")
    if prefix is None or field is None:
        raise IllegalArgumentError(
            f"suggester [{name}] requires [prefix] and [completion.field]")
    size = int(body.get("size", 5))
    fuzzy = body.get("fuzzy")  # {} means fuzzy-with-defaults
    fuzzy_enabled = fuzzy is not None and fuzzy is not False
    options = []
    seen = set()
    for ex in executors:
        for seg in ex.reader.segments:
            ocol = seg.ordinal_dv.get(field)
            if ocol is None:
                continue
            for doc_id, ord_ in zip(ocol.doc_ids, ocol.ords):
                if not seg.live[doc_id]:
                    continue
                value = ocol.dictionary[ord_]
                if value in seen:
                    continue
                if value.lower().startswith(str(prefix).lower()):
                    matched = True
                    score = 1.0
                elif fuzzy_enabled:
                    fuzziness = int((fuzzy or {}).get("fuzziness", 1)) \
                        if not isinstance(fuzzy, bool) else 1
                    p = str(prefix).lower()
                    # an edit may change the matched prefix length, so try
                    # value prefixes of len±fuzziness and keep the best
                    dist = min(
                        edit_distance(p, value.lower()[:length],
                                      cap=fuzziness)
                        for length in range(max(1, len(p) - fuzziness),
                                            len(p) + fuzziness + 1))
                    matched = dist <= fuzziness
                    score = 1.0 / (1 + dist)
                else:
                    matched = False
                    score = 0.0
                if matched:
                    seen.add(value)
                    options.append({
                        "text": value, "_index": ex.reader.index_name,
                        "_id": seg.doc_ids[int(doc_id)], "_score": score,
                        "_source": seg.sources[int(doc_id)]})
    options.sort(key=lambda o: (-o["_score"], o["text"]))
    return [{"text": str(prefix), "offset": 0,
             "length": len(str(prefix)), "options": options[:size]}]


def execute_suggest(executors, suggest_body: dict) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    global_text = suggest_body.get("text")
    for name, spec in suggest_body.items():
        if name == "text":
            continue
        if not isinstance(spec, dict):
            raise IllegalArgumentError(f"suggester [{name}] malformed")
        spec = dict(spec)
        if global_text is not None:
            spec.setdefault("text", global_text)
        if "term" in spec:
            out[name] = term_suggest(executors, name, spec)
        elif "phrase" in spec:
            out[name] = phrase_suggest(executors, name, spec)
        elif "completion" in spec:
            out[name] = completion_suggest(executors, name, spec)
        else:
            raise IllegalArgumentError(
                f"suggester [{name}] requires one of [term, phrase, "
                f"completion]")
    return out
