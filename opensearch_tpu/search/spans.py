"""Host-side span and interval algebra over stored term positions.

Reference: Lucene's spans package driven by the 9 Span*QueryBuilder classes
(server/src/main/java/org/opensearch/index/query/SpanNearQueryBuilder.java et
al.) and the minimal-interval algebra behind IntervalQueryBuilder.java /
IntervalsSourceProvider.java.

Design: positional matching is irreducibly per-document sparse work that would
waste MXU lanes as a dense device kernel — exactly like phrase matching, it
runs on host over the segment's (field, term) position lists and enters the
device plan as a precomputed dense (scores, matches) pair (see
compile.py:phrase_eval for the established pattern). A span is represented as
``(start, end, cost)`` with ``end`` exclusive and ``cost`` the accumulated
slop/gap penalty; sloppy frequency is ``sum(1 / (1 + cost))`` over matched
spans, mirroring Lucene's SpanScorer sloppyFreq accumulation.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from opensearch_tpu.common.errors import ParsingError, QueryShardError
from opensearch_tpu.search import dsl

Span = Tuple[int, int, int]              # (start, end_exclusive, cost)
DocSpans = Dict[int, List[Span]]         # doc ord -> sorted span list

_UNLIMITED = 1 << 30


def _merge(per_doc: List[DocSpans]) -> DocSpans:
    out: DocSpans = {}
    for ds in per_doc:
        for doc, spans in ds.items():
            out.setdefault(doc, []).extend(spans)
    for doc in out:
        out[doc].sort()
    return out


def _term_spans(seg, field: str, term: str) -> DocSpans:
    plist = seg._positions_for(field, term)
    if plist is None:
        return {}
    return {doc: [(int(p), int(p) + 1, 0) for p in pos]
            for doc, pos in plist.items() if seg.live[doc]}


def _near_ordered(children: List[DocSpans], slop: int) -> DocSpans:
    """Ordered near: one candidate chain per first-clause span, greedily
    extended with the minimal-end non-overlapping following span of each next
    clause (Lucene NearSpansOrdered's advance strategy; minimal-end choice so
    an earlier-starting long span can't shadow a shorter later one)."""
    out: DocSpans = {}
    docs = set(children[0].keys())
    for ds in children[1:]:
        docs &= set(ds.keys())
    for doc in docs:
        matches: List[Span] = []
        for (s0, e0, c0) in children[0][doc]:
            end, cost, ok = e0, c0, True
            for ds in children[1:]:
                best: Optional[Span] = None
                for (s, e, c) in ds[doc]:
                    if s >= end and (best is None
                                     or (e, s + c) < (best[1], best[0] + best[2])):
                        best = (s, e, c)
                if best is None:
                    ok = False
                    break
                cost += best[2] + (best[0] - end)   # gap between clauses
                end = best[1]
            if ok and cost <= slop:
                matches.append((s0, end, cost))
        if matches:
            out[doc] = matches
    return out


def _near_unordered(children: List[DocSpans], slop: int) -> DocSpans:
    """Unordered near: minimal windows containing one span per clause;
    slop charged as window width minus the total clause width (Lucene
    NearSpansUnordered)."""
    out: DocSpans = {}
    docs = set(children[0].keys())
    for ds in children[1:]:
        docs &= set(ds.keys())
    for doc in docs:
        # tag each span with its clause index, sweep minimal windows
        tagged: List[Tuple[int, int, int, int]] = []
        for ci, ds in enumerate(children):
            for (s, e, c) in ds[doc]:
                tagged.append((s, e, c, ci))
        tagged.sort()
        n = len(children)
        matches: List[Span] = []
        for i, (s0, e0, c0, ci0) in enumerate(tagged):
            seen = {ci0: (s0, e0, c0)}
            for j in range(i + 1, len(tagged)):
                s, e, c, ci = tagged[j]
                if ci not in seen:
                    seen[ci] = (s, e, c)
                if len(seen) == n:
                    break
            if len(seen) < n:
                continue
            w_start = min(sp[0] for sp in seen.values())
            w_end = max(sp[1] for sp in seen.values())
            total_len = sum(sp[1] - sp[0] for sp in seen.values())
            inner = sum(sp[2] for sp in seen.values())
            cost = inner + max(0, (w_end - w_start) - total_len)
            if cost <= slop:
                matches.append((w_start, w_end, cost))
        if matches:
            # dedupe identical windows produced from different anchors
            out[doc] = sorted(set(matches))
    return out


def _span_not(include: DocSpans, exclude: DocSpans, pre: int,
              post: int) -> DocSpans:
    out: DocSpans = {}
    for doc, spans in include.items():
        excl = exclude.get(doc, [])
        kept = [sp for sp in spans
                if not any(es < sp[1] + post and ee > sp[0] - pre
                           for (es, ee, _) in excl)]
        if kept:
            out[doc] = kept
    return out


def _span_containing(big: DocSpans, little: DocSpans) -> DocSpans:
    out: DocSpans = {}
    for doc, bigs in big.items():
        littles = little.get(doc)
        if not littles:
            continue
        kept = [bp for bp in bigs
                if any(bp[0] <= ls and le <= bp[1]
                       for (ls, le, _) in littles)]
        if kept:
            out[doc] = kept
    return out


def _span_within(big: DocSpans, little: DocSpans) -> DocSpans:
    out: DocSpans = {}
    for doc, littles in little.items():
        bigs = big.get(doc)
        if not bigs:
            continue
        kept = [lp for lp in littles
                if any(bs <= lp[0] and lp[1] <= be
                       for (bs, be, _) in bigs)]
        if kept:
            out[doc] = kept
    return out


class SpanEvaluator:
    """Evaluates a span query tree against one segment.

    ``expand`` resolves a multi-term query node (prefix/wildcard/fuzzy/regexp)
    to the matching terms of this segment's term dictionary — supplied by the
    compiler so expansion predicates stay in one place.
    """

    def __init__(self, seg, expand: Callable[[dsl.QueryNode], List[str]]):
        self.seg = seg
        self.expand = expand
        self.leaf_terms: List[Tuple[str, str]] = []   # (field, term) for idf

    def field_of(self, node: dsl.QueryNode) -> str:
        """The effective (scoring) field of a span clause; mismatched inner
        fields are a QueryShardError exactly like Lucene's SpanNearQuery
        constructor check, with field_masking_span as the sanctioned bridge."""
        if isinstance(node, dsl.FieldMaskingSpanQuery):
            return node.field
        if isinstance(node, (dsl.SpanTermQuery,)):
            return node.field
        if isinstance(node, dsl.SpanMultiQuery):
            return node.match.field
        if isinstance(node, dsl.SpanFirstQuery):
            return self.field_of(node.match)
        if isinstance(node, dsl.SpanNotQuery):
            return self._same_field(node.include, node.exclude)
        if isinstance(node, (dsl.SpanContainingQuery, dsl.SpanWithinQuery)):
            return self._same_field(node.big, node.little)
        if isinstance(node, (dsl.SpanNearQuery, dsl.SpanOrQuery)):
            fields = {self.field_of(c) for c in node.clauses}
            if len(fields) != 1:
                raise QueryShardError(
                    "Clauses must have same field")
            return fields.pop()
        raise ParsingError(f"not a span query: {type(node).__name__}")

    def _same_field(self, a: dsl.QueryNode, b: dsl.QueryNode) -> str:
        fa, fb = self.field_of(a), self.field_of(b)
        if fa != fb:
            raise QueryShardError("Clauses must have same field")
        return fa

    def eval(self, node: dsl.QueryNode) -> DocSpans:
        if isinstance(node, dsl.SpanTermQuery):
            self.leaf_terms.append((node.field, node.value))
            return _term_spans(self.seg, node.field, node.value)
        if isinstance(node, dsl.SpanMultiQuery):
            field = node.match.field
            terms = self.expand(node.match)
            self.leaf_terms.extend((field, t) for t in terms)
            return _merge([_term_spans(self.seg, field, t) for t in terms])
        if isinstance(node, dsl.FieldMaskingSpanQuery):
            return self.eval(node.query)
        if isinstance(node, dsl.SpanOrQuery):
            return _merge([self.eval(c) for c in node.clauses])
        if isinstance(node, dsl.SpanNearQuery):
            children = [self.eval(c) for c in node.clauses]
            if len(children) == 1:
                return children[0]
            if node.in_order:
                return _near_ordered(children, node.slop)
            return _near_unordered(children, node.slop)
        if isinstance(node, dsl.SpanFirstQuery):
            inner = self.eval(node.match)
            out = {}
            for doc, spans in inner.items():
                kept = [sp for sp in spans if sp[1] <= node.end]
                if kept:
                    out[doc] = kept
            return out
        if isinstance(node, dsl.SpanNotQuery):
            include = self.eval(node.include)
            return _span_not(include, self._eval_unscored(node.exclude),
                             node.pre, node.post)
        if isinstance(node, dsl.SpanContainingQuery):
            return _span_containing(self.eval(node.big), self.eval(node.little))
        if isinstance(node, dsl.SpanWithinQuery):
            return _span_within(self.eval(node.big), self.eval(node.little))
        raise ParsingError(f"not a span query: {type(node).__name__}")

    def _eval_unscored(self, node: dsl.QueryNode) -> DocSpans:
        """Evaluate a clause whose terms must NOT enter the similarity weight
        (span_not's exclude — Lucene visits it as MUST_NOT and never folds it
        into the sim weight)."""
        saved = self.leaf_terms
        self.leaf_terms = []
        try:
            return self.eval(node)
        finally:
            self.leaf_terms = saved


# ------------------------------------------------------------------ intervals

class IntervalEvaluator:
    """Evaluates an intervals source tree (the JSON rule dict) for one field.

    Interval sources share the span representation; ``all_of`` maps to
    near (ordered or not) with ``max_gaps`` as the slop budget, ``any_of``
    to union, ``match`` to a phrase-shaped near over the analyzed terms.
    Filters implement the minimal-interval relations of
    IntervalsSourceProvider.IntervalFilter.
    """

    def __init__(self, seg, field: str,
                 analyze: Callable[[str, Optional[str]], List[str]],
                 expand: Callable[[dsl.QueryNode], List[str]]):
        self.seg = seg
        self.field = field
        self.analyze = analyze          # (text, analyzer_name) -> terms
        self.expand = expand
        self.leaf_terms: List[Tuple[str, str]] = []

    def eval(self, rule: Dict) -> DocSpans:
        kind, spec = next(iter(rule.items()))
        spans = getattr(self, f"_r_{kind}")(spec)
        filt = spec.get("filter") if isinstance(spec, dict) else None
        if filt:
            spans = self._apply_filter(spans, filt)
        return spans

    def _terms_spans(self, terms: List[str]) -> List[DocSpans]:
        self.leaf_terms.extend((self.field, t) for t in terms)
        return [_term_spans(self.seg, self.field, t) for t in terms]

    def _r_match(self, spec: Dict) -> DocSpans:
        terms = self.analyze(str(spec["query"]), spec.get("analyzer"))
        if not terms:
            return {}
        children = self._terms_spans(terms)
        if len(children) == 1:
            return children[0]
        max_gaps = int(spec.get("max_gaps", -1))
        slop = _UNLIMITED if max_gaps < 0 else max_gaps
        if bool(spec.get("ordered", False)):
            return _near_ordered(children, slop)
        return _near_unordered(children, slop)

    def _r_any_of(self, spec: Dict) -> DocSpans:
        return _merge([self.eval(sub) for sub in spec["intervals"]])

    def _r_all_of(self, spec: Dict) -> DocSpans:
        children = [self.eval(sub) for sub in spec["intervals"]]
        if len(children) == 1:
            return children[0]
        max_gaps = int(spec.get("max_gaps", -1))
        slop = _UNLIMITED if max_gaps < 0 else max_gaps
        if bool(spec.get("ordered", False)):
            return _near_ordered(children, slop)
        return _near_unordered(children, slop)

    def _r_prefix(self, spec: Dict) -> DocSpans:
        node = dsl.PrefixQuery(field=self.field, value=str(spec["prefix"]))
        return _merge(self._terms_spans(self.expand(node)))

    def _r_wildcard(self, spec: Dict) -> DocSpans:
        node = dsl.WildcardQuery(field=self.field, value=str(spec["pattern"]))
        return _merge(self._terms_spans(self.expand(node)))

    def _r_fuzzy(self, spec: Dict) -> DocSpans:
        node = dsl.FuzzyQuery(field=self.field, value=str(spec["term"]),
                              fuzziness=str(spec.get("fuzziness", "AUTO")),
                              prefix_length=int(spec.get("prefix_length", 0)))
        return _merge(self._terms_spans(self.expand(node)))

    def _apply_filter(self, spans: DocSpans, filt: Dict) -> DocSpans:
        relation, fspec = next(iter(filt.items()))
        # the filter reference source positions intervals but does not score:
        # keep its terms out of the idf sum (IntervalFilter sources are not
        # part of the IntervalQuery's term set)
        saved = self.leaf_terms
        self.leaf_terms = []
        try:
            ref = self.eval(fspec)
        finally:
            self.leaf_terms = saved
        out: DocSpans = {}
        for doc, doc_spans in spans.items():
            refs = ref.get(doc, [])
            kept = [sp for sp in doc_spans
                    if _interval_rel(sp, refs, relation)]
            if kept:
                out[doc] = kept
        return out


def _interval_rel(sp: Span, refs: List[Span], relation: str) -> bool:
    s, e, _ = sp
    if relation == "containing":
        return any(s <= rs and re_ <= e for (rs, re_, _) in refs)
    if relation == "contained_by":
        return any(rs <= s and e <= re_ for (rs, re_, _) in refs)
    if relation == "not_containing":
        return not any(s <= rs and re_ <= e for (rs, re_, _) in refs)
    if relation == "not_contained_by":
        return not any(rs <= s and e <= re_ for (rs, re_, _) in refs)
    if relation == "overlapping":
        return any(rs < e and re_ > s for (rs, re_, _) in refs)
    if relation == "not_overlapping":
        return not any(rs < e and re_ > s for (rs, re_, _) in refs)
    if relation == "before":
        return any(e <= rs for (rs, re_, _) in refs)
    if relation == "after":
        return any(s >= re_ for (rs, re_, _) in refs)
    raise ParsingError(f"unknown intervals filter [{relation}]")


def score_spans(seg, stats, field: str, doc_spans: DocSpans,
                leaf_terms: List[Tuple[str, str]], boost: float,
                length_table: np.ndarray, k1: float, b: float
                ) -> Tuple[np.ndarray, np.ndarray]:
    """BM25-shaped scoring over matched spans: sloppy freq sum(1/(1+cost))
    plugged into the same similarity the phrase path uses, idf summed over
    the distinct leaf terms against the scoring field's statistics
    (Lucene SpanWeight.buildSimWeight)."""
    n = seg.num_docs
    scores = np.zeros(n, dtype=np.float32)
    matches = np.zeros(n, dtype=bool)
    if not doc_spans:
        return scores, matches
    sum_idf = sum(stats.idf(field, t)
                  for t in sorted({t for (_, t) in leaf_terms}))
    dc, ttf = stats.field_stats(field)
    avgdl = (ttf / dc) if dc else 1.0
    norms = seg.norms.get(field)
    for doc, spans in doc_spans.items():
        if not seg.live[doc]:
            continue
        freq = sum(1.0 / (1.0 + c) for (_, _, c) in spans)
        if freq <= 0:
            continue
        dl = float(length_table[norms[doc]]) if norms is not None else 1.0
        b_eff = b if norms is not None else 0.0
        denom = freq + k1 * (1 - b_eff + b_eff * dl / avgdl)
        scores[doc] = boost * sum_idf * freq * (k1 + 1) / denom
        matches[doc] = True
    return scores, matches
