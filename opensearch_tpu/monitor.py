"""OS and process probes for node stats.

Re-design of monitor/os/OsProbe.java + monitor/process/ProcessProbe.java:
the reference reads /proc and MXBeans; here /proc and the resource module
cover the same surface (load average, memory, swap, cgroup limits where
visible, open file descriptors, process CPU). Every read degrades to
best-effort: a missing /proc entry yields -1 fields, never an exception —
exactly the probe contract in the reference (it returns -1 on unsupported
platforms)."""

from __future__ import annotations

import os
import time
from typing import Optional

_START = time.time()


def _read_proc(path: str) -> str:
    try:
        with open(path, "r") as f:
            return f.read()
    except OSError:
        return ""


def os_probe() -> dict:
    """OsProbe.osStats(): load average, cpu percent proxy, mem/swap."""
    out: dict = {"timestamp": int(time.time() * 1000)}
    try:
        la1, la5, la15 = os.getloadavg()
        out["cpu"] = {"load_average": {"1m": round(la1, 2),
                                       "5m": round(la5, 2),
                                       "15m": round(la15, 2)}}
    except OSError:
        out["cpu"] = {"load_average": {"1m": -1, "5m": -1, "15m": -1}}
    total = free = available = swap_total = swap_free = -1
    for line in _read_proc("/proc/meminfo").splitlines():
        parts = line.split()
        if len(parts) < 2:
            continue
        kb = int(parts[1]) * 1024 if parts[1].isdigit() else -1
        key = parts[0].rstrip(":")
        if key == "MemTotal":
            total = kb
        elif key == "MemFree":
            free = kb
        elif key == "MemAvailable":
            available = kb
        elif key == "SwapTotal":
            swap_total = kb
        elif key == "SwapFree":
            swap_free = kb
    used = (total - available) if total > 0 and available >= 0 else -1
    out["mem"] = {
        "total_in_bytes": total, "free_in_bytes": free,
        "used_in_bytes": used,
        "used_percent": round(100.0 * used / total, 1)
        if total > 0 and used >= 0 else -1,
    }
    out["swap"] = {"total_in_bytes": swap_total,
                   "free_in_bytes": swap_free,
                   "used_in_bytes": (swap_total - swap_free)
                   if swap_total >= 0 and swap_free >= 0 else -1}
    return out


class FsHealthService:
    """FsHealthService.java:74 analog: periodically writes + fsyncs a probe
    file under the data path; failures mark the node UNHEALTHY, which the
    Coordinator consumes (fails follower checks → leader removes the node;
    refuses pre-votes and elections). A later successful write heals."""

    PROBE_FILE = ".os_temp_health_probe"

    def __init__(self, path: Optional[str], interval_s: float = 5.0):
        import tempfile
        self.path = path or tempfile.gettempdir()
        self.interval_s = interval_s
        self.healthy = True
        self._stop = False
        self._thread = None

    def probe_once(self) -> bool:
        import os as _os
        try:
            # the node owns its data path; it may not exist before the
            # first write (gateway creates it lazily)
            _os.makedirs(self.path, exist_ok=True)
            probe = _os.path.join(self.path, self.PROBE_FILE)
            with open(probe, "wb") as f:
                f.write(b"ok")
                f.flush()
                _os.fsync(f.fileno())
            ok = True
        except OSError:
            ok = False
        if not self._stop:
            # a stopped service must not overwrite a pinned verdict (an
            # in-flight probe racing stop() would re-mark healed)
            self.healthy = ok
        return ok

    def start(self):
        import threading as _threading

        def loop():
            import time as _time
            while not self._stop:
                self.probe_once()
                _time.sleep(self.interval_s)

        self.probe_once()
        self._thread = _threading.Thread(target=loop, name="fs-health",
                                         daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop = True
        if self._thread is not None:
            self._thread.join(timeout=1.0)


def fs_probe(path: Optional[str] = None) -> dict:
    """FsProbe.stats(): disk totals for the data path (or cwd)."""
    import shutil
    try:
        usage = shutil.disk_usage(path or ".")
        return {"total_in_bytes": usage.total, "free_in_bytes": usage.free,
                "available_in_bytes": usage.free,
                "used_in_bytes": usage.used}
    except OSError:
        return {"total_in_bytes": -1, "free_in_bytes": -1,
                "available_in_bytes": -1, "used_in_bytes": -1}


def process_probe() -> dict:
    """ProcessProbe.processStats(): open fds, max fds, process CPU."""
    pid = os.getpid()
    try:
        open_fds = len(os.listdir(f"/proc/{pid}/fd"))
    except OSError:
        open_fds = -1
    max_fds = -1
    try:
        import resource
        max_fds = resource.getrlimit(resource.RLIMIT_NOFILE)[0]
    except (ImportError, OSError, ValueError):
        pass
    cpu_ms = -1
    try:
        t = os.times()
        cpu_ms = int((t.user + t.system) * 1000)
    except OSError:
        pass
    return {
        "timestamp": int(time.time() * 1000),
        "id": pid,
        "open_file_descriptors": open_fds,
        "max_file_descriptors": max_fds,
        "cpu": {"total_in_millis": cpu_ms},
        "uptime_in_millis": int((time.time() - _START) * 1000),
    }
