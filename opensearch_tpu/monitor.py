"""OS and process probes for node stats.

Re-design of monitor/os/OsProbe.java + monitor/process/ProcessProbe.java:
the reference reads /proc and MXBeans; here /proc and the resource module
cover the same surface (load average, memory, swap, cgroup limits where
visible, open file descriptors, process CPU). Every read degrades to
best-effort: a missing /proc entry yields -1 fields, never an exception —
exactly the probe contract in the reference (it returns -1 on unsupported
platforms)."""

from __future__ import annotations

import os
import time
from typing import Optional

_START = time.time()


def _read_proc(path: str) -> str:
    try:
        with open(path, "r") as f:
            return f.read()
    except OSError:
        return ""


def os_probe() -> dict:
    """OsProbe.osStats(): load average, cpu percent proxy, mem/swap."""
    out: dict = {"timestamp": int(time.time() * 1000)}
    try:
        la1, la5, la15 = os.getloadavg()
        out["cpu"] = {"load_average": {"1m": round(la1, 2),
                                       "5m": round(la5, 2),
                                       "15m": round(la15, 2)}}
    except OSError:
        out["cpu"] = {"load_average": {"1m": -1, "5m": -1, "15m": -1}}
    total = free = available = swap_total = swap_free = -1
    for line in _read_proc("/proc/meminfo").splitlines():
        parts = line.split()
        if len(parts) < 2:
            continue
        kb = int(parts[1]) * 1024 if parts[1].isdigit() else -1
        key = parts[0].rstrip(":")
        if key == "MemTotal":
            total = kb
        elif key == "MemFree":
            free = kb
        elif key == "MemAvailable":
            available = kb
        elif key == "SwapTotal":
            swap_total = kb
        elif key == "SwapFree":
            swap_free = kb
    used = (total - available) if total > 0 and available >= 0 else -1
    out["mem"] = {
        "total_in_bytes": total, "free_in_bytes": free,
        "used_in_bytes": used,
        "used_percent": round(100.0 * used / total, 1)
        if total > 0 and used >= 0 else -1,
    }
    out["swap"] = {"total_in_bytes": swap_total,
                   "free_in_bytes": swap_free,
                   "used_in_bytes": (swap_total - swap_free)
                   if swap_total >= 0 and swap_free >= 0 else -1}
    return out


def fs_probe(path: Optional[str] = None) -> dict:
    """FsProbe.stats(): disk totals for the data path (or cwd)."""
    import shutil
    try:
        usage = shutil.disk_usage(path or ".")
        return {"total_in_bytes": usage.total, "free_in_bytes": usage.free,
                "available_in_bytes": usage.free,
                "used_in_bytes": usage.used}
    except OSError:
        return {"total_in_bytes": -1, "free_in_bytes": -1,
                "available_in_bytes": -1, "used_in_bytes": -1}


def process_probe() -> dict:
    """ProcessProbe.processStats(): open fds, max fds, process CPU."""
    pid = os.getpid()
    try:
        open_fds = len(os.listdir(f"/proc/{pid}/fd"))
    except OSError:
        open_fds = -1
    max_fds = -1
    try:
        import resource
        max_fds = resource.getrlimit(resource.RLIMIT_NOFILE)[0]
    except (ImportError, OSError, ValueError):
        pass
    cpu_ms = -1
    try:
        t = os.times()
        cpu_ms = int((t.user + t.system) * 1000)
    except OSError:
        pass
    return {
        "timestamp": int(time.time() * 1000),
        "id": pid,
        "open_file_descriptors": open_fds,
        "max_file_descriptors": max_fds,
        "cpu": {"total_in_millis": cpu_ms},
        "uptime_in_millis": int((time.time() - _START) * 1000),
    }
