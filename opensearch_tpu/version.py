"""Version constants (reference: server/src/main/java/org/opensearch/Version.java:101)."""

__version__ = "0.1.0"

# Wire/index compatibility version, bumped when the segment format changes.
SEGMENT_FORMAT_VERSION = 1
CLUSTER_STATE_VERSION = 1
