"""opensearch_tpu — a TPU-native distributed search & analytics engine.

A from-scratch re-design of the capabilities of OpenSearch (reference:
anasalkouz/OpenSearch, surveyed in /root/repo/SURVEY.md) for TPU hardware:

- Immutable columnar segments resident in HBM (blocked postings, dense
  doc-value columns, quantized norms) replace Lucene's file formats
  (reference: server/src/main/java/org/opensearch/index/engine/Engine.java).
- The query hot path — BM25 scoring over postings, boolean combination,
  aggregation bucket collection, k-NN distance — runs as jitted JAX/XLA
  (and Pallas) kernels instead of Lucene's BulkScorer loop
  (reference: search/internal/ContextIndexSearcher.java:260).
- Shard scatter-gather and the aggregation partial reduce become a
  `shard_map` over a `jax.sharding.Mesh` with ICI collectives
  (reference: action/search/SearchPhaseController.java:453).
- The control plane (mapping, routing, cluster state, translog, REST API)
  stays host-side Python, mirroring OpenSearch's layering
  (reference: server/src/main/java/org/opensearch/node/Node.java:372).
"""

from opensearch_tpu.version import __version__  # noqa: F401
