"""Task management: registration, listing, cancellation.

Re-design of tasks/TaskManager.java + CancellableTask + the list/cancel
APIs (action/admin/cluster/node/tasks). Every REST action that can run
long registers a task; cancellable tasks expose a flag the execution path
checks at safe points — for device programs that means BETWEEN per-segment
launches (the reference's CancellableBulkScorer checks between scored
blocks; XLA programs are not interruptible mid-launch either, so the
boundary is the same). Cancellation of a parent propagates to children
(TaskCancellationService ban propagation, single-process form).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from opensearch_tpu.common.errors import OpenSearchTpuError, TaskCancelledError


class Task:
    __slots__ = ("task_id", "action", "description", "start_time_ms",
                 "cancellable", "cancelled", "reason", "parent_task_id",
                 "start_nanos")

    def __init__(self, task_id: int, action: str, description: str = "",
                 cancellable: bool = False,
                 parent_task_id: Optional[int] = None):
        self.task_id = task_id
        self.action = action
        self.description = description
        # wall-clock start for display; perf_counter_ns start for the
        # running-time accounting (Task.java keeps the same split:
        # startTime vs startTimeNanos)
        self.start_time_ms = int(time.time() * 1000)
        self.start_nanos = time.perf_counter_ns()
        self.cancellable = cancellable
        self.cancelled = False
        self.reason: Optional[str] = None
        self.parent_task_id = parent_task_id

    def check_cancelled(self):
        """Call at safe points; raises if the task was cancelled
        (CancellableTask.ensureNotCancelled)."""
        if self.cancelled:
            raise TaskCancelledError(
                f"task cancelled [{self.reason or 'by user request'}]")

    def running_time_in_nanos(self) -> int:
        return time.perf_counter_ns() - self.start_nanos

    def to_dict(self, node_id: str = "_local") -> dict:
        return {
            "node": node_id,
            "id": self.task_id,
            "type": "transport",
            "action": self.action,
            "description": self.description,
            "start_time_in_millis": self.start_time_ms,
            "running_time_in_nanos": self.running_time_in_nanos(),
            "cancellable": self.cancellable,
            "cancelled": self.cancelled,
            **({"parent_task_id": f"_local:{self.parent_task_id}"}
               if self.parent_task_id is not None else {}),
        }


class TaskManager:
    def __init__(self):
        self._lock = threading.Lock()
        self._counter = 0
        self.tasks: Dict[int, Task] = {}

    def register(self, action: str, description: str = "",
                 cancellable: bool = False,
                 parent_task_id: Optional[int] = None) -> Task:
        with self._lock:
            self._counter += 1
            task = Task(self._counter, action, description, cancellable,
                        parent_task_id)
            self.tasks[task.task_id] = task
            return task

    def unregister(self, task: Task):
        with self._lock:
            self.tasks.pop(task.task_id, None)

    def list_tasks(self, actions: Optional[str] = None) -> List[Task]:
        with self._lock:
            tasks = list(self.tasks.values())
        if actions:
            import fnmatch
            patterns = actions.split(",")
            tasks = [t for t in tasks
                     if any(fnmatch.fnmatchcase(t.action, p)
                            for p in patterns)]
        return tasks

    def cancel(self, task_id: int, reason: str = "by user request") -> bool:
        """Cancel a task and all its descendants (ban propagation)."""
        with self._lock:
            task = self.tasks.get(task_id)
            if task is None or not task.cancellable:
                return False
            to_cancel = [task]
            # descendants
            frontier = {task_id}
            while frontier:
                children = [t for t in self.tasks.values()
                            if t.parent_task_id in frontier
                            and not t.cancelled]
                frontier = {t.task_id for t in children}
                to_cancel.extend(children)
            for t in to_cancel:
                t.cancelled = True
                t.reason = reason
            return True


class TaskContext:
    """`with task_manager.task(...)` helper for REST handlers."""

    def __init__(self, manager: TaskManager, action: str, description: str,
                 cancellable: bool):
        self.manager = manager
        self.task = manager.register(action, description, cancellable)

    def __enter__(self) -> Task:
        return self.task

    def __exit__(self, *exc):
        self.manager.unregister(self.task)
        return False
