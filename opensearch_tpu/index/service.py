"""IndexService: one index = N shards + mapper + settings; document-level API.

Re-design of the reference IndexService (index/IndexService.java:133) plus the
document-action layer that sits above it: murmur3 doc→shard routing
(cluster/routing/OperationRouting.java:412), the update API's
get-merge-reindex loop (action/update/UpdateHelper.java), _bulk grouping by
shard (action/bulk/TransportBulkAction.java:484), and multi-shard search via
the coordinator reduce (search/controller.py).
"""

from __future__ import annotations

import json
import logging
import secrets
import time
import uuid
from typing import Any, Dict, List, Optional

from opensearch_tpu.cluster.routing import generate_shard_id
from opensearch_tpu.common.errors import (
    DocumentMissingError, IllegalArgumentError, OpenSearchTpuError,
    VersionConflictError)
from opensearch_tpu.analysis import AnalysisRegistry
from opensearch_tpu.index.mapper import MapperService
from opensearch_tpu.index.shard import IndexShard


def _auto_id() -> str:
    """Auto-generated doc id (reference: time-based UUID, 20 url-safe chars)."""
    return secrets.token_urlsafe(15)


# ------------------------------------------------------- indexing slow log

# child logger under the reference's name shape (IndexingSlowLog.java:
# "index.indexing.slowlog.index") so existing capture config keeps
# working — the search slow log's sibling (rest/actions.py)
_INDEXING_SLOW_LOGGER = logging.getLogger(
    "opensearch_tpu.index.indexing.slowlog.index")

# most severe first: the first threshold the op time clears wins
_INDEXING_SLOW_LEVELS = (("warn", logging.WARNING),
                         ("info", logging.INFO),
                         ("debug", logging.DEBUG), ("trace", 5))

_SLOWLOG_THRESHOLD_KEYS = tuple(
    f"indexing.slowlog.threshold.index.{level}"
    for level, _ in _INDEXING_SLOW_LEVELS)


def _slow_log_source(settings: dict, source: dict) -> str:
    """Render the source line per reference semantics
    (IndexingSlowLogMessage): `index.indexing.slowlog.source` is the max
    characters to include (default 1000), `false`/`0` omits the source
    entirely, `true` logs it whole."""
    raw = settings.get("indexing.slowlog.source", 1000)
    if isinstance(raw, str):
        low = raw.strip().lower()
        if low == "true":
            limit = -1
        elif low == "false":
            limit = 0
        else:
            try:
                limit = int(low)
            except ValueError:
                limit = 1000      # unparseable: reference default
    elif raw is True:
        limit = -1
    elif raw is False:
        limit = 0
    else:
        try:
            limit = int(raw)
        except (TypeError, ValueError):
            limit = 1000          # null/odd types: a bad SOURCE
            # setting must degrade like a bad threshold does, never
            # 500 the write that tripped the slow log
    if limit == 0:
        return ""
    try:
        text = json.dumps(source, default=str)
    except (TypeError, ValueError):
        text = str(source)
    if limit > 0 and len(text) > limit:
        # reference Strings.cleanTruncate semantics: hard cut at the
        # character budget (surrogate safety is a non-issue here)
        text = text[:limit]
    return text


def _maybe_indexing_slow_log(settings: dict, index_name: str,
                             doc_id: Optional[str], source: dict,
                             took_ms: float) -> None:
    """Per-index indexing slow log (reference IndexingSlowLog.java):
    `index.indexing.slowlog.threshold.index.{warn,info,debug,trace}`
    each log at the matching level on the shared child logger; `-1` (any
    negative) disables a threshold; the most severe matching level wins.
    Covers index/create ops (the reference hook, IndexingOperationListener
    postIndex) — the paths IndexService.index_doc serves."""
    from opensearch_tpu.common.errors import SettingsError
    from opensearch_tpu.common.settings import parse_time_value
    for level, py_level in _INDEXING_SLOW_LEVELS:
        threshold = settings.get(
            f"indexing.slowlog.threshold.index.{level}")
        if threshold is None:
            continue
        try:
            threshold_s = parse_time_value(threshold, "slowlog")
        except (SettingsError, TypeError, ValueError):
            continue              # unparseable threshold never logs
        if threshold_s < 0 or took_ms < threshold_s * 1000:
            continue
        _INDEXING_SLOW_LOGGER.log(
            py_level,
            "[%s] took[%.1fms], took_millis[%d], id[%s], source[%s]",
            index_name, took_ms, int(took_ms), doc_id,
            _slow_log_source(settings, source))
        break                     # most severe matching level only


def deep_merge(base: dict, patch: dict) -> dict:
    """Recursive map merge used by partial-doc updates (UpdateHelper)."""
    out = dict(base)
    for k, v in patch.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = deep_merge(out[k], v)
        else:
            out[k] = v
    return out


class IndexService:
    def __init__(self, index_name: str, mapping: Optional[dict] = None,
                 settings: Optional[dict] = None,
                 data_path: Optional[str] = None,
                 script_service=None):
        settings = settings or {}
        self.index_name = index_name
        self.settings = settings
        # index UUID (IndexMetadata.SETTING_INDEX_UUID): identifies this
        # *incarnation* of the index — snapshot blob paths key on it so a
        # delete+recreate under the same name can never alias stale blobs
        self.uuid = settings.get("uuid") or uuid.uuid4().hex[:22]
        settings.setdefault("uuid", self.uuid)
        self._script_service = script_service
        # index open/close lifecycle (MetadataIndexStateService analog):
        # a closed index keeps its data and metadata but rejects every
        # data-plane operation until reopened
        self.closed = bool(settings.get("closed", False))
        self.num_shards = int(settings.get("number_of_shards", 1))
        self.num_replicas = int(settings.get("number_of_replicas", 0))
        self.routing_partition_size = int(
            settings.get("routing_partition_size", 1))
        self.routing_num_shards = int(
            settings.get("number_of_routing_shards", self.num_shards))
        if self.num_shards < 1:
            raise IllegalArgumentError("number_of_shards must be >= 1")
        # reference (IndexMetadata.java:784): routingNumShards must be a
        # positive multiple of numberOfShards or routing goes out of range
        if (self.routing_num_shards < self.num_shards
                or self.routing_num_shards % self.num_shards != 0):
            raise IllegalArgumentError(
                f"number_of_routing_shards [{self.routing_num_shards}] must "
                f"be a multiple of number_of_shards [{self.num_shards}]")
        if self.routing_partition_size < 1 or (
                self.routing_partition_size > 1
                and self.routing_partition_size >= self.num_shards):
            raise IllegalArgumentError(
                f"routing_partition_size [{self.routing_partition_size}] "
                f"should be a positive number less than number_of_shards "
                f"[{self.num_shards}]")
        # un-flatten index.analysis.* settings back into the nested config
        # AnalysisRegistry consumes (custom analyzers/tokenizers/filters,
        # incl. plugin-registered ones — AnalysisModule analog)
        analysis_cfg: dict = {}
        for k, v in settings.items():
            if k.startswith("analysis."):
                parts = k.split(".")[1:]
                d = analysis_cfg
                for p in parts[:-1]:
                    d = d.setdefault(p, {})
                d[parts[-1]] = v
        registry = AnalysisRegistry(analysis_cfg) if analysis_cfg else None
        self.mapper = MapperService(mapping, analysis_registry=registry)
        durability = settings.get("translog.durability", "request")
        self.shards: List[IndexShard] = [
            IndexShard(i, self.mapper, index_name=index_name,
                       data_path=data_path, durability=durability)
            for i in range(self.num_shards)
        ]
        self.creation_date = int(time.time() * 1000)
        window = int(self.settings.get("max_result_window", 10000))
        for shard in self.shards:
            shard.executor.max_result_window = window
        # ingest-concurrent serving knobs (ISSUE 16), all OFF by
        # default: bounded merge windows ("index.merge.windowed" +
        # "index.merge.window_budget_ms") and segment-keyed memo carry
        # ("index.search.memo_carry"). Strict boolean parse — a typo'd
        # value fails index creation, never silently stays off.
        from opensearch_tpu.common.settings import _parse_bool
        raw_windowed = settings.get("merge.windowed")
        raw_budget = settings.get("merge.window_budget_ms")
        raw_carry = settings.get("search.memo_carry")
        for shard in self.shards:
            if raw_windowed is not None:
                shard.engine.merge_windowed = _parse_bool(
                    raw_windowed, "index.merge.windowed")
            if raw_budget is not None:
                shard.engine.merge_window_budget_ms = float(raw_budget)
            if raw_carry is not None:
                shard.reader.memo_carry = _parse_bool(
                    raw_carry, "index.search.memo_carry")

    # --------------------------------------------------------------- routing

    def check_open(self):
        """Data-plane gate for closed indices (IndexClosedException)."""
        if self.closed:
            from opensearch_tpu.common.errors import IndexClosedError
            raise IndexClosedError(self.index_name)

    def shard_for(self, doc_id: str, routing: Optional[str] = None) -> IndexShard:
        sid = generate_shard_id(
            doc_id, self.num_shards, routing=routing,
            routing_num_shards=self.routing_num_shards,
            routing_partition_size=self.routing_partition_size)
        return self.shards[sid]

    # ------------------------------------------------------------- doc CRUD

    def _indexing_slowlog_armed(self) -> bool:
        """One threshold configured = time every op; none = zero-cost
        fast path (no clock reads on the write path)."""
        s = self.settings
        return any(s.get(k) is not None for k in _SLOWLOG_THRESHOLD_KEYS)

    def index_doc(self, doc_id: Optional[str], source: dict,
                  routing: Optional[str] = None, op_type: str = "index",
                  **kw) -> dict:
        self.check_open()
        if doc_id is None:
            doc_id = _auto_id()
            op_type = "create"
        shard = self.shard_for(doc_id, routing)
        if not self._indexing_slowlog_armed():
            res = shard.index_doc(doc_id, source, op_type=op_type, **kw)
        else:
            t0 = time.monotonic()
            res = shard.index_doc(doc_id, source, op_type=op_type, **kw)
            _maybe_indexing_slow_log(
                self.settings, self.index_name, doc_id, source,
                (time.monotonic() - t0) * 1000)
        return self._write_response(res, shard,
                                    "created" if res.created else "updated")

    def get_doc(self, doc_id: str, routing: Optional[str] = None,
                realtime: bool = True) -> dict:
        self.check_open()
        shard = self.shard_for(doc_id, routing)
        res = shard.get_doc(doc_id, realtime=realtime)
        if res is None:
            return {"_index": self.index_name, "_id": doc_id, "found": False}
        return {"_index": self.index_name, "_id": doc_id, "found": True,
                "_version": res.version, "_seq_no": res.seq_no,
                "_primary_term": res.primary_term, "_source": res.source}

    def delete_doc(self, doc_id: str, routing: Optional[str] = None,
                   **kw) -> dict:
        self.check_open()
        shard = self.shard_for(doc_id, routing)
        res = shard.delete_doc(doc_id, **kw)
        return self._write_response(res, shard,
                                    "deleted" if res.found else "not_found")

    def update_doc(self, doc_id: str, body: dict,
                   routing: Optional[str] = None,
                   if_seq_no: Optional[int] = None,
                   if_primary_term: Optional[int] = None,
                   external_version: Optional[int] = None) -> dict:
        """Partial update: realtime GET → merge → reindex with seq-no CAS
        (UpdateHelper semantics: detect_noop default true, upsert,
        doc_as_upsert, retry left to the caller). A caller-supplied
        if_seq_no/if_primary_term CAS is checked against the current doc."""
        self.check_open()
        if external_version is not None:
            # reference: UpdateRequest.validate rejects external versioning
            raise IllegalArgumentError(
                "internal versioning can not be used for optimistic "
                "concurrency control. Please use `if_seq_no` and "
                "`if_primary_term` instead")
        _KNOWN = {"doc", "doc_as_upsert", "script", "upsert",
                  "scripted_upsert", "detect_noop", "_source", "lang",
                  "if_seq_no", "if_primary_term", "fields"}
        for key in body:
            if key not in _KNOWN:
                import difflib
                guess = difflib.get_close_matches(key, sorted(_KNOWN), n=1)
                hint = f" did you mean [{guess[0]}]?" if guess else ""
                raise IllegalArgumentError(
                    f"[UpdateRequest] unknown field [{key}]{hint}")
        # CAS values may arrive in the body instead of URL params
        # (UpdateRequest.fromXContent parses both)
        if if_seq_no is None and body.get("if_seq_no") is not None:
            if_seq_no = int(body["if_seq_no"])
        if if_primary_term is None and body.get("if_primary_term") is not None:
            if_primary_term = int(body["if_primary_term"])
        shard = self.shard_for(doc_id, routing)
        cur = shard.get_doc(doc_id)
        # CAS applies to scripted updates too — check BEFORE dispatching
        # to the script path or a stale writer wins a lost update
        if if_seq_no is not None or if_primary_term is not None:
            if cur is None:
                # a CAS against a missing doc is a 404, not a conflict
                # (UpdateHelper prepare: DocumentMissingException wins)
                raise DocumentMissingError(
                    f"[{doc_id}]: document missing")
            if ((if_seq_no is not None and cur.seq_no != if_seq_no)
                    or (if_primary_term is not None
                        and cur.primary_term != if_primary_term)):
                raise VersionConflictError(
                    f"[{doc_id}]: version conflict, required seqNo "
                    f"[{if_seq_no}], primary term [{if_primary_term}]. "
                    f"current document has seqNo [{cur.seq_no}] and primary "
                    f"term [{cur.primary_term}]")
        if "script" in body:
            return self._update_with_script(shard, doc_id, body, cur)
        doc_patch = body.get("doc")
        if cur is None:
            if body.get("doc_as_upsert") and doc_patch is not None:
                new_source = doc_patch
            elif "upsert" in body:
                new_source = body["upsert"]
            else:
                raise DocumentMissingError(
                    f"[{doc_id}]: document missing")
            res = shard.index_doc(doc_id, new_source, op_type="create")
            return self._write_response(res, shard, "created")
        if doc_patch is None:
            raise IllegalArgumentError("update requires [doc] or [upsert]")
        merged = deep_merge(cur.source, doc_patch)
        if body.get("detect_noop", True) and merged == cur.source:
            return {"_index": self.index_name, "_id": doc_id,
                    "_version": cur.version, "result": "noop",
                    "_seq_no": cur.seq_no, "_primary_term": cur.primary_term,
                    "_shards": {"total": 0, "successful": 0, "failed": 0}}
        res = shard.index_doc(doc_id, merged, if_seq_no=cur.seq_no,
                              if_primary_term=cur.primary_term)
        return self._write_response(res, shard, "updated")

    def _update_with_script(self, shard, doc_id: str, body: dict, cur) -> dict:
        """Scripted update (reference: UpdateHelper.prepareUpdateScriptRequest
        — ctx._source mutation, ctx.op = index|delete|none)."""
        if self._script_service is None:
            from opensearch_tpu.script.service import ScriptService
            self._script_service = ScriptService()
        script = self._script_service.compile(body["script"], "update")
        if cur is None:
            if "upsert" in body:
                if body.get("scripted_upsert", False):
                    ctx = {"_source": dict(body["upsert"]), "op": "create",
                           "_index": self.index_name, "_id": doc_id}
                    script.execute(ctx)
                    if ctx.get("op") in ("none", "noop"):
                        return {"_index": self.index_name, "_id": doc_id,
                                "result": "noop",
                                "_shards": {"total": 0, "successful": 0,
                                            "failed": 0}}
                    new_source = ctx["_source"]
                else:
                    new_source = body["upsert"]
                res = shard.index_doc(doc_id, new_source, op_type="create")
                return self._write_response(res, shard, "created")
            raise DocumentMissingError(f"[{doc_id}]: document missing")
        ctx = {"_source": dict(cur.source), "op": "index",
               "_index": self.index_name, "_id": doc_id,
               "_version": cur.version, "_now": int(time.time() * 1000)}
        script.execute(ctx)
        op = ctx.get("op", "index")
        if op in ("none", "noop"):
            return {"_index": self.index_name, "_id": doc_id,
                    "_version": cur.version, "result": "noop",
                    "_seq_no": cur.seq_no, "_primary_term": cur.primary_term,
                    "_shards": {"total": 0, "successful": 0, "failed": 0}}
        if op == "delete":
            res = shard.delete_doc(doc_id)
            return self._write_response(res, shard, "deleted")
        if op != "index" and op != "create":
            raise IllegalArgumentError(
                f"Operation type [{op}] not allowed, only [noop, index, "
                f"delete] are allowed")
        res = shard.index_doc(doc_id, ctx["_source"], if_seq_no=cur.seq_no,
                              if_primary_term=cur.primary_term)
        return self._write_response(res, shard, "updated")

    def mget(self, ids: List[Any]) -> dict:
        self.check_open()
        docs = []
        for item in ids:
            if isinstance(item, dict):
                docs.append(self.get_doc(item["_id"],
                                         routing=item.get("routing")))
            else:
                docs.append(self.get_doc(item))
        return {"docs": docs}

    def _write_response(self, res, shard: IndexShard, result: str) -> dict:
        return {
            "_index": self.index_name,
            "_id": res.doc_id,
            "_version": res.version,
            "result": result,
            "_shards": {"total": 1 + self.num_replicas,
                        "successful": 1, "failed": 0},
            "_seq_no": res.seq_no,
            "_primary_term": res.primary_term,
        }

    # ------------------------------------------------------------------ bulk

    def bulk(self, operations: List[dict]) -> dict:
        """Execute parsed bulk items: [{action, id, source, routing, ...}].
        Items are routed per doc and executed in order per shard
        (TransportShardBulkAction.performOnPrimary runs items serially)."""
        self.check_open()
        start = time.monotonic()
        items = []
        errors = False
        for op in operations:
            action = op["action"]
            cas = {k: op[k] for k in ("if_seq_no", "if_primary_term")
                   if op.get(k) is not None}
            try:
                if action in ("index", "create"):
                    resp = self.index_doc(op.get("id"), op["source"],
                                          routing=op.get("routing"),
                                          op_type=("create"
                                                   if action == "create"
                                                   else "index"), **cas)
                    status = 201 if resp["result"] == "created" else 200
                elif action == "delete":
                    resp = self.delete_doc(op["id"], routing=op.get("routing"),
                                           **cas)
                    status = 200 if resp["result"] == "deleted" else 404
                elif action == "update":
                    resp = self.update_doc(op["id"], op["source"],
                                           routing=op.get("routing"), **cas)
                    status = 200
                else:
                    raise IllegalArgumentError(
                        f"unknown bulk action [{action}]")
                resp["status"] = status
                items.append({action: resp})
            except OpenSearchTpuError as e:
                errors = True
                items.append({action: {
                    "_index": self.index_name, "_id": op.get("id"),
                    "status": e.status,
                    "error": e.to_xcontent(),
                }})
        return {"took": int((time.monotonic() - start) * 1000),
                "errors": errors, "items": items}

    # ---------------------------------------------------------------- search

    def search(self, body: Optional[dict] = None) -> dict:
        self.check_open()
        from opensearch_tpu.search.controller import execute_search
        return execute_search([s.executor for s in self.shards], body,
                              allow_envelope=True)

    def multi_search(self, bodies: List[dict], task=None,
                     deadline=None) -> dict:
        self.check_open()
        if self.num_shards == 1:
            return self.shards[0].executor.multi_search(
                bodies, task=task, deadline=deadline)
        # multi-shard fallback keeps the same per-item failure contract
        # as the batched envelope: one malformed body renders an error
        # item, siblings execute (TransportMultiSearchAction semantics).
        # Cancellation kills the envelope at item boundaries; a passed
        # deadline renders the unlaunched tail as timed-out partials.
        import time as _time
        from opensearch_tpu.search.executor import (
            _item_error, _item_error_untyped, _timed_out_item)
        start = _time.monotonic()
        responses = []
        for b in bodies:
            if task is not None:
                task.check_cancelled()
            if deadline is not None and _time.monotonic() > deadline:
                responses.append(_timed_out_item(start))
                continue
            try:
                responses.append(self.search(b))
            except OpenSearchTpuError as e:
                responses.append(_item_error(e))
            except Exception as e:
                responses.append(_item_error_untyped(e))
        return {"took": 0, "responses": responses}

    def count(self, body: Optional[dict] = None) -> int:
        self.check_open()
        body = dict(body or {})
        body["size"] = 0
        body.pop("from", None)
        return self.search(body)["hits"]["total"]["value"]

    # ------------------------------------------------------------- lifecycle

    def refresh(self):
        self.check_open()
        for s in self.shards:
            s.refresh()

    def flush(self):
        self.check_open()
        for s in self.shards:
            s.flush()

    def force_merge(self):
        self.check_open()
        for s in self.shards:
            s.force_merge()

    def close(self):
        for s in self.shards:
            s.close()

    def stats(self) -> dict:
        shard_stats = [s.stats() for s in self.shards]
        return {
            "index": self.index_name,
            "docs": {"count": sum(s["docs"]["count"] for s in shard_stats),
                     "deleted": sum(s["docs"]["deleted"]
                                    for s in shard_stats)},
            "segments": {"count": sum(s["segments"]["count"]
                                      for s in shard_stats)},
            "shards": shard_stats,
        }

    def mapping_dict(self) -> dict:
        return self.mapper.mapping_dict()

    def put_mapping(self, mapping: dict):
        self.mapper.merge(mapping)
