"""Per-shard durable write-ahead log.

TPU-native re-design of the reference translog (index/translog/Translog.java:115;
`add()` at :540): every accepted operation is appended to the current
generation file before it is acknowledged; the fsync policy is configurable
(`request` = fsync per op batch, `async` = fsync on interval/explicit sync,
matching `index.translog.durability`). Generations roll on flush
(`rollGeneration`), old generations are trimmed once their ops are safely in a
commit point. On engine reopen the translog is replayed above the commit
point's persisted local checkpoint (reference recovery path:
index/engine/InternalEngine.java recoverFromTranslog).

Frame format per op (binary, little-endian):
    u32 length | u32 crc32(payload) | payload (JSON utf-8)
A torn tail (partial frame / checksum mismatch) is truncated on open, the
reference's behavior for a crash mid-write.
"""

from __future__ import annotations

import json
import os
import struct
import zlib
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional

_HEADER = struct.Struct("<II")
CHECKPOINT_FILE = "translog.ckp"


@dataclass
class TranslogOp:
    """One logged operation: index / delete / no-op (reference Translog.Operation)."""
    op_type: str              # "index" | "delete" | "noop"
    seq_no: int
    primary_term: int
    doc_id: Optional[str] = None
    source: Optional[dict] = None
    version: int = 1
    reason: Optional[str] = None   # for no-ops

    def to_payload(self) -> bytes:
        return json.dumps({
            "op": self.op_type, "seq_no": self.seq_no,
            "primary_term": self.primary_term, "id": self.doc_id,
            "source": self.source, "version": self.version,
            "reason": self.reason,
        }, separators=(",", ":")).encode("utf-8")

    @staticmethod
    def from_payload(raw: bytes) -> "TranslogOp":
        d = json.loads(raw.decode("utf-8"))
        return TranslogOp(op_type=d["op"], seq_no=d["seq_no"],
                          primary_term=d["primary_term"], doc_id=d.get("id"),
                          source=d.get("source"), version=d.get("version", 1),
                          reason=d.get("reason"))


def _gen_path(directory: str, gen: int) -> str:
    return os.path.join(directory, f"translog-{gen}.tlog")


def _read_gen_file(path: str, truncate_torn: bool = True) -> List[TranslogOp]:
    ops: List[TranslogOp] = []
    with open(path, "rb") as f:
        data = f.read()
    pos = 0
    good_end = 0
    while pos + _HEADER.size <= len(data):
        length, crc = _HEADER.unpack_from(data, pos)
        start = pos + _HEADER.size
        end = start + length
        if end > len(data):
            break  # torn tail
        payload = data[start:end]
        if zlib.crc32(payload) != crc:
            break  # corrupt tail — stop, keep prefix
        ops.append(TranslogOp.from_payload(payload))
        pos = end
        good_end = end
    if truncate_torn and good_end < len(data):
        with open(path, "r+b") as f:
            f.truncate(good_end)
    return ops


class Translog:
    """Generational WAL for one shard."""

    def __init__(self, directory: str, durability: str = "request"):
        self.directory = directory
        self.durability = durability  # "request" | "async"
        os.makedirs(directory, exist_ok=True)
        self._ckp_path = os.path.join(directory, CHECKPOINT_FILE)
        ckp = self._read_checkpoint()
        self.current_gen: int = ckp.get("gen", 1)
        self.min_retained_gen: int = ckp.get("min_gen", self.current_gen)
        # retained ops per generation (loaded lazily for replay)
        self._fh = open(_gen_path(directory, self.current_gen), "ab")
        self._unsynced = 0
        self._op_count: Optional[int] = None  # lazy cache for stats

    # ------------------------------------------------------------ checkpoint

    def _read_checkpoint(self) -> dict:
        if os.path.exists(self._ckp_path):
            with open(self._ckp_path, "r", encoding="utf-8") as f:
                return json.load(f)
        return {}

    def _write_checkpoint(self):
        tmp = self._ckp_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"gen": self.current_gen,
                       "min_gen": self.min_retained_gen}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._ckp_path)

    # ------------------------------------------------------------ write path

    def add(self, op: TranslogOp):
        """Append one op to the current generation (Translog.java:540)."""
        payload = op.to_payload()
        frame = _HEADER.pack(len(payload), zlib.crc32(payload)) + payload
        self._fh.write(frame)
        self._unsynced += 1
        if self._op_count is not None:
            self._op_count += 1
        if self.durability == "request":
            self.sync()

    def sync(self):
        if self._unsynced:
            self._fh.flush()
            os.fsync(self._fh.fileno())
            self._unsynced = 0

    def roll_generation(self) -> int:
        """Seal the current generation and start a new one (flush path)."""
        self.sync()
        self._fh.close()
        self.current_gen += 1
        self._fh = open(_gen_path(self.directory, self.current_gen), "ab")
        self._write_checkpoint()
        return self.current_gen

    def trim_unreferenced(self, keep_from_gen: int):
        """Delete generations below `keep_from_gen` whose ops are committed."""
        for gen in range(self.min_retained_gen, keep_from_gen):
            path = _gen_path(self.directory, gen)
            if os.path.exists(path):
                os.remove(path)
        self.min_retained_gen = max(self.min_retained_gen, keep_from_gen)
        self._op_count = None
        self._write_checkpoint()

    def trim_below_seqno(self, min_retained_seq_no: int, max_gen: int):
        """Drop whole generations whose every op is below the retention floor
        (retention leases / global checkpoint), never past `max_gen`."""
        keep_from = self.min_retained_gen
        for gen in range(self.min_retained_gen, max_gen):
            path = _gen_path(self.directory, gen)
            if os.path.exists(path):
                ops = _read_gen_file(path, truncate_torn=False)
                if any(op.seq_no >= min_retained_seq_no for op in ops):
                    break
            keep_from = gen + 1
        self.trim_unreferenced(keep_from)

    # ------------------------------------------------------------- read path

    def read_ops(self, from_seq_no: int = 0) -> List[TranslogOp]:
        """All retained ops with seq_no >= from_seq_no, generation order.

        Used for (a) engine reopen replay, (b) peer-recovery phase2 op
        shipping (RecoverySourceHandler phase2 analog).
        """
        self.sync()
        out: List[TranslogOp] = []
        for gen in range(self.min_retained_gen, self.current_gen + 1):
            path = _gen_path(self.directory, gen)
            if not os.path.exists(path):
                continue
            for op in _read_gen_file(path, truncate_torn=(gen == self.current_gen)):
                if op.seq_no >= from_seq_no:
                    out.append(op)
        return out

    def total_operations(self) -> int:
        if self._op_count is None:
            self._op_count = len(self.read_ops())
        return self._op_count

    def close(self):
        self.sync()
        self._fh.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
