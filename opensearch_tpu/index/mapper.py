"""Schema layer: mappings, field types, and JSON document parsing.

Re-designs the reference's mapper package (server/src/main/java/org/opensearch/
index/mapper/MapperService.java, DocumentParser.java, the ~30 FieldMapper
types) for a columnar TPU segment model:

- text fields    → analyzed terms feeding blocked postings (+ field length for norms)
- keyword fields → exact values feeding both postings (term queries) and an
                   ordinal doc-value column (terms aggs, sorting)
- numeric/date/boolean/ip → dense f64/i64 doc-value columns; range/term queries
                   compile to vectorized compares on the column, not postings
- dense/knn vectors → [dims] f32 rows in a matrix column
- metadata fields _id/_source/_routing/_seq_no/_version handled explicitly
  (reference: index/mapper/SourceFieldMapper.java, SeqNoFieldMapper.java)

Dynamic mapping inference mirrors the reference's DocumentParser defaults:
JSON string → text + `.keyword` subfield, integer → long, float → float,
bool → boolean, object → dotted subfields, array → per-element.
"""

from __future__ import annotations

import datetime as _dt
import functools
import ipaddress
import math
import re
from dataclasses import dataclass, field as dc_field
from typing import Any, Dict, List, Optional, Tuple

from opensearch_tpu.common.errors import IllegalArgumentError, MapperParsingError
from opensearch_tpu.analysis import AnalysisRegistry, get_default_registry

TEXT_TYPES = {"text", "match_only_text", "search_as_you_type"}
KEYWORD_TYPES = {"keyword", "constant_keyword", "wildcard",
                 # completion fields store their suggestions as exact values;
                 # the suggester walks the ordinal dictionary by prefix
                 "completion", "search_as_you_type"}
NUMERIC_TYPES = {"long", "integer", "short", "byte", "double", "float", "half_float",
                 "scaled_float", "unsigned_long",
                 # mapper-extras rank features are positive floats with doc
                 # values; scoring behavior lives in rank_feature queries
                 "rank_feature"}
DATE_TYPES = {"date", "date_nanos"}
VECTOR_TYPES = {"knn_vector", "dense_vector"}
# late-interaction multi-vector fields (ColBERT-style): one [tokens, dims]
# matrix per doc, scored by the fused MaxSim kernel (ops/maxsim.py)
RANK_VECTOR_TYPES = {"rank_vectors"}
RANK_VECTORS_COMPRESSION = ("none", "pq")
DEFAULT_MAX_TOKENS = 128
BOOL_TYPES = {"boolean"}
IP_TYPES = {"ip"}
RANGE_TYPES = {"integer_range", "long_range", "float_range", "double_range",
               "date_range", "ip_range"}
# range type -> element type of the hidden #lo / #hi bound columns
_RANGE_ELEM = {"integer_range": "integer", "long_range": "long",
               "float_range": "float", "double_range": "double",
               "date_range": "date", "ip_range": "ip"}
# inclusive-bound adjustment step for exclusive gt/lt on discrete elements
_RANGE_STEP = {"integer": 1.0, "long": 1.0, "date": 1.0, "ip": 1.0}
RANGE_UNBOUNDED = 1e308
GEO_TYPES = {"geo_point", "geo_shape"}

_INT_BOUNDS = {
    "byte": (-2 ** 7, 2 ** 7 - 1),
    "short": (-2 ** 15, 2 ** 15 - 1),
    "integer": (-2 ** 31, 2 ** 31 - 1),
    "long": (-2 ** 63, 2 ** 63 - 1),
    "unsigned_long": (0, 2 ** 64 - 1),
}


def parse_date_millis(value: Any, fmt: Optional[str] = None) -> int:
    """Parse a date into epoch milliseconds.

    Covers the reference's default `strict_date_optional_time||epoch_millis`
    (index/mapper/DateFieldMapper.java DEFAULT_DATE_TIME_FORMATTER).
    """
    if isinstance(value, bool):
        raise MapperParsingError(f"failed to parse date field [{value}]")
    if isinstance(value, (int, float)):
        n = int(value)
        return n * 1000 if fmt == "epoch_second" else n
    text = str(value).strip()
    if fmt in ("epoch_millis", "epoch_second") or re.fullmatch(r"-?\d{10,}", text):
        try:
            n = int(text)
            return n * 1000 if fmt == "epoch_second" else n
        except ValueError:
            pass
    # ISO-8601 family: yyyy, yyyy-MM, yyyy-MM-dd, with optional time and zone
    t = text.replace("Z", "+00:00")
    for pattern in (None, "%Y-%m", "%Y"):
        try:
            if pattern is None:
                dt = _dt.datetime.fromisoformat(t)
            else:
                dt = _dt.datetime.strptime(t, pattern)
            if dt.tzinfo is None:
                dt = dt.replace(tzinfo=_dt.timezone.utc)
            return int(dt.timestamp() * 1000)
        except ValueError:
            continue
    raise MapperParsingError(f"failed to parse date field [{value}] with format "
                             f"[{fmt or 'strict_date_optional_time||epoch_millis'}]")


@functools.lru_cache(maxsize=1 << 16)
def format_date_millis(millis: int) -> str:
    # memoized: histogram renders format the same bucket keys for every
    # query of a dashboard workload
    dt = _dt.datetime.fromtimestamp(millis / 1000.0, tz=_dt.timezone.utc)
    return dt.strftime("%Y-%m-%dT%H:%M:%S.") + f"{dt.microsecond // 1000:03d}Z"


def ip_to_long(value: str) -> int:
    """Encode an IP as a sortable integer (v4 mapped into v6 space)."""
    try:
        addr = ipaddress.ip_address(str(value))
    except ValueError as e:
        raise MapperParsingError(f"'{value}' is not an IP string literal.") from e
    if isinstance(addr, ipaddress.IPv4Address):
        addr = ipaddress.IPv6Address(b"\x00" * 10 + b"\xff\xff" + addr.packed)
    return int(addr)


@dataclass
class MappedFieldType:
    """Per-field schema record answering query/agg/fielddata questions.

    Reference: index/mapper/MappedFieldType.java.
    """
    name: str
    type: str
    analyzer: str = "standard"
    search_analyzer: Optional[str] = None
    index: bool = True
    doc_values: bool = True
    store: bool = False
    fmt: Optional[str] = None            # date format
    scaling_factor: float = 100.0        # scaled_float
    dims: int = 0                        # vectors
    similarity_space: str = "l2"         # vectors: l2 | cosinesimil | innerproduct
    knn_method: str = "exact"            # vectors: exact | ivf (HNSW → IVF on TPU)
    knn_nlist: int = 128                 # ivf: number of centroids
    knn_nprobe: int = 0                  # ivf: default probes (0 → nlist/8)
    max_tokens: int = 0                  # rank_vectors: per-doc token cap
    compression: str = "none"            # rank_vectors: none | pq
    pq_m: int = 0                        # rank_vectors pq: subspace count
    ignore_above: Optional[int] = None   # keyword
    null_value: Any = None
    boost: float = 1.0
    meta: dict = dc_field(default_factory=dict)

    @property
    def is_text(self):
        return self.type in TEXT_TYPES

    @property
    def is_keyword(self):
        return self.type in KEYWORD_TYPES

    @property
    def is_numeric(self):
        return self.type in NUMERIC_TYPES

    @property
    def is_date(self):
        return self.type in DATE_TYPES

    @property
    def is_bool(self):
        return self.type in BOOL_TYPES

    @property
    def is_range(self):
        return self.type in RANGE_TYPES

    @property
    def is_ip(self):
        return self.type in IP_TYPES

    @property
    def is_vector(self):
        return self.type in VECTOR_TYPES

    @property
    def is_rank_vectors(self):
        return self.type in RANK_VECTOR_TYPES

    @property
    def has_ordinals(self):
        """Fields whose doc values are ordinal-encoded strings."""
        return self.is_keyword or self.is_ip or self.is_bool

    def parse_numeric(self, value: Any) -> float:
        """Note: doc-value columns are float64, so integer fields keep exact
        values only up to 2**53 (a documented deviation from Lucene's int64
        doc values); bounds checks below are exact regardless."""
        if isinstance(value, bool):
            raise MapperParsingError(
                f"failed to parse field [{self.name}] of type [{self.type}]: "
                f"boolean value not allowed")
        if self.type in _INT_BOUNDS:
            if isinstance(value, int):
                n = value
            elif isinstance(value, str) and re.fullmatch(r"-?\d+", value.strip()):
                n = int(value.strip())
            else:
                try:
                    num = float(value)
                except (TypeError, ValueError) as e:
                    raise MapperParsingError(
                        f"failed to parse field [{self.name}] of type [{self.type}] "
                        f"value [{value}]") from e
                if math.isnan(num) or math.isinf(num):
                    raise MapperParsingError(
                        f"[{self.type}] supports only finite values, but got [{value}]")
                n = int(num)  # coerce: truncate decimals, matching coerce=true default
            lo, hi = _INT_BOUNDS[self.type]
            if not (lo <= n <= hi):
                raise MapperParsingError(
                    f"Value [{value}] is out of range for a {self.type}")
            return float(n)
        try:
            num = float(value)
        except (TypeError, ValueError) as e:
            raise MapperParsingError(
                f"failed to parse field [{self.name}] of type [{self.type}] "
                f"value [{value}]") from e
        if math.isnan(num) or math.isinf(num):
            raise MapperParsingError(f"[{self.type}] supports only finite values, "
                                     f"but got [{value}]")
        if self.type == "scaled_float":
            return float(round(num * self.scaling_factor)) / self.scaling_factor
        return num

    def to_comparable(self, value: Any) -> float:
        """Convert a user-supplied query value to the doc-value column domain."""
        if self.is_date:
            return float(parse_date_millis(value, self.fmt))
        if self.is_ip:
            return float(ip_to_long(value))
        if self.is_bool:
            return 1.0 if _parse_boolish(value) else 0.0
        return self.parse_numeric(value)


def _parse_boolish(value: Any) -> bool:
    if isinstance(value, bool):
        return value
    text = str(value).strip().lower()
    if text in ("true",):
        return True
    if text in ("false", ""):
        return False
    raise MapperParsingError(f"Failed to parse value [{value}] as only [true] or [false] "
                             f"are allowed.")


@dataclass
class ParsedField:
    """One field's contribution of a parsed document."""
    terms: Optional[List[Tuple[str, int]]] = None  # analyzed (term, position) for text
    length: int = 0                                 # token count for norms
    exact_values: Optional[List[str]] = None        # keyword-style exact terms
    numeric_values: Optional[List[float]] = None    # numeric/date/bool/ip doc values
    vector: Optional[List[float]] = None
    token_vectors: Optional[List[List[float]]] = None  # rank_vectors matrix


@dataclass
class ParsedDocument:
    """Reference: index/mapper/ParsedDocument.java. `children` carries one
    (nested path, fields) entry per nested object — each becomes its own
    row in the segment's doc block, children before the parent, exactly
    like Lucene's block-join document ordering."""
    doc_id: str
    source: dict
    routing: Optional[str]
    fields: Dict[str, ParsedField]
    children: List[Tuple[str, Dict[str, ParsedField]]] = \
        dc_field(default_factory=list)


DEFAULT_MAPPING_LIMIT = 1000  # index.mapping.total_fields.limit default


class MapperService:
    """Holds the mapping for one index; parses documents and merges mapping updates.

    Reference: index/mapper/MapperService.java:725-file. Mapping dict uses the
    REST shape: {"properties": {"f": {"type": "text", "fields": {...}}}}.
    """

    def __init__(self, mapping: Optional[dict] = None,
                 analysis_registry: Optional[AnalysisRegistry] = None,
                 dynamic: Any = True, total_fields_limit: int = DEFAULT_MAPPING_LIMIT):
        self.analysis = analysis_registry or get_default_registry()
        self.field_types: Dict[str, MappedFieldType] = {}
        # see expand_field_patterns below
        self._multi_children: Dict[str, List[str]] = {}  # parent → direct sub-fields
        # nested object paths (index/mapper/ObjectMapper nested=true): each
        # value under such a path becomes its own segment row (doc block)
        self.nested_paths: set = set()
        # parent-join (modules/parent-join JoinFieldMapper): one join field
        # per index; relations maps parent type -> [child types]
        self.join_field: Optional[str] = None
        self.join_relations: Dict[str, List[str]] = {}
        self.dynamic = dynamic
        self.total_fields_limit = total_fields_limit
        self._source_enabled = True
        # monotonically bumped per merge: keys compiled template skeletons
        # (search/compile.py compile_interned), whose captured field types
        # must not survive a mapping change
        self.version = 0
        if mapping:
            self.merge(mapping)

    # ------------------------------------------------------------- mapping
    def merge(self, mapping: dict):
        self.version += 1
        mapping = mapping.get("mappings", mapping)
        if "dynamic" in mapping:
            self.dynamic = mapping["dynamic"]
        src = mapping.get("_source")
        if isinstance(src, dict) and "enabled" in src:
            self._source_enabled = bool(src["enabled"])
        self._merge_properties("", mapping.get("properties", {}))

    def _merge_properties(self, prefix: str, properties: dict):
        for name, spec in properties.items():
            if not isinstance(spec, dict):
                raise MapperParsingError(f"Expected map for property [{prefix}{name}]")
            full = f"{prefix}{name}"
            sub_properties = spec.get("properties")
            if spec.get("type") == "nested":
                self.nested_paths.add(full)
                self._merge_properties(f"{full}.", sub_properties or {})
                continue
            if spec.get("type") == "join":
                # one join field per index (JoinFieldMapper); the relation
                # name indexes like a keyword, the parent id goes into a
                # hidden <field>#parent keyword column for the host join
                self.join_field = full
                for parent, kids in (spec.get("relations") or {}).items():
                    self.join_relations[parent] = (
                        kids if isinstance(kids, list) else [kids])
                self._put_field(full, {"type": "keyword"})
                self._put_field(f"{full}#parent", {"type": "keyword"})
                continue
            if sub_properties is not None or spec.get("type") == "object":
                self._merge_properties(f"{full}.", sub_properties or {})
                continue
            ftype = spec.get("type")
            if ftype is None:
                raise MapperParsingError(
                    f"No type specified for field [{full}]")
            self._put_field(full, spec)

    def _put_field(self, full_name: str, spec: dict):
        ftype = spec.get("type")
        known = (TEXT_TYPES | KEYWORD_TYPES | NUMERIC_TYPES | DATE_TYPES | VECTOR_TYPES
                 | RANK_VECTOR_TYPES
                 | BOOL_TYPES | IP_TYPES | GEO_TYPES | RANGE_TYPES
                 | {"object", "binary", "percolator"})
        if ftype not in known:
            raise MapperParsingError(
                f"No handler for type [{ftype}] declared on field [{full_name.split('.')[-1]}]")
        if ftype in RANGE_TYPES and not full_name.endswith(("#lo", "#hi")):
            # hidden inclusive-bound columns back every range field
            # (reference RangeFieldMapper encodes ranges in BinaryDocValues;
            # two numeric columns give the same query power on device)
            elem = _RANGE_ELEM[ftype]
            self._put_field(f"{full_name}#lo", {"type": elem, **({"format": spec["format"]} if "format" in spec else {})})
            self._put_field(f"{full_name}#hi", {"type": elem, **({"format": spec["format"]} if "format" in spec else {})})
        existing = self.field_types.get(full_name)
        if existing is not None and existing.type != ftype:
            raise IllegalArgumentError(
                f"mapper [{full_name}] cannot be changed from type [{existing.type}] "
                f"to [{ftype}]")
        if len(self.field_types) >= self.total_fields_limit and existing is None:
            raise IllegalArgumentError(
                f"Limit of total fields [{self.total_fields_limit}] has been exceeded")
        dims = 0
        if ftype in VECTOR_TYPES or ftype in RANK_VECTOR_TYPES:
            dims = int(spec.get("dimension", spec.get("dims", 0)))
            if dims <= 0:
                raise MapperParsingError(
                    f"dimension must be set for vector field [{full_name}]")
        max_tokens = 0
        compression = "none"
        pq_m = 0
        if ftype in RANK_VECTOR_TYPES:
            max_tokens = int(spec.get("max_tokens", DEFAULT_MAX_TOKENS))
            if max_tokens <= 0:
                raise MapperParsingError(
                    f"max_tokens must be a positive integer for "
                    f"rank_vectors field [{full_name}]")
            compression = str(spec.get("compression", "none"))
            if compression not in RANK_VECTORS_COMPRESSION:
                raise MapperParsingError(
                    f"compression must be one of "
                    f"{list(RANK_VECTORS_COMPRESSION)} for rank_vectors "
                    f"field [{full_name}], got [{compression}]")
            if compression == "pq":
                # subspace count: explicit `pq_m` or the widest divisor
                # giving 4-dim subvectors (falling back to scalar
                # subspaces for odd dims)
                pq_m = int(spec.get("pq_m",
                                    dims // 4 if dims % 4 == 0 else dims))
                if pq_m <= 0 or dims % pq_m != 0:
                    raise MapperParsingError(
                        f"pq_m [{pq_m}] must evenly divide dimension "
                        f"[{dims}] for rank_vectors field [{full_name}]")
        analyzer = spec.get("analyzer", "standard")
        if not self.analysis.has(analyzer):
            raise MapperParsingError(
                f"analyzer [{analyzer}] has not been configured in mappings")
        method_spec = spec.get("method", {}) or {}
        space = method_spec.get("space_type", spec.get("space_type", "l2"))
        # HNSW has no TPU-friendly equivalent (pointer-chasing graph walk);
        # map it to IVF, the dense ANN structure (BASELINE.md config 5)
        method_name = method_spec.get("name", "exact")
        if method_name in ("hnsw", "ivf"):
            method_name = "ivf"
        method_params = method_spec.get("parameters", {}) or {}
        if ftype == "geo_point":
            for axis in ("lat", "lon"):
                self.field_types[f"{full_name}.{axis}"] = MappedFieldType(
                    name=f"{full_name}.{axis}", type="double")
        if ftype == "geo_shape":
            # hidden bbox columns back every shape (the device-side coarse
            # filter; exact refinement parses geometries from _source —
            # reference contrast: AbstractShapeGeometryFieldMapper encodes
            # a triangle tree into BKD points)
            for corner in ("minx", "maxx", "miny", "maxy"):
                self.field_types[f"{full_name}#{corner}"] = MappedFieldType(
                    name=f"{full_name}#{corner}", type="double")
        self.field_types[full_name] = MappedFieldType(
            name=full_name, type=ftype,
            analyzer=analyzer,
            search_analyzer=spec.get("search_analyzer"),
            index=bool(spec.get("index", True)),
            doc_values=bool(spec.get("doc_values", True)),
            store=bool(spec.get("store", False)),
            fmt=spec.get("format"),
            scaling_factor=float(spec.get("scaling_factor", 100.0)),
            dims=dims,
            similarity_space=space,
            knn_method=method_name,
            knn_nlist=int(method_params.get("nlist", 128)),
            knn_nprobe=int(method_params.get("nprobes",
                                             method_params.get("nprobe", 0))),
            max_tokens=max_tokens,
            compression=compression,
            pq_m=pq_m,
            ignore_above=spec.get("ignore_above"),
            null_value=spec.get("null_value"),
            boost=float(spec.get("boost", 1.0)),
            meta=spec.get("meta", {}),
        )
        for sub_name, sub_spec in spec.get("fields", {}).items():
            sub_full = f"{full_name}.{sub_name}"
            self._put_field(sub_full, sub_spec)
            children = self._multi_children.setdefault(full_name, [])
            if sub_full not in children:
                children.append(sub_full)

    def mapping_dict(self) -> dict:
        """Render back the REST mapping shape (GET _mapping contract)."""
        properties: dict = {}
        multi_fields = [n for n in self.field_types if "." in n
                        and n.rsplit(".", 1)[0] in self.field_types]
        for name, ft in self.field_types.items():
            if name in multi_fields:
                continue
            spec: dict = {"type": ft.type}
            if ft.is_vector:
                spec["dimension"] = ft.dims
            if ft.is_rank_vectors:
                spec["dimension"] = ft.dims
                spec["max_tokens"] = ft.max_tokens
                if ft.compression != "none":
                    spec["compression"] = ft.compression
                    spec["pq_m"] = ft.pq_m
            if ft.fmt:
                spec["format"] = ft.fmt
            if ft.analyzer != "standard" and ft.is_text:
                spec["analyzer"] = ft.analyzer
            subs = {m.rsplit(".", 1)[1]: {"type": self.field_types[m].type}
                    for m in multi_fields if m.rsplit(".", 1)[0] == name}
            for sub_name, sub_spec in subs.items():
                if self.field_types[f"{name}.{sub_name}"].ignore_above is not None:
                    sub_spec["ignore_above"] = self.field_types[f"{name}.{sub_name}"].ignore_above
            if subs:
                spec["fields"] = subs
            node = properties
            parts = name.split(".")
            for p in parts[:-1]:
                node = node.setdefault(p, {}).setdefault("properties", {})
            node[parts[-1]] = spec
        return {"properties": properties}

    # ------------------------------------------------------------ documents
    def parse_document(self, doc_id: str, source: dict,
                       routing: Optional[str] = None) -> ParsedDocument:
        if not isinstance(source, dict):
            raise MapperParsingError("failed to parse: document must be an object")
        fields: Dict[str, ParsedField] = {}
        children: List[Tuple[str, Dict[str, ParsedField]]] = []
        self._parse_object("", source, fields, children)
        return ParsedDocument(doc_id=doc_id, source=source, routing=routing,
                              fields=fields, children=children)

    def _parse_object(self, prefix: str, obj: dict,
                      out: Dict[str, ParsedField],
                      children: Optional[List] = None):
        for key, value in obj.items():
            full = f"{prefix}{key}"
            ft = self.field_types.get(full)
            if ft is not None and ft.type == "percolator":
                # stored-query field: kept in _source only, matched at
                # percolate time (modules/percolator PercolatorFieldMapper)
                continue
            if ft is not None and ft.is_range:
                self._parse_range(full, ft, value, out)
                continue
            if ft is not None and ft.type == "geo_shape":
                # GeoJSON dicts must NOT fall into the object walk
                self._parse_value(full, value, out)
                continue
            if full == self.join_field and children is not None:
                # join value: "parent_type" or {"name": t, "parent": id}
                if isinstance(value, dict):
                    self._parse_value(full, value.get("name"), out)
                    if value.get("parent") is not None:
                        self._parse_value(f"{full}#parent",
                                          str(value["parent"]), out)
                else:
                    self._parse_value(full, value, out)
                continue
            if full in self.nested_paths and children is not None:
                # nested object(s): each becomes its own doc-block row;
                # sub-fields do NOT join the parent row's fields. `children`
                # is passed through so nested-inside-nested paths also get
                # their own rows (each joins to the root block).
                elems = value if isinstance(value, list) else [value]
                for elem in elems:
                    if elem is None:
                        continue    # explicit null = absent, like the ref
                    if not isinstance(elem, dict):
                        raise MapperParsingError(
                            f"object mapping for [{full}] tried to parse "
                            f"field as object, but found a concrete value")
                    child_fields: Dict[str, ParsedField] = {}
                    self._parse_object(f"{full}.", elem, child_fields,
                                       children)
                    children.append((full, child_fields))
                continue
            if isinstance(value, dict):
                self._parse_object(f"{full}.", value, out, children)
            elif isinstance(value, list) and value and all(
                    isinstance(v, dict) for v in value):
                for v in value:
                    self._parse_object(f"{full}.", v, out, children)
            else:
                self._parse_value(full, value, out)

    def _parse_range(self, name: str, ft: MappedFieldType, value: Any,
                     out: Dict[str, ParsedField]):
        """Range value(s) {gte/gt/lte/lt} -> inclusive bounds in the hidden
        #lo / #hi columns (RangeFieldMapper analog); exclusive bounds shift
        by one step on discrete elements, one ulp on floats."""
        elem_ft = self.field_types[f"{name}#lo"]
        step = _RANGE_STEP.get(elem_ft.type, 0.0)

        def conv(v):
            if elem_ft.is_date:
                return float(parse_date_millis(v, elem_ft.fmt))
            if elem_ft.is_ip:
                return float(ip_to_long(v))
            return elem_ft.parse_numeric(v)

        lo_pf = out.setdefault(f"{name}#lo", ParsedField())
        hi_pf = out.setdefault(f"{name}#hi", ParsedField())
        lo_pf.numeric_values = lo_pf.numeric_values or []
        hi_pf.numeric_values = hi_pf.numeric_values or []
        for elem in (value if isinstance(value, list) else [value]):
            if elem is None:
                continue
            if not isinstance(elem, dict):
                raise MapperParsingError(
                    f"error parsing field [{name}], expected an object "
                    f"with gte/gt/lte/lt bounds")
            lo, hi = -RANGE_UNBOUNDED, RANGE_UNBOUNDED
            if elem.get("gte") is not None:
                lo = conv(elem["gte"])
            if elem.get("gt") is not None:
                v = conv(elem["gt"])
                lo = v + step if step else math.nextafter(v, math.inf)
            if elem.get("lte") is not None:
                hi = conv(elem["lte"])
            if elem.get("lt") is not None:
                v = conv(elem["lt"])
                hi = v - step if step else math.nextafter(v, -math.inf)
            lo_pf.numeric_values.append(lo)
            hi_pf.numeric_values.append(hi)

    def _dynamic_map(self, name: str, value: Any):
        if self.dynamic in (False, "false", "strict"):
            if self.dynamic == "strict":
                raise MapperParsingError(
                    f"mapping set to strict, dynamic introduction of [{name}] "
                    f"within [_doc] is not allowed")
            return  # dynamic:false — ignore unmapped fields
        sample = value[0] if isinstance(value, list) and value else value
        if isinstance(sample, bool):
            self._put_field(name, {"type": "boolean"})
        elif isinstance(sample, int):
            self._put_field(name, {"type": "long"})
        elif isinstance(sample, float):
            self._put_field(name, {"type": "float"})
        elif isinstance(sample, str):
            try:
                parse_date_millis(sample)
                looks_like_date = bool(re.match(r"^\d{4}-\d{2}-\d{2}", sample))
            except MapperParsingError:
                looks_like_date = False
            if looks_like_date:
                self._put_field(name, {"type": "date"})
            else:
                self._put_field(name, {"type": "text",
                                       "fields": {"keyword": {"type": "keyword",
                                                              "ignore_above": 256}}})
        else:
            return

    def _parse_value(self, name: str, value: Any, out: Dict[str, ParsedField],
                     into_multi_fields: bool = True):
        if name not in self.field_types:
            if value is None:
                return
            self._dynamic_map(name, value)
            if name not in self.field_types:
                return
        if into_multi_fields:
            # fan the same raw value into multi-fields (title → title.keyword)
            for sub in self._multi_children.get(name, ()):
                self._parse_value(sub, value, out, into_multi_fields=False)
        ft = self.field_types[name]
        values = value if isinstance(value, list) else [value]
        values = [v for v in values if v is not None]
        if ft.null_value is not None and not values:
            values = [ft.null_value]
        if not values:
            return
        pf = out.setdefault(name, ParsedField())
        if ft.is_text:
            analyzer = self.analysis.get(ft.analyzer)
            terms: List[Tuple[str, int]] = pf.terms or []
            # continue positions past the last emitted one, with the standard
            # 100-position gap between values (Lucene position_increment_gap)
            base = (terms[-1][1] + 1 + 100) if terms else 0
            for v in values:
                toks = analyzer.analyze(str(v))
                terms.extend((t, base + p) for t, p in toks)
                if toks:
                    base += toks[-1][1] + 1 + 100
            pf.terms = terms
            pf.length = len(terms)
        elif ft.is_keyword:
            vals = pf.exact_values or []
            for v in values:
                s = str(v)
                if ft.ignore_above is not None and len(s) > int(ft.ignore_above):
                    continue
                vals.append(s)
            pf.exact_values = vals
        elif ft.is_numeric:
            nums = pf.numeric_values or []
            nums.extend(ft.parse_numeric(v) for v in values)
            pf.numeric_values = nums
        elif ft.is_date:
            nums = pf.numeric_values or []
            nums.extend(float(parse_date_millis(v, ft.fmt)) for v in values)
            pf.numeric_values = nums
        elif ft.is_bool:
            nums = pf.numeric_values or []
            bools = [_parse_boolish(v) for v in values]
            nums.extend(1.0 if b else 0.0 for b in bools)
            pf.numeric_values = nums
            pf.exact_values = (pf.exact_values or []) + [
                "true" if b else "false" for b in bools]
        elif ft.is_ip:
            nums = pf.numeric_values or []
            nums.extend(float(ip_to_long(v)) for v in values)
            pf.numeric_values = nums
            pf.exact_values = (pf.exact_values or []) + [str(v) for v in values]
        elif ft.is_rank_vectors:
            # one [tokens, dims] matrix per doc: an array of per-token
            # vectors (an empty array is a valid zero-token doc)
            if not isinstance(value, list) or not all(
                    isinstance(t, list) for t in values):
                raise MapperParsingError(
                    f"failed to parse rank_vectors field [{name}]: "
                    f"expected an array of token vectors")
            if len(values) > ft.max_tokens:
                raise MapperParsingError(
                    f"rank_vectors field [{name}] has {len(values)} token "
                    f"vectors, more than max_tokens [{ft.max_tokens}]")
            toks: List[List[float]] = []
            for t in values:
                if len(t) != ft.dims or not all(
                        isinstance(v, (int, float)) and
                        not isinstance(v, bool) for v in t):
                    raise MapperParsingError(
                        f"Vector dimension mismatch for field [{name}]: "
                        f"expected {ft.dims}, got {len(t)}")
                toks.append([float(v) for v in t])
            pf.token_vectors = toks
        elif ft.is_vector:
            if isinstance(value, list) and all(isinstance(v, (int, float)) for v in value):
                vec = [float(v) for v in value]
            else:
                raise MapperParsingError(
                    f"failed to parse vector field [{name}]: expected array of numbers")
            if len(vec) != ft.dims:
                raise MapperParsingError(
                    f"Vector dimension mismatch for field [{name}]: expected {ft.dims}, "
                    f"got {len(vec)}")
            pf.vector = vec
        elif ft.type == "geo_point":
            # store as two aligned numeric columns (.lat/.lon) — a sorted
            # value-pair column would scramble which value is which axis;
            # the parent field keeps lat for exists checks
            if isinstance(value, (list, tuple)) and len(value) == 2 \
                    and all(isinstance(v, (int, float)) for v in value):
                points = [list(value)]  # bare GeoJSON [lon, lat] point
            elif isinstance(value, list):
                points = value
            else:
                points = [value]
            nums = pf.numeric_values or []
            lat_pf = out.setdefault(f"{name}.lat", ParsedField())
            lon_pf = out.setdefault(f"{name}.lon", ParsedField())
            lat_pf.numeric_values = lat_pf.numeric_values or []
            lon_pf.numeric_values = lon_pf.numeric_values or []
            for v in points:
                lat, lon = _parse_geo_point(v)
                nums.append(lat)
                lat_pf.numeric_values.append(lat)
                lon_pf.numeric_values.append(lon)
            pf.numeric_values = nums
        elif ft.type == "geo_shape":
            from opensearch_tpu.common.geo import parse_geojson
            try:
                geom = parse_geojson(value)
            except (ValueError, TypeError, KeyError, IndexError) as e:
                raise MapperParsingError(
                    f"failed to parse field [{name}] of type [geo_shape]: "
                    f"{e}")
            minx, miny, maxx, maxy = geom.bbox
            pf.numeric_values = (pf.numeric_values or []) + [minx]
            for corner, v in (("minx", minx), ("maxx", maxx),
                              ("miny", miny), ("maxy", maxy)):
                cpf = out.setdefault(f"{name}#{corner}", ParsedField())
                cpf.numeric_values = (cpf.numeric_values or []) + [v]
        # binary/object: stored in _source only

    def get_field(self, name: str) -> Optional[MappedFieldType]:
        return self.field_types.get(name)

    def expand_field_patterns(self, fields) -> List[str]:
        """Wildcard field specs ("text*", "*_name^2") expand against the
        mapping (QueryParserHelper.resolveMappingFields), skipping hidden
        bound/join columns; boost suffixes carry to every expansion. The
        single shared implementation for the compiler, highlighter, and
        term collector — the hidden-field filter must never diverge."""
        import fnmatch as _fn
        out: List[str] = []
        for fspec in fields:
            fname, caret, fboost = str(fspec).partition("^")
            if "*" not in fname:
                out.append(fspec)
                continue
            for actual in self.field_types:
                if "#" in actual:
                    continue
                if _fn.fnmatchcase(actual, fname):
                    out.append(f"{actual}^{fboost}" if caret else actual)
        return out


def _parse_geo_point(value: Any) -> Tuple[float, float]:
    if isinstance(value, dict):
        return float(value["lat"]), float(value["lon"])
    if isinstance(value, (list, tuple)) and len(value) == 2:
        return float(value[1]), float(value[0])  # GeoJSON order [lon, lat]
    if isinstance(value, str) and "," in value:
        lat, lon = value.split(",", 1)
        return float(lat), float(lon)
    raise MapperParsingError(f"failed to parse geo_point [{value}]")
