"""Sequence-number machinery: local checkpoints, replication tracking, leases.

Re-design of the reference seqno subsystem:
- `LocalCheckpointTracker` (index/seqno/LocalCheckpointTracker.java): assigns
  monotonically increasing seq_nos on the primary and tracks the max
  contiguous processed/persisted seq_no (the local checkpoint) as ops complete
  possibly out of order.
- `ReplicationTracker` (index/seqno/ReplicationTracker.java:103): on the
  primary, tracks every in-sync copy's local checkpoint; the **global
  checkpoint** is the minimum over in-sync copies — everything at or below it
  is durable on every in-sync copy. Retention leases
  (RetentionLease*.java) pin translog ops above a peer's checkpoint so
  ops-based recovery stays possible.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Set

NO_OPS_PERFORMED = -1
UNASSIGNED_SEQ_NO = -2


class LocalCheckpointTracker:
    """Max contiguous completed seq_no; ops may complete out of order."""

    def __init__(self, max_seq_no: int = NO_OPS_PERFORMED,
                 local_checkpoint: int = NO_OPS_PERFORMED):
        self._max_seq_no = max_seq_no
        self._checkpoint = local_checkpoint
        self._pending: Set[int] = set()  # completed above the checkpoint

    def generate_seq_no(self) -> int:
        self._max_seq_no += 1
        return self._max_seq_no

    def advance_max_seq_no(self, seq_no: int):
        """Replica path: seq_nos arrive pre-assigned by the primary."""
        if seq_no > self._max_seq_no:
            self._max_seq_no = seq_no

    def mark_processed(self, seq_no: int):
        if seq_no <= self._checkpoint:
            return
        self._pending.add(seq_no)
        while (self._checkpoint + 1) in self._pending:
            self._checkpoint += 1
            self._pending.discard(self._checkpoint)

    @property
    def max_seq_no(self) -> int:
        return self._max_seq_no

    @property
    def checkpoint(self) -> int:
        return self._checkpoint

    def has_processed(self, seq_no: int) -> bool:
        return seq_no <= self._checkpoint or seq_no in self._pending


@dataclass
class RetentionLease:
    """Pins translog retention for a peer (index/seqno/RetentionLease.java)."""
    lease_id: str
    retaining_seq_no: int
    timestamp_ms: int
    source: str


@dataclass
class CheckpointState:
    """Per-copy tracking entry (ReplicationTracker.CheckpointState :681)."""
    local_checkpoint: int = UNASSIGNED_SEQ_NO
    in_sync: bool = False
    tracked: bool = False


class ReplicationTracker:
    """Primary-side global-checkpoint computation over in-sync copies."""

    def __init__(self, shard_allocation_id: str, primary_term: int = 1):
        self.shard_allocation_id = shard_allocation_id
        self.primary_term = primary_term
        self.checkpoints: Dict[str, CheckpointState] = {
            shard_allocation_id: CheckpointState(in_sync=True, tracked=True)
        }
        self.global_checkpoint = NO_OPS_PERFORMED
        self.retention_leases: Dict[str, RetentionLease] = {}

    # ------------------------------------------------------------ membership

    def init_tracking(self, allocation_id: str):
        """Start tracking a recovering copy (not yet in-sync)."""
        self.checkpoints.setdefault(allocation_id, CheckpointState(tracked=True))

    def mark_in_sync(self, allocation_id: str, local_checkpoint: int):
        st = self.checkpoints.setdefault(allocation_id, CheckpointState())
        st.tracked = True
        st.in_sync = True
        st.local_checkpoint = max(st.local_checkpoint, local_checkpoint)
        self._recompute()

    def remove_copy(self, allocation_id: str):
        if allocation_id != self.shard_allocation_id:
            self.checkpoints.pop(allocation_id, None)
            self._recompute()

    # ----------------------------------------------------------- checkpoints

    def update_local_checkpoint(self, allocation_id: str, local_checkpoint: int):
        st = self.checkpoints.get(allocation_id)
        if st is None:
            return
        if local_checkpoint > st.local_checkpoint:
            st.local_checkpoint = local_checkpoint
        self._recompute()

    def _recompute(self):
        in_sync = [st.local_checkpoint for st in self.checkpoints.values()
                   if st.in_sync]
        if in_sync:
            new_gcp = min(in_sync)
            if new_gcp > self.global_checkpoint:
                self.global_checkpoint = new_gcp

    def in_sync_ids(self) -> Set[str]:
        return {aid for aid, st in self.checkpoints.items() if st.in_sync}

    # ---------------------------------------------------------------- leases

    def add_lease(self, lease_id: str, retaining_seq_no: int, source: str,
                  timestamp_ms: int = 0) -> RetentionLease:
        lease = RetentionLease(lease_id, retaining_seq_no, timestamp_ms, source)
        self.retention_leases[lease_id] = lease
        return lease

    def renew_lease(self, lease_id: str, retaining_seq_no: int,
                    timestamp_ms: int = 0):
        lease = self.retention_leases.get(lease_id)
        if lease is None:
            raise KeyError(lease_id)
        lease.retaining_seq_no = max(lease.retaining_seq_no, retaining_seq_no)
        lease.timestamp_ms = timestamp_ms

    def remove_lease(self, lease_id: str):
        self.retention_leases.pop(lease_id, None)

    def min_retained_seq_no(self) -> int:
        """Lowest seq_no that must stay replayable from the translog."""
        floors = [l.retaining_seq_no for l in self.retention_leases.values()]
        floors.append(self.global_checkpoint + 1)
        return min(floors)
