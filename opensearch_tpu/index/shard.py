"""IndexShard: the per-shard state machine gluing engine, store, and search.

Re-design of the reference IndexShard (index/shard/IndexShard.java:231):
holds the engine, exposes the primary/replica operation entry points
(applyIndexOperationOnPrimary :881 / applyIndexOperationOnReplica :906),
tracks the primary term, and keeps the search reader (ShardReader — the
acquireSearcher analog) in sync with the engine's sealed segments: refresh
seals the RAM buffer and uploads the new columnar segment to device HBM,
deletes propagate to device liveness masks.
"""

from __future__ import annotations

import os
from typing import List, Optional

from opensearch_tpu.index.engine import EngineResult, GetResult, InternalEngine
from opensearch_tpu.index.mapper import MapperService
from opensearch_tpu.search.executor import SearchExecutor, ShardReader


class IndexShard:
    def __init__(self, shard_id: int, mapper: MapperService,
                 index_name: str = "_index",
                 data_path: Optional[str] = None,
                 durability: str = "request", primary_term: int = 1,
                 primary: bool = True, allocation_id: Optional[str] = None):
        self.shard_id = shard_id
        self.index_name = index_name
        self.primary = primary
        # node-level data path → per-index per-shard directory (reference
        # layout: nodes/0/indices/<index-uuid>/<shard>); without index_name
        # two indices sharing a data path would corrupt each other
        shard_path = (os.path.join(data_path, index_name, str(shard_id))
                      if data_path is not None else None)
        self.engine = InternalEngine(
            mapper, data_path=shard_path, durability=durability,
            primary_term=primary_term,
            allocation_id=allocation_id
            or f"{index_name}_{shard_id}_alloc")
        self.reader = ShardReader(mapper, index_name=index_name)
        self.executor = SearchExecutor(self.reader)
        self._sync_reader()

    # --------------------------------------------------------------- writes

    def index_doc(self, doc_id: str, source: dict, **kw) -> EngineResult:
        return self.engine.index(doc_id, source, **kw)

    def index_on_replica(self, doc_id: str, source: dict, seq_no: int,
                         primary_term: int, version: int) -> EngineResult:
        return self.engine.index_on_replica(doc_id, source, seq_no,
                                            primary_term, version)

    def delete_doc(self, doc_id: str, **kw) -> EngineResult:
        return self.engine.delete(doc_id, **kw)

    def delete_on_replica(self, doc_id: str, seq_no: int, primary_term: int,
                          version: int) -> EngineResult:
        return self.engine.delete_on_replica(doc_id, seq_no, primary_term,
                                             version)

    def get_doc(self, doc_id: str, realtime: bool = True) -> Optional[GetResult]:
        return self.engine.get(doc_id, realtime=realtime)

    # ------------------------------------------------------------ lifecycle

    def refresh(self):
        self.engine.refresh()
        self._sync_reader()

    def flush(self):
        self.engine.flush()
        self._sync_reader()

    def force_merge(self):
        """Merge down to one segment (_forcemerge analog)."""
        prev = self.engine.merge_max_segments
        self.engine.merge_max_segments = 1
        try:
            while self.engine.maybe_merge() is not None:
                pass
        finally:
            self.engine.merge_max_segments = prev
        self._sync_reader()

    def maybe_merge(self):
        merged = self.engine.maybe_merge()
        if merged is not None:
            self._sync_reader()
        return merged

    def _sync_reader(self):
        """Reconcile the device-resident reader with engine segments."""
        engine_ids = {s.seg_id for s in self.engine.segments}
        for seg in list(self.reader.segments):
            if seg.seg_id not in engine_ids:
                self.reader.remove_segment(seg.seg_id)
        reader_ids = {s.seg_id for s in self.reader.segments}
        for seg in self.engine.segments:
            if seg.seg_id not in reader_ids:
                self.reader.add_segment(seg)
            else:
                self.reader.update_segment(seg)

    def close(self):
        self.engine.close()

    def stats(self) -> dict:
        st = self.engine.stats()
        st["shard_id"] = self.shard_id
        st["primary"] = self.primary
        return st
