"""IndexShard: the per-shard state machine gluing engine, store, and search.

Re-design of the reference IndexShard (index/shard/IndexShard.java:231):
holds the engine, exposes the primary/replica operation entry points
(applyIndexOperationOnPrimary :881 / applyIndexOperationOnReplica :906),
tracks the primary term, and keeps the search reader (ShardReader — the
acquireSearcher analog) in sync with the engine's sealed segments: refresh
seals the RAM buffer and uploads the new columnar segment to device HBM,
deletes propagate to device liveness masks.

Churn attribution (ISSUE 13): refresh/merge are where the write path
touches the device — this is the layer that can see BOTH sides (the
engine event and the reader's device uploads), so the segment-churn
ledger (telemetry/ledger.py ChurnLedger) is fed here: each effective
refresh/merge publishes one churn record carrying the `upload.corpus`
bytes it re-shipped, the recompile/warmup-hit verdict per new segment,
and how many interned RotatingMemo entries it invalidated (the whole
ShardStats memo dies whenever the segment list changes — every skeleton
and bundle rebuilds on the host — plus the subset keyed to removed
(segment-uid, mapper-version) pairs).
"""

from __future__ import annotations

import os
import time
from contextlib import contextmanager
from typing import List, Optional

from opensearch_tpu.index.engine import EngineResult, GetResult, InternalEngine
from opensearch_tpu.index.mapper import MapperService
from opensearch_tpu.search.executor import SearchExecutor, ShardReader
from opensearch_tpu.telemetry import TELEMETRY

_CHURN = TELEMETRY.churn

# RotatingMemo key prefixes whose second element is a segment uid
# (compile.py skeletons/text-clause plans/slice buckets, executor agg
# plans, fetch join columns) — the keys a removed segment invalidates
# by (segment-uid, mapper-version) construction
_UID_KEYED_PREFIXES = ("skel", "tc", "aggc", "slice", "join_cols",
                      "join_match")


def _memo_keyed_count(cache, removed_uids) -> int:
    """Entries in a ShardStats memo keyed to one of `removed_uids` —
    the precisely-attributable slice of the invalidation (the wholesale
    drop is reported separately)."""
    if cache is None or not removed_uids:
        return 0
    uids = set(removed_uids)
    n = 0
    for key in cache.memo.keys():
        if not isinstance(key, tuple) or len(key) < 2:
            continue
        if key[0] in _UID_KEYED_PREFIXES and key[1] in uids:
            n += 1
        elif key[0] in uids and isinstance(key[1], str):
            # bare (uid, fingerprint) keys (fetch-phase highlight/join
            # memos)
            n += 1
    return n


class IndexShard:
    def __init__(self, shard_id: int, mapper: MapperService,
                 index_name: str = "_index",
                 data_path: Optional[str] = None,
                 durability: str = "request", primary_term: int = 1,
                 primary: bool = True, allocation_id: Optional[str] = None):
        self.shard_id = shard_id
        self.index_name = index_name
        self.primary = primary
        # node-level data path → per-index per-shard directory (reference
        # layout: nodes/0/indices/<index-uuid>/<shard>); without index_name
        # two indices sharing a data path would corrupt each other
        shard_path = (os.path.join(data_path, index_name, str(shard_id))
                      if data_path is not None else None)
        self.engine = InternalEngine(
            mapper, data_path=shard_path, durability=durability,
            primary_term=primary_term,
            allocation_id=allocation_id
            or f"{index_name}_{shard_id}_alloc")
        self.reader = ShardReader(mapper, index_name=index_name)
        # shard attribution for the scanned-bytes heat map
        # (telemetry/scan.py, ISSUE 14): the reader is what the
        # executor sees, so it carries the shard id
        self.reader.shard_id = shard_id
        self.executor = SearchExecutor(self.reader)
        self._sync_reader()

    # --------------------------------------------------------------- writes

    def index_doc(self, doc_id: str, source: dict, **kw) -> EngineResult:
        return self.engine.index(doc_id, source, **kw)

    def index_on_replica(self, doc_id: str, source: dict, seq_no: int,
                         primary_term: int, version: int) -> EngineResult:
        return self.engine.index_on_replica(doc_id, source, seq_no,
                                            primary_term, version)

    def delete_doc(self, doc_id: str, **kw) -> EngineResult:
        return self.engine.delete(doc_id, **kw)

    def delete_on_replica(self, doc_id: str, seq_no: int, primary_term: int,
                          version: int) -> EngineResult:
        return self.engine.delete_on_replica(doc_id, seq_no, primary_term,
                                             version)

    def get_doc(self, doc_id: str, realtime: bool = True) -> Optional[GetResult]:
        return self.engine.get(doc_id, realtime=realtime)

    # ------------------------------------------------------------ lifecycle

    def refresh(self):
        scope = _CHURN.scope()
        if scope is None:
            self.engine.refresh()
            binfo = {}
            with self._publish_barrier(binfo):
                self._sync_reader()
            self._carry_report()
            self._maybe_precompile(None)
            return
        t0 = time.perf_counter()
        cache = self.reader._stats_cache
        segments_before = len(self.reader.segments)
        new_seg = self.engine.refresh()
        binfo = {}
        with self._publish_barrier(binfo):
            with _CHURN.bound(scope):
                self._sync_reader()
        if new_seg is None and not scope.upload_bytes \
                and not scope.live_mask_bytes:
            return                          # no-op refresh: no record
        report = self._carry_report()
        ev = self.engine.last_ingest_event
        rec = _CHURN.publish(
            scope, "refresh",
            segments_before=segments_before,
            segments_after=len(self.reader.segments),
            docs=new_seg.num_docs if new_seg is not None else 0,
            wall_ms=(time.perf_counter() - t0) * 1000,
            # a new segment changes the segment list, which drops the
            # WHOLE ShardStats memo (stats() rebuild) — every interned
            # skeleton/bundle rebuilds on the host — UNLESS segment-
            # keyed carry is on, in which case the honest invalidation
            # count is the carry's eviction subset
            memo_entries_dropped=(
                len(cache.memo) if cache is not None
                and self.reader._stats_cache is not cache else 0),
            memo_entries_keyed=0,          # refresh removes no segment
            memo_invalidations=(report["evicted"]
                                if report is not None else None),
            memo_entries_kept=(report["kept"]
                               if report is not None else None),
            event_id=ev.get("event_id") if ev else None,
            shard=f"{self.index_name}[{self.shard_id}]",
            warmup_registered=self._warmup_registered())
        if binfo.get("precompiled"):
            _CHURN.mark_precompiled([rec["churn_id"]],
                                    binfo["took_ms"], by="barrier")
        else:
            self._maybe_precompile(rec)

    def flush(self):
        self.engine.flush()
        self._sync_reader()

    def force_merge(self):
        """Merge down to one segment (_forcemerge analog)."""
        prev = self.engine.merge_max_segments
        self.engine.merge_max_segments = 1
        try:
            while self.maybe_merge() is not None:
                pass
        finally:
            self.engine.merge_max_segments = prev
        self._sync_reader()

    def maybe_merge(self):
        scope = _CHURN.scope()
        if scope is None:
            merged = self.engine.maybe_merge()
            if merged is not None:
                binfo = {}
                with self._publish_barrier(binfo):
                    self._sync_reader()
                self._carry_report()
                self._maybe_precompile(None)
            return merged
        t0 = time.perf_counter()
        cache = self.reader._stats_cache
        before = {s.seg_id: s.uid for s in self.engine.segments}
        segments_before = len(self.reader.segments)
        merged = self.engine.maybe_merge()
        if merged is None:
            return None
        removed_ids = [sid for sid in before
                       if all(s.seg_id != sid
                              for s in self.engine.segments)]
        removed_uids = [before[sid] for sid in removed_ids]
        binfo = {}
        with self._publish_barrier(binfo):
            with _CHURN.bound(scope):
                self._sync_reader()
        report = self._carry_report()
        ev = self.engine.last_ingest_event
        rec = _CHURN.publish(
            scope, "merge",
            segments_before=segments_before,
            segments_after=len(self.reader.segments),
            docs=merged.num_docs,
            wall_ms=(time.perf_counter() - t0) * 1000,
            memo_entries_dropped=(
                len(cache.memo) if cache is not None
                and self.reader._stats_cache is not cache else 0),
            memo_entries_keyed=_memo_keyed_count(cache, removed_uids),
            memo_invalidations=(report["evicted"]
                                if report is not None else None),
            memo_entries_kept=(report["kept"]
                               if report is not None else None),
            removed_seg_ids=removed_ids,
            event_id=ev.get("event_id") if ev else None,
            shard=f"{self.index_name}[{self.shard_id}]",
            warmup_registered=self._warmup_registered())
        if binfo.get("precompiled"):
            _CHURN.mark_precompiled([rec["churn_id"]],
                                    binfo["took_ms"], by="barrier")
        else:
            self._maybe_precompile(rec)
        return merged

    @contextmanager
    def _publish_barrier(self, out: dict):
        """Barrier-mode publish (ISSUE 16, `search.precompile.barrier`):
        the reader mutations inside this block build a STAGED pair; the
        warmup registry replays against it with only this thread seeing
        the stage; then the pair commits atomically. Serving threads
        can never observe a segment set whose executables are not
        compiled — recompile-on-serve is structurally zero, at the cost
        of delaying each publish's visibility by the replay (the async
        worker instead races the first query). No-op passthrough unless
        both precompiler flags are on."""
        from opensearch_tpu.search.warmup import PRECOMPILE
        pc = PRECOMPILE.gate()
        if pc is None or not pc.barrier:
            yield
            return
        self.reader.begin_staged_publish()
        try:
            with self.reader.staged_visible():
                yield
                # replay unconditionally: shape novelty is judged against
                # the process-wide seen-set, but compiled bundles live per
                # executor — a globally-known shape can still be cold
                # HERE. A warm replay costs microseconds (every JIT call
                # cache-hits), so the gate would only save noise while
                # risking a serve-path compile.
                self.reader.take_novel_shapes()
                out["took_ms"] = pc.precompile_staged(
                    self.executor, self.index_name)
                out["precompiled"] = True
        finally:
            self.reader.commit_staged_publish()

    def _carry_report(self) -> Optional[dict]:
        """Eager ShardStats rebuild when segment-keyed memo carry is on
        (ISSUE 16): the carry pass runs here at publish time — on the
        writing thread, off the serving path — and its kept/evicted
        counts land on this event's churn record. With carry off this
        is a no-op (stats rebuild stays lazy, on first search)."""
        if not self.reader.memo_carry:
            return None
        return getattr(self.reader.rebuild_stats(), "carry_report", None)

    def _maybe_precompile(self, rec: Optional[dict]) -> None:
        """Hand novel device shapes from this event to the off-path
        precompiler. One attribute load + branch while the gate is off
        (the no-op discipline)."""
        from opensearch_tpu.search.warmup import PRECOMPILE
        if PRECOMPILE.gate() is None:
            return
        shapes = self.reader.take_novel_shapes()
        if not shapes:
            return
        PRECOMPILE.request(self.executor, self.index_name, shapes,
                           churn_id=(rec or {}).get("churn_id"))

    def _warmup_registered(self) -> int:
        """Warmup-registry coverage stamped on churn records: how many
        (plan-struct, shape-bucket) entries a replay could pre-compile
        for this index after the event."""
        from opensearch_tpu.search.warmup import WARMUP
        return WARMUP.registered_count(self.index_name)

    def _sync_reader(self):
        """Reconcile the device-resident reader with engine segments."""
        engine_ids = {s.seg_id for s in self.engine.segments}
        for seg in list(self.reader.segments):
            if seg.seg_id not in engine_ids:
                self.reader.remove_segment(seg.seg_id)
        reader_ids = {s.seg_id for s in self.reader.segments}
        for seg in self.engine.segments:
            if seg.seg_id not in reader_ids:
                self.reader.add_segment(seg)
            else:
                self.reader.update_segment(seg)

    def close(self):
        self.engine.close()

    def stats(self) -> dict:
        st = self.engine.stats()
        st["shard_id"] = self.shard_id
        st["primary"] = self.primary
        return st
