"""Shard replication: primary-backup writes, peer recovery, segment copy.

Re-design of three reference subsystems (SURVEY.md §2.1/§3.3/§3.5):
  - **write replication** — ReplicationOperation
    (action/support/replication/ReplicationOperation.java:175,221): the
    primary executes, fans the op with its assigned seq_no to every in-sync
    copy, piggybacks the global checkpoint, and fails slow/broken copies out
    of the in-sync set;
  - **peer recovery** — RecoverySourceHandler
    (indices/recovery/RecoverySourceHandler.java:164): retention-lease
    ops-only recovery when the primary's translog still has the replica's
    missing ops, else phase1 segment copy + phase2 translog replay, then
    finalize (mark in-sync);
  - **segment replication** — SegmentReplicationTargetService
    (indices/replication/SegmentReplicationTargetService.java:192): replicas
    adopt the primary's sealed segments at each refresh checkpoint instead
    of re-indexing (the NRTReplicationEngine model — a natural fit here
    since segments are immutable arrays).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from opensearch_tpu.common.errors import OpenSearchTpuError
from opensearch_tpu.index.shard import IndexShard


class ReplicationFailedError(OpenSearchTpuError):
    status = 500
    error_type = "replication_failed_exception"


@dataclass
class ReplicationCheckpoint:
    """Segment-replication checkpoint published after primary refresh
    (indices/replication/checkpoint/ReplicationCheckpoint.java)."""
    primary_term: int
    segment_infos_version: int
    max_seq_no: int
    local_checkpoint: int


class ShardReplicationGroup:
    """One logical shard: a primary plus replica copies on the same host
    boundary (cross-node placement rides the transport layer; the protocol
    below is identical either way)."""

    def __init__(self, primary: IndexShard, replicas: List[IndexShard],
                 replication_mode: str = "document"):
        if replication_mode not in ("document", "segment"):
            raise ValueError(f"unknown replication mode {replication_mode}")
        self.primary = primary
        self.replicas: Dict[str, IndexShard] = {}
        self.failed: Dict[str, str] = {}  # alloc id → failure reason
        self.mode = replication_mode
        self._ckpt_version = 0
        for replica in replicas:
            self.add_replica(replica, recover=False)
            # pristine empty replicas start in-sync (allocation of a new
            # index); later joiners must go through recover_replica
            self._tracker().mark_in_sync(self._alloc(replica),
                                         replica.engine.local_checkpoint)
        if self.mode == "segment":
            self.primary.engine.add_refresh_listener(
                lambda seg, deleted: self.publish_checkpoint())

    # ------------------------------------------------------------- plumbing

    def _tracker(self):
        return self.primary.engine.replication_tracker

    @staticmethod
    def _alloc(shard: IndexShard) -> str:
        return shard.engine.replication_tracker.shard_allocation_id \
            if hasattr(shard.engine.replication_tracker,
                       "shard_allocation_id") else str(id(shard))

    def add_replica(self, replica: IndexShard, recover: bool = True):
        alloc = self._alloc(replica)
        self.replicas[alloc] = replica
        self._tracker().init_tracking(alloc)
        if recover:
            self.recover_replica(replica)

    def fail_replica(self, replica: IndexShard, reason: str):
        """ReplicationOperation.onNoLongerPrimary path: a copy that failed
        an op is removed from the in-sync set (the cluster manager would
        reallocate it; here it must re-recover to return)."""
        alloc = self._alloc(replica)
        self.replicas.pop(alloc, None)
        self.failed[alloc] = reason
        self._tracker().remove_copy(alloc)

    @property
    def global_checkpoint(self) -> int:
        return self._tracker().global_checkpoint

    def in_sync_replicas(self) -> List[IndexShard]:
        in_sync = self._tracker().in_sync_ids()
        return [s for a, s in self.replicas.items() if a in in_sync]

    # ------------------------------------------------------ replicated write

    def index(self, doc_id: str, source: dict, **kw) -> dict:
        res = self.primary.index_doc(doc_id, source, **kw)
        self._replicate("index", doc_id, source, res)
        return {"result": "updated" if not res.created else "created",
                "_id": doc_id, "_seq_no": res.seq_no,
                "_version": res.version,
                "_shards": self._shards_header()}

    def delete(self, doc_id: str, **kw) -> dict:
        res = self.primary.delete_doc(doc_id, **kw)
        if res.found:
            self._replicate("delete", doc_id, None, res)
        return {"result": "deleted" if res.found else "not_found",
                "_id": doc_id, "_seq_no": res.seq_no,
                "_shards": self._shards_header()}

    def _replicate(self, op: str, doc_id: str, source: Optional[dict], res):
        term = self.primary.engine.primary_term
        tracker = self._tracker()
        if self.mode == "segment":
            # segment mode: replicas get data via checkpoint copy; the
            # replica translog still records the op for durability — modeled
            # by advancing its checkpoint state only
            self._advance_checkpoints()
            return
        for alloc, replica in list(self.replicas.items()):
            if alloc not in tracker.in_sync_ids():
                continue
            try:
                if op == "index":
                    replica.index_on_replica(doc_id, source, res.seq_no,
                                             term, res.version)
                else:
                    replica.delete_on_replica(doc_id, res.seq_no, term,
                                              res.version)
                # piggyback the global checkpoint (ReplicationOperation
                # sends globalCheckpoint with every replica request)
                replica.engine.replication_tracker.global_checkpoint = \
                    max(replica.engine.replication_tracker.global_checkpoint,
                        tracker.global_checkpoint)
            except Exception as e:
                self.fail_replica(replica, f"{op} failed: {e}")
        self._advance_checkpoints()

    def _advance_checkpoints(self):
        tracker = self._tracker()
        tracker.update_local_checkpoint(
            tracker.shard_allocation_id
            if hasattr(tracker, "shard_allocation_id") else "primary",
            self.primary.engine.local_checkpoint)
        for alloc, replica in self.replicas.items():
            tracker.update_local_checkpoint(
                alloc, replica.engine.local_checkpoint)

    def _shards_header(self) -> dict:
        total = 1 + len(self.replicas)
        return {"total": total, "successful": 1 + len(self.in_sync_replicas()),
                "failed": len(self.failed)}

    # --------------------------------------------------------- peer recovery

    def recover_replica(self, replica: IndexShard) -> dict:
        """Bring a (re)joining copy in sync. Returns recovery stats with the
        strategy used, mirroring the recovery API's output."""
        alloc = self._alloc(replica)
        self.replicas[alloc] = replica
        self.failed.pop(alloc, None)
        tracker = self._tracker()
        tracker.init_tracking(alloc)
        primary_engine = self.primary.engine
        # retention lease pins ops from the replica's checkpoint
        # (RecoverySourceHandler tries ops-only recovery under a lease)
        replica_ckpt = replica.engine.local_checkpoint
        tracker.add_lease(f"peer_recovery/{alloc}", replica_ckpt + 1,
                          "peer recovery")
        ops = (primary_engine.translog.read_ops(from_seq_no=replica_ckpt + 1)
               if primary_engine.translog is not None else None)
        # ops-based recovery requires the translog to still hold EVERY op
        # in (replica_ckpt, primary max_seq_no] — else fall back to files
        expected = set(range(replica_ckpt + 1, primary_engine.max_seq_no + 1))
        have_all_ops = ops is not None and \
            expected <= {o.seq_no for o in ops}
        phase = "ops" if have_all_ops else "file"
        if not have_all_ops:
            # phase1: copy the primary's sealed segments (flush first so the
            # RAM buffer is included in the copy)
            primary_engine.refresh()
            segs = list(primary_engine.segments)
            copied_ckpt = primary_engine.local_checkpoint
            replica.engine.install_segments(
                segs, max_seq_no=primary_engine.max_seq_no,
                local_checkpoint=copied_ckpt)
            replica._sync_reader()
            ops = (primary_engine.translog.read_ops(
                from_seq_no=copied_ckpt + 1)
                if primary_engine.translog is not None else [])
        # phase2: replay missing ops through the normal replica path
        term = primary_engine.primary_term
        for op in ops or []:
            if op.op_type == "index":
                replica.index_on_replica(op.doc_id, op.source, op.seq_no,
                                         term, op.version)
            elif op.op_type == "delete":
                replica.delete_on_replica(op.doc_id, op.seq_no, term,
                                          op.version)
        # finalize: mark in-sync, release the lease
        tracker.mark_in_sync(alloc, replica.engine.local_checkpoint)
        tracker.remove_lease(f"peer_recovery/{alloc}")
        self._advance_checkpoints()
        replica.refresh()
        return {"type": phase, "ops_replayed": len(ops or []),
                "global_checkpoint": self.global_checkpoint}

    # ---------------------------------------------------- segment replication

    def publish_checkpoint(self):
        """Primary refresh → push the new segment set to every replica
        (SegmentReplicationTargetService.onNewCheckpoint:192)."""
        if self.mode != "segment":
            return
        self._ckpt_version += 1
        engine = self.primary.engine
        ckpt = ReplicationCheckpoint(
            primary_term=engine.primary_term,
            segment_infos_version=self._ckpt_version,
            max_seq_no=engine.max_seq_no,
            local_checkpoint=engine.local_checkpoint)
        segs = list(engine.segments)
        for alloc, replica in list(self.replicas.items()):
            try:
                replica.engine.install_segments(
                    segs, max_seq_no=ckpt.max_seq_no,
                    local_checkpoint=ckpt.local_checkpoint)
                replica._sync_reader()
                self._tracker().update_local_checkpoint(
                    alloc, replica.engine.local_checkpoint)
            except Exception as e:
                self.fail_replica(replica, f"segment replication failed: {e}")
        self._advance_checkpoints()

    # ------------------------------------------------------ primary failover

    def promote_replica(self) -> IndexShard:
        """Primary failed: promote an in-sync replica (reference: replica
        promoted via in-sync allocation ids; new primary term; ops above the
        global checkpoint are rolled back/refilled on other copies)."""
        candidates = self.in_sync_replicas()
        if not candidates:
            raise ReplicationFailedError(
                "no in-sync copy available for promotion")
        new_primary = candidates[0]
        alloc = self._alloc(new_primary)
        old = self.primary
        self.primary = new_primary
        new_primary.primary = True
        new_primary.engine.primary_term += 1
        del self.replicas[alloc]
        self.failed[self._alloc(old)] = "primary failed"
        # rebuild tracker state on the new primary
        tracker = self._tracker()
        tracker.global_checkpoint = max(tracker.global_checkpoint,
                                        new_primary.engine.local_checkpoint)
        for a, replica in self.replicas.items():
            tracker.init_tracking(a)
            self.recover_replica(replica)
        return new_primary
