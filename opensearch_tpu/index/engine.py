"""The per-shard write engine: versioned indexing over immutable columnar segments.

Re-design of the reference InternalEngine (index/engine/InternalEngine.java):
- `index()` (:845) runs a versioning plan against the live version map
  (LiveVersionMap.java) — internal version increments, optimistic-concurrency
  via if_seq_no/if_primary_term, op_type=create conflict — assigns a seq_no
  (:823, via LocalCheckpointTracker), buffers the doc in the in-memory
  SegmentBuilder (the IndexWriter-RAM-buffer analog, :1098/:1177), and appends
  to the translog (Translog.java:540) before acking.
- `refresh()` seals the RAM buffer into an immutable columnar segment and
  uploads it to HBM — Lucene's refresh → new-segment-visible semantics:
  writes/deletes become searchable only at refresh.
- `flush()` = refresh + persist segments + commit point + translog roll/trim
  (the Lucene-commit analog via Store commit points).
- deletes/updates against sealed segments are buffered and applied to the
  liveness bitmaps at refresh (Lucene buffers deletes in the writer the same
  way); within one RAM buffer, later versions of a doc supersede earlier ords
  at seal.
- reopen after crash: load latest commit point, replay translog ops above the
  committed local checkpoint (recoverFromTranslog analog).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from opensearch_tpu.common.errors import VersionConflictError
from opensearch_tpu.index.mapper import MapperService
from opensearch_tpu.index.segment import Segment, SegmentBuilder, merge_segments
from opensearch_tpu.index.seqno import (
    NO_OPS_PERFORMED, LocalCheckpointTracker, ReplicationTracker)
from opensearch_tpu.index.store import Store
from opensearch_tpu.index.translog import Translog, TranslogOp
from opensearch_tpu.telemetry import INGEST_EVENTS, TELEMETRY

# write-path observability handles (ISSUE 13). The metrics registry is
# always-on by contract (one lock + a few float ops per REFRESH, never
# per query); the ingest recorder is OFF by default and `current()`
# tests its flag before touching thread-local state — the disabled
# index() path costs one attribute load and a branch.
_METRICS = TELEMETRY.metrics
_INGEST = TELEMETRY.ingest

_logger = logging.getLogger("opensearch_tpu.index.engine")


@dataclass
class VersionValue:
    """LiveVersionMap entry: last known version/seqno/term for a doc id."""
    version: int
    seq_no: int
    primary_term: int
    deleted: bool = False


@dataclass
class EngineResult:
    """Result of an index/delete op (reference Engine.IndexResult/DeleteResult)."""
    doc_id: str
    version: int
    seq_no: int
    primary_term: int
    created: bool = False
    found: bool = True


@dataclass
class GetResult:
    doc_id: str
    source: dict
    version: int
    seq_no: int
    primary_term: int


class InternalEngine:
    """Single-shard versioned write engine over columnar segments."""

    def __init__(self, mapper: MapperService, data_path: Optional[str] = None,
                 durability: str = "request", primary_term: int = 1,
                 allocation_id: str = "alloc_0",
                 merge_max_segments: int = 8):
        self.mapper = mapper
        self.primary_term = primary_term
        self.merge_max_segments = merge_max_segments
        # ISSUE 16 bounded merge windows: OFF by default (gate-lint row)
        # — the default engine keeps the one-shot merge-half policy.
        # When on, maybe_merge() runs incremental pair merges with the
        # segment rebuild OUTSIDE the engine lock, stopping after
        # merge_window_budget_ms so a merge never walls serving cores
        # for the full 234-389 ms the one-shot policy pays.
        self.merge_windowed = False
        self.merge_window_budget_ms = 25.0
        self._merge_active = False
        self._lock = threading.RLock()
        self._seg_counter = 0
        self._persisted: Set[str] = set()
        self.segments: List[Segment] = []          # sealed, search-visible
        self.builder = SegmentBuilder(mapper, self._next_seg_id())
        self._builder_ords: Dict[str, int] = {}    # doc_id → last builder ord
        self.version_map: Dict[str, VersionValue] = {}
        self.local_checkpoint_tracker = LocalCheckpointTracker()
        self.replication_tracker = ReplicationTracker(allocation_id,
                                                      primary_term)
        # sealed-segment deletes buffered until refresh (Lucene buffered deletes)
        self._pending_seal_deletes: List[str] = []
        self._dirty_live: Set[str] = set()  # segs whose live mask changed
        self._refresh_listeners: List = []
        # the IngestEventLog record of the last effective refresh/merge
        # (None when the last call was a no-op) — IndexShard joins its
        # churn record against it by event_id. THREAD-LOCAL: the shard
        # reads it on the same thread right after the call, and a
        # concurrent refresh/merge on another thread must not null or
        # swap the handle between an effective refresh and its read
        # (a mispaired event_id would corrupt the churn join).
        self._ingest_event_tls = threading.local()
        self.store: Optional[Store] = None
        self.translog: Optional[Translog] = None
        if data_path is not None:
            self.store = Store(os.path.join(data_path, "store"))
            self.translog = Translog(os.path.join(data_path, "translog"),
                                     durability=durability)
            self._recover_from_store()

    # ------------------------------------------------------------- plumbing

    def _next_seg_id(self) -> str:
        sid = f"s{self._seg_counter:06d}"
        self._seg_counter += 1
        return sid

    def add_refresh_listener(self, fn):
        """fn(new_segment | None, deleted_from: List[Segment]) on each refresh."""
        self._refresh_listeners.append(fn)

    def _notify_refresh_listeners(self, new_seg, deleted_from):
        """Run refresh listeners isolated per listener: a raising
        listener must not abort segment publish (the refresh already
        happened — segments are live) nor starve later listeners of the
        notification. Failures log typed and count on
        `indexing.refresh_listener_failures` (ISSUE 13 satellite)."""
        for fn in self._refresh_listeners:
            try:
                fn(new_seg, deleted_from)
            except Exception as e:  # except-ok: listener isolation -- segment publish already happened; one bad listener must not abort it or starve siblings
                _METRICS.counter(
                    "indexing.refresh_listener_failures").inc()
                _logger.warning(
                    "refresh listener %r failed: %s: %s",
                    getattr(fn, "__qualname__", fn),
                    type(e).__name__, e)

    @property
    def last_ingest_event(self) -> Optional[dict]:
        """This thread's last refresh/merge event record (None when the
        last call on this thread was a no-op)."""
        return getattr(self._ingest_event_tls, "event", None)

    @last_ingest_event.setter
    def last_ingest_event(self, ev: Optional[dict]) -> None:
        self._ingest_event_tls.event = ev

    @property
    def max_seq_no(self) -> int:
        return self.local_checkpoint_tracker.max_seq_no

    @property
    def local_checkpoint(self) -> int:
        return self.local_checkpoint_tracker.checkpoint

    # ------------------------------------------------------- versioning plan

    def _current_version(self, doc_id: str) -> Optional[VersionValue]:
        vv = self.version_map.get(doc_id)
        if vv is not None:
            return vv
        # fall back to sealed segments: doc_meta carries the persisted
        # (version, seq_no, term), so CAS keeps working after reopen
        for seg in reversed(self.segments):
            ord_ = seg.ord_of(doc_id)
            if ord_ is not None:
                meta = seg.doc_meta.get(doc_id)
                if meta is not None:
                    return VersionValue(*meta)
                return VersionValue(version=1, seq_no=NO_OPS_PERFORMED,
                                    primary_term=self.primary_term)
        return None

    def _plan_versioning(self, doc_id: str, op_type: str,
                         if_seq_no: Optional[int],
                         if_primary_term: Optional[int],
                         external_version: Optional[int]) -> Tuple[int, bool]:
        """Returns (new_version, created). Raises VersionConflictError."""
        cur = self._current_version(doc_id)
        exists = cur is not None and not cur.deleted
        if if_seq_no is not None or if_primary_term is not None:
            if not exists:
                raise VersionConflictError(
                    f"[{doc_id}]: version conflict, document does not exist")
            if ((if_seq_no is not None and cur.seq_no != if_seq_no) or
                    (if_primary_term is not None
                     and cur.primary_term != if_primary_term)):
                raise VersionConflictError(
                    f"[{doc_id}]: version conflict, required seqNo "
                    f"[{if_seq_no}], primary term [{if_primary_term}], "
                    f"current document has seqNo [{cur.seq_no}] and primary "
                    f"term [{cur.primary_term}]")
        if op_type == "create" and exists:
            raise VersionConflictError(
                f"[{doc_id}]: version conflict, document already exists "
                f"(current version [{cur.version}])")
        if external_version is not None:
            cur_v = cur.version if exists else 0
            if external_version <= cur_v:
                raise VersionConflictError(
                    f"[{doc_id}]: version conflict, current version [{cur_v}] "
                    f"is higher or equal to the one provided "
                    f"[{external_version}]")
            return external_version, not exists
        # a delete tombstone keeps the version chain alive (LiveVersionMap
        # retains tombstones for index.gc_deletes): re-create continues it
        return (cur.version + 1 if cur is not None else 1), not exists

    # ------------------------------------------------------------ operations

    def index(self, doc_id: str, source: dict, op_type: str = "index",
              if_seq_no: Optional[int] = None,
              if_primary_term: Optional[int] = None,
              version: Optional[int] = None,
              external_version: Optional[int] = None) -> EngineResult:
        """Primary-path indexing (InternalEngine.index :845).
        `version`/`external_version` are the same thing under both names
        the write path uses (REST ?version=N&version_type=external): the
        caller-assigned version that must exceed the current one."""
        if external_version is not None:
            version = external_version
        _METRICS.counter("indexing.ops").inc()
        # ingest lifecycle (telemetry/lifecycle.py): the thread-bound
        # timeline, None when the recorder is off — the disabled path
        # pays this one call + branch per op
        itl = _INGEST.current()
        with self._lock:
            # ONE copy of the write sequence — the timeline checkpoints
            # bracket it conditionally, so instrumented and plain runs
            # execute identical engine code (the off-differential pin)
            if itl is not None:
                t0 = time.perf_counter()
            new_version, created = self._plan_versioning(
                doc_id, op_type, if_seq_no, if_primary_term, version)
            seq_no = self.local_checkpoint_tracker.generate_seq_no()
            if itl is not None:
                t1 = time.perf_counter()
                itl.phase_add("version_plan", (t1 - t0) * 1000)
            self._do_index(doc_id, source, seq_no, new_version)
            if itl is not None:
                t2 = time.perf_counter()
                itl.phase_add("parse", (t2 - t1) * 1000)
            self._log_op(TranslogOp("index", seq_no, self.primary_term,
                                    doc_id=doc_id, source=source,
                                    version=new_version))
            if itl is not None:
                itl.phase_add("translog_append",
                              (time.perf_counter() - t2) * 1000)
            self.local_checkpoint_tracker.mark_processed(seq_no)
            self._sync_own_checkpoint()
            return EngineResult(doc_id, new_version, seq_no,
                                self.primary_term, created=created)

    def index_on_replica(self, doc_id: str, source: dict, seq_no: int,
                         primary_term: int, version: int) -> EngineResult:
        """Replica path: seq_no/version pre-assigned, no conflict checks
        (IndexShard.applyIndexOperationOnReplica → same engine, no versioning)."""
        with self._lock:
            self.local_checkpoint_tracker.advance_max_seq_no(seq_no)
            cur = self.version_map.get(doc_id)
            # out-of-order delivery: ignore ops older than what we've applied
            if cur is not None and cur.seq_no >= seq_no:
                self.local_checkpoint_tracker.mark_processed(seq_no)
                self._sync_own_checkpoint()
                return EngineResult(doc_id, cur.version, seq_no, primary_term)
            self._do_index(doc_id, source, seq_no, version)
            self._log_op(TranslogOp("index", seq_no, primary_term,
                                    doc_id=doc_id, source=source,
                                    version=version))
            self.local_checkpoint_tracker.mark_processed(seq_no)
            self._sync_own_checkpoint()
            return EngineResult(doc_id, version, seq_no, primary_term)

    def _do_index(self, doc_id: str, source: dict, seq_no: int, version: int):
        doc = self.mapper.parse_document(doc_id, source)
        ord_ = self.builder.add(doc)
        self._builder_ords[doc_id] = ord_
        # supersede any sealed copy at next refresh
        self._pending_seal_deletes.append(doc_id)
        self.version_map[doc_id] = VersionValue(version, seq_no,
                                                self.primary_term)

    def delete(self, doc_id: str, if_seq_no: Optional[int] = None,
               if_primary_term: Optional[int] = None,
               version: Optional[int] = None,
               external_version: Optional[int] = None) -> EngineResult:
        if external_version is not None:
            version = external_version
        with self._lock:
            cur = self._current_version(doc_id)
            found = cur is not None and not cur.deleted
            # same versioning plan as index (op_type "delete" never
            # create-conflicts); shares CAS + external-version checks
            new_version, _ = self._plan_versioning(
                doc_id, "delete", if_seq_no, if_primary_term, version)
            seq_no = self.local_checkpoint_tracker.generate_seq_no()
            self._do_delete(doc_id, seq_no, new_version)
            self._log_op(TranslogOp("delete", seq_no, self.primary_term,
                                    doc_id=doc_id, version=new_version))
            self.local_checkpoint_tracker.mark_processed(seq_no)
            self._sync_own_checkpoint()
            return EngineResult(doc_id, new_version, seq_no, self.primary_term,
                                found=found)

    def delete_on_replica(self, doc_id: str, seq_no: int, primary_term: int,
                          version: int) -> EngineResult:
        with self._lock:
            self.local_checkpoint_tracker.advance_max_seq_no(seq_no)
            cur = self.version_map.get(doc_id)
            if cur is not None and cur.seq_no >= seq_no:
                self.local_checkpoint_tracker.mark_processed(seq_no)
                self._sync_own_checkpoint()
                return EngineResult(doc_id, cur.version, seq_no, primary_term)
            self._do_delete(doc_id, seq_no, version)
            self._log_op(TranslogOp("delete", seq_no, primary_term,
                                    doc_id=doc_id, version=version))
            self.local_checkpoint_tracker.mark_processed(seq_no)
            self._sync_own_checkpoint()
            return EngineResult(doc_id, version, seq_no, primary_term)

    def _do_delete(self, doc_id: str, seq_no: int, version: int):
        self._builder_ords.pop(doc_id, None)
        self._pending_seal_deletes.append(doc_id)
        self.version_map[doc_id] = VersionValue(version, seq_no,
                                                self.primary_term, deleted=True)

    def noop(self, seq_no: int, primary_term: int, reason: str):
        """Seq-no gap filler (reference Engine.NoOp)."""
        with self._lock:
            self.local_checkpoint_tracker.advance_max_seq_no(seq_no)
            self._log_op(TranslogOp("noop", seq_no, primary_term,
                                    reason=reason))
            self.local_checkpoint_tracker.mark_processed(seq_no)
            self._sync_own_checkpoint()

    def _log_op(self, op: TranslogOp):
        if self.translog is not None:
            self.translog.add(op)

    def _sync_own_checkpoint(self):
        self.replication_tracker.update_local_checkpoint(
            self.replication_tracker.shard_allocation_id,
            self.local_checkpoint_tracker.checkpoint)

    # --------------------------------------------------------- realtime GET

    def get(self, doc_id: str, realtime: bool = True) -> Optional[GetResult]:
        """Realtime GET (reference index/get/ShardGetService.java): reads the
        version map + RAM buffer so un-refreshed writes are visible."""
        with self._lock:
            if realtime:
                vv = self.version_map.get(doc_id)
                if vv is not None:
                    if vv.deleted:
                        return None
                    ord_ = self._builder_ords.get(doc_id)
                    if ord_ is not None:
                        return GetResult(doc_id, self.builder.sources[ord_],
                                         vv.version, vv.seq_no, vv.primary_term)
                    # refreshed already: fall through to segments with known vv
                    for seg in reversed(self.segments):
                        o = seg.ord_of(doc_id)
                        if o is not None:
                            return GetResult(doc_id, seg.sources[o] or {},
                                             vv.version, vv.seq_no,
                                             vv.primary_term)
                    return None
            for seg in reversed(self.segments):
                o = seg.ord_of(doc_id)
                if o is not None:
                    version, seq_no, term = seg.doc_meta.get(
                        doc_id, (1, NO_OPS_PERFORMED, self.primary_term))
                    return GetResult(doc_id, seg.sources[o] or {}, version,
                                     seq_no, term)
            return None

    # ------------------------------------------------------- refresh / flush

    def refresh(self) -> Optional[Segment]:
        """Seal the RAM buffer; make buffered writes+deletes searchable.

        Instrumented (ISSUE 13): always-on metrics (docs sealed,
        segments in/out, seal wall, live-doc ratio), one IngestEventLog
        record per effective refresh (the flight recorder joins tail
        captures against it), and an engine-side span when tracing is
        on. The no-op case (empty buffer, no pending deletes) records
        nothing — a bench's per-op `refresh=true` probe must not flood
        the event log."""
        t0_mono = time.monotonic()
        span = TELEMETRY.tracer.start_trace("engine.refresh")
        try:
            new_seg, deleted_from = self._refresh_locked()
        except BaseException as e:  # except-ok: span lifecycle -- closes the engine span with error status, then always re-raises
            span.end(error=e)
            TELEMETRY.tracer.finish(span)
            raise
        self.last_ingest_event = None
        if new_seg is not None or deleted_from:
            t1_mono = time.monotonic()
            wall_ms = (t1_mono - t0_mono) * 1000
            docs = new_seg.num_docs if new_seg is not None else 0
            live = new_seg.live_doc_count if new_seg is not None else 0
            _METRICS.counter("indexing.refreshes").inc()
            _METRICS.counter("indexing.refresh_docs").inc(docs)
            _METRICS.histogram("indexing.refresh_ms").observe(wall_ms)
            self.last_ingest_event = INGEST_EVENTS.note(
                "refresh", t0_mono, t1_mono,
                seg_id=new_seg.seg_id if new_seg is not None else None,
                docs=docs,
                live_doc_ratio=round(live / docs, 4) if docs else None,
                segments=len(self.segments),
                deletes_applied=len(deleted_from))
            if span.recording:
                span.set_attribute("seg_id", new_seg.seg_id
                                   if new_seg is not None else None)
                span.set_attribute("docs", docs)
                span.set_attribute("deletes_applied", len(deleted_from))
            itl = _INGEST.current()
            if itl is not None:
                itl.phase_add("refresh", wall_ms)
        TELEMETRY.tracer.finish(span)
        return new_seg

    def _refresh_locked(self):
        """The seal proper; returns (new_segment | None, deleted_from)."""
        with self._lock:
            deleted_from: List[Segment] = []
            # apply buffered deletes/updates to sealed segments' live bitmaps
            if self._pending_seal_deletes:
                pending = set(self._pending_seal_deletes)
                for seg in self.segments:
                    hit = False
                    for did in pending:
                        if seg.delete(did):
                            hit = True
                    if hit:
                        deleted_from.append(seg)
                        self._dirty_live.add(seg.seg_id)
                self._pending_seal_deletes = []
            new_seg: Optional[Segment] = None
            if len(self.builder):
                new_seg = self.builder.seal()
                # within-buffer supersession: keep only the last ord per id,
                # and ids deleted after their last index. Nested child rows
                # (doc_id None) inherit their parent row's verdict — the
                # whole doc block lives or dies together.
                for ord_ in range(new_seg.num_docs):
                    did = new_seg.doc_ids[ord_]
                    if did is None:
                        continue
                    vv = self.version_map.get(did)
                    last = self._builder_ords.get(did)
                    if last != ord_ or (vv is not None and vv.deleted):
                        new_seg.live[ord_] = False
                    elif vv is not None:
                        new_seg.doc_meta[did] = (vv.version, vv.seq_no,
                                                 vv.primary_term)
                if new_seg.nested_paths:
                    child = new_seg.parent_ptr >= 0
                    new_seg.live[child] = \
                        new_seg.live[new_seg.parent_ptr[child]]
                self.segments.append(new_seg)
                self.builder = SegmentBuilder(self.mapper, self._next_seg_id())
                self._builder_ords = {}
            if new_seg is not None or deleted_from:
                self._notify_refresh_listeners(new_seg, deleted_from)
            return new_seg, deleted_from

    def flush(self) -> None:
        """Refresh + durable commit point + translog roll/trim
        (InternalEngine.flush → Lucene commit analog)."""
        t0_mono = time.monotonic()
        span = TELEMETRY.tracer.start_trace("engine.flush")
        try:
            persisted = self._flush_inner()
        except BaseException as e:  # except-ok: span lifecycle -- closes the engine span with error status, then always re-raises
            span.end(error=e)
            TELEMETRY.tracer.finish(span)
            raise
        t1_mono = time.monotonic()
        wall_ms = (t1_mono - t0_mono) * 1000
        _METRICS.counter("indexing.flushes").inc()
        _METRICS.histogram("indexing.flush_ms").observe(wall_ms)
        if persisted:
            INGEST_EVENTS.note("flush", t0_mono, t1_mono,
                               segments_persisted=persisted,
                               segments=len(self.segments))
        if span.recording:
            span.set_attribute("segments_persisted", persisted)
        TELEMETRY.tracer.finish(span)
        itl = _INGEST.current()
        if itl is not None:
            itl.phase_add("flush", wall_ms)

    def _flush_inner(self) -> int:
        """The commit proper; returns how many segments persisted."""
        with self._lock:
            self.refresh()
            if self.store is None:
                return 0
            persisted = 0
            for seg in self.segments:
                if seg.seg_id not in self._persisted:
                    self.store.write_segment(seg)
                    self._persisted.add(seg.seg_id)
                    persisted += 1
                elif seg.seg_id in self._dirty_live:
                    self.store.write_live_mask(seg)
            self._dirty_live.clear()
            tl_gen = (self.translog.roll_generation()
                      if self.translog is not None else 0)
            self.store.write_commit(
                generation=tl_gen,
                seg_ids=[s.seg_id for s in self.segments],
                local_checkpoint=self.local_checkpoint,
                max_seq_no=self.max_seq_no,
                translog_gen=tl_gen,
                extra={"seg_counter": self._seg_counter,
                       "primary_term": self.primary_term})
            if self.translog is not None:
                # ops ≤ committed checkpoint are recoverable from the store —
                # but retention leases pin older ops for ops-based peer
                # recovery (ReplicationTracker.min_retained_seq_no)
                self.translog.trim_below_seqno(
                    self.replication_tracker.min_retained_seq_no(),
                    max_gen=tl_gen)
            self.store.cleanup_unreferenced()
            return persisted

    def maybe_merge(self) -> Optional[Segment]:
        """Tiered-merge-lite (MergePolicyConfig/OpenSearchTieredMergePolicy
        analog): when sealed segments exceed the cap, merge the smallest half
        into one. Host-side rebuild; the merged segment replaces its inputs."""
        if self.merge_windowed:
            return self._maybe_merge_windowed()
        t0_mono = time.monotonic()
        span = TELEMETRY.tracer.start_trace("engine.merge")
        with self._lock:
            self.last_ingest_event = None
            if len(self.segments) <= self.merge_max_segments:
                TELEMETRY.tracer.finish(span)
                return None
            ranked = sorted(self.segments, key=lambda s: s.num_docs)
            victims = ranked[:max(2, len(ranked) // 2)]
            try:
                merged = merge_segments(self.mapper, victims,
                                        self._next_seg_id())
            except BaseException as e:  # except-ok: span lifecycle -- closes the engine span with error status, then always re-raises
                span.end(error=e)
                TELEMETRY.tracer.finish(span)
                raise
            victim_ids = {s.seg_id for s in victims}
            self.segments = [s for s in self.segments
                             if s.seg_id not in victim_ids]
            self.segments.append(merged)
            self._persisted -= victim_ids
            t1_mono = time.monotonic()
            wall_ms = (t1_mono - t0_mono) * 1000
            docs_in = sum(s.num_docs for s in victims)
            _METRICS.counter("indexing.merges").inc()
            _METRICS.counter("indexing.merge_docs").inc(merged.num_docs)
            _METRICS.histogram("indexing.merge_ms").observe(wall_ms)
            self.last_ingest_event = INGEST_EVENTS.note(
                "merge", t0_mono, t1_mono,
                seg_id=merged.seg_id,
                segments_in=len(victims),
                docs_in=docs_in,
                docs=merged.num_docs,
                live_doc_ratio=round(
                    merged.live_doc_count / merged.num_docs, 4)
                if merged.num_docs else None,
                segments=len(self.segments))
            if span.recording:
                span.set_attribute("seg_id", merged.seg_id)
                span.set_attribute("segments_in", len(victims))
                span.set_attribute("docs", merged.num_docs)
            self._notify_refresh_listeners(merged, [])
            TELEMETRY.tracer.finish(span)
            return merged

    def _maybe_merge_windowed(self) -> Optional[Segment]:
        """Incremental pair merges under a wall-clock budget. Each pass
        merges the two smallest sealed segments, rebuilding OUTSIDE the
        engine lock (writes keep landing), then re-acquires the lock to
        re-apply any deletes that raced the rebuild and atomically swap
        the pair for the merged segment. At least one pass runs whenever
        the cap is exceeded (so repeated calls converge); further passes
        run until the budget is spent or the cap is satisfied."""
        with self._lock:
            self.last_ingest_event = None
            if self._merge_active or \
                    len(self.segments) <= self.merge_max_segments:
                return None
            self._merge_active = True
        budget_s = self.merge_window_budget_ms / 1000.0
        t_window = time.monotonic()
        last_merged: Optional[Segment] = None
        _METRICS.counter("indexing.merge_windows").inc()
        try:
            while True:
                t0_mono = time.monotonic()
                with self._lock:
                    if len(self.segments) <= self.merge_max_segments:
                        break
                    ranked = sorted(self.segments,
                                    key=lambda s: s.num_docs)
                    victims = ranked[:2]
                    # live-mask snapshot: docs dead BEFORE the rebuild
                    # must NOT be re-applied afterwards — a superseded
                    # doc_id (dead in one victim, re-indexed live in the
                    # other) would have its live merged copy killed
                    pre_live = [np.asarray(v.live[:v.num_docs],
                                           bool).copy() for v in victims]
                    seg_id = self._next_seg_id()
                span = TELEMETRY.tracer.start_trace("engine.merge")
                try:
                    merged = merge_segments(self.mapper, victims, seg_id)
                except BaseException as e:  # except-ok: span lifecycle -- closes the engine span with error status, then always re-raises
                    span.end(error=e)
                    TELEMETRY.tracer.finish(span)
                    raise
                with self._lock:
                    victim_ids = {s.seg_id for s in victims}
                    current = {s.seg_id for s in self.segments}
                    if not victim_ids <= current:
                        # a concurrent install/merge replaced a victim
                        # while we rebuilt off-lock — abandon the pass
                        TELEMETRY.tracer.finish(span)
                        break
                    # deletes that landed on the victims during the
                    # off-lock rebuild: re-apply by doc_id (idempotent —
                    # a doc the rebuild already saw dead was never
                    # copied, so delete() is a no-op for it)
                    for v, was_live in zip(victims, pre_live):
                        now_dead = was_live & ~np.asarray(
                            v.live[:v.num_docs], bool)
                        for ord_ in np.nonzero(now_dead)[0]:
                            did = v.doc_ids[int(ord_)]
                            if did is not None:
                                merged.delete(did)
                    self.segments = [s for s in self.segments
                                     if s.seg_id not in victim_ids]
                    self.segments.append(merged)
                    self._persisted -= victim_ids
                    t1_mono = time.monotonic()
                    wall_ms = (t1_mono - t0_mono) * 1000
                    docs_in = sum(s.num_docs for s in victims)
                    _METRICS.counter("indexing.merges").inc()
                    _METRICS.counter("indexing.merge_docs").inc(
                        merged.num_docs)
                    _METRICS.histogram("indexing.merge_ms").observe(
                        wall_ms)
                    self.last_ingest_event = INGEST_EVENTS.note(
                        "merge", t0_mono, t1_mono,
                        seg_id=merged.seg_id,
                        segments_in=len(victims),
                        docs_in=docs_in,
                        docs=merged.num_docs,
                        live_doc_ratio=round(
                            merged.live_doc_count / merged.num_docs, 4)
                        if merged.num_docs else None,
                        segments=len(self.segments))
                    if span.recording:
                        span.set_attribute("seg_id", merged.seg_id)
                        span.set_attribute("segments_in", len(victims))
                        span.set_attribute("docs", merged.num_docs)
                    self._notify_refresh_listeners(merged, [])
                    TELEMETRY.tracer.finish(span)
                    last_merged = merged
                if time.monotonic() - t_window >= budget_s:
                    break
        finally:
            with self._lock:
                self._merge_active = False
        return last_merged

    def install_segments(self, segments: List[Segment], max_seq_no: int,
                         local_checkpoint: int):
        """Adopt a copied segment set (recovery phase1 / segment-replication
        checkpoint sync). Segments are immutable; sharing references is the
        in-process equivalent of the reference's file copy
        (RecoverySourceHandler.phase1 / SegmentReplicationTarget)."""
        with self._lock:
            # columns are immutable and safely shared; liveness (deletes
            # bitmap) and doc_meta are per-copy mutable state — clone them
            # so a later delete on this copy can't corrupt the source
            self.segments = [seg.clone_for_copy() for seg in segments]
            # advance the id counter past every installed segment id: a
            # fresh replica (counter≈1) adopting s000000..s00000N must not
            # mint a builder id that collides with an installed one —
            # flush would then skip persisting the new segment (id already
            # in _persisted) and silently lose docs
            for seg in self.segments:
                suffix = seg.seg_id.lstrip("s")
                if suffix.isdigit():
                    self._seg_counter = max(self._seg_counter,
                                            int(suffix) + 1)
            self.builder = SegmentBuilder(self.mapper, self._next_seg_id())
            self._builder_ords = {}
            self.version_map = {}
            # buffered ops/deletes predate the copied checkpoint: the
            # installed segments already reflect them
            self._pending_seal_deletes = []
            self.local_checkpoint_tracker = LocalCheckpointTracker(
                max_seq_no=max_seq_no, local_checkpoint=local_checkpoint)
            self._sync_own_checkpoint()
            self._notify_refresh_listeners(None, [])

    # --------------------------------------------------------------- reopen

    def _recover_from_store(self):
        commit = self.store.read_latest_commit()
        replay_from = 0
        if commit is not None:
            for sid in commit["segments"]:
                seg = self.store.read_segment(sid)
                self.segments.append(seg)
                self._persisted.add(sid)
            self._seg_counter = commit["extra"].get("seg_counter",
                                                    len(self.segments))
            self.builder = SegmentBuilder(self.mapper, self._next_seg_id())
            ckpt = commit["local_checkpoint"]
            # restore max_seq_no too: a gap above the checkpoint must not
            # cause reissued seq_nos colliding with committed ops
            self.local_checkpoint_tracker = LocalCheckpointTracker(
                max_seq_no=max(commit.get("max_seq_no", ckpt), ckpt),
                local_checkpoint=ckpt)
            replay_from = ckpt + 1
        if self.translog is not None:
            for op in self.translog.read_ops(from_seq_no=replay_from):
                self.local_checkpoint_tracker.advance_max_seq_no(op.seq_no)
                if op.op_type == "index":
                    self._do_index(op.doc_id, op.source, op.seq_no, op.version)
                elif op.op_type == "delete":
                    self._do_delete(op.doc_id, op.seq_no, op.version)
                self.local_checkpoint_tracker.mark_processed(op.seq_no)
        self._sync_own_checkpoint()

    def close(self):
        if self.translog is not None:
            self.translog.close()

    # ----------------------------------------------------------------- stats

    def stats(self) -> dict:
        with self._lock:
            live = sum(s.live_doc_count for s in self.segments)
            return {
                "docs": {"count": live + len(self.builder),
                         "deleted": sum(s.num_docs - s.live_doc_count
                                        for s in self.segments)},
                "segments": {"count": len(self.segments),
                             "memory_bytes": sum(s.memory_bytes()
                                                 for s in self.segments)},
                "seq_no": {"max_seq_no": self.max_seq_no,
                           "local_checkpoint": self.local_checkpoint,
                           "global_checkpoint":
                               self.replication_tracker.global_checkpoint},
                "translog": {"operations":
                             (self.translog.total_operations()
                              if self.translog else 0)},
            }
