"""Immutable columnar segment format — the TPU replacement for Lucene's file formats.

Reference behaviors re-designed here:
- Lucene postings lists (reference hot loop: search/internal/ContextIndexSearcher.java:260
  driving BulkScorer over per-term postings) become **blocked CSR** arrays: one
  global `[num_blocks, 128]` int32 doc-id matrix plus a parallel float32
  term-frequency matrix, padded with -1/0. A (field, term) entry in the term
  dictionary points at a contiguous run of blocks. A query gathers just its
  terms' block rows on device and scatter-adds BM25 partials into a dense
  per-doc score vector — turning Lucene's pointer-chasing skip lists into a
  dense, MXU/VPU-friendly batch computation.
- Lucene norms (SmallFloat-encoded doc lengths used by BM25Similarity) are kept
  bit-identical: `smallfloat_int_to_byte4` mirrors Lucene's
  `SmallFloat.intToByte4`, and scoring decodes through a 256-entry length
  table, so BM25 scores match Lucene's to float precision.
- Doc values (reference: index/fielddata/) become value-pair columns
  `(doc_ids[int32], values[float64])` per field — the scatter/segment-sum
  friendly layout for aggregations — plus a dense `exists` bitmap per field.
- Keyword fields get sorted ordinal dictionaries (reference:
  index/fielddata/ordinals/GlobalOrdinalsBuilder.java builds the same thing
  lazily; here ordinals are a seal-time artifact).

Segments are append-only and immutable after `seal()`, exactly like Lucene
segments; deletes are a liveness bitmap applied in the scoring kernels.
"""

from __future__ import annotations

import hashlib
import itertools
from dataclasses import dataclass, field as dc_field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from opensearch_tpu.index.mapper import MapperService, ParsedDocument

BLOCK = 128  # postings block width == TPU lane width

# ------------------------------------------------------------- SmallFloat ----

def smallfloat_int_to_byte4(i: int) -> int:
    """Lucene SmallFloat.intToByte4: lossy 8-bit encoding of a non-negative int.

    Values < 16 are exact; larger values keep 3 mantissa bits + implicit leading
    one, with the exponent biased by +1 in the high 5 bits.
    """
    if i < 0:
        raise ValueError(f"only supports positive values, got {i}")
    num_bits = i.bit_length()
    if num_bits < 4:
        return i
    shift = num_bits - 4
    encoded = (i >> shift) & 0x07
    encoded |= (shift + 1) << 3
    if encoded > 255:
        return 255
    return encoded


def smallfloat_byte4_to_int(b: int) -> int:
    """Inverse of intToByte4 (returns the quantization bucket's lower bound)."""
    bits = b & 0x07
    shift = (b >> 3) - 1
    if shift == -1:
        return bits
    return (bits | 0x08) << shift


# 256-entry doc-length decode table, identical to BM25Similarity.LENGTH_TABLE
LENGTH_TABLE = np.array([smallfloat_byte4_to_int(b) for b in range(256)],
                        dtype=np.float32)


def _pad_to(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple


def ident_pairs(col) -> bool:
    """True when a doc-value column's (doc, value) pairs are the identity
    layout (single-valued dense column: doc k <-> lane k, -1 tail). Device
    programs then SLICE or pad per-lane results into doc space instead of
    gathering/scattering — XLA's gather/scatter lower to scalar loops on
    CPU and a serial path on TPU, and these ops sit on every query's hot
    path.

    Memoized on the column: sealed columns are immutable, and this is
    called per range/terms clause compile (the O(n_pairs) scan must not
    run per query)."""
    cached = getattr(col, "_ident_pairs", None)
    if cached is not None:
        return cached
    d = col.doc_ids
    nv = int((d >= 0).sum())
    out = bool(np.array_equal(d[:nv], np.arange(nv, dtype=d.dtype))
               and (d[nv:] < 0).all())
    col._ident_pairs = out
    return out


def token_mask_rows(token_count: np.ndarray, t_bucket: int) -> np.ndarray:
    """Host mask of the real (non-padded) rows in a flattened [D*T, dims]
    token block — seal-time PQ trains only on real token vectors."""
    lanes = np.arange(t_bucket)[None, :] < token_count[:, None]
    return lanes.reshape(-1)


def pad_bucket(n: int, minimum: int = 128) -> int:
    """Round up to the next power-of-two bucket to bound jit recompiles."""
    size = max(minimum, 1)
    while size < n:
        size *= 2
    return size


# BM25 parameters the seal-time block bounds are computed against. Query-time
# k1/b/avgdl may differ (per-request similarity overrides, shard-level vs
# segment-level avgdl); the compiler ships a >=1 correction factor (bscale)
# derived from these constants, so the bounds stay upper bounds under any
# query parameters (search/compile.py:_blockmax_scale).
SEAL_K1 = 1.2
SEAL_B = 0.75

_BOUNDS_CHUNK_ROWS = 1 << 16    # bound host memory on multi-GB postings


def block_score_bounds(seg: "Segment") -> np.ndarray:
    """Per-posting-block BM25 score upper bounds: max over the block's lanes
    of tf/(tf + SEAL_K1·(1−SEAL_B+SEAL_B·dl/avgdl)), f32 [NB].

    The block-max skipping invariant (BM25S / Lucene BMW analog): every
    partial score a query can extract from block X of (field, term) is
    ≤ w·(k1+1)·bscale·bounds[X], so blocks whose summed upper bound falls
    below the competitive threshold provably hold no top-k docs. Fields
    without norms score with b=0 (denominator tf + k1), matching the
    query-side omit-norms path. Padding lanes (doc -1, tf 0) contribute 0.

    Memoized on the segment: sealed postings are immutable and this scans
    every lane once (chunked — NB can reach millions of rows at 10M docs).
    """
    cached = getattr(seg, "_block_bounds", None)
    if cached is not None:
        return cached
    nb = seg.post_docs.shape[0]
    bounds = np.zeros(nb, dtype=np.float32)
    # group the term dict's contiguous block runs by field: the denominator
    # constant c(dl) = 1−b+b·dl/avgdl is a per-field per-doc vector
    field_rows: Dict[str, List[np.ndarray]] = {}
    for (field, _term), tm in seg.term_dict.items():
        if tm.num_blocks:
            field_rows.setdefault(field, []).append(
                np.arange(tm.start_block, tm.start_block + tm.num_blocks,
                          dtype=np.int64))
    for field, runs in field_rows.items():
        norm = seg.norms.get(field)
        stats = seg.field_stats.get(field)
        if norm is not None and stats is not None and stats.doc_count > 0:
            avgdl = max(stats.sum_total_term_freq / stats.doc_count, 1e-9)
            dl = LENGTH_TABLE[norm]
            c_doc = (1.0 - SEAL_B + SEAL_B * dl / avgdl).astype(np.float32)
        else:
            c_doc = None        # omit-norms field: c ≡ 1
        rows = np.concatenate(runs)
        for lo in range(0, len(rows), _BOUNDS_CHUNK_ROWS):
            chunk = rows[lo:lo + _BOUNDS_CHUNK_ROWS]
            docs = seg.post_docs[chunk]
            tfs = seg.post_tf[chunk]
            if c_doc is None:
                c = np.float32(1.0)
            else:
                c = c_doc[np.where(docs >= 0, docs, 0)]
            g = tfs / (tfs + np.float32(SEAL_K1) * c)
            g[docs < 0] = 0.0
            bounds[chunk] = g.max(axis=1)
    seg._block_bounds = bounds
    return bounds


# ------------------------------------------------------------ data classes ---

@dataclass
class TermMeta:
    """Per-(field,term) postings metadata (Lucene TermState analog)."""
    doc_freq: int
    total_term_freq: int
    start_block: int
    num_blocks: int


@dataclass
class FieldStats:
    """Per text/keyword field collection stats feeding BM25 idf/avgdl.

    Reference: Lucene CollectionStatistics as consumed by BM25Similarity.
    """
    doc_count: int = 0            # docs containing the field
    sum_total_term_freq: int = 0  # total tokens across docs
    sum_doc_freq: int = 0


@dataclass
class DocValuesColumn:
    """Value-pair doc values for one field: sorted (doc, value) pairs.

    `value_ords` rank-encodes each value into `unique` (sorted distinct f64s).
    Device kernels only ever see int32 ranks — range bounds are converted to
    rank space host-side via searchsorted, keeping comparisons exact without
    f64 emulation on TPU. `unique` stays host-side; a float32 copy is uploaded
    for metric aggregations.
    """
    doc_ids: np.ndarray      # int32 [NV]
    values: np.ndarray       # float64 [NV] exact values (host only)
    exists: np.ndarray      # bool [D]
    counts: np.ndarray       # int32 [D] values per doc
    value_ords: np.ndarray   # int32 [NV] rank into `unique`
    unique: np.ndarray       # float64 [U] sorted distinct values (host)


@dataclass
class OrdinalsColumn:
    """Ordinal-encoded string doc values: sorted dictionary + (doc, ord) pairs."""
    doc_ids: np.ndarray      # int32 [NV]
    ords: np.ndarray         # int32 [NV]
    exists: np.ndarray       # bool [D]
    dictionary: List[str]    # ord → term, lexicographically sorted
    ord_hashes: np.ndarray   # uint64 [card] murmur-style hash per dictionary entry


@dataclass
class VectorColumn:
    vectors: np.ndarray      # float32 [D, dims]
    exists: np.ndarray       # bool [D]
    ivf: Any = None          # Optional[opensearch_tpu.ops.knn.IVFIndex]


@dataclass
class RankVectorsColumn:
    """Late-interaction multi-vector doc values (rank_vectors fields):
    one padded [T_bucket, dims] token matrix per doc, scored by the
    fused MaxSim kernels (ops/maxsim.py). `t_bucket` is the segment's
    power-of-two token bucket (pad_bucket of the longest stored doc,
    capped by the mapping's max_tokens bucket) so device executables
    key on the bucket, not the raw token count. PQ-compressed mappings
    additionally carry seal-trained uint8 codes + the codebook; the
    raw f32 matrices stay host-side for rescoring and differentials."""
    tokens: np.ndarray       # float32 [D, T_bucket, dims], padded lanes 0
    token_count: np.ndarray  # int32 [D] real tokens per doc
    exists: np.ndarray       # bool [D] doc has >= 1 token vector
    t_bucket: int
    codes: Optional[np.ndarray] = None      # uint8 [D, T_bucket, M]
    codebook: Optional[np.ndarray] = None   # float32 [M, 256, dsub]


_SEGMENT_UID = itertools.count(1)


class Segment:
    """A sealed, immutable columnar segment (host numpy representation)."""

    def __init__(self, seg_id: str, num_docs: int, doc_ids: List[str],
                 sources: List[Optional[dict]],
                 term_dict: Dict[Tuple[str, str], TermMeta],
                 post_docs: np.ndarray, post_tf: np.ndarray,
                 norms: Dict[str, np.ndarray],
                 field_stats: Dict[str, FieldStats],
                 numeric_dv: Dict[str, DocValuesColumn],
                 ordinal_dv: Dict[str, OrdinalsColumn],
                 vector_dv: Dict[str, VectorColumn],
                 positions: Optional[Dict[Tuple[str, str], List[np.ndarray]]] = None,
                 parent_ptr: Optional[np.ndarray] = None,
                 path_ords: Optional[np.ndarray] = None,
                 nested_paths: Optional[List[str]] = None,
                 rank_vectors_dv: Optional[Dict[str, RankVectorsColumn]] = None):
        self.seg_id = seg_id
        # process-unique identity: seg_id is a per-engine counter and can
        # repeat across indices/engines, so caches keyed on segments (e.g.
        # the SPMD HbmShardSet residency cache) must use `uid`
        self.uid = next(_SEGMENT_UID)
        self.num_docs = num_docs
        self.doc_ids = doc_ids              # _id per local doc ord
        self.sources = sources              # _source per local doc ord
        self.term_dict = term_dict
        self.post_docs = post_docs          # int32 [NB, BLOCK], -1 padded
        self.post_tf = post_tf              # float32 [NB, BLOCK]
        self.norms = norms                  # field → uint8 [D]
        self.field_stats = field_stats
        self.numeric_dv = numeric_dv
        self.ordinal_dv = ordinal_dv
        self.vector_dv = vector_dv
        self.rank_vectors_dv = rank_vectors_dv or {}
        # host-only term positions per (field, term), lists parallel to the
        # postings entries — consumed by the phrase-query host verifier
        # (reference: Lucene's .pos files feeding PhraseQuery's ExactPhraseMatcher)
        self.positions = positions or {}
        self.live = np.ones(num_docs, dtype=bool)  # deletes bitmap
        # doc-block structure (Lucene block-join layout): nested child rows
        # sit immediately before their parent row. parent_ptr[-1 for
        # roots]; path_ords indexes nested_paths (-1 for roots). Root-only
        # segments get the trivial all-root encoding.
        self.parent_ptr = parent_ptr if parent_ptr is not None \
            else np.full(num_docs, -1, dtype=np.int32)
        self.path_ords = path_ords if path_ords is not None \
            else np.full(num_docs, -1, dtype=np.int32)
        self.nested_paths = list(nested_paths or [])
        self.root = self.parent_ptr < 0
        self._id_to_ord = {d: i for i, d in enumerate(doc_ids)
                           if d is not None}
        # doc_id → (version, seq_no, primary_term) — Lucene stores these as
        # per-doc fields (_version docvalue, _seq_no); here a host-side map
        # attached by the engine at seal/merge time
        self.doc_meta: Dict[str, Tuple[int, int, int]] = {}

    @property
    def live_doc_count(self) -> int:
        return int(self.live.sum())

    def ord_of(self, doc_id: str) -> Optional[int]:
        ord_ = self._id_to_ord.get(doc_id)
        if ord_ is None or not self.live[ord_]:
            return None
        return ord_

    def delete(self, doc_id: str) -> bool:
        ord_ = self._id_to_ord.get(doc_id)
        if ord_ is None or not self.live[ord_]:
            return False
        self.live[ord_] = False
        if self.nested_paths:
            # the whole doc block dies with its root (Lucene deletes the
            # child docs of a block together with the parent)
            self.live[self.parent_ptr == ord_] = False
        return True

    def clone_for_copy(self) -> "Segment":
        """Shallow copy for recovery/segment-replication installs: immutable
        columns shared, mutable per-copy state (live bitmap, doc_meta)
        cloned — the in-memory analog of copying segment files while each
        copy keeps its own .liv deletes file."""
        import copy as _copy
        clone = _copy.copy(self)
        clone.uid = next(_SEGMENT_UID)
        clone.live = self.live.copy()
        clone.doc_meta = dict(self.doc_meta)
        return clone

    def __setstate__(self, state):
        # a segment arriving over the wire (recovery) carries the SENDER's
        # uid; re-mint locally so process-wide uniqueness holds
        self.__dict__.update(state)
        self.uid = next(_SEGMENT_UID)

    def get_term(self, field: str, term: str) -> Optional[TermMeta]:
        return self.term_dict.get((field, term))

    def _positions_for(self, field: str, term: str) -> Optional[Dict[int, np.ndarray]]:
        """doc ord → positions array for one term (host phrase matching)."""
        key = (field, term)
        pos_lists = self.positions.get(key)
        meta = self.term_dict.get(key)
        if pos_lists is None or meta is None:
            return None
        cache = getattr(self, "_pos_cache", None)
        if cache is None:
            cache = self._pos_cache = {}
        if key not in cache:
            docs = self.post_docs[
                meta.start_block:meta.start_block + meta.num_blocks].ravel()
            docs = docs[docs >= 0]
            cache[key] = {int(d): pos_lists[i] for i, d in enumerate(docs)}
        return cache[key]

    def terms_for_field(self, field: str) -> List[str]:
        return [t for (f, t) in self.term_dict if f == field]

    def memory_bytes(self) -> int:
        total = self.post_docs.nbytes + self.post_tf.nbytes
        for arr in self.norms.values():
            total += arr.nbytes
        for col in self.numeric_dv.values():
            total += (col.doc_ids.nbytes + col.values.nbytes + col.exists.nbytes
                      + col.counts.nbytes + col.value_ords.nbytes
                      + col.unique.nbytes)
        for col in self.ordinal_dv.values():
            total += (col.doc_ids.nbytes + col.ords.nbytes + col.exists.nbytes
                      + col.ord_hashes.nbytes)
        for col in self.vector_dv.values():
            total += col.vectors.nbytes + col.exists.nbytes
        for col in self.rank_vectors_dv.values():
            total += (col.tokens.nbytes + col.token_count.nbytes
                      + col.exists.nbytes)
            if col.codes is not None:
                total += col.codes.nbytes + col.codebook.nbytes
        for pos_lists in self.positions.values():
            total += sum(p.nbytes for p in pos_lists)
        return total


def _hash64(s: str) -> int:
    """Stable 64-bit hash for HLL cardinality (host-side, seal-time)."""
    return int.from_bytes(hashlib.blake2b(s.encode("utf-8"), digest_size=8).digest(),
                          "little")


# ------------------------------------------------------------ the builder ----

class SegmentBuilder:
    """In-memory segment under construction (Lucene IndexWriter's RAM buffer analog).

    Reference write path: index/engine/InternalEngine.java:1098 indexIntoLucene
    → IndexWriter.addDocument. Here documents accumulate host-side; `seal()`
    produces the immutable columnar arrays in one vectorized pass.
    """

    def __init__(self, mapper: MapperService, seg_id: str = "seg_0"):
        self.mapper = mapper
        self.seg_id = seg_id
        self.doc_ids: List[str] = []
        self.sources: List[Optional[dict]] = []
        # (field, term) → [(doc_ord, tf)] accumulated in insertion doc order
        self._postings: Dict[Tuple[str, str], List[Tuple[int, int]]] = {}
        self._positions: Dict[Tuple[str, str], List[np.ndarray]] = {}
        self._field_lengths: Dict[str, Dict[int, int]] = {}
        self._numeric: Dict[str, List[Tuple[int, float]]] = {}
        self._ordinal_raw: Dict[str, List[Tuple[int, str]]] = {}
        self._vectors: Dict[str, Dict[int, List[float]]] = {}
        self._rank_vectors: Dict[str, Dict[int, List[List[float]]]] = {}
        self._field_stats: Dict[str, FieldStats] = {}
        # doc-block structure (Lucene block-join layout: nested child rows
        # precede their parent row): parent row ord per row (-1 = root) and
        # nested-path ordinal per row (-1 = root)
        self._parent_ptr: List[int] = []
        self._path_ords: List[int] = []
        self._nested_paths: List[str] = []

    def __len__(self):
        return len(self.doc_ids)

    @property
    def num_docs(self):
        return len(self.doc_ids)

    def add(self, doc: ParsedDocument) -> int:
        child_ords = []
        for path, child_fields in getattr(doc, "children", ()):
            if path not in self._nested_paths:
                self._nested_paths.append(path)
            child_ords.append(self._add_row(
                None, None, child_fields,
                path_ord=self._nested_paths.index(path)))
        parent_ord = self._add_row(doc.doc_id, doc.source, doc.fields)
        for c in child_ords:
            self._parent_ptr[c] = parent_ord
        return parent_ord

    def _add_row(self, doc_id, source, fields,
                 path_ord: int = -1) -> int:
        ord_ = len(self.doc_ids)
        self.doc_ids.append(doc_id)
        self.sources.append(source)
        self._parent_ptr.append(-1)
        self._path_ords.append(path_ord)
        for field, pf in fields.items():
            ft = self.mapper.get_field(field)
            if ft is None:
                continue
            if pf.terms is not None and ft.index:
                tf_map: Dict[str, int] = {}
                pos_map: Dict[str, List[int]] = {}
                for term, pos in pf.terms:
                    tf_map[term] = tf_map.get(term, 0) + 1
                    pos_map.setdefault(term, []).append(pos)
                for term, tf in tf_map.items():
                    self._postings.setdefault((field, term), []).append((ord_, tf))
                    self._positions.setdefault((field, term), []).append(
                        np.asarray(sorted(pos_map[term]), dtype=np.int32))
                self._field_lengths.setdefault(field, {})[ord_] = pf.length
                stats = self._field_stats.setdefault(field, FieldStats())
                stats.doc_count += 1
                stats.sum_total_term_freq += pf.length
                stats.sum_doc_freq += len(tf_map)
            if pf.exact_values is not None:
                if ft.index:
                    seen = set()
                    for v in pf.exact_values:
                        if v not in seen:
                            seen.add(v)
                            self._postings.setdefault((field, v), []).append((ord_, 1))
                    stats = self._field_stats.setdefault(field, FieldStats())
                    stats.doc_count += 1
                    stats.sum_total_term_freq += len(pf.exact_values)
                    stats.sum_doc_freq += len(seen)
                if ft.doc_values and ft.has_ordinals:
                    for v in pf.exact_values:
                        self._ordinal_raw.setdefault(field, []).append((ord_, v))
            if pf.numeric_values is not None and ft.doc_values:
                for v in pf.numeric_values:
                    self._numeric.setdefault(field, []).append((ord_, v))
            if pf.vector is not None:
                self._vectors.setdefault(field, {})[ord_] = pf.vector
            if pf.token_vectors is not None:
                self._rank_vectors.setdefault(field, {})[ord_] = pf.token_vectors
        return ord_

    def seal(self) -> Segment:
        n_docs = len(self.doc_ids)

        # ---- postings: sort terms (field, term) for deterministic layout
        term_dict: Dict[Tuple[str, str], TermMeta] = {}
        block_rows_docs: List[np.ndarray] = []
        block_rows_tf: List[np.ndarray] = []
        next_block = 0
        for key in sorted(self._postings.keys()):
            plist = self._postings[key]  # already in ascending doc order
            docs = np.fromiter((d for d, _ in plist), dtype=np.int32, count=len(plist))
            tfs = np.fromiter((t for _, t in plist), dtype=np.float32, count=len(plist))
            padded = _pad_to(len(plist), BLOCK)
            docs_p = np.full(padded, -1, dtype=np.int32)
            tfs_p = np.zeros(padded, dtype=np.float32)
            docs_p[:len(plist)] = docs
            tfs_p[:len(plist)] = tfs
            nb = padded // BLOCK
            block_rows_docs.append(docs_p.reshape(nb, BLOCK))
            block_rows_tf.append(tfs_p.reshape(nb, BLOCK))
            term_dict[key] = TermMeta(doc_freq=len(plist),
                                      total_term_freq=int(tfs.sum()),
                                      start_block=next_block, num_blocks=nb)
            next_block += nb
        if block_rows_docs:
            post_docs = np.concatenate(block_rows_docs, axis=0)
            post_tf = np.concatenate(block_rows_tf, axis=0)
        else:
            post_docs = np.full((1, BLOCK), -1, dtype=np.int32)
            post_tf = np.zeros((1, BLOCK), dtype=np.float32)

        # ---- norms (SmallFloat-quantized field lengths)
        norms: Dict[str, np.ndarray] = {}
        for field, lengths in self._field_lengths.items():
            arr = np.zeros(n_docs, dtype=np.uint8)
            for ord_, length in lengths.items():
                arr[ord_] = smallfloat_int_to_byte4(length)
            norms[field] = arr

        # ---- numeric doc values as sorted (doc, value) pairs
        numeric_dv: Dict[str, DocValuesColumn] = {}
        for field, pairs in self._numeric.items():
            pairs.sort(key=lambda p: p[0])
            doc_arr = np.fromiter((d for d, _ in pairs), dtype=np.int32, count=len(pairs))
            val_arr = np.fromiter((v for _, v in pairs), dtype=np.float64, count=len(pairs))
            exists = np.zeros(n_docs, dtype=bool)
            if len(doc_arr):
                exists[doc_arr] = True
            counts = np.bincount(doc_arr, minlength=n_docs).astype(np.int32)
            unique, value_ords = np.unique(val_arr, return_inverse=True)
            numeric_dv[field] = DocValuesColumn(doc_arr, val_arr, exists, counts,
                                                value_ords.astype(np.int32), unique)

        # ---- ordinal doc values: sorted dictionary, (doc, ord) pairs
        ordinal_dv: Dict[str, OrdinalsColumn] = {}
        for field, pairs in self._ordinal_raw.items():
            dictionary = sorted({v for _, v in pairs})
            ord_of = {v: i for i, v in enumerate(dictionary)}
            pairs.sort(key=lambda p: p[0])
            doc_arr = np.fromiter((d for d, _ in pairs), dtype=np.int32, count=len(pairs))
            ords = np.fromiter((ord_of[v] for _, v in pairs), dtype=np.int32,
                               count=len(pairs))
            exists = np.zeros(n_docs, dtype=bool)
            if len(doc_arr):
                exists[doc_arr] = True
            hashes = np.array([_hash64(v) for v in dictionary], dtype=np.uint64) \
                if dictionary else np.zeros(0, dtype=np.uint64)
            ordinal_dv[field] = OrdinalsColumn(doc_arr, ords, exists, dictionary, hashes)

        # ---- vectors: dense [D, dims]; IVF built at seal for ANN mappings
        vector_dv: Dict[str, VectorColumn] = {}
        for field, rows in self._vectors.items():
            ft = self.mapper.get_field(field)
            mat = np.zeros((n_docs, ft.dims), dtype=np.float32)
            exists = np.zeros(n_docs, dtype=bool)
            for ord_, vec in rows.items():
                mat[ord_] = np.asarray(vec, dtype=np.float32)
                exists[ord_] = True
            col = VectorColumn(mat, exists)
            if ft.knn_method == "ivf" and int(exists.sum()) >= 256:
                from opensearch_tpu.ops.knn import build_ivf
                col.ivf = build_ivf(mat, exists, nlist=ft.knn_nlist,
                                    nprobe=ft.knn_nprobe)
            vector_dv[field] = col

        # ---- rank_vectors: padded [D, T_bucket, dims] token matrices with
        # token-count mask lanes; PQ mappings train their codebook at seal
        # (the Lucene-analog moment — expensive work happens once per
        # segment, never on the query path)
        rank_vectors_dv: Dict[str, RankVectorsColumn] = {}
        for field, rows in self._rank_vectors.items():
            ft = self.mapper.get_field(field)
            max_seen = max((len(toks) for toks in rows.values()), default=0)
            t_bucket = min(pad_bucket(max(max_seen, 1), minimum=8),
                           pad_bucket(ft.max_tokens, minimum=8))
            tokens = np.zeros((n_docs, t_bucket, ft.dims), dtype=np.float32)
            token_count = np.zeros(n_docs, dtype=np.int32)
            exists = np.zeros(n_docs, dtype=bool)
            for ord_, toks in rows.items():
                nt = len(toks)
                if nt:
                    tokens[ord_, :nt] = np.asarray(toks, dtype=np.float32)
                token_count[ord_] = nt
                exists[ord_] = nt > 0
            col = RankVectorsColumn(tokens, token_count, exists, t_bucket)
            if ft.compression == "pq":
                from opensearch_tpu.ops.maxsim import train_pq, encode_pq
                flat = tokens.reshape(-1, ft.dims)
                real = flat[token_mask_rows(token_count, t_bucket)]
                col.codebook = train_pq(real, ft.pq_m)
                codes = encode_pq(flat, col.codebook)
                col.codes = codes.reshape(n_docs, t_bucket, ft.pq_m)
            rank_vectors_dv[field] = col

        return Segment(self.seg_id, n_docs, list(self.doc_ids), list(self.sources),
                       term_dict, post_docs, post_tf, norms, self._field_stats,
                       numeric_dv, ordinal_dv, vector_dv,
                       positions=dict(self._positions),
                       parent_ptr=np.asarray(self._parent_ptr, np.int32),
                       path_ords=np.asarray(self._path_ords, np.int32),
                       nested_paths=list(self._nested_paths),
                       rank_vectors_dv=rank_vectors_dv)


def merge_segments(mapper: MapperService, segments: List[Segment],
                   seg_id: str) -> Segment:
    """Merge live docs of several segments into one (Lucene TieredMergePolicy's
    work product; reference: index/engine merges via IndexWriter).

    Round-trips through the builder with reconstructed ParsedDocuments parsed
    from _source — correctness-first; a zero-reparse columnar merge is a later
    optimization.
    """
    builder = SegmentBuilder(mapper, seg_id=seg_id)
    doc_meta = {}
    for seg in segments:
        for ord_ in range(seg.num_docs):
            if not seg.live[ord_] or seg.doc_ids[ord_] is None:
                # child rows re-expand from their root's _source reparse
                continue
            doc = mapper.parse_document(seg.doc_ids[ord_], seg.sources[ord_] or {})
            builder.add(doc)
            if seg.doc_ids[ord_] in seg.doc_meta:
                doc_meta[seg.doc_ids[ord_]] = seg.doc_meta[seg.doc_ids[ord_]]
    merged = builder.seal()
    merged.doc_meta = doc_meta
    return merged
