"""On-disk shard store: segment persistence, commit points, checksums.

Re-design of the reference Store (index/store/Store.java) + commit-point
handling (index/engine/CombinedDeletionPolicy.java): sealed columnar segments
are written as `.npz` array bundles plus a JSON sidecar for dictionaries, a
`.liv` numpy file mirrors Lucene's live-docs files (deletes applied after
seal), and a `segments_N.json` commit point lists the referenced files with
content checksums — the metadata-snapshot diffing that powers file-based peer
recovery (indices/recovery/RecoverySourceHandler.java:349 phase1) compares
exactly these checksums.
"""

from __future__ import annotations

import hashlib
import json
import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from opensearch_tpu.index.mapper import MapperService
from opensearch_tpu.index.segment import (
    DocValuesColumn, FieldStats, OrdinalsColumn, Segment, TermMeta, VectorColumn)


def _fsync_path(path: str):
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _file_checksum(path: str) -> str:
    h = hashlib.blake2b(digest_size=16)
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


@dataclass
class StoreFileMetadata:
    """Name + length + checksum (reference StoreFileMetadata)."""
    name: str
    length: int
    checksum: str


class Store:
    """Directory of segment files + commit points for one shard."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        # file-metadata cache: .npz/.meta.json are immutable once written,
        # .liv entries are refreshed by write_live_mask — so commits avoid
        # re-checksumming the whole store (O(delta), not O(store))
        self._file_cache: Dict[str, dict] = {}

    # ---------------------------------------------------------- segment io

    def _seg_paths(self, seg_id: str) -> Tuple[str, str, str]:
        base = os.path.join(self.directory, f"seg_{seg_id}")
        return base + ".npz", base + ".meta.json", base + ".liv.npy"

    def write_segment(self, seg: Segment):
        npz_path, meta_path, liv_path = self._seg_paths(seg.seg_id)
        arrays: Dict[str, np.ndarray] = {
            "post_docs": seg.post_docs, "post_tf": seg.post_tf,
        }
        for f, arr in seg.norms.items():
            arrays[f"norms::{f}"] = arr
        for f, col in seg.numeric_dv.items():
            arrays[f"ndv_docs::{f}"] = col.doc_ids
            arrays[f"ndv_vals::{f}"] = col.values
            arrays[f"ndv_exists::{f}"] = col.exists
            arrays[f"ndv_counts::{f}"] = col.counts
            arrays[f"ndv_ords::{f}"] = col.value_ords
            arrays[f"ndv_unique::{f}"] = col.unique
        for f, col in seg.ordinal_dv.items():
            arrays[f"odv_docs::{f}"] = col.doc_ids
            arrays[f"odv_ords::{f}"] = col.ords
            arrays[f"odv_exists::{f}"] = col.exists
            arrays[f"odv_hashes::{f}"] = col.ord_hashes
        ivf_meta = {}
        for f, col in seg.vector_dv.items():
            arrays[f"vec::{f}"] = col.vectors
            arrays[f"vec_exists::{f}"] = col.exists
            if col.ivf is not None:
                arrays[f"ivf_c::{f}"] = col.ivf.centroids
                arrays[f"ivf_l::{f}"] = col.ivf.lists
                arrays[f"ivf_bc::{f}"] = col.ivf.block_centroid
                ivf_meta[f] = {"nlist": col.ivf.nlist,
                               "nprobe": col.ivf.nprobe}
        # ragged positions → flat + offsets per (field, term)
        pos_keys: List[List[str]] = []
        pos_flat: List[np.ndarray] = []
        pos_offsets: List[int] = [0]
        pos_counts: List[int] = []
        for (f, t), plists in seg.positions.items():
            pos_keys.append([f, t])
            pos_counts.append(len(plists))
            for p in plists:
                pos_flat.append(p)
                pos_offsets.append(pos_offsets[-1] + len(p))
        arrays["pos_flat"] = (np.concatenate(pos_flat)
                              if pos_flat else np.zeros(0, np.int32))
        arrays["pos_offsets"] = np.asarray(pos_offsets, np.int64)
        np.savez_compressed(npz_path + ".tmp.npz", **arrays)
        _fsync_path(npz_path + ".tmp.npz")
        os.replace(npz_path + ".tmp.npz", npz_path)

        meta = {
            "seg_id": seg.seg_id,
            "num_docs": seg.num_docs,
            "doc_ids": seg.doc_ids,
            "sources": seg.sources,
            "term_dict": [[f, t, m.doc_freq, m.total_term_freq, m.start_block,
                           m.num_blocks] for (f, t), m in seg.term_dict.items()],
            "field_stats": {f: [s.doc_count, s.sum_total_term_freq, s.sum_doc_freq]
                            for f, s in seg.field_stats.items()},
            "ordinal_dicts": {f: col.dictionary
                              for f, col in seg.ordinal_dv.items()},
            "pos_keys": pos_keys,
            "pos_counts": pos_counts,
            "doc_meta": {d: list(m) for d, m in seg.doc_meta.items()},
            "ivf": ivf_meta,
        }
        tmp = meta_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(meta, fh, separators=(",", ":"))
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, meta_path)
        for path in (npz_path, meta_path):
            self._cache_file(path)
        self.write_live_mask(seg)

    def write_live_mask(self, seg: Segment):
        _, _, liv_path = self._seg_paths(seg.seg_id)
        np.save(liv_path + ".tmp.npy", seg.live)
        _fsync_path(liv_path + ".tmp.npy")
        os.replace(liv_path + ".tmp.npy", liv_path)
        self._cache_file(liv_path)

    def _cache_file(self, path: str):
        name = os.path.basename(path)
        self._file_cache[name] = {"name": name,
                                  "length": os.path.getsize(path),
                                  "checksum": _file_checksum(path)}

    def read_segment(self, seg_id: str) -> Segment:
        npz_path, meta_path, liv_path = self._seg_paths(seg_id)
        with open(meta_path, "r", encoding="utf-8") as fh:
            meta = json.load(fh)
        z = np.load(npz_path, allow_pickle=False)
        norms, numeric_dv, ordinal_dv, vector_dv = {}, {}, {}, {}
        for key in z.files:
            if key.startswith("norms::"):
                norms[key.split("::", 1)[1]] = z[key]
        ndv_fields = {k.split("::", 1)[1] for k in z.files
                      if k.startswith("ndv_docs::")}
        for f in ndv_fields:
            numeric_dv[f] = DocValuesColumn(
                z[f"ndv_docs::{f}"], z[f"ndv_vals::{f}"], z[f"ndv_exists::{f}"],
                z[f"ndv_counts::{f}"], z[f"ndv_ords::{f}"], z[f"ndv_unique::{f}"])
        for f, dictionary in meta["ordinal_dicts"].items():
            ordinal_dv[f] = OrdinalsColumn(
                z[f"odv_docs::{f}"], z[f"odv_ords::{f}"], z[f"odv_exists::{f}"],
                dictionary, z[f"odv_hashes::{f}"])
        vec_fields = {k.split("::", 1)[1] for k in z.files if k.startswith("vec::")}
        for f in vec_fields:
            col = VectorColumn(z[f"vec::{f}"], z[f"vec_exists::{f}"])
            if f in meta.get("ivf", {}):
                from opensearch_tpu.ops.knn import IVFIndex, build_ivf
                im = meta["ivf"][f]
                if f"ivf_bc::{f}" in z.files:
                    col.ivf = IVFIndex(z[f"ivf_c::{f}"], z[f"ivf_l::{f}"],
                                       z[f"ivf_bc::{f}"],
                                       nlist=im["nlist"],
                                       nprobe=im["nprobe"])
                else:
                    # pre-block-layout store (no block_centroid array and
                    # [nlist, max_len] lists): rebuild the IVF structure
                    # from the vectors instead of mis-reading old shapes
                    col.ivf = build_ivf(col.vectors, col.exists,
                                        nlist=im["nlist"],
                                        nprobe=im["nprobe"])
            vector_dv[f] = col
        term_dict = {(f, t): TermMeta(df, ttf, sb, nb)
                     for f, t, df, ttf, sb, nb in meta["term_dict"]}
        field_stats = {f: FieldStats(*vals)
                       for f, vals in meta["field_stats"].items()}
        positions: Dict[Tuple[str, str], List[np.ndarray]] = {}
        flat, offsets = z["pos_flat"], z["pos_offsets"]
        i = 0
        for (f, t), cnt in zip(meta["pos_keys"], meta["pos_counts"]):
            lists = [flat[offsets[i + j]:offsets[i + j + 1]] for j in range(cnt)]
            positions[(f, t)] = lists
            i += cnt
        seg = Segment(meta["seg_id"], meta["num_docs"], meta["doc_ids"],
                      meta["sources"], term_dict, z["post_docs"], z["post_tf"],
                      norms, field_stats, numeric_dv, ordinal_dv, vector_dv,
                      positions=positions)
        seg.doc_meta = {d: tuple(m)
                        for d, m in meta.get("doc_meta", {}).items()}
        if os.path.exists(liv_path):
            seg.live = np.load(liv_path)
        return seg

    def delete_segment_files(self, seg_id: str):
        for path in self._seg_paths(seg_id):
            if os.path.exists(path):
                os.remove(path)

    # -------------------------------------------------------- commit points

    def _commit_path(self, generation: int) -> str:
        return os.path.join(self.directory, f"segments_{generation}.json")

    def write_commit(self, generation: int, seg_ids: List[str],
                     local_checkpoint: int, max_seq_no: int,
                     translog_gen: int, extra: Optional[dict] = None):
        prev_commit = self.read_latest_commit()
        prev = {f["name"]: f for f in (prev_commit or {}).get("files", [])}
        files: List[dict] = []
        for sid in seg_ids:
            for path in self._seg_paths(sid):
                if not os.path.exists(path):
                    continue
                name = os.path.basename(path)
                entry = self._file_cache.get(name)
                if entry is None and not name.endswith(".liv.npy"):
                    # immutable segment file carried over from a previous
                    # commit (engine reopened): reuse its recorded checksum
                    entry = prev.get(name)
                if entry is None:
                    self._cache_file(path)
                    entry = self._file_cache[name]
                files.append(entry)
        commit = {
            "generation": generation, "segments": seg_ids,
            "local_checkpoint": local_checkpoint, "max_seq_no": max_seq_no,
            "translog_generation": translog_gen,
            "files": files, "extra": extra or {},
        }
        tmp = self._commit_path(generation) + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(commit, fh)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, self._commit_path(generation))
        # drop older commit points (CombinedDeletionPolicy keeps the latest)
        for name in os.listdir(self.directory):
            if name.startswith("segments_") and name.endswith(".json"):
                gen = int(name[len("segments_"):-len(".json")])
                if gen < generation:
                    os.remove(os.path.join(self.directory, name))

    def read_latest_commit(self) -> Optional[dict]:
        best: Optional[Tuple[int, str]] = None
        for name in os.listdir(self.directory):
            if name.startswith("segments_") and name.endswith(".json"):
                gen = int(name[len("segments_"):-len(".json")])
                if best is None or gen > best[0]:
                    best = (gen, name)
        if best is None:
            return None
        with open(os.path.join(self.directory, best[1]), "r",
                  encoding="utf-8") as fh:
            return json.load(fh)

    def metadata_snapshot(self) -> Dict[str, StoreFileMetadata]:
        """Checksummed file listing of the latest commit (recovery diffing)."""
        commit = self.read_latest_commit()
        if commit is None:
            return {}
        return {f["name"]: StoreFileMetadata(f["name"], f["length"], f["checksum"])
                for f in commit["files"]}

    def cleanup_unreferenced(self):
        commit = self.read_latest_commit()
        if commit is None:
            return
        live = {f["name"] for f in commit["files"]}
        for name in os.listdir(self.directory):
            if name.startswith("seg_") and name not in live:
                os.remove(os.path.join(self.directory, name))
