"""Device-resident segment: the HBM image of a sealed columnar segment.

This is the TPU analog of Lucene's on-heap/off-heap segment readers
(reference: the SegmentReader/LeafReaderContext machinery consumed by
search/internal/ContextIndexSearcher.java). All arrays are padded to
power-of-two buckets so differently-sized segments reuse the same compiled
executable (XLA recompiles per shape — bucketing bounds the compile count).

Layout:
- `post_docs`/`post_tf`: the global blocked postings matrices `[NBp, 128]`.
- `norms`: stacked `[F, Dp]` uint8 SmallFloat norms, one row per indexed text
  field (row index assigned in `DeviceSegmentMeta.norm_rows`).
- numeric doc values per field: `(doc_ids, val_ords, values_f32)` value-pair
  arrays (pad doc_id = -1) + dense `exists`, `min_rank`/`max_rank` per doc for
  sorting and can-match pruning.
- ordinal (keyword) doc values per field: `(doc_ids, ords)` pairs + `exists`.
- vectors per field: dense `[Dp, dims]` float32.
- `live`: deletion bitmap, AND-ed into every match mask.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Dict, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from opensearch_tpu.index.segment import (LENGTH_TABLE, Segment,
                                          block_score_bounds, pad_bucket)

INT32_MAX = np.int32(2 ** 31 - 1)
_F32_MAX = float(np.finfo(np.float32).max)

# ISSUE 16 delta publish: when ON, publish_segment() ships only the
# populated prefix of every padded column to the device and expands it
# to the padded bucket on-chip (jnp.full + .at[].set under jit) — the
# resident image is byte-identical to a full upload_segment(), but the
# host→device transfer (and the churn ledger's upload.corpus bytes) is
# proportional to real data, not the power-of-two bucket. OFF by
# default: the default write path is exactly upload_segment().
DELTA_PUBLISH = False


def _to_f32_finite(values: np.ndarray) -> np.ndarray:
    """float64 → float32 with saturation instead of overflow-to-inf: range
    fields store an unbounded-side sentinel (mapper.RANGE_UNBOUNDED = 1e308)
    that must stay finite on device so metric kernels over the decode tables
    never see inf."""
    return np.clip(values, -_F32_MAX, _F32_MAX).astype(np.float32)


@dataclass(frozen=True)
class DeviceSegmentMeta:
    """Static (hashable) shape/layout info — safe to close over in jit."""
    seg_id: str
    num_docs: int
    d_pad: int
    nb_pad: int
    norm_rows: Tuple[Tuple[str, int], ...]   # field → row in norms stack
    numeric_fields: Tuple[str, ...]
    ordinal_fields: Tuple[str, ...]
    vector_fields: Tuple[str, ...]
    # (field, token_bucket, compression) per rank_vectors field — the
    # token bucket and storage variant are executable-shaping facts, so
    # they live in the compile key, not just the runtime array shapes
    rank_vector_fields: Tuple[Tuple[str, int, str], ...] = ()
    # seal-time per-block score bounds leaf (ISSUE 20 block-max pruning):
    # always present in the image ([nb_pad] f32 rides next to the block
    # metadata, ~0.4% of the postings bytes) so flipping the query-time
    # gate never forces a re-upload; part of the compile key because the
    # leaf's existence shapes every traced program's input tree
    block_bounds: bool = True

    def norm_row(self, field: str) -> Optional[int]:
        for f, r in self.norm_rows:
            if f == field:
                return r
        return None

    def compile_key(self) -> tuple:
        """Everything a compiled program closes over, seg_id EXCLUDED —
        seg_id is pure identity metadata, never read in traced code, so
        two segments equal on this key (plus equal runtime arg shapes)
        share every compiled executable. Keying the executor's JIT
        cache on this instead of the whole meta is what lets a freshly
        refreshed segment land in an already-compiled (plan-struct,
        shape-bucket) family instead of paying a per-segment XLA
        recompile (ISSUE 13 / ROADMAP item 5: incremental segment
        publish without cold recompiles)."""
        return (self.num_docs, self.d_pad, self.nb_pad, self.norm_rows,
                self.numeric_fields, self.ordinal_fields,
                self.vector_fields, self.rank_vector_fields,
                self.block_bounds)


def upload_segment(seg: Segment, to_device: bool = True):
    """Build the device pytree (dict of jnp arrays) + static meta for a segment."""
    d_pad = pad_bucket(max(seg.num_docs, 1))
    nb = seg.post_docs.shape[0]
    nb_pad = pad_bucket(nb, minimum=8)

    post_docs = np.full((nb_pad, seg.post_docs.shape[1]), -1, dtype=np.int32)
    post_docs[:nb] = seg.post_docs
    post_tf = np.zeros((nb_pad, seg.post_tf.shape[1]), dtype=np.float32)
    post_tf[:nb] = seg.post_tf
    # seal-time per-block score upper bounds (block-max pruning, ISSUE 20):
    # [nb_pad] f32 next to the block matrices; padding blocks bound 0
    post_bound = np.zeros(nb_pad, dtype=np.float32)
    post_bound[:nb] = block_score_bounds(seg)

    norm_fields = sorted(seg.norms.keys())
    norms = np.zeros((max(len(norm_fields), 1), d_pad), dtype=np.int32)
    for row, fname in enumerate(norm_fields):
        norms[row, :seg.num_docs] = seg.norms[fname]

    live = np.zeros(d_pad, dtype=bool)
    live[:seg.num_docs] = seg.live

    # doc-block structure for nested queries: root mask (top-level rows —
    # the only rows a search may return), parent row pointer, nested-path
    # ordinal (segment.py block-join layout). Root-only segments carry the
    # trivial encoding so all segments share one array layout.
    root = np.zeros(d_pad, dtype=bool)
    root[:seg.num_docs] = getattr(seg, "root",
                                  np.ones(seg.num_docs, bool))
    parent_ptr = np.full(d_pad, -1, dtype=np.int32)
    parent_ptr[:seg.num_docs] = getattr(
        seg, "parent_ptr", np.full(seg.num_docs, -1, np.int32))
    nested_path = np.full(d_pad, -1, dtype=np.int32)
    nested_path[:seg.num_docs] = getattr(
        seg, "path_ords", np.full(seg.num_docs, -1, np.int32))

    arrays: Dict = {
        "post_docs": post_docs,
        "post_tf": post_tf,
        "post_bound": post_bound,
        "norms": norms,
        "length_table": LENGTH_TABLE,
        "live": live,
        "root": root,
        "parent_ptr": parent_ptr,
        "nested_path": nested_path,
        "numeric": {},
        "ordinal": {},
        "vector": {},
        "rank_vectors": {},
    }

    for fname, col in seg.numeric_dv.items():
        nv_pad = pad_bucket(max(len(col.doc_ids), 1))
        doc_ids = np.full(nv_pad, -1, dtype=np.int32)
        doc_ids[:len(col.doc_ids)] = col.doc_ids
        val_ords = np.zeros(nv_pad, dtype=np.int32)
        val_ords[:len(col.doc_ids)] = col.value_ords
        values_f32 = np.zeros(nv_pad, dtype=np.float32)
        values_f32[:len(col.doc_ids)] = _to_f32_finite(col.values)
        exists = np.zeros(d_pad, dtype=bool)
        exists[:seg.num_docs] = col.exists
        min_rank = np.full(d_pad, INT32_MAX, dtype=np.int32)
        max_rank = np.full(d_pad, -1, dtype=np.int32)
        if len(col.doc_ids):
            np.minimum.at(min_rank, col.doc_ids, col.value_ords)
            np.maximum.at(max_rank, col.doc_ids, col.value_ords)
        # rank → value decode table (f32) for device-side metric aggregations
        u_pad = pad_bucket(max(len(col.unique), 1), minimum=8)
        unique_f32 = np.zeros(u_pad, dtype=np.float32)
        unique_f32[:len(col.unique)] = _to_f32_finite(col.unique)
        arrays["numeric"][fname] = {
            "doc_ids": doc_ids, "val_ords": val_ords, "values_f32": values_f32,
            "exists": exists, "min_rank": min_rank, "max_rank": max_rank,
            "unique_f32": unique_f32,
        }

    for fname, col in seg.ordinal_dv.items():
        nv_pad = pad_bucket(max(len(col.doc_ids), 1))
        doc_ids = np.full(nv_pad, -1, dtype=np.int32)
        doc_ids[:len(col.doc_ids)] = col.doc_ids
        ords = np.zeros(nv_pad, dtype=np.int32)
        ords[:len(col.doc_ids)] = col.ords
        exists = np.zeros(d_pad, dtype=bool)
        exists[:seg.num_docs] = col.exists
        arrays["ordinal"][fname] = {
            "doc_ids": doc_ids, "ords": ords, "exists": exists,
        }

    for fname, col in seg.vector_dv.items():
        vecs = np.zeros((d_pad, col.vectors.shape[1]), dtype=np.float32)
        vecs[:seg.num_docs] = col.vectors
        exists = np.zeros(d_pad, dtype=bool)
        exists[:seg.num_docs] = col.exists
        entry = {"vectors": vecs, "exists": exists}
        if col.ivf is not None:
            from opensearch_tpu.ops.knn import pack_ivf_lists
            packed, flat_ids = pack_ivf_lists(col.vectors, col.ivf.lists)
            entry["ivf_centroids"] = col.ivf.centroids
            entry["ivf_block_centroid"] = col.ivf.block_centroid
            entry["ivf_packed_vecs"] = packed
            entry["ivf_packed_ids"] = flat_ids
        arrays["vector"][fname] = entry

    # rank_vectors (late-interaction token matrices): docs axis padded to
    # d_pad like every dense column; the token axis keeps the segment's
    # power-of-two bucket from seal. PQ mappings ship codes + codebook
    # instead of the raw f32 matrices (the kernel decodes in-register).
    rank_vector_fields = []
    for fname, col in sorted(getattr(seg, "rank_vectors_dv", {}).items()):
        token_count = np.zeros(d_pad, dtype=np.int32)
        token_count[:seg.num_docs] = col.token_count
        exists = np.zeros(d_pad, dtype=bool)
        exists[:seg.num_docs] = col.exists
        entry = {"token_count": token_count, "exists": exists}
        if col.codes is not None:
            codes = np.zeros((d_pad,) + col.codes.shape[1:], dtype=np.uint8)
            codes[:seg.num_docs] = col.codes
            entry["codes"] = codes
            entry["codebook"] = col.codebook
            compression = "pq"
        else:
            tokens = np.zeros((d_pad,) + col.tokens.shape[1:], dtype=np.float32)
            tokens[:seg.num_docs] = col.tokens
            entry["tokens"] = tokens
            compression = "none"
        arrays["rank_vectors"][fname] = entry
        rank_vector_fields.append((fname, col.t_bucket, compression))

    if to_device:
        arrays = _tree_to_jnp(arrays)

    meta = DeviceSegmentMeta(
        seg_id=seg.seg_id,
        num_docs=seg.num_docs,
        d_pad=d_pad,
        nb_pad=nb_pad,
        norm_rows=tuple((f, i) for i, f in enumerate(norm_fields)),
        numeric_fields=tuple(sorted(seg.numeric_dv.keys())),
        ordinal_fields=tuple(sorted(seg.ordinal_dv.keys())),
        vector_fields=tuple(sorted(seg.vector_dv.keys())),
        rank_vector_fields=tuple(rank_vector_fields),
    )
    return arrays, meta


def _tree_to_jnp(tree):
    if isinstance(tree, dict):
        return {k: _tree_to_jnp(v) for k, v in tree.items()}
    return jnp.asarray(tree)


def tree_nbytes(tree) -> int:
    """Total array bytes in a device pytree (dict-of-dicts-of-arrays) —
    `nbytes` is shape·itemsize metadata on both numpy and jax arrays, so
    this never forces a device sync. Feeds the transfer ledger's
    `upload.corpus` channel and the corpus-columns memory gauge."""
    if isinstance(tree, dict):
        return sum(tree_nbytes(v) for v in tree.values())
    return int(getattr(tree, "nbytes", 0))


def _compact_spec(seg: Segment, meta: DeviceSegmentMeta) -> Dict[tuple, tuple]:
    """Tree-path → ((compact extents, None = full axis), pad fill) for
    every leaf whose padded tail is a constant fill. Leaves absent from
    the spec (length_table, ivf_* packings) transfer in full."""
    nd = seg.num_docs
    nb = seg.post_docs.shape[0]
    # postings width is sized to the DOC pad bucket by the builder, but
    # a term's doc list can never exceed num_docs — on a small segment
    # (the refresh-churn case) the width axis is almost all fill, and
    # it is the dominant share of the padded image
    spec: Dict[tuple, tuple] = {
        ("post_docs",): ((nb, nd), -1),
        ("post_tf",): ((nb, nd), 0.0),
        ("post_bound",): ((nb,), 0.0),
        ("norms",): ((None, nd), 0),
        ("live",): ((nd,), False),
        ("root",): ((nd,), False),
        ("parent_ptr",): ((nd,), -1),
        ("nested_path",): ((nd,), -1),
    }
    for fname, col in seg.numeric_dv.items():
        nv = len(col.doc_ids)
        spec[("numeric", fname, "doc_ids")] = ((nv,), -1)
        spec[("numeric", fname, "val_ords")] = ((nv,), 0)
        spec[("numeric", fname, "values_f32")] = ((nv,), 0.0)
        spec[("numeric", fname, "exists")] = ((nd,), False)
        # minimum.at/maximum.at only touch rows < num_docs, so the
        # padded tail keeps the initial fill
        spec[("numeric", fname, "min_rank")] = ((nd,), int(INT32_MAX))
        spec[("numeric", fname, "max_rank")] = ((nd,), -1)
        spec[("numeric", fname, "unique_f32")] = ((len(col.unique),), 0.0)
    for fname, col in seg.ordinal_dv.items():
        nv = len(col.doc_ids)
        spec[("ordinal", fname, "doc_ids")] = ((nv,), -1)
        spec[("ordinal", fname, "ords")] = ((nv,), 0)
        spec[("ordinal", fname, "exists")] = ((nd,), False)
    for fname in seg.vector_dv:
        spec[("vector", fname, "vectors")] = ((nd, None), 0.0)
        spec[("vector", fname, "exists")] = ((nd,), False)
    for fname, col in getattr(seg, "rank_vectors_dv", {}).items():
        spec[("rank_vectors", fname, "token_count")] = ((nd,), 0)
        spec[("rank_vectors", fname, "exists")] = ((nd,), False)
        if col.codes is not None:
            spec[("rank_vectors", fname, "codes")] = ((nd, None, None), 0)
            # codebook is query-shaped, not doc-shaped: full transfer
        else:
            spec[("rank_vectors", fname, "tokens")] = ((nd, None, None), 0.0)
    return spec


_EXPAND_CACHE: Dict[tuple, object] = {}


def _expand_fn(compact_shape: tuple, full_shape: tuple, fill, dtype_str: str):
    """Compiled on-device expansion: fill-pad a compact prefix block out
    to the padded bucket shape. Cached per (shapes, fill, dtype) family —
    compact extents are power-of-two bucketed by the caller so this stays
    a bounded set of executables, not one per document count.

    The explicit miss/hit split (vs the old lru_cache) exists for the
    compile-event discipline (ISSUE 19): the MISS returns the shared
    first-call timer — so the expander's XLA compile reaches
    `search.xla_compile_ms` / `xla_cache_miss` and the executable
    census like every executor jit site — while hits return the raw
    executable, paying nothing."""
    key = (compact_shape, full_shape, fill, dtype_str)
    fn = _EXPAND_CACHE.get(key)
    if fn is not None:
        return fn

    def expand(x):
        out = jnp.full(full_shape, fill, dtype=dtype_str)
        return out.at[tuple(slice(0, s) for s in compact_shape)].set(x)

    fn = jax.jit(expand)
    _EXPAND_CACHE[key] = fn  # shared-state-ok: benign double-jit race; dict slot write is GIL-atomic
    from opensearch_tpu.telemetry.kernels import timed_first_call
    nbytes = float(np.prod(full_shape)) * np.dtype(dtype_str).itemsize \
        if full_shape else float(np.dtype(dtype_str).itemsize)
    return timed_first_call(
        fn, family="expand",
        shape="x".join(str(s) for s in full_shape) or "scalar", key=key,
        cost=(float(np.prod(full_shape) if full_shape else 1), nbytes))


def _delta_tree(host, spec: Dict[tuple, tuple], transferred: list,
                path: tuple = ()):
    """Walk the host pytree; ship each specced leaf as its compact prefix
    + on-device expansion, everything else in full. `transferred[0]`
    accumulates actual host→device bytes."""
    if isinstance(host, dict):
        return {k: _delta_tree(v, spec, transferred, path + (k,))
                for k, v in host.items()}
    full = tuple(int(s) for s in host.shape)
    entry = spec.get(path)
    if entry is not None:
        raw, fill = entry
        # bucket the compact extents so the expansion executables form a
        # bounded power-of-two family (same trick as pad_bucket itself)
        cshape = tuple(
            f if c is None else min(pad_bucket(max(int(c), 1), minimum=8), f)
            for c, f in zip(raw, full))
        if cshape != full:
            compact = np.ascontiguousarray(
                host[tuple(slice(0, s) for s in cshape)])
            transferred[0] += int(compact.nbytes)
            return _expand_fn(cshape, full, fill,
                              str(host.dtype))(jnp.asarray(compact))
    transferred[0] += int(host.nbytes)
    return jnp.asarray(host)


def publish_segment(seg: Segment, to_device: bool = True):
    """upload_segment + transfer accounting: returns (arrays, meta,
    transfer_nbytes). With DELTA_PUBLISH off (the default) this is
    exactly upload_segment and the transfer equals the resident image;
    with it on, only the populated prefixes cross the host→device link
    and transfer_nbytes is the byte-exact compact total."""
    if not DELTA_PUBLISH or not to_device:
        arrays, meta = upload_segment(seg, to_device=to_device)
        return arrays, meta, tree_nbytes(arrays)
    host, meta = upload_segment(seg, to_device=False)
    spec = _compact_spec(seg, meta)
    transferred = [0]
    arrays = _delta_tree(host, spec, transferred)
    return arrays, meta, transferred[0]


def refresh_live(arrays: Dict, seg: Segment):
    """Re-upload just the liveness bitmap after deletes."""
    d_pad = arrays["live"].shape[0]
    live = np.zeros(d_pad, dtype=bool)
    live[:seg.num_docs] = seg.live
    arrays["live"] = jnp.asarray(live) if isinstance(arrays["post_docs"], jnp.ndarray) \
        else live
    return arrays
