"""Late-interaction MaxSim kernels: tiled exact scoring and fused-PQ ADC.

ColBERT-style scoring (arxiv 1707.08275): a doc stores one vector per
token, a query brings one vector per query token, and the doc score is

    score(doc) = sum_t  max_s  q_t . d_s

over query tokens t and doc tokens s. The reference ecosystem serves
this from CPU/GPU ANN libraries; here both storage layouts are
TPU-native, shaped by FLASH-MAXSIM (arxiv 2605.29517) and TileMaxSim
(arxiv 2606.26439):

- **Exact**: per-doc token matrices live as one padded [D, T, dims] f32
  block. The kernel walks the dims axis in MXU-friendly tiles
  (DIM_TILE lanes at a time) accumulating partial dot products, so the
  working set per step is the [D*T, tile] slab — the dimension-tiling
  loop TileMaxSim shows is what keeps HBM traffic linear in dims.
  Padded token lanes (s >= token_count) are masked to -inf BEFORE the
  max so they can never win; zero-token docs score 0 and stay
  ineligible via the exists mask.
- **PQ (fused decode)**: token vectors are product-quantized at seal
  time (index/segment.py) into [D, T, M] uint8 codes against a
  [M, 256, dsub] codebook. The kernel builds the per-query ADC lookup
  table lut[Tq, M, 256] = codebook . q_subvectors once per (query,
  segment) and scores codes by table gather inside the loop — the
  compressed vectors are decoded in-register, never materialized
  (FLASH-MAXSIM's fusion contract).

Both variants end in the same top-k epilogue as k-NN
(ops/knn.knn_match_topk): a dense masked score vector restricted to
the k best eligible docs, so cross-segment merge, the value-keyed
result page (ops/topk.py), and the msearch envelope all work
unchanged.

Query token matrices are padded to power-of-two token buckets by the
compiler (search/compile.py) with a qmask zeroing padded query lanes —
executables are keyed on the bucket, not the raw token count.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np
import jax.numpy as jnp

# dims-axis tile width for the exact kernel: one VPU/MXU lane group
# (the last-axis native lane width); dims smaller than a tile take one
# partial step
DIM_TILE = 128

# PQ geometry: 8-bit codes -> 256 centroids per subspace
PQ_CODES = 256


def token_mask(token_count: jnp.ndarray, t_bucket: int) -> jnp.ndarray:
    """[D, T] bool: True for real token lanes (s < token_count[d])."""
    lanes = jnp.arange(t_bucket, dtype=jnp.int32)
    return lanes[None, :] < token_count[:, None]


def _tiled_token_dots(tokens2d: jnp.ndarray, query: jnp.ndarray) -> jnp.ndarray:
    """[N, dims] x [Tq, dims] -> [N, Tq] dot products, accumulated over
    DIM_TILE-wide dims slices (the TileMaxSim loop). Tile count is
    static per (shape bucket), so the loop unrolls into a fixed chain
    of MXU matmuls."""
    dims = tokens2d.shape[1]
    acc = None
    for lo in range(0, dims, DIM_TILE):
        hi = min(lo + DIM_TILE, dims)
        part = tokens2d[:, lo:hi] @ query[:, lo:hi].T
        acc = part if acc is None else acc + part
    return acc


def exact_maxsim_scores(tokens: jnp.ndarray, token_count: jnp.ndarray,
                        query: jnp.ndarray, qmask: jnp.ndarray) -> jnp.ndarray:
    """Fused exact MaxSim over a padded token block.

    tokens: [D, T, dims] f32 (padded lanes zero), token_count: [D] i32,
    query: [Tq, dims] f32 (padded query lanes zero), qmask: [Tq] f32
    (1.0 real / 0.0 padding). Returns [D] f32 scores; zero-token docs
    score 0.
    """
    d, t_bucket, dims = tokens.shape
    tq = query.shape[0]
    tmask = token_mask(token_count, t_bucket)            # [D, T]
    # [D*T, Tq] partial-dot accumulation over dims tiles, then the
    # masked max over doc-token lanes per query token
    dots = _tiled_token_dots(tokens.reshape(d * t_bucket, dims), query)
    dots = dots.reshape(d, t_bucket, tq)
    dots = jnp.where(tmask[:, :, None], dots, -jnp.inf)
    best = jnp.max(dots, axis=1)                         # [D, Tq]
    # empty docs have every lane at -inf: clamp to 0 before the sum so
    # they contribute nothing (they are masked ineligible anyway)
    best = jnp.where(jnp.isfinite(best), best, 0.0)
    return jnp.sum(best * qmask[None, :], axis=1)


def pq_lut(codebook: jnp.ndarray, query: jnp.ndarray) -> jnp.ndarray:
    """ADC lookup table lut[Tq, M, 256]: each query token's dot product
    against every subspace centroid. codebook: [M, 256, dsub] f32,
    query: [Tq, dims] with dims == M * dsub."""
    m, codes, dsub = codebook.shape
    tq = query.shape[0]
    qsub = query.reshape(tq, m, dsub)
    return jnp.einsum("mcd,tmd->tmc", codebook, qsub)


def pq_maxsim_scores(codes: jnp.ndarray, codebook: jnp.ndarray,
                     token_count: jnp.ndarray, query: jnp.ndarray,
                     qmask: jnp.ndarray) -> jnp.ndarray:
    """Fused-PQ MaxSim: codes are scored against the per-query ADC
    table inside the loop — decoded vectors are never materialized.

    codes: [D, T, M] uint8, codebook: [M, 256, dsub] f32,
    token_count: [D] i32, query: [Tq, dims] f32, qmask: [Tq] f32.
    Returns [D] f32 approximate MaxSim scores.
    """
    d, t_bucket, m = codes.shape
    tq = query.shape[0]
    lut = pq_lut(codebook, query)                        # [Tq, M, 256]
    tmask = token_mask(token_count, t_bucket)            # [D, T]
    idx = codes.astype(jnp.int32)
    sub = jnp.arange(m, dtype=jnp.int32)[None, None, :]
    out = []
    # per-query-token gather keeps the live slab at [D, T, M] — the
    # [D, T, Tq] cross product never materializes (Tq is a static
    # bucket, so this unrolls like the exact kernel's tile chain)
    for t in range(tq):
        dots = jnp.sum(lut[t][sub, idx], axis=-1)        # [D, T]
        dots = jnp.where(tmask, dots, -jnp.inf)
        best = jnp.max(dots, axis=1)                     # [D]
        out.append(jnp.where(jnp.isfinite(best), best, 0.0))
    return jnp.sum(jnp.stack(out, axis=1) * qmask[None, :], axis=1)


# ------------------------------------------------------- seal-time PQ ----

def train_pq(vectors: np.ndarray, m: int, iters: int = 8,
             seed: int = 29) -> np.ndarray:
    """Per-subspace k-means codebook [m, 256, dsub] over the segment's
    token vectors (host/seal path). Fewer distinct tokens than 256
    leaves the tail centroids zero — codes never reference them."""
    n, dims = vectors.shape
    dsub = dims // m
    codebook = np.zeros((m, PQ_CODES, dsub), dtype=np.float32)
    if n == 0:
        return codebook
    rng = np.random.RandomState(seed)
    data = vectors.astype(np.float32).reshape(n, m, dsub)
    for sub in range(m):
        x = data[:, sub, :]
        ncent = min(PQ_CODES, n)
        cent = x[rng.choice(n, size=ncent, replace=False)].copy()
        for _ in range(iters):
            d2 = ((x[:, None, :] - cent[None, :, :]) ** 2).sum(axis=2)
            assign = np.argmin(d2, axis=1)
            for c in range(ncent):
                members = x[assign == c]
                if len(members):
                    cent[c] = members.mean(axis=0)
        codebook[sub, :ncent] = cent
    return codebook


def encode_pq(vectors: np.ndarray, codebook: np.ndarray) -> np.ndarray:
    """[N, dims] -> [N, M] uint8 nearest-centroid codes (host/seal)."""
    n = vectors.shape[0]
    m, _, dsub = codebook.shape
    if n == 0:
        return np.zeros((0, m), dtype=np.uint8)
    data = vectors.astype(np.float32).reshape(n, m, dsub)
    codes = np.zeros((n, m), dtype=np.uint8)
    for sub in range(m):
        x = data[:, sub, :]
        cent = codebook[sub]
        d2 = ((x[:, None, :] - cent[None, :, :]) ** 2).sum(axis=2)
        codes[:, sub] = np.argmin(d2, axis=1).astype(np.uint8)
    return codes


def decode_pq(codes: np.ndarray, codebook: np.ndarray) -> np.ndarray:
    """[N, M] codes -> [N, dims] reconstructed vectors (host-side
    differential/debug only — the device kernel never calls this)."""
    n, m = codes.shape
    dsub = codebook.shape[2]
    out = np.zeros((n, m * dsub), dtype=np.float32)
    for sub in range(m):
        out[:, sub * dsub:(sub + 1) * dsub] = codebook[sub][codes[:, sub]]
    return out


def maxsim_match_topk(scores: jnp.ndarray, eligible: jnp.ndarray,
                      k: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Top-k epilogue — identical contract to ops/knn.knn_match_topk so
    cross-segment merge and the result page treat maxsim matches like
    any other dense score vector."""
    from opensearch_tpu.ops.knn import knn_match_topk
    return knn_match_topk(scores, eligible, k)
