"""BM25 scoring kernels — the TPU replacement for Lucene's BulkScorer hot loop.

Reference hot loop: search/internal/ContextIndexSearcher.java:260 →
Lucene Weight.bulkScorer → BM25 per posting, one doc at a time. Here the same
math runs data-parallel: a query clause gathers its terms' 128-wide postings
blocks from the resident `[NB, 128]` matrices, computes BM25 partials for all
lanes at once on the VPU, and scatter-adds into a dense per-doc score vector.
Conjunction/disjunction semantics fall out of a parallel hit-count scatter
(each (term, doc) pair appears exactly once in postings, so the hit count per
doc equals the number of distinct clause terms that matched).

Score parity: idf = ln(1 + (docCount - df + 0.5)/(df + 0.5)) per
LegacyBM25Similarity (reference: index/similarity/SimilarityService.java:85 —
OpenSearch's default keeps the (k1+1) numerator factor), doc length decoded
from SmallFloat-quantized norms through the 256-entry LENGTH_TABLE, and
avgdl = sumTotalTermFreq / docCount, all matching Lucene to float precision.
"""

from __future__ import annotations

import math

import jax.numpy as jnp


def idf(doc_count: int, doc_freq: int) -> float:
    """Lucene BM25Similarity.idfExplain."""
    return math.log(1.0 + (doc_count - doc_freq + 0.5) / (doc_freq + 0.5))


def score_text_clause(seg, blk, k1):
    """Score one text clause (match / term / terms over one field family).

    seg: device segment dict (post_docs, post_tf, norms, length_table).
    blk: per-block gathered inputs:
      - ids:    int32 [QB] block row indices into post_docs/post_tf
                (power-of-two bucketed; -1 = padding lane)
      - w:      float32 [QB] idf * boost * multiplicity for the block's term
      - row:    int32 scalar norms-stack row of the clause's field
      - avgdl:  float32 scalar average field length for the clause's field
      - b:      float32 scalar BM25 b (0 for norm-less keyword fields,
                matching Lucene's omit-norms denominator tf + k1)
    k1: BM25 k1 (traced scalar).

    Clause constants are SCALARS (one field per clause): per-lane data is
    only (ids, w), which halves the msearch envelope bytes per query.

    Returns (scores f32 [Dp], hits int32 [Dp]) — hits counts distinct matched
    clause terms per doc, powering operator=and / minimum_should_match.
    """
    d_pad = seg["live"].shape[0]
    lane_real = blk["ids"] >= 0                  # [QB]
    safe_ids = jnp.where(lane_real, blk["ids"], 0)
    docs = seg["post_docs"][safe_ids]            # [QB, 128]
    tfs = seg["post_tf"][safe_ids]               # [QB, 128]
    valid = docs >= 0
    safe_docs = jnp.where(valid, docs, 0)
    norm_bytes = seg["norms"][blk["row"]][safe_docs]              # [QB, 128]
    dl = seg["length_table"][norm_bytes]
    b = blk["b"]
    denom = tfs + k1 * (1.0 - b + b * dl / blk["avgdl"])
    partial = blk["w"][:, None] * tfs * (k1 + 1.0) / denom
    real = valid & lane_real[:, None]
    partial = jnp.where(real, partial, 0.0)
    ones = jnp.where(real, 1, 0).astype(jnp.int32)
    # padding lanes scatter to index d_pad which is dropped (out of bounds)
    scatter_idx = jnp.where(real, docs, d_pad).ravel()
    scores = jnp.zeros(d_pad, jnp.float32).at[scatter_idx].add(
        partial.ravel(), mode="drop")
    hits = jnp.zeros(d_pad, jnp.int32).at[scatter_idx].add(
        ones.ravel(), mode="drop")
    return scores, hits


def _pairs_to_docs(hit, doc_ids, d_pad, ident: bool):
    """Per-pair hit flags → per-doc bool [d_pad]. Identity pair layouts
    (single-valued dense columns, doc k ↔ lane k) skip the scatter-max —
    XLA scatters lower to a serial per-element loop on CPU and a slow
    path on TPU, and this op sits on every range/terms query."""
    if ident:
        n = hit.shape[-1]
        if n == d_pad:
            return hit
        if n < d_pad:
            pad = jnp.zeros(d_pad - n, jnp.bool_)
            return jnp.concatenate([hit, jnp.broadcast_to(
                pad, hit.shape[:-1] + pad.shape)], axis=-1)
        return hit[..., :d_pad]
    pair_valid = doc_ids >= 0
    scatter_idx = jnp.where(pair_valid, doc_ids, d_pad)
    return jnp.zeros(d_pad, jnp.bool_).at[scatter_idx].max(hit, mode="drop")


def range_match_on_ranks(doc_ids, ords, lo_rank, hi_rank, d_pad,
                         ident: bool = False):
    """Doc matches if ANY of its values has rank in [lo_rank, hi_rank).

    (doc_ids, ords) are a value-pair column (doc_id -1 = padding). Rank bounds
    come from the host's searchsorted over the column's sorted unique values —
    integer compares on device, exact for dates/longs/doubles alike.
    """
    pair_valid = doc_ids >= 0
    in_range = (ords >= lo_rank) & (ords < hi_rank) & pair_valid
    return _pairs_to_docs(in_range, doc_ids, d_pad, ident)


def ordinal_terms_match(doc_ids, ords, ord_mask, d_pad, ident: bool = False):
    """Doc matches if ANY of its ordinals is in the query's ordinal set.

    ord_mask: bool [card_pad] — query-side mask over the field's dictionary
    (keyword ordinals or numeric value ranks alike).
    """
    pair_valid = doc_ids >= 0
    hit = ord_mask[ords] & pair_valid
    return _pairs_to_docs(hit, doc_ids, d_pad, ident)
