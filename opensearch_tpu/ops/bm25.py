"""BM25 scoring kernels — the TPU replacement for Lucene's BulkScorer hot loop.

Reference hot loop: search/internal/ContextIndexSearcher.java:260 →
Lucene Weight.bulkScorer → BM25 per posting, one doc at a time. Here the same
math runs data-parallel: a query clause gathers its terms' 128-wide postings
blocks from the resident `[NB, 128]` matrices, computes BM25 partials for all
lanes at once on the VPU, and scatter-adds into a dense per-doc score vector.
Conjunction/disjunction semantics fall out of a parallel hit-count scatter
(each (term, doc) pair appears exactly once in postings, so the hit count per
doc equals the number of distinct clause terms that matched).

Score parity: idf = ln(1 + (docCount - df + 0.5)/(df + 0.5)) per
LegacyBM25Similarity (reference: index/similarity/SimilarityService.java:85 —
OpenSearch's default keeps the (k1+1) numerator factor), doc length decoded
from SmallFloat-quantized norms through the 256-entry LENGTH_TABLE, and
avgdl = sumTotalTermFreq / docCount, all matching Lucene to float precision.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

# Block-max pruning (ISSUE 20, ROADMAP item 4): skip posting blocks whose
# seal-time score upper bound cannot reach the query's competitive top-k
# threshold — the BMW/BM25S family of impact-bounded skipping, rank-exact
# by construction. OFF by default; flipped by the dynamic node setting
# `search.blockmax.enabled` (see node.py), never flip inline in library code.
BLOCKMAX = False

# Phase A derives the competitive threshold from an exactly-scored slice of
# the highest-bound blocks: top SLICE_BLOCKS blocks by upper bound are fully
# scored (gather + sort + windowed run-sum), and the k-th best eligible doc
# score in that slice lower-bounds the true k-th best — every block whose
# upper bound falls below it is provably beaten.
BLOCKMAX_SLICE_BLOCKS = 8
# Clauses touching fewer blocks than this skip phase A entirely (static,
# host-side admission): the slice would cover most of the postings anyway.
BLOCKMAX_MIN_BLOCKS = 16

_NEG_INF = jnp.float32(-jnp.inf)
# min_score above this sentinel means the caller set a real floor (or this is
# an SPMD padding row with +inf) — pruning is disabled for those rows.
_MIN_SCORE_OFF = -1e30


def idf(doc_count: int, doc_freq: int) -> float:
    """Lucene BM25Similarity.idfExplain."""
    return math.log(1.0 + (doc_count - doc_freq + 0.5) / (doc_freq + 0.5))


def blockmax_keep_mask(seg, blk, k1, n_terms, k, min_score=None):
    """Phase A of the two-phase block-max kernel: per-block keep mask.

    seg must carry the seal-time `post_bound` leaf (f32 [NBp]: per-block
    max(tf/(tf+k1_seal*norm))). blk carries, beyond score_text_clause's
    inputs, `tid` (int32 [QB] query-term index per lane) and `bscale`
    (f32 scalar: host-computed ceiling on g_query/g_seal over the doc
    lengths occurring in the segment, so sealed bounds stay upper bounds
    under the query's own k1/b/avgdl).

    n_terms, k are STATIC python ints (clause term count, top-k depth);
    callers must statically skip phase A when k > SLICE_BLOCKS*128 or the
    clause has fewer than BLOCKMAX_MIN_BLOCKS lanes.

    Rank-exactness: ub(block X of term t) = self_ub(X) + sum_{t'!=t} tmax(t')
    where self_ub = max(w,0)*(k1+1)*bscale*bound upper-bounds the term's
    partial for any doc in X and tmax(t') that of any other term, so any
    doc's full score is <= the ub of EVERY block holding one of its
    postings. theta is the k-th best exact score of an eligible-doc subset,
    hence <= the true k-th best; `keep = ub >= theta` therefore never drops
    a block containing a top-k doc, and boundary ties survive strictness.

    Returns (keep bool [QB], pruned int32 scalar — real lanes masked off).
    """
    lane_real = blk["ids"] >= 0                            # [QB]
    safe_ids = jnp.where(lane_real, blk["ids"], 0)
    safe_tid = jnp.where(lane_real, blk["tid"], 0)
    w_pos = jnp.maximum(blk["w"], 0.0)
    self_ub = (w_pos * (k1 + 1.0) * blk["bscale"]
               * seg["post_bound"][safe_ids])
    self_ub = jnp.where(lane_real, self_ub, 0.0)           # [QB]
    # per-term best bound (static loop: n_terms is a compile-time fact)
    tmax = jnp.stack([
        jnp.max(jnp.where(lane_real & (blk["tid"] == t), self_ub, 0.0))
        for t in range(n_terms)])                          # [T]
    ub = self_ub + (jnp.sum(tmax) - tmax[safe_tid])        # [QB]

    # --- exact-score the top-bound slice to derive theta ---
    n_slice = min(BLOCKMAX_SLICE_BLOCKS, ub.shape[0])
    _, sidx = jax.lax.top_k(jnp.where(lane_real, ub, _NEG_INF), n_slice)
    s_real = lane_real[sidx]                               # [S]
    docs = seg["post_docs"][safe_ids[sidx]]                # [S, 128]
    tfs = seg["post_tf"][safe_ids[sidx]]
    valid = (docs >= 0) & s_real[:, None]
    safe_docs = jnp.where(valid, docs, 0)
    norm_bytes = seg["norms"][blk["row"]][safe_docs]
    dl = seg["length_table"][norm_bytes]
    b = blk["b"]
    denom = tfs + k1 * (1.0 - b + b * dl / blk["avgdl"])
    partial = blk["w"][sidx][:, None] * tfs * (k1 + 1.0) / denom
    # theta must come from truly-eligible docs only: deleted/nested docs
    # could otherwise inflate it past the real k-th best (unsafe)
    elig0 = valid & seg["live"][safe_docs] & seg["root"][safe_docs]
    sentinel = jnp.int32(2 ** 31 - 1)
    flat_docs = jnp.where(elig0, docs, sentinel).ravel()   # [S*128]
    flat_p = jnp.where(elig0, partial, 0.0).ravel()
    flat_h = jnp.where(elig0, 1, 0).astype(jnp.int32).ravel()
    sdocs, sp, sh = jax.lax.sort((flat_docs, flat_p, flat_h), num_keys=1)
    # per-doc windowed run-sum: a doc appears at most once per term
    tot, hits = sp, sh
    for j in range(1, n_terms):
        same = jnp.concatenate(
            [sdocs[j:] == sdocs[:-j], jnp.zeros(j, jnp.bool_)])
        tot = tot + jnp.where(
            same, jnp.concatenate([sp[j:], jnp.zeros(j, jnp.float32)]), 0.0)
        hits = hits + jnp.where(
            same, jnp.concatenate([sh[j:], jnp.zeros(j, jnp.int32)]), 0)
    head = jnp.concatenate(
        [jnp.ones(1, jnp.bool_), sdocs[1:] != sdocs[:-1]])
    elig = head & (sdocs < sentinel) & (hits >= blk["min_hits"])
    cand = jnp.where(elig, tot, _NEG_INF)
    theta = jax.lax.top_k(cand, min(k, cand.shape[0]))[0][-1]
    # fewer than k eligible slice docs -> -inf padding -> no pruning; rows
    # with a caller-set score floor (incl. SPMD +inf padding rows) never prune
    if min_score is not None:
        theta = jnp.where(min_score > _MIN_SCORE_OFF, _NEG_INF, theta)
    keep = ub >= theta
    pruned = jnp.sum((lane_real & ~keep).astype(jnp.int32))
    return keep, pruned


def score_text_clause(seg, blk, k1, block_keep=None):
    """Score one text clause (match / term / terms over one field family).

    seg: device segment dict (post_docs, post_tf, norms, length_table).
    blk: per-block gathered inputs:
      - ids:    int32 [QB] block row indices into post_docs/post_tf
                (power-of-two bucketed; -1 = padding lane)
      - w:      float32 [QB] idf * boost * multiplicity for the block's term
      - row:    int32 scalar norms-stack row of the clause's field
      - avgdl:  float32 scalar average field length for the clause's field
      - b:      float32 scalar BM25 b (0 for norm-less keyword fields,
                matching Lucene's omit-norms denominator tf + k1)
    k1: BM25 k1 (traced scalar).

    Clause constants are SCALARS (one field per clause): per-lane data is
    only (ids, w), which halves the msearch envelope bytes per query.

    block_keep: optional bool [QB] phase-A mask (blockmax_keep_mask): pruned
    lanes gather the shared row 0 instead of streaming their posting block
    and contribute nothing downstream. Rank-exact for top-k pages; the hit
    count (hence `total`) becomes a lower bound, mirroring Lucene BMW under
    track_total_hits.

    Returns (scores f32 [Dp], hits int32 [Dp]) — hits counts distinct matched
    clause terms per doc, powering operator=and / minimum_should_match.
    """
    d_pad = seg["live"].shape[0]
    lane_real = blk["ids"] >= 0                  # [QB]
    if block_keep is not None:
        lane_real = lane_real & block_keep
    safe_ids = jnp.where(lane_real, blk["ids"], 0)
    docs = seg["post_docs"][safe_ids]            # [QB, 128]
    tfs = seg["post_tf"][safe_ids]               # [QB, 128]
    valid = docs >= 0
    safe_docs = jnp.where(valid, docs, 0)
    norm_bytes = seg["norms"][blk["row"]][safe_docs]              # [QB, 128]
    dl = seg["length_table"][norm_bytes]
    b = blk["b"]
    denom = tfs + k1 * (1.0 - b + b * dl / blk["avgdl"])
    partial = blk["w"][:, None] * tfs * (k1 + 1.0) / denom
    real = valid & lane_real[:, None]
    partial = jnp.where(real, partial, 0.0)
    ones = jnp.where(real, 1, 0).astype(jnp.int32)
    # padding lanes scatter to index d_pad which is dropped (out of bounds)
    scatter_idx = jnp.where(real, docs, d_pad).ravel()
    scores = jnp.zeros(d_pad, jnp.float32).at[scatter_idx].add(
        partial.ravel(), mode="drop")
    hits = jnp.zeros(d_pad, jnp.int32).at[scatter_idx].add(
        ones.ravel(), mode="drop")
    return scores, hits


def _pairs_to_docs(hit, doc_ids, d_pad, ident: bool):
    """Per-pair hit flags → per-doc bool [d_pad]. Identity pair layouts
    (single-valued dense columns, doc k ↔ lane k) skip the scatter-max —
    XLA scatters lower to a serial per-element loop on CPU and a slow
    path on TPU, and this op sits on every range/terms query."""
    if ident:
        n = hit.shape[-1]
        if n == d_pad:
            return hit
        if n < d_pad:
            pad = jnp.zeros(d_pad - n, jnp.bool_)
            return jnp.concatenate([hit, jnp.broadcast_to(
                pad, hit.shape[:-1] + pad.shape)], axis=-1)
        return hit[..., :d_pad]
    pair_valid = doc_ids >= 0
    scatter_idx = jnp.where(pair_valid, doc_ids, d_pad)
    return jnp.zeros(d_pad, jnp.bool_).at[scatter_idx].max(hit, mode="drop")


def range_match_on_ranks(doc_ids, ords, lo_rank, hi_rank, d_pad,
                         ident: bool = False):
    """Doc matches if ANY of its values has rank in [lo_rank, hi_rank).

    (doc_ids, ords) are a value-pair column (doc_id -1 = padding). Rank bounds
    come from the host's searchsorted over the column's sorted unique values —
    integer compares on device, exact for dates/longs/doubles alike.
    """
    pair_valid = doc_ids >= 0
    in_range = (ords >= lo_rank) & (ords < hi_rank) & pair_valid
    return _pairs_to_docs(in_range, doc_ids, d_pad, ident)


def ordinal_terms_match(doc_ids, ords, ord_mask, d_pad, ident: bool = False):
    """Doc matches if ANY of its ordinals is in the query's ordinal set.

    ord_mask: bool [card_pad] — query-side mask over the field's dictionary
    (keyword ordinals or numeric value ranks alike).
    """
    pair_valid = doc_ids >= 0
    hit = ord_mask[ords] & pair_valid
    return _pairs_to_docs(hit, doc_ids, d_pad, ident)
