"""Top-k collection constants and helpers.

Replaces Lucene's TopScoreDocCollector / priority-queue per segment
(reference: search/query/TopDocsCollectorContext.java) with `lax.top_k` over a
dense masked key vector — the selection itself lives in the executor's jitted
program (search/executor.py _runner) so it fuses with plan evaluation.
Lucene's tie-break contract (score desc, then doc id asc) is finished on the
host over the over-fetched candidate set.

Value-keyed merges (the SPMD collective merge in parallel/distributed.py and
the single-round-trip result page in search/executor.py) share the helpers
below: a cross-segment-comparable f32 merge key decoded from the column's
rank -> value table, plus the host-side admission predicates that keep the
f32 key selection exactly equal to the host path's f64 selection.
"""

import numpy as np

NEG_INF = float("-inf")

# Missing-field sentinel for VALUE-keyed merges: below every admissible
# value key (f32_sortable admits |v| < 1e29 only, so -|v| > -1e29) but
# above the NEG_INF ineligibility mask — a doc missing the sort field
# stays a candidate that sorts last, matching _compare_candidates'
# missing-last semantics, while masked/padding lanes stay unselectable.
MISSING_VALUE_KEY = -1e30


def f32_sortable(col) -> bool:
    """Merge keys sort by decoded f32 values: admit a column only when
    every unique value is EXACTLY f32-representable (selection then
    matches the host path's exact f64 keys) and within the sentinel
    range. Memoized on the immutable column. Epoch-millis dates usually
    fail (f32 spacing ~131 s at 2e12) and take the host path."""
    cached = getattr(col, "_f32_sortable", None)
    if cached is None:
        u = col.unique
        cached = bool(
            len(u) == 0
            or (np.all(np.abs(u) < 1e29)
                and np.array_equal(u.astype(np.float32).astype(np.float64),
                                   u)))
        col._f32_sortable = cached
    return cached


def single_valued(col) -> bool:
    """True when no doc in the column carries more than one value — the
    admission predicate for the result page's fused docvalue gather: a
    single min_rank per winning ordinal then reproduces the full
    docvalue_fields output for the doc (multi-valued docs need the
    variable-length value list and keep the host scan). Memoized on the
    immutable column."""
    cached = getattr(col, "_single_valued", None)
    if cached is None:
        cached = bool(np.unique(col.doc_ids).size == col.doc_ids.size)
        col._single_valued = cached
    return cached


def value_merge_key(col, order: str, d_pad: int):
    """Dense [d_pad] f32 cross-segment merge key for a numeric-field
    sort, built inside a jitted program from the DEVICE column dict
    (ops/device_segment.py layout). The key is the doc's decoded f32
    VALUE — comparable across segments, unlike the host path's
    segment-local ranks — negated for asc so `lax.top_k` always selects
    descending-key; a missing field takes MISSING_VALUE_KEY (sorts last
    but stays eligible). `col` None (segment has no column for the
    field) keys every doc as missing."""
    import jax.numpy as jnp
    if col is None:
        return jnp.full(d_pad, jnp.float32(MISSING_VALUE_KEY))
    u = col["unique_f32"]
    hi = u.shape[0] - 1
    if order == "asc":
        keys = -u[jnp.clip(col["min_rank"], 0, hi)]
    else:
        keys = u[jnp.clip(col["max_rank"], 0, hi)]
    return jnp.where(col["exists"], keys, jnp.float32(MISSING_VALUE_KEY))
