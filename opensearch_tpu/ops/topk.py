"""Top-k collection constants and helpers.

Replaces Lucene's TopScoreDocCollector / priority-queue per segment
(reference: search/query/TopDocsCollectorContext.java) with `lax.top_k` over a
dense masked key vector — the selection itself lives in the executor's jitted
program (search/executor.py _runner) so it fuses with plan evaluation.
Lucene's tie-break contract (score desc, then doc id asc) is finished on the
host over the over-fetched candidate set.
"""

NEG_INF = float("-inf")
