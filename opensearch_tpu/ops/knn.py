"""k-NN kernels: exact brute-force distances and IVF approximate search.

The reference ships dense_vector storage only (modules/mapper-extras
DenseVectorFieldMapper) with brute-force painless `script_score`; the k-NN
plugin (opensearch-project/k-NN, out-of-repo — SURVEY.md §2.3 note) adds
HNSW/IVF via native faiss/nmslib. Here both are TPU-native:

- **Exact**: one [D, dims] × [dims] matmul on the MXU per (segment, query) —
  with msearch batching it becomes [D, dims] × [dims, Q]. L2 uses the
  ||x||² - 2x·q + ||q||² expansion so document norms are precomputed once.
- **IVF**: k-means centroids (built at seal time, Lloyd's on device),
  inverted lists as a padded [nlist, max_len] int32 matrix. A query scores
  centroids, takes the top-nprobe lists, gathers their candidates, and
  scores only those — graph walks (HNSW) are TPU-hostile; IVF reaches the
  recall targets with dense, statically-shaped compute (BASELINE.md config 5).

Score conventions follow the k-NN plugin's spaces:
  l2: 1/(1+d²), cosinesimil: (1+cos)/2, innerproduct: ip≥0 → ip+1 else 1/(1-ip).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

SPACES = ("l2", "cosinesimil", "innerproduct")


def _check_space(space: str):
    if space not in SPACES:
        raise ValueError(f"unknown knn space [{space}]")


def raw_similarity(vectors: jnp.ndarray, query: jnp.ndarray,
                   space: str) -> jnp.ndarray:
    """Higher-is-closer raw similarity per doc ([D, dims] × [dims] → [D])."""
    dots = vectors @ query                       # MXU matvec
    if space == "l2":
        dn = jnp.sum(vectors * vectors, axis=1)
        qn = jnp.sum(query * query)
        return -(dn - 2.0 * dots + qn)           # negative squared distance
    if space == "cosinesimil":
        dn = jnp.sqrt(jnp.sum(vectors * vectors, axis=1))
        qn = jnp.sqrt(jnp.sum(query * query))
        return dots / jnp.maximum(dn * qn, 1e-30)
    return dots                                  # innerproduct


def space_score(raw: jnp.ndarray, space: str) -> jnp.ndarray:
    """Raw similarity → k-NN plugin score (rank-monotone per space)."""
    if space == "l2":
        return 1.0 / (1.0 + jnp.maximum(-raw, 0.0))
    if space == "cosinesimil":
        return (1.0 + jnp.clip(raw, -1.0, 1.0)) / 2.0
    return jnp.where(raw >= 0, raw + 1.0, 1.0 / (1.0 - raw))


def exact_knn_scores(vectors: jnp.ndarray, query: jnp.ndarray,
                     space: str) -> jnp.ndarray:
    _check_space(space)
    return space_score(raw_similarity(vectors, query, space), space)


def knn_match_topk(scores: jnp.ndarray, eligible: jnp.ndarray,
                   k: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Restrict a dense score vector to its top-k eligible docs.

    Returns (scores, matches): matches true only for the k best eligible
    docs (score-desc, doc-asc tie-break via top_k's lowest-index rule)."""
    d = scores.shape[0]
    masked = jnp.where(eligible, scores, -jnp.inf)
    k_eff = min(int(k), int(d))
    top_vals, top_idx = jax.lax.top_k(masked, k_eff)
    valid = top_vals > -jnp.inf
    # invalid slots scatter out of bounds and are dropped — routing them to
    # index 0 would clobber a real winner at doc ord 0
    matches = jnp.zeros(d, jnp.bool_).at[
        jnp.where(valid, top_idx, d)].set(True, mode="drop")
    matches = matches & eligible
    return jnp.where(matches, scores, 0.0), matches


# ------------------------------------------------------------------- IVF ----

# fixed block width for inverted-list storage: probing slices whole
# blocks, so the per-query candidate count is budget · IVF_BLOCK
# regardless of how imbalanced the clusters are (a worst-case list no
# longer inflates every probe — the round-4 layout padded ALL lists to
# the longest list's length, making nprobe·max_len ≈ the whole corpus)
IVF_BLOCK = 256


@dataclass
class IVFIndex:
    """Host-side IVF structure attached to a VectorColumn at seal time.

    Lists are stored as fixed-width BLOCKS: `lists[i]` is one block of
    IVF_BLOCK doc ords (-1 padded) owned by centroid
    `block_centroid[i]`; a cluster with many members spans several
    consecutive blocks."""
    centroids: np.ndarray        # [nlist, dims] float32
    lists: np.ndarray            # [n_blocks, IVF_BLOCK] int32, -1 padded
    block_centroid: np.ndarray   # int32 [n_blocks] owning centroid
    nlist: int
    nprobe: int              # default probe count from the mapping


def _kmeans(vectors: np.ndarray, nlist: int, iters: int = 10,
            seed: int = 17) -> np.ndarray:
    """Lloyd's k-means on device (jit per (shape, nlist)); returns centroids."""
    n = vectors.shape[0]
    rng = np.random.RandomState(seed)
    init = vectors[rng.choice(n, size=nlist, replace=False)]

    @jax.jit
    def step(data, centroids):
        # assign: [n, nlist] distances via the same matmul expansion
        dots = data @ centroids.T
        dn = jnp.sum(data * data, axis=1, keepdims=True)
        cn = jnp.sum(centroids * centroids, axis=1)
        assign = jnp.argmin(dn - 2 * dots + cn, axis=1)
        # update: segment mean
        one_hot = jax.nn.one_hot(assign, nlist, dtype=jnp.float32)
        sums = one_hot.T @ data
        counts = one_hot.sum(axis=0)[:, None]
        return jnp.where(counts > 0, sums / jnp.maximum(counts, 1), centroids)

    data = jnp.asarray(vectors, dtype=jnp.float32)
    centroids = jnp.asarray(init, dtype=jnp.float32)
    # the first step call pays the XLA compile for this (shape, nlist)
    # — routed through the shared first-call timer (ISSUE 19) so the
    # compile reaches `search.xla_compile_ms` and the executable census
    # like every executor jit site; the remaining iters call the raw fn
    from opensearch_tpu.telemetry.kernels import timed_first_call
    first = timed_first_call(
        step, family="knn",
        shape=f"n{data.shape[0]}/d{data.shape[1]}/c{nlist}",
        key=("kmeans", data.shape, nlist))
    for it in range(iters):
        centroids = first(data, centroids) if it == 0 \
            else step(data, centroids)
    return np.asarray(centroids)


def build_ivf(vectors: np.ndarray, exists: np.ndarray, nlist: int,
              nprobe: int = 0, iters: int = 10, seed: int = 17) -> IVFIndex:
    """Cluster present vectors; inverted lists hold doc ords per centroid."""
    present = np.nonzero(exists)[0].astype(np.int32)
    nlist = max(1, min(nlist, len(present)))
    data = vectors[present].astype(np.float32)
    centroids = _kmeans(data, nlist, iters=iters, seed=seed)
    dots = data @ centroids.T
    dn = (data ** 2).sum(axis=1, keepdims=True)
    cn = (centroids ** 2).sum(axis=1)
    assign = np.argmin(dn - 2 * dots + cn, axis=1)
    blocks = []
    block_centroid = []
    for c in range(nlist):
        members = present[assign == c]
        # empty clusters emit NO block: an all-padding block would still
        # win probe-budget slots whenever its centroid lands near the
        # query, displacing blocks with real candidates
        for off in range(0, len(members), IVF_BLOCK):
            chunk = members[off:off + IVF_BLOCK]
            row = np.full(IVF_BLOCK, -1, dtype=np.int32)
            row[:len(chunk)] = chunk
            blocks.append(row)
            block_centroid.append(c)
    if not blocks:          # no vectors at all: one padding block keeps
        blocks.append(np.full(IVF_BLOCK, -1, dtype=np.int32))
        block_centroid.append(0)        # shapes valid for the scan
    lists = np.stack(blocks)
    if nprobe <= 0:
        nprobe = max(1, nlist // 8)
    return IVFIndex(centroids=centroids, lists=lists,
                    block_centroid=np.asarray(block_centroid, np.int32),
                    nlist=nlist, nprobe=nprobe)


def pack_ivf_lists(vectors: np.ndarray, lists: np.ndarray):
    """List-contiguous copies of the vector rows + their doc ords.

    IVF probing gathers ~nprobe·max_len arbitrary vector rows per query;
    XLA lowers that gather to a scalar loop on CPU and a serial path on
    TPU, and it dominated the IVF scan. With the rows laid out list-major
    at build time, each probed list is ONE contiguous dynamic_slice —
    pure copies + matmul. Costs a second copy of the vector matrix
    (inflated by list padding) in exchange."""
    flat = lists.reshape(-1)
    safe = np.where(flat >= 0, flat, 0)
    packed = np.ascontiguousarray(vectors[safe].astype(np.float32))
    packed[flat < 0] = 0.0
    return packed, np.ascontiguousarray(flat.astype(np.int32))


def ivf_knn_scores(packed_vecs: jnp.ndarray, packed_ids: jnp.ndarray,
                   centroids: jnp.ndarray, block_centroid: jnp.ndarray,
                   d: int, query: jnp.ndarray, space: str,
                   nprobe: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """IVF probe: returns (dense scores [D], candidate mask [D]).

    Scores are exact for candidate docs; non-candidates are masked out —
    the standard IVF recall/compute trade. Blocks are ranked by their
    owning centroid's distance and the best `budget` blocks are sliced
    CONTIGUOUSLY from the packed copy (see pack_ivf_lists) — no row
    gather, and the probe budget is independent of cluster imbalance
    (budget ≈ nprobe · avg-blocks-per-list; a skewed list legitimately
    consumes more of the budget because it holds more of the mass)."""
    _check_space(space)
    # centroid ranking always by L2 (clusters were built in L2 space); for
    # innerproduct/cosine the probe order still correlates (faiss does the
    # same for IVF+IP via L2-clustered coarse quantizers)
    cd = jnp.sum(centroids * centroids, axis=1) - 2.0 * (centroids @ query)
    nlist = int(centroids.shape[0])
    n_blocks = int(block_centroid.shape[0])
    nprobe_eff = min(int(nprobe), nlist)
    budget = min(n_blocks,
                 -(-nprobe_eff * n_blocks // nlist) + 1)
    key = cd[block_centroid]                         # [n_blocks] tiny
    _, blk_ids = jax.lax.top_k(-key, budget)
    dims = packed_vecs.shape[1]
    # BLOCK-level gather: each gathered element is a contiguous
    # [IVF_BLOCK, dims] chunk (a memcpy, not the per-row scalar gather
    # this layout exists to avoid), and the graph stays O(1) in budget
    cand_vecs = jnp.take(packed_vecs.reshape(n_blocks, IVF_BLOCK, dims),
                         blk_ids, axis=0).reshape(budget * IVF_BLOCK,
                                                  dims)
    cand = jnp.take(packed_ids.reshape(n_blocks, IVF_BLOCK),
                    blk_ids, axis=0).reshape(budget * IVF_BLOCK)
    raw = raw_similarity(cand_vecs, query, space)
    scores01 = space_score(raw, space)
    valid = cand >= 0
    # padding slots scatter out of bounds (dropped) — using index 0 would
    # overwrite doc ord 0's entries
    cand_scatter = jnp.where(valid, cand, d)
    dense = jnp.zeros(d, jnp.float32).at[cand_scatter].max(
        scores01, mode="drop")
    mask = jnp.zeros(d, jnp.bool_).at[cand_scatter].set(True, mode="drop")
    return dense, mask
