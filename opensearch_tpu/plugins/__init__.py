"""Plugin SPI: third-party extension points consumed by the core
registries.

Re-design of the reference's plugin architecture (server/src/main/java/org/
opensearch/plugins/ — 18 SPI interfaces such as AnalysisPlugin,
SearchPlugin, IngestPlugin, RepositoryPlugin; EnginePlugin.java:61 is the
north-star hook). The JVM reference discovers plugins from jars via
classloaders (PluginsService); here a plugin is a Python object passed to
`install_plugin` (or `Node(plugins=[...])`), and installation pushes its
contributions into the same module-level registries the built-ins live in
— an example plugin adds a tokenizer and a query type without touching
core (tests/test_plugins.py).

Extension points covered (reference SPI in parentheses):
  - tokenizers / token filters / char filters   (AnalysisPlugin)
  - query types: a parser producing a QueryNode, optionally with a
    compiler for new node classes                (SearchPlugin#getQueries)
  - ingest processors                            (IngestPlugin)
  - snapshot repository types                    (RepositoryPlugin)
  - wire-safe classes for Opaque transport       (NamedWriteable registry)
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Tuple

INSTALLED: List["Plugin"] = []


class Plugin:
    """Subclass and override the getters for the extension points you
    provide; every getter defaults to 'nothing'."""

    name: str = "unnamed"

    # ---- AnalysisPlugin
    def get_tokenizers(self) -> Dict[str, Callable]:
        """name -> tokenizer(text, **params) -> List[Token]"""
        return {}

    def get_token_filters(self) -> Dict[str, Callable]:
        """name -> filter(tokens, **params) -> List[Token]"""
        return {}

    def get_char_filters(self) -> Dict[str, Callable]:
        return {}

    # ---- SearchPlugin
    def get_queries(self) -> Dict[str, Callable]:
        """query name -> parser(body) -> QueryNode. The node may be a
        composition of existing DSL nodes (a rewrite macro — the common
        case, like the reference's QueryBuilder#rewrite), or a new node
        class registered via get_query_compilers."""
        return {}

    def get_query_compilers(self) -> Dict[type, Callable]:
        """QueryNode class -> fn(compiler, node, seg, meta) -> Plan"""
        return {}

    # ---- IngestPlugin
    def get_processors(self) -> Dict[str, Callable]:
        """processor type -> factory(config) -> processor"""
        return {}

    # ---- RepositoryPlugin
    def get_repositories(self) -> Dict[str, Callable]:
        """repository type -> factory(name, settings) -> repository"""
        return {}

    # ---- wire registry (NamedWriteableRegistry analog)
    def get_wire_classes(self) -> Tuple[type, ...]:
        return ()


def install_plugin(plugin: Plugin) -> Plugin:
    """Push a plugin's contributions into the live registries.

    Installation is process-global (the registries are module-level, like
    the reference's node-wide modules) and idempotent by plugin name — a
    second Node passing the same plugin does not double-register."""
    for existing in INSTALLED:
        if existing.name == plugin.name:
            return existing
    from opensearch_tpu.analysis import registry as analysis_registry
    from opensearch_tpu.ingest import service as ingest_service
    from opensearch_tpu.repositories import blobstore
    from opensearch_tpu.search import compile as compile_mod
    from opensearch_tpu.search import dsl
    from opensearch_tpu.transport import serde

    analysis_registry.TOKENIZERS.update(plugin.get_tokenizers())
    analysis_registry.TOKEN_FILTERS.update(plugin.get_token_filters())
    analysis_registry.CHAR_FILTERS.update(plugin.get_char_filters())
    dsl.PLUGIN_QUERIES.update(plugin.get_queries())
    compile_mod.PLUGIN_COMPILERS.update(plugin.get_query_compilers())
    ingest_service.PROCESSOR_TYPES.update(plugin.get_processors())
    blobstore.REPOSITORY_TYPES.update(plugin.get_repositories())
    wire = plugin.get_wire_classes()
    if wire:
        serde.allow_opaque(*wire)
    INSTALLED.append(plugin)
    return plugin


def installed_info() -> List[dict]:
    return [{"name": p.name, "component": type(p).__name__}
            for p in INSTALLED]
