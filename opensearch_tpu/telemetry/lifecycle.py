"""Request lifecycle timeline + tail-latency flight recorder + the
write-path ingest lifecycle (ISSUE 13).

ROADMAP item 2 (cross-request dynamic batching) needs to be judged
against numbers, and the numbers that matter under contention are
per-request *when-did-you-wait* numbers: how long a request queued, which
device wave it shared with how many co-batched siblings, and where the
p99.9 outliers actually spent their wall. Nothing in the repo could see
any of that — the tracer times phases, the ledger counts bytes, but
neither records the request's *schedule*. This module is that contract:

- `Timeline` — one request's monotonic-timestamped lifecycle events:
  `arrive` (implicit at construction), `admit`/`reject`, `queue_wait`
  (how long admission held the request; the future wave scheduler fills
  this with real queue delay — today the backpressure gate admits
  immediately, so it reads ~0), `coalesce` (wave id + co-batched
  request count), `dispatch` (wave id + in-flight pipeline depth),
  `collect`, `overlap` (per-wave dispatch/collect overlap, the PR 9
  pipeline win) and `respond`. Phase milliseconds (the controller's
  phase dict / the msearch envelope's ph map) merge in so a completed
  timeline decomposes its own wall. Completed timelines attach to the
  request's root span as the `lifecycle` attribute.

- `FlightRecorder` — the tail-latency capture ring: a completed
  timeline is retained when the request breached an explicit SLO
  threshold (`threshold_ms`) or beat the LIVE rolling p99 of recent
  takes (telemetry/rolling.py, min_samples warmup so the first requests
  don't all self-trigger). Served by `GET /_telemetry/tail`, togglable
  via `POST /_telemetry/tail/_enable|_disable|_clear`, optional JSONL
  export under `_state/tail.jsonl`, rendered by tools/tail_report.py.
  Every capture carries an `ingest_events` annotation: the engine
  refresh/merge/flush events whose wall overlapped the captured
  request's window (empty list when the write path was quiet) — the
  "did a merge cause this p99" join tools/tail_report.py renders.

- `IngestEventLog` — the engine's write-path event log: one bounded
  record per refresh/merge/flush (seg ids, docs, seal wall, live-doc
  ratio) on the monotonic clock, fed by index/engine.py. Live
  regardless of any gate (the inflight-wave-gauge contract: one lock +
  append per REFRESH, never per op) so a tail capture can always be
  joined against the write path that ran under it.

- `IngestRecorder` — the write path's FlightRecorder analog (ISSUE 13):
  per-op and per-bulk ingest timelines (arrive/admit/parse/
  version_plan/translog_append/refresh_wait/respond) recorded into a
  bounded ring with rolling took percentiles, OFF by default behind the
  same None-returning `timeline()` gate (gate-lint registry row,
  asserted pristine by bench.py). The engine reads the thread-bound
  timeline via `current()` — write ops run start-to-finish on one
  thread, so ambient context is safe here (unlike the msearch
  envelope). Served by `GET /_telemetry/ingest`.

No-op discipline (the tracer/ledger/faults contract, statically enforced
by gate-lint's subsystem registry and asserted by bench.py): the
recorder is OFF by default and the hot-path gate is `timeline()`
returning None — one attribute load and a branch, nothing else runs.
Event appends are plain list appends (GIL-atomic): a timeline is written
by at most the request thread + the wave collector thread, and only read
after the pipeline drained.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Tuple

from opensearch_tpu.telemetry.rolling import RollingEstimator

DEFAULT_TAIL_RING = 64

# the lifecycle event vocabulary (README Observability documents each);
# fanout/partial/merge are the collective-phase events the SPMD path
# emits (ISSUE 14) — "which chip was the straggler" answered the way
# coalesce/dispatch/collect already answer "did a merge cause this p99"
EVENTS = ("arrive", "admit", "reject", "queue_wait", "coalesce",
          "dispatch", "collect", "overlap", "respond",
          "fanout", "partial", "merge", "device_share")

# phase_times carries non-time fields next to the millisecond ones
# (LedgerScope.publish writes bytes/waves into the same dict the slow
# log reads); a timeline's phase map keeps only durations
_NON_TIME_PHASES = frozenset({"bytes_fetched", "bytes_to_device",
                              "waves"})


class Timeline:
    """One request's lifecycle: monotonic event offsets + phase times.

    `t_arrive` anchors every event at construction time; offsets are
    milliseconds since arrival, so a dumped timeline reads as the
    request's own clock. `queue_wait_ms` is a first-class field (not
    just an event) because it is THE number the wave-scheduler's
    admission work will be judged by."""

    __slots__ = ("t_arrive", "t_ready", "events", "phases",
                 "queue_wait_ms", "device_share_ms", "took_ms", "status",
                 "detail", "shape")

    def __init__(self):
        self.t_arrive = time.monotonic()
        self.t_ready: Optional[float] = None
        # (event name, ms since arrive, extra fields or None)
        self.events: List[Tuple[str, float, Optional[dict]]] = [
            ("arrive", 0.0, None)]
        self.phases: Dict[str, float] = {}
        self.queue_wait_ms = 0.0
        # this request's proportional slice of the shared wave's device
        # wall (ISSUE 14 per-tenant attribution): filled by the wave
        # scheduler after dispatch — wall × (own items / wave items)
        self.device_share_ms = 0.0
        self.took_ms: Optional[float] = None
        self.status = "ok"
        # detail=True: producers may append per-step events in addition
        # to phase accumulation (set for single-op ingest timelines; a
        # 1000-op bulk accumulates phases only, or its event list would
        # balloon to 3N tuples)
        self.detail = False
        # the request's shape class (ISSUE 15): the interned-template /
        # structural-hash id telemetry/insights.py groups costs by —
        # stamped by the executor/controller when they resolve it, so a
        # tail capture answers "which shape owns this p99" the way
        # ingest_events answers "did a merge cause it" (None = the
        # serving path never resolved one, e.g. a rejected request)
        self.shape: Optional[str] = None

    def event(self, name: str, **fields) -> None:
        self.events.append(
            (name, round((time.monotonic() - self.t_arrive) * 1000, 3),
             fields or None))

    def queue_wait(self, ms: float) -> None:
        """Time the request spent waiting for admission/scheduling —
        measured by whoever held it (the backpressure gate today, the
        wave scheduler's queue tomorrow)."""
        self.queue_wait_ms += ms
        self.event("queue_wait", ms=round(ms, 3))

    def device_share(self, ms: float, wave_ms: float,
                     co_batched: int) -> None:
        """This request's proportional slice of a shared wave's device
        wall (ISSUE 14): the scheduler splits each dispatch's wall
        across its co-batched owners by item count — the usage-side
        number the per-tenant accounting accumulates."""
        self.device_share_ms += ms
        self.event("device_share", ms=round(ms, 3),
                   wave_ms=round(wave_ms, 3), co_batched=int(co_batched))

    def route(self) -> None:
        """Attribute the so-far-unexplained arrive→now interval as the
        `route` phase: REST glue, pipeline resolution and parse/
        validation plumbing between a request's arrival and the phase-
        timed engine taking over. Anchored on the arrive clock and
        called at engine entry points (controller impl, msearch
        envelope) — each call covers only the gap not yet explained by
        queue_wait + recorded phases, so the calls compose and a slow
        request's pre-engine wall (GIL starvation under concurrent
        clients lives exactly here) stops reading as unattributed."""
        gap = (time.monotonic() - self.t_arrive) * 1000 \
            - self.queue_wait_ms - sum(self.phases.values())
        if gap > 0:
            self.phases["route"] = self.phases.get("route", 0.0) + gap

    def mark_ready(self) -> None:
        """Stamp the response-assembled instant. `complete()` turns the
        ready→completed interval into the `handoff` phase: coordinator
        exit glue + response processors + GIL/scheduler starvation on
        the way out. Under N concurrent clients this is real, otherwise
        invisible wall (a slow request can spend tens of ms here), and
        it is measured from two clock reads, never derived as a
        remainder."""
        self.t_ready = time.monotonic()
        self.event("ready")

    def phase_add(self, name: str, ms: float) -> None:
        """Accumulate one phase's milliseconds; when `detail` is set,
        also append a discrete event (the per-op ingest timeline shape —
        arrive/parse/version_plan/translog_append read as a sequence)."""
        self.phases[name] = self.phases.get(name, 0.0) + ms
        if self.detail:
            self.event(name, ms=round(ms, 3))

    def merge_phases(self, phase_ms: Dict[str, float]) -> None:
        """Accumulate per-phase milliseconds (controller phase dict or
        msearch ph map); non-duration fields riding the same dict
        (bytes, wave counts) are dropped."""
        for name, ms in phase_ms.items():
            if name in _NON_TIME_PHASES:
                continue
            self.phases[name] = self.phases.get(name, 0.0) + float(ms)

    def to_dict(self) -> dict:
        out: Dict[str, Any] = {
            "status": self.status,
            "took_ms": self.took_ms,
            "queue_wait_ms": round(self.queue_wait_ms, 3),
            "events": [
                {"event": name, "t_ms": t, **(fields or {})}
                for name, t, fields in self.events],
        }
        if self.device_share_ms:
            out["device_share_ms"] = round(self.device_share_ms, 3)
        if self.shape is not None:
            out["shape"] = self.shape
        if self.phases:
            out["phases"] = {name: round(ms, 3)
                             for name, ms in self.phases.items()}
        return out


class IngestEventLog:
    """Bounded node-wide log of engine write-path events (refresh /
    merge / flush), fed by index/engine.py on the monotonic clock.

    Live regardless of any enable flag — the inflight-wave-gauge
    contract, not the per-request gate discipline: the cost is one lock
    acquire + deque append per REFRESH (never per op), and a
    `_nodes/stats` poll or a tail capture must be able to join against
    the write path that actually ran, whether or not anyone thought to
    enable ingest telemetry first."""

    def __init__(self, ring_size: int = 256):
        self._lock = threading.Lock()
        self._ring: "deque[dict]" = deque(maxlen=ring_size)
        self._seq = 0
        self.counts: Dict[str, int] = {}

    def note(self, kind: str, t0_mono: float, t1_mono: float,
             **fields) -> dict:
        """Record one engine event; returns the stored record (the
        engine's refresh/merge paths hand it to the churn ledger so a
        churn record and its event share an `event_id`)."""
        ev = {"kind": kind,
              "t0_mono": round(t0_mono, 6),
              "t1_mono": round(t1_mono, 6),
              "wall_ms": round((t1_mono - t0_mono) * 1000, 3),
              **fields}
        with self._lock:
            self._seq += 1
            ev["event_id"] = self._seq
            self._ring.append(ev)
            self.counts[kind] = self.counts.get(kind, 0) + 1
        return ev

    def overlapping(self, t0_mono: float, t1_mono: float) -> List[dict]:
        """Events whose wall intersects [t0, t1] on the monotonic clock
        — the `ingest_events` annotation a flight capture carries. Event
        times are rebased to ms offsets from t0 so the annotation reads
        on the capture's own clock."""
        with self._lock:
            evs = list(self._ring)
        out = []
        for ev in evs:
            if ev["t0_mono"] <= t1_mono and ev["t1_mono"] >= t0_mono:
                rec = {k: v for k, v in ev.items()
                       if k not in ("t0_mono", "t1_mono")}
                rec["t_rel_ms"] = round(
                    (ev["t0_mono"] - t0_mono) * 1000, 3)
                out.append(rec)
        return out

    def recent(self, size: Optional[int] = None) -> List[dict]:
        with self._lock:
            out = [{k: v for k, v in ev.items()
                    if k not in ("t0_mono", "t1_mono")}
                   for ev in self._ring]
        out.reverse()
        return out[:size] if size is not None else out

    def events_by_id(self) -> Dict[int, dict]:
        """{event_id: record} over the retained ring (consistency checks
        in tests join capture annotations against this)."""
        with self._lock:
            return {ev["event_id"]: dict(ev) for ev in self._ring}

    def stats(self) -> dict:
        with self._lock:
            return {"events": self._seq, "retained": len(self._ring),
                    "by_kind": dict(self.counts)}

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self.counts = {}


# node-wide write-path event log: engine feeds it, flight captures and
# GET /_telemetry/ingest read it
INGEST_EVENTS = IngestEventLog()


class FlightRecorder:
    """Bounded ring of slow requests' complete timelines.

    Capture policy (decided at `complete()`):
      - `threshold_ms` set and took >= it  -> trigger "threshold";
      - otherwise, once `min_samples` takes have been observed, took
        above the LIVE rolling p99 of recent takes -> trigger "p99".
    The rolling estimator decays (telemetry/rolling.py), so "p99" means
    the p99 of the last few minutes of traffic, not since node start —
    a latency regression shows up as captures within its half-life.
    """

    def __init__(self, ring_size: int = DEFAULT_TAIL_RING):
        self.enabled = False
        self.threshold_ms: Optional[float] = None
        self.p99_trigger = True
        self.min_samples = 32
        self.took = RollingEstimator()
        self._ring: "deque[dict]" = deque(maxlen=ring_size)
        self._lock = threading.Lock()
        self._io_lock = threading.Lock()
        self.jsonl_path: Optional[str] = None
        self.completed = 0
        self.events_total = 0
        self.captures = {"threshold": 0, "p99": 0}
        self.export_errors = 0
        self._tls = threading.local()

    # ------------------------------------------------------------- hot path

    def timeline(self) -> Optional[Timeline]:
        """The per-request gate: a Timeline when the recorder is on,
        else None — callers guard with `if tl is not None`, so the
        disabled query path costs one attribute load and a branch."""
        if not self.enabled:
            return None
        return Timeline()

    def current(self) -> Optional[Timeline]:
        """The thread's bound request timeline, if a caller bound one."""
        return getattr(self._tls, "timeline", None)

    def bind(self, tl: Optional[Timeline]) -> Optional[Timeline]:
        """Bind a request's timeline to this thread (the REST layer owns
        the request; the controller/executor read it back via
        `current()`). Returns the previous binding for `unbind`."""
        prev = getattr(self._tls, "timeline", None)
        self._tls.timeline = tl
        return prev

    def unbind(self, prev: Optional[Timeline]) -> None:
        self._tls.timeline = prev

    def complete(self, tl: Timeline, status: str = "ok",
                 span=None) -> Optional[str]:
        """Close a request's timeline: stamp took, feed the live take
        estimator, decide capture, attach to the root span. Returns the
        capture trigger (or None). Idempotence is the caller's job
        (guard on `tl.took_ms is None` when two exit paths can race)."""
        tl.status = status
        t_done = time.monotonic()
        tl.took_ms = round((t_done - tl.t_arrive) * 1000, 3)
        if tl.t_ready is not None:
            handoff = (t_done - tl.t_ready) * 1000
            if handoff > 0:
                tl.phases["handoff"] = \
                    tl.phases.get("handoff", 0.0) + handoff
        trigger = None
        thr = self.threshold_ms
        if thr is not None and tl.took_ms >= thr:
            trigger = "threshold"
        elif self.p99_trigger:
            # trigger reads the estimator BEFORE this sample lands, so
            # one slow request cannot raise the bar it is judged against.
            # warmup gates on LIFETIME completions (self.completed, not
            # the estimator's decayed total): on a sparse-traffic node
            # the decayed mass can sit below min_samples forever, which
            # would silence the p99 trigger exactly where an explicit
            # threshold is least likely to be configured
            p99 = self.took.quantile(0.99)
            if p99 is not None and self.completed >= self.min_samples \
                    and tl.took_ms > p99:
                trigger = "p99"
        self.took.observe(tl.took_ms)
        if span is not None and getattr(span, "recording", False):
            span.set_attribute("lifecycle", tl.to_dict())
        rec = None
        if trigger is not None:
            # the write-path join (ISSUE 13): every capture carries the
            # engine refresh/merge/flush events whose wall overlapped
            # this request's window — "did a merge cause this p99" is
            # answerable from the capture alone (empty list = the write
            # path was quiet). Built outside the ring lock.
            ingest_events = INGEST_EVENTS.overlapping(tl.t_arrive, t_done)
        with self._lock:
            self.completed += 1
            self.events_total += len(tl.events)
            if trigger is not None:
                rec = {"ts_ms": int(time.time() * 1000),
                       "trigger": trigger, **tl.to_dict(),
                       "ingest_events": ingest_events}
                self._ring.append(rec)
                self.captures[trigger] += 1
        if rec is not None and self.jsonl_path is not None:
            line = json.dumps(rec, default=str) + "\n"
            try:
                with self._io_lock, open(self.jsonl_path, "a") as f:
                    f.write(line)
            except OSError:
                self.export_errors += 1
        return trigger

    # --------------------------------------------------------------- reading

    def captured(self, size: Optional[int] = None) -> List[dict]:
        """Most-recent-first dump of the capture ring."""
        with self._lock:
            out = list(self._ring)
        out.reverse()
        return out[:size] if size is not None else out

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self.completed = 0
            self.events_total = 0
            self.captures = {"threshold": 0, "p99": 0}
        self.took.reset()

    def resize(self, ring_size: int) -> None:
        with self._lock:
            self._ring = deque(self._ring, maxlen=max(int(ring_size), 1))

    def stats(self) -> dict:
        with self._lock:
            retained = len(self._ring)
            maxlen = self._ring.maxlen
            completed = self.completed
            events_total = self.events_total
            captures = dict(self.captures)
        return {"enabled": self.enabled,
                "threshold_ms": self.threshold_ms,
                "p99_trigger": self.p99_trigger,
                "min_samples": self.min_samples,
                "completed": completed,
                "events_total": events_total,
                "captured": retained,
                "captures": captures,
                "ring_size": maxlen,
                "jsonl_path": self.jsonl_path,
                "export_errors": self.export_errors,
                "took_rolling": self.took.summary()}


class SpmdTimeline:
    """The collective-phase timeline gate (ISSUE 14): when enabled, the
    SPMD query phase (search/spmd.py) emits `fanout` (devices, rows),
    per-device `partial` (device, wall) and `merge` (straggler skew +
    analytic collective bytes) events onto whatever request Timeline is
    bound — so a tail capture of an SPMD-served request answers "which
    chip was the straggler" from the capture alone, the way it already
    answers "did a merge cause this p99" via ingest_events.

    This is a gate over EMISSION, not a recorder: the events land on
    the FlightRecorder's per-request timelines and ride its capture
    ring; rendering is tools/tail_report.py's per-device table.

    No-op discipline (tracer/ledger/faults contract, gate-lint registry
    row, asserted by bench.py): OFF by default, `gate()` returns None —
    the disabled SPMD path costs one attribute load and a branch."""

    def __init__(self):
        self.enabled = False

    def gate(self) -> Optional["SpmdTimeline"]:
        """The per-query gate: None when collective-phase timeline
        emission is off — search/spmd.py falls straight through."""
        if not self.enabled:
            return None
        return self


DEFAULT_INGEST_RING = 64


class IngestRecorder:
    """Write-path lifecycle recorder: per-op and per-bulk ingest
    timelines (ISSUE 13), the FlightRecorder's ingest analog.

    No-op discipline (the tracer/ledger/faults contract, gate-lint
    registry row, asserted by bench.py): OFF by default, the per-request
    gate is `timeline()` returning None, and the engine-side ambient
    read `current()` tests the flag BEFORE touching thread-local state —
    the disabled write path costs one attribute load and a branch per
    op. Binding is thread-local (`bound()`): a write op runs
    start-to-finish on one thread, so ambient context is safe here,
    unlike the msearch envelope's B-requests-one-thread fan-in.

    Completed timelines land in a bounded ring (most recent first via
    `captured()`) with rolling took percentiles split per kind (op vs
    bulk) — there is no SLO trigger: ingest tails are joined against
    search tails through INGEST_EVENTS, not captured independently."""

    def __init__(self, ring_size: int = DEFAULT_INGEST_RING):
        self.enabled = False
        self._ring: "deque[dict]" = deque(maxlen=ring_size)
        self._lock = threading.Lock()
        self._tls = threading.local()
        self.took_op = RollingEstimator()
        self.took_bulk = RollingEstimator()
        self.completed = {"op": 0, "bulk": 0}
        self.ops_total = 0
        self.errors = 0

    # ------------------------------------------------------------- hot path

    def timeline(self, detail: bool = True) -> Optional[Timeline]:
        """The per-request gate: a Timeline when the recorder is on,
        else None. `detail` marks single-op timelines (discrete
        parse/version_plan/translog_append events next to the phase
        sums); bulk timelines pass detail=False and accumulate phases
        only."""
        if not self.enabled:
            return None
        tl = Timeline()
        tl.detail = detail
        return tl

    def current(self) -> Optional[Timeline]:
        """The thread's bound ingest timeline — the engine's read. Tests
        the flag first so the disabled path never touches the TLS."""
        if not self.enabled:
            return None
        return getattr(self._tls, "timeline", None)

    def bind(self, tl: Optional[Timeline]) -> Optional[Timeline]:
        prev = getattr(self._tls, "timeline", None)
        self._tls.timeline = tl
        return prev

    def unbind(self, prev: Optional[Timeline]) -> None:
        self._tls.timeline = prev

    @contextmanager
    def bound(self, tl: Optional[Timeline]):
        """Bind a request's ingest timeline for the duration of the
        engine call chain. A None timeline still binds (clears any stale
        outer binding) — cheap, and only reached when enabled."""
        prev = self.bind(tl)
        try:
            yield tl
        finally:
            self.unbind(prev)

    def complete(self, tl: Timeline, status: str = "ok",
                 kind: str = "op", ops: int = 1) -> None:
        tl.status = status
        tl.took_ms = round((time.monotonic() - tl.t_arrive) * 1000, 3)
        (self.took_bulk if kind == "bulk" else self.took_op).observe(
            tl.took_ms)
        rec = {"ts_ms": int(time.time() * 1000), "kind": kind,
               "ops": int(ops), **tl.to_dict()}
        with self._lock:
            self.completed[kind] = self.completed.get(kind, 0) + 1
            self.ops_total += int(ops)
            if status != "ok":
                self.errors += 1
            self._ring.append(rec)

    # --------------------------------------------------------------- reading

    def captured(self, size: Optional[int] = None) -> List[dict]:
        with self._lock:
            out = list(self._ring)
        out.reverse()
        return out[:size] if size is not None else out

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self.completed = {"op": 0, "bulk": 0}
            self.ops_total = 0
            self.errors = 0
        self.took_op.reset()
        self.took_bulk.reset()

    def stats(self) -> dict:
        with self._lock:
            retained = len(self._ring)
            completed = dict(self.completed)
            ops_total = self.ops_total
            errors = self.errors
        return {"enabled": self.enabled,
                "completed": completed,
                "ops_total": ops_total,
                "errors": errors,
                "retained": retained,
                "took_op_rolling": self.took_op.summary(),
                "took_bulk_rolling": self.took_bulk.summary(),
                "events": INGEST_EVENTS.stats()}
