"""Always-on scanned-bytes accounting for the query kernels (ISSUE 14).

ROADMAP item 4 defers block-max (WAND) pruning behind a measured
trigger: "add on-device block-max skipping once scanned-bytes/query
starts dominating" (BM25S, arxiv 2407.03618). SCALING.md computed that
number OFFLINE, once, at three corpus sizes — this module is the LIVE
version: per-query counters for the bytes each kernel class touches,
aggregated into a per-shard/per-segment heat map on `_nodes/stats`
(`telemetry.scan`), so the go/no-go trigger is a standing dashboard
number instead of an archaeology exercise.

Two byte classes, matching SCALING.md's columns exactly (the committed
acceptance: the live p50 at 100K docs must agree with the offline
3.1 KB within 10%):

- **posting bytes** (candidate-buffer kernel): the query terms' posting
  blocks — `blocks × 128 lanes × 8 B` (docs int32 + tf f32), the same
  formula tools/scaling_bench.py evaluates offline from term metadata.
  Counted from `Plan.scan_blocks`, a static the compiler records at
  plan build; per query this is one attribute read per plan node —
  no per-lane work, no device sync.
- **dense-lane bytes** (dense kernel): `d_pad × 9 B` per clause
  evaluation — score f32 + hit i32 + live bool per doc lane, the
  "~9 bytes/doc-lane" O(d_pad) HBM traffic SCALING.md's dense-kernel
  refutation priced.

Always-on discipline: this is NOT a gated subsystem — the counters are
the trigger metric for a capacity decision, so they must be live on
every node like the inflight-wave gauge and the engine event log. The
budget that buys: O(plan nodes) integer adds per (query, segment) on
the host, one dict update per segment and one rolling observe per
query. Nothing allocates per lane, nothing syncs the device.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from opensearch_tpu.telemetry.rolling import RollingEstimator

# posting block geometry (ops/device_segment.py): 128 lanes per block,
# docs int32 + tf f32 = 8 bytes per lane
POSTING_BLOCK_BYTES = 128 * 8
# dense kernel per-lane traffic: score f32 + hit i32 + live bool
DENSE_LANE_BYTES = 9

# bound on distinct tracked (index, shard) rows and per-shard segment
# rows: corpus/segment churn must not grow the map without bound — past
# the cap, new keys fold into the overflow row
_MAX_SHARDS = 128
_MAX_SEGMENTS_PER_SHARD = 16
_OVERFLOW = "_other"


def plan_scan_blocks(plan) -> int:
    """Total posting blocks a compiled plan tree gathers — the sum of
    each text node's `scan_blocks` static (compile.py records it at
    plan build). Memoized on the root plan object: plans are immutable
    and memo-shared, so the warm path is one attribute read."""
    cached = getattr(plan, "_scan_blocks_total", None)
    if cached is None:
        cached = plan.scan_blocks + sum(
            plan_scan_blocks(c) for c in plan.children)
        try:
            plan._scan_blocks_total = cached
        except AttributeError:      # frozen/slotted plan variants
            pass
    return cached


def plan_scan_extra(plan) -> int:
    """Total extra-class bytes a compiled plan tree scans — the sum of
    each node's `scan_extra` static (rank_vectors token-matrix / PQ-code
    bytes the maxsim kernels walk, recorded by compile.py). Memoized
    like plan_scan_blocks; plans without the field cost one getattr."""
    cached = getattr(plan, "_scan_extra_total", None)
    if cached is None:
        cached = getattr(plan, "scan_extra", 0) + sum(
            plan_scan_extra(c) for c in plan.children)
        try:
            plan._scan_extra_total = cached
        except AttributeError:      # frozen/slotted plan variants
            pass
    return cached


class ScanAccounting:
    """Node-wide scanned-bytes aggregates + the per-shard heat map."""

    def __init__(self):
        self._lock = threading.Lock()
        self.queries = 0
        self.posting_bytes_total = 0
        self.dense_bytes_total = 0
        # block-max pruning overlay (ISSUE 20): bytes the phase-B mask
        # kept OUT of the posting gathers. Static accounting above is
        # untouched (Plan.scan_blocks stays the ceiling); effective
        # bytes derive as posting - pruned at read time, so with the
        # gate off (no note_pruned_* calls) effective == static exactly.
        self.pruned_bytes_total = 0
        self.pruned_queries = 0
        # per-query posting-bytes distribution — THE trigger metric
        # (SCALING.md's scanned-bytes/query column, live)
        self.per_query_posting = RollingEstimator()
        self.per_query_dense = RollingEstimator()
        # per-query EFFECTIVE posting bytes (static - pruned), fed only
        # by waves that ran a pruning-admitted program
        self.per_query_effective = RollingEstimator()
        # (index, shard) -> heat-map row
        self._shards: Dict[Tuple[str, str], dict] = {}

    # ------------------------------------------------------------- hot path

    def note_segment(self, index: str, shard: str, seg_id: str,
                     posting_bytes: int, dense_bytes: int,
                     kernel: str) -> None:
        """One (query, segment) execution's scan attribution. `kernel`
        names the program class that ran: `candidate` (candidate-buffer
        kernel), `dense` (per-doc dense vector), `spmd` (the
        distributed program — dense per row), `hybrid`."""
        key = (str(index), str(shard))
        with self._lock:
            row = self._shards.get(key)
            if row is None:
                if len(self._shards) >= _MAX_SHARDS:
                    key = (_OVERFLOW, _OVERFLOW)
                    row = self._shards.get(key)
                if row is None:
                    row = self._shards[key] = {
                        "queries": 0, "posting_bytes": 0,
                        "dense_bytes": 0, "kernels": {}, "segments": {}}
            row["queries"] += 1
            row["posting_bytes"] += int(posting_bytes)
            row["dense_bytes"] += int(dense_bytes)
            row["kernels"][kernel] = row["kernels"].get(kernel, 0) + 1
            segs = row["segments"]
            seg = segs.get(seg_id)
            if seg is None:
                if len(segs) >= _MAX_SEGMENTS_PER_SHARD:
                    seg_id = _OVERFLOW
                    seg = segs.get(seg_id)
                if seg is None:
                    seg = segs[seg_id] = {
                        "queries": 0, "posting_bytes": 0,
                        "dense_bytes": 0}
            seg["queries"] += 1
            seg["posting_bytes"] += int(posting_bytes)
            seg["dense_bytes"] += int(dense_bytes)

    def note_query(self, posting_bytes: int, dense_bytes: int) -> None:
        """One request's total scan bytes across every segment it
        touched — feeds the per-query distribution the block-max
        trigger reads."""
        with self._lock:
            self.queries += 1
            self.posting_bytes_total += int(posting_bytes)
            self.dense_bytes_total += int(dense_bytes)
        self.per_query_posting.observe(float(posting_bytes))
        if dense_bytes:
            self.per_query_dense.observe(float(dense_bytes))

    def note_batch(self, index: str, shard: str, seg_rows: Dict,
                   per_query: List[Tuple[int, int]]) -> None:
        """One msearch wave's scan attribution in a single flush: the
        envelope path accumulates per-(segment, kernel) rows and
        per-item (posting, dense) totals LOCALLY while packing (plain
        dict adds, no lock), then lands everything here — one lock
        acquire per WAVE instead of two per query, which is what keeps
        the always-on counters inside the <2% analytic overhead gate
        at B=1024. `seg_rows`: {seg_id: [queries, posting_bytes,
        dense_bytes, {kernel: count}]}."""
        if not per_query:
            return
        key = (str(index), str(shard))
        agg_posting = sum(p for p, _ in per_query)
        agg_dense = sum(d for _, d in per_query)
        with self._lock:
            row = self._shards.get(key)
            if row is None:
                if len(self._shards) >= _MAX_SHARDS:
                    key = (_OVERFLOW, _OVERFLOW)
                    row = self._shards.get(key)
                if row is None:
                    row = self._shards[key] = {
                        "queries": 0, "posting_bytes": 0,
                        "dense_bytes": 0, "kernels": {}, "segments": {}}
            row["queries"] += len(per_query)
            row["posting_bytes"] += agg_posting
            row["dense_bytes"] += agg_dense
            segs = row["segments"]
            for seg_id, (n, posting, dense, kernels) in seg_rows.items():
                for kernel, cnt in kernels.items():
                    row["kernels"][kernel] = \
                        row["kernels"].get(kernel, 0) + cnt
                seg = segs.get(seg_id)
                if seg is None:
                    if len(segs) >= _MAX_SEGMENTS_PER_SHARD:
                        seg_id = _OVERFLOW
                        seg = segs.get(seg_id)
                    if seg is None:
                        seg = segs[seg_id] = {
                            "queries": 0, "posting_bytes": 0,
                            "dense_bytes": 0}
                seg["queries"] += n
                seg["posting_bytes"] += posting
                seg["dense_bytes"] += dense
            self.queries += len(per_query)
            self.posting_bytes_total += agg_posting
            self.dense_bytes_total += agg_dense
        for posting, dense in per_query:
            self.per_query_posting.observe(float(posting))
            if dense:
                self.per_query_dense.observe(float(dense))

    def note_pruned_batch(self, index: str, shard: str,
                          seg_pruned: Dict[str, int],
                          per_query: List[Tuple[int, int]]) -> None:
        """Block-max pruning overlay for one msearch wave (ISSUE 20),
        flushed at FINISH time (the pruned counts ride the existing
        result page — phase-A popcounts fetched with the top-k rows, no
        extra round trip). The static note_batch accounting for the same
        wave already landed at prepare; this call only adds the pruned
        deltas, so effective = posting - pruned stays conservative
        (effective <= static always, == when the gate is off).

        seg_pruned: {seg_id: pruned_bytes}; per_query: [(static_posting
        _bytes, pruned_bytes)] for every query in the wave's
        pruning-admitted groups (pruned may be 0 — those still feed the
        effective distribution so pruned/unpruned p50s compare like for
        like). The shard row's pruned bytes derive from seg_pruned, not
        per_query: the SPMD path spans shards in one query and calls
        once per shard, with the single per_query entry on the first
        call only."""
        if not per_query and not seg_pruned:
            return
        key = (str(index), str(shard))
        agg_pruned = sum(int(p) for p in seg_pruned.values())
        with self._lock:
            row = self._shards.get(key)
            if row is None:
                if len(self._shards) >= _MAX_SHARDS:
                    key = (_OVERFLOW, _OVERFLOW)
                    row = self._shards.get(key)
                if row is None:
                    row = self._shards[key] = {
                        "queries": 0, "posting_bytes": 0,
                        "dense_bytes": 0, "kernels": {}, "segments": {}}
            row["pruned_bytes"] = row.get("pruned_bytes", 0) + agg_pruned
            segs = row["segments"]
            for seg_id, pruned in seg_pruned.items():
                seg = segs.get(seg_id)
                if seg is None:
                    seg_id = _OVERFLOW
                    seg = segs.get(seg_id)
                if seg is not None:
                    seg["pruned_bytes"] = \
                        seg.get("pruned_bytes", 0) + int(pruned)
            self.pruned_bytes_total += agg_pruned
            self.pruned_queries += len(per_query)
        for posting, pruned in per_query:
            self.per_query_effective.observe(float(posting - pruned))

    # --------------------------------------------------------------- reading

    def stats(self) -> dict:
        with self._lock:
            shards = {}
            for (index, shard), row in sorted(self._shards.items()):
                pruned = row.get("pruned_bytes", 0)
                segments = {}
                for sid, seg in sorted(row["segments"].items()):
                    s = dict(seg)
                    sp = s.pop("pruned_bytes", 0)
                    s["pruned_bytes"] = sp
                    s["effective_posting_bytes"] = s["posting_bytes"] - sp
                    segments[sid] = s
                shards[f"{index}[{shard}]"] = {
                    "queries": row["queries"],
                    "posting_bytes": row["posting_bytes"],
                    # effective = static ceiling minus phase-B pruned
                    # bytes; identical to posting_bytes when the
                    # blockmax gate is off (conservation contract)
                    "pruned_bytes": pruned,
                    "effective_posting_bytes": row["posting_bytes"] - pruned,
                    "dense_bytes": row["dense_bytes"],
                    "kernels": dict(sorted(row["kernels"].items())),
                    "segments": segments,
                }
            queries = self.queries
            posting = self.posting_bytes_total
            dense = self.dense_bytes_total
            pruned_total = self.pruned_bytes_total
            pruned_queries = self.pruned_queries
        # with no pruning-admitted traffic the effective distribution has
        # no observations of its own: report the static distribution so
        # effective == static holds byte-exactly, not vacuously
        effective = self.per_query_effective.summary() if pruned_queries \
            else self.per_query_posting.summary()
        return {
            "queries": queries,
            "posting_bytes_total": posting,
            "pruned_bytes_total": pruned_total,
            "effective_posting_bytes_total": posting - pruned_total,
            "dense_bytes_total": dense,
            "per_query": {
                "posting_bytes": self.per_query_posting.summary(),
                "effective_posting_bytes": effective,
                "dense_bytes": self.per_query_dense.summary(),
            },
            "shards": shards,
        }

    def reset(self) -> None:
        with self._lock:
            self.queries = 0
            self.posting_bytes_total = 0
            self.dense_bytes_total = 0
            self.pruned_bytes_total = 0
            self.pruned_queries = 0
            self._shards.clear()
        self.per_query_posting.reset()
        self.per_query_dense.reset()
        self.per_query_effective.reset()


# process-wide singleton (the TELEMETRY.scan face; module-level like
# INGEST_EVENTS so deep call sites need no service plumbing)
SCAN = ScanAccounting()
