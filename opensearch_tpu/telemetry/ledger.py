"""Transfer ledger + device-memory accounting for the TPU query path.

PROFILE.md round 8 left the warm B=1024 msearch batch ~266 ms of which
~214 ms is one opaque `device_get` number. ROADMAP item 1 (on-device
top-k/gather + overlapped transfers) needs to know WHICH bytes cross the
tunnel before tearing that wall down; item 2's wave scheduler needs the
live tail. This module is that accounting contract:

- `TransferLedger` attributes every host↔device transfer on the query
  path to a named channel (`topk_ids`, `scores`, `sort_keys`,
  `docvalues`, `agg_buffers`, `result_page` — the single-round-trip
  fused page when `search.result_page.enabled` is on —
  `upload.literals`, `upload.corpus`, `upload.agg_constants`,
  `padding`, ...) with direction, bytes (from
  array `nbytes` / shape·dtype — never an extra device sync), wave id
  and round-trip participation. Aggregates serve
  `GET /_telemetry/transfers` and the `telemetry` section of
  `_nodes/stats`; per-request `LedgerScope` objects feed the Profile
  API's `transfers[]` and the slow log's `bytes_fetched`/
  `device_get_ms` fields.

- `DeviceMemoryAccounting` is the HBM analog of the reference's JVM mem
  stats: live-bytes gauges per channel class (corpus columns, interned
  plan bundles, in-flight wave buffers, agg executable constants,
  compiled-executable counts) fed by registration at the owning layer,
  plus raw `jax.local_devices()[0].memory_stats()` where the backend
  provides it.

No-op discipline (same contract as the PR 4 tracer and the PR 6 fault
injector, asserted by bench.py): the ledger is OFF by default and the
hot-path guard is `LEDGER.scope(trace)` returning None — one attribute
load and a branch, nothing else runs. Per-channel `round_trips` counts
the transfer rounds a channel RODE (channels sharing one fused
`device_get` each count that round); the true global round-trip count is
`device_get.calls` in the snapshot.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Tuple

from opensearch_tpu.telemetry.rolling import RollingEstimator

H2D = "h2d"
D2H = "d2h"

# the host loop and envelope path talk to exactly one device; their
# transfers attribute to it so the per-device table always conserves
# against the channel totals (ISSUE 14's pinned invariant)
DEFAULT_DEVICE = 0

# a query only NAMES a straggler when its per-chip skew clears this
# floor: the per-device walls are measured by blocking replicas in
# device order, so sub-millisecond "skew" is block-ordering noise that
# would otherwise pin every straggler_hit on the last-blocked chip
# (tools/bench_compare.py's skew gate uses the same 1 ms floor)
STRAGGLER_FLOOR_MS = 1.0


class DeviceScope:
    """Per-query per-device accumulator for the SPMD serving path
    (ISSUE 14): the phase breakdown FLASH-MAXSIM's IO-aware framing
    asks for — where and when the bytes moved, per chip.

    Filled by DistributedSearcher.search_resident on the request
    thread:
      - `upload_ms` / `upload_bytes`: the per-query flat-input upload
        (h2d wall measured on host; bytes split per device);
      - `partials`: [(device_id, wall_ms)] — per-chip dispatch→done
        wall, measured by blocking on each device's replica of the
        merged output in device order. The collective aligns chips at
        the merge, so these walls bound each chip's partial top-k
        compute + its wait at the gather; the SKEW (max − median) is
        the straggler signal even when the absolute walls overlap;
      - `merge_*`: the analytic collective-merge accounting — payload
        gathered per device and total ICI bytes (k_local × 3 channels
        × 4 B over the mesh), computed from program statics, never a
        device sync;
      - `pull_ms` / `pull_bytes` / `pull_device`: the result-page
        fetch (the np.asarray d2h sync)."""

    __slots__ = ("devices", "rows", "upload_ms", "upload_bytes",
                 "partials", "merge_payload_bytes", "merge_ici_bytes",
                 "pull_ms", "pull_bytes", "pull_device")

    def __init__(self):
        self.devices = 0
        self.rows = 0
        self.upload_ms = 0.0
        self.upload_bytes = 0
        self.partials: List[Tuple[int, float]] = []
        self.merge_payload_bytes = 0
        self.merge_ici_bytes = 0
        self.pull_ms = 0.0
        self.pull_bytes = 0
        self.pull_device = DEFAULT_DEVICE

    def skew_ms(self) -> float:
        """Straggler skew: max − median per-chip wall for this query
        (0 for a single-chip mesh — there is nobody to straggle
        behind). LOWER median for even chip counts: the upper median
        of two walls IS the max, which would make skew identically 0
        on a 2-chip mesh and structurally blind its straggler gate."""
        if len(self.partials) < 2:
            return 0.0
        walls = sorted(w for _, w in self.partials)
        return walls[-1] - walls[(len(walls) - 1) // 2]

    def straggler(self) -> Optional[int]:
        """The device id with the max per-chip wall — None when fewer
        than two chips reported OR the skew sits under
        STRAGGLER_FLOOR_MS (naming a straggler out of block-ordering
        noise would pin every hit on the last-blocked chip)."""
        if len(self.partials) < 2 \
                or self.skew_ms() < STRAGGLER_FLOOR_MS:
            return None
        return max(self.partials, key=lambda p: p[1])[0]

    def to_dict(self) -> dict:
        """JSON-able phase breakdown — the shape the Profile API's
        SPMD shard entry, the timeline `merge` event and the scaling
        bench all read."""
        return {
            "devices": self.devices,
            "rows": self.rows,
            "upload_ms": round(self.upload_ms, 3),
            "upload_bytes": self.upload_bytes,
            "partials": [{"device": d, "wall_ms": round(w, 3)}
                         for d, w in self.partials],
            "straggler_skew_ms": round(self.skew_ms(), 3),
            "straggler": self.straggler(),
            "collective": {
                "payload_bytes_per_device": self.merge_payload_bytes
                // max(self.devices, 1),
                "payload_bytes": self.merge_payload_bytes,
                "ici_bytes": self.merge_ici_bytes,
            },
            "pull_ms": round(self.pull_ms, 3),
            "pull_bytes": self.pull_bytes,
            "pull_device": self.pull_device,
        }


class DeviceLedger:
    """Per-device attribution for sharded serving (ISSUE 14): the
    `device` dimension on transfer records, the per-chip SPMD phase
    aggregates, and the straggler-skew rolling estimator — the
    measurement layer ROADMAP item 4's multi-chip scale-out is judged
    against, surfaced as `telemetry.devices` on `_nodes/stats`.

    No-op discipline (tracer/ledger/faults contract, gate-lint registry
    row, asserted by bench.py): OFF by default, the per-query gate is
    `scope()` returning None — the disabled SPMD path costs one
    attribute load and a branch, and the disabled TransferLedger.record
    path never touches the per-device table.

    Conservation invariant (pinned by tests/test_device_ledger.py):
    for every channel, the sum of per-device bytes equals the channel
    total in TransferLedger — transfers without an explicit device
    split attribute to DEFAULT_DEVICE (the only device the host loop
    talks to), so nothing ever leaks out of the table."""

    def __init__(self):
        self.enabled = False
        self._lock = threading.Lock()
        # device id -> {channel: {"h2d": bytes, "d2h": bytes}}
        self._transfers: Dict[int, Dict[str, Dict[str, int]]] = {}
        # device id -> per-chip phase aggregates
        self._phases: Dict[int, Dict[str, float]] = {}
        self.queries = 0
        self.collective_payload_bytes = 0
        self.collective_ici_bytes = 0
        self.skew = RollingEstimator()
        self.partial_wall = RollingEstimator()
        self._tls = threading.local()

    # ------------------------------------------------------------- hot path

    def scope(self) -> Optional[DeviceScope]:
        """The per-query gate: a DeviceScope when per-device
        attribution is on, else None — search/spmd.py guards its whole
        capture block with `if scope is not None`."""
        if not self.enabled:
            return None
        return DeviceScope()

    def note_transfer(self, channel: str, direction: str,
                      splits: List[Tuple[int, int]]) -> None:
        """Per-device byte rows for one transfer; `splits` must sum to
        the transfer's channel-recorded bytes (the conservation
        invariant). Called by TransferLedger.record under the enabled
        guard."""
        with self._lock:
            for dev, nbytes in splits:
                chans = self._transfers.setdefault(int(dev), {})
                ent = chans.get(channel)
                if ent is None:
                    ent = chans[channel] = {H2D: 0, D2H: 0}
                ent[direction] += int(nbytes)

    def note_query(self, scope: DeviceScope) -> None:
        """Fold one query's DeviceScope into the node-wide per-chip
        aggregates + the straggler estimators, and stash it as the
        thread's `last` for the Profile API (the SPMD query phase and
        the profile assembly run on the same request thread)."""
        skew = scope.skew_ms()
        straggler = scope.straggler()
        with self._lock:
            self.queries += 1
            self.collective_payload_bytes += scope.merge_payload_bytes
            self.collective_ici_bytes += scope.merge_ici_bytes
            for dev, wall in scope.partials:
                ph = self._phases.get(dev)
                if ph is None:
                    ph = self._phases[dev] = {
                        "queries": 0, "partial_ms": 0.0,
                        "straggler_hits": 0}
                ph["queries"] += 1
                ph["partial_ms"] += wall
                if dev == straggler:
                    ph["straggler_hits"] += 1
        self.skew.observe(skew)
        for _, wall in scope.partials:
            self.partial_wall.observe(wall)
        self._tls.last = scope

    def take_last(self) -> Optional[DeviceScope]:
        """Pop the thread's most recent query scope (profile assembly
        reads it once; popping keeps a later request on this thread
        from inheriting a stale breakdown)."""
        last = getattr(self._tls, "last", None)
        self._tls.last = None
        return last

    # --------------------------------------------------------------- reading

    def snapshot(self) -> dict:
        with self._lock:
            devices = {}
            for dev in sorted(set(self._transfers) | set(self._phases)):
                ent: Dict[str, Any] = {}
                chans = self._transfers.get(dev)
                if chans:
                    ent["transfer_bytes"] = {
                        c: dict(d) for c, d in sorted(chans.items())}
                    ent["h2d_bytes"] = sum(d[H2D] for d in chans.values())
                    ent["d2h_bytes"] = sum(d[D2H] for d in chans.values())
                ph = self._phases.get(dev)
                if ph:
                    ent.update({"queries": int(ph["queries"]),
                                "partial_ms":
                                    round(ph["partial_ms"], 3),
                                "straggler_hits":
                                    int(ph["straggler_hits"])})
                devices[str(dev)] = ent
            queries = self.queries
            payload = self.collective_payload_bytes
            ici = self.collective_ici_bytes
        return {
            "enabled": self.enabled,
            "queries": queries,
            "devices": devices,
            "collective": {
                "payload_bytes_total": payload,
                "ici_bytes_total": ici,
                "ici_bytes_per_query":
                    round(ici / queries, 1) if queries else 0.0,
            },
            "rolling": {"straggler_skew_ms": self.skew.summary(),
                        "partial_wall_ms": self.partial_wall.summary()},
        }

    def device_bytes(self) -> Dict[int, Dict[str, Dict[str, int]]]:
        """{device: {channel: {h2d, d2h}}} — the conservation test's
        read side."""
        with self._lock:
            return {dev: {c: dict(d) for c, d in chans.items()}
                    for dev, chans in self._transfers.items()}

    def reset(self) -> None:
        with self._lock:
            self._transfers.clear()
            self._phases.clear()
            self.queries = 0
            self.collective_payload_bytes = 0
            self.collective_ici_bytes = 0
        self.skew.reset()
        self.partial_wall.reset()


class LedgerScope:
    """Per-request transfer accumulator (explicit context, like spans:
    the msearch envelope runs B requests on one thread, so ambient
    context would misattribute). Entries are (channel, direction,
    bytes, round_trips, wave) tuples."""

    __slots__ = ("entries", "h2d_bytes", "d2h_bytes", "device_get_ms",
                 "round_trips", "waves", "overlap_ms")

    def __init__(self):
        self.entries: List[Tuple[str, str, int, int, Optional[int]]] = []
        self.h2d_bytes = 0
        self.d2h_bytes = 0
        self.device_get_ms = 0.0
        self.round_trips = 0
        # wave-pipeline attribution: how many device waves served this
        # request and how much of their dispatch work ran WHILE an
        # earlier wave's device_get was in flight (the overlap win)
        self.waves = 0
        self.overlap_ms = 0.0

    def absorb(self, other: "LedgerScope") -> None:
        self.entries.extend(other.entries)
        self.h2d_bytes += other.h2d_bytes
        self.d2h_bytes += other.d2h_bytes
        self.device_get_ms += other.device_get_ms
        self.round_trips += other.round_trips
        self.waves += other.waves
        self.overlap_ms += other.overlap_ms

    def to_list(self) -> List[dict]:
        """JSON-able per-transfer records for the Profile API."""
        return [{"channel": c, "direction": d, "bytes": b,
                 "round_trips": r, **({"wave": w} if w is not None else {})}
                for c, d, b, r, w in self.entries]

    def publish(self, span=None, phase_times=None) -> None:
        """The one publication contract for a request's attribution:
        span attributes (bytes_to_device / bytes_fetched / transfers[])
        when the span records, and the phase_times fields the slow log
        reads. Both the controller and the msearch envelope call THIS so
        the two surfaces can never drift."""
        if span is not None and getattr(span, "recording", False):
            span.set_attribute("bytes_to_device", self.h2d_bytes)
            span.set_attribute("bytes_fetched", self.d2h_bytes)
            span.set_attribute("transfers", self.to_list())
            if self.waves:
                span.set_attribute("waves", self.waves)
                span.set_attribute("overlap_ms", round(self.overlap_ms, 3))
        if phase_times is not None:
            phase_times["device_get"] = self.device_get_ms
            phase_times["bytes_fetched"] = self.d2h_bytes
            phase_times["bytes_to_device"] = self.h2d_bytes
            if self.waves:
                phase_times["waves"] = self.waves
                phase_times["overlap_ms"] = self.overlap_ms


class TransferLedger:
    """Node-wide per-channel transfer aggregates + wave accounting."""

    def __init__(self):
        self.enabled = False
        # per-device attribution (ISSUE 14): its own gate — a node can
        # run channel accounting without paying the per-device table,
        # and vice versa the device ledger implies nothing about the
        # channel aggregates' enabled state
        self.devices = DeviceLedger()
        self._lock = threading.Lock()
        # (channel, direction) -> [transfers, round_trips, bytes]
        self._channels: Dict[Tuple[str, str], List[int]] = {}
        self._wave_seq = 0
        self._device_get_calls = 0
        self._device_get_ms = 0.0
        # wave-pipeline gauges: waves dispatched but not yet collected
        # (live like the device-memory classes, not ledger-gated — the
        # update is one lock acquire per WAVE, not per item) plus the
        # measured dispatch/collect overlap the pipeline actually won
        self._inflight_waves = 0
        self._max_inflight_waves = 0
        self._overlap_events = 0
        self._overlap_ms = 0.0
        # live views for the wave scheduler: bytes fetched per wave and
        # device_get wall per wave (rolling.py — O(1) reads)
        self.wave_bytes = RollingEstimator()
        self.wave_ms = RollingEstimator()
        self.wave_overlap_ms = RollingEstimator()
        self._tls = threading.local()

    # ------------------------------------------------------------- hot path

    def scope(self, trace=None) -> Optional[LedgerScope]:
        """The per-request accounting gate: a LedgerScope when either the
        ledger is enabled or the request's trace records (profile /
        tracing), else None — callers guard every accounting block with
        `if scope is not None`, so the disabled path costs one attribute
        load and a branch."""
        if self.enabled or (trace is not None
                            and getattr(trace, "recording", False)):
            return LedgerScope()
        return None

    def new_wave(self) -> Optional[int]:
        """Next global wave id — None when the ledger is disabled (a
        traced-only request still accounts per-request, but must not
        advance the node-wide sequence: snapshot()'s `waves` has to stay
        consistent with its device_get/channel counts)."""
        if not self.enabled:
            return None
        with self._lock:
            self._wave_seq += 1
            return self._wave_seq

    def record(self, channel: str, direction: str, nbytes: int,
               round_trips: int = 1, wave: Optional[int] = None,
               scope: Optional[LedgerScope] = None,
               devices: Optional[List[Tuple[int, int]]] = None) -> None:
        """`devices`: optional per-device byte split [(device_id,
        nbytes), ...] for transfers sharded over a mesh; splits must
        sum to `nbytes` (conservation). None attributes the whole
        transfer to DEFAULT_DEVICE when the device ledger is on — the
        host loop and envelope path talk to exactly one device."""
        nbytes = int(nbytes)
        if scope is not None:
            scope.entries.append((channel, direction, nbytes, round_trips,
                                  wave))
            if direction == H2D:
                scope.h2d_bytes += nbytes
            else:
                scope.d2h_bytes += nbytes
        if not self.enabled:
            return
        tag = getattr(self._tls, "tag", None)
        if tag is not None:
            channel = f"{tag}.{channel}"
        if self.devices.enabled:
            self.devices.note_transfer(
                channel, direction,
                devices if devices is not None
                else [(DEFAULT_DEVICE, nbytes)])
        key = (channel, direction)
        with self._lock:
            ent = self._channels.get(key)
            if ent is None:
                ent = self._channels[key] = [0, 0, 0]
            ent[0] += 1
            ent[1] += round_trips
            ent[2] += nbytes

    def note_device_get(self, ms: float, nbytes: Optional[int] = None,
                        scope: Optional[LedgerScope] = None,
                        round_trips: int = 1) -> None:
        """One collect: wall time + fetched bytes. `round_trips` > 1 when
        the collect degraded to per-program gathers (the msearch
        fallback fetch) — `device_get.calls` stays the TRUE global
        round-trip count, consistent with the channel records."""
        if scope is not None:
            scope.device_get_ms += ms
            scope.round_trips += round_trips
        if not self.enabled:
            return
        with self._lock:
            self._device_get_calls += round_trips
            self._device_get_ms += ms
        self.wave_ms.observe(ms)
        if nbytes:
            self.wave_bytes.observe(float(nbytes))

    def note_round_trip(self, channel: str, ms: float = 0.0,
                        scope: Optional[LedgerScope] = None,
                        wave: Optional[int] = None) -> None:
        """One device round trip that moved no accountable wire bytes on
        THIS backend: the host-mirror stand-in for a device-resident
        column read (the legacy sort-key re-key, fetch.py's per-leaf
        docvalue scans). Records a zero-byte channel entry — byte
        conservation against measured `device_get` nbytes stays exact —
        while `round_trips` and `device_get.calls` count the
        synchronization a tunneled device would pay, which is the wall
        the result page removes (ISSUE 17 satellite 1)."""
        self.record(channel, D2H, 0, round_trips=1, wave=wave,
                    scope=scope)
        if scope is not None:
            scope.device_get_ms += ms
            scope.round_trips += 1
        if not self.enabled:
            return
        with self._lock:
            self._device_get_calls += 1
            self._device_get_ms += ms

    def note_wave_inflight(self, delta: int) -> None:
        """In-flight wave gauge: +1 at dispatch, -1 when the wave's
        collect completes. Live regardless of `enabled` (same contract
        as the device-memory gauges): a `_nodes/stats` poll must see the
        pipeline depth even when per-channel accounting is off."""
        with self._lock:
            self._inflight_waves = max(self._inflight_waves + delta, 0)
            if self._inflight_waves > self._max_inflight_waves:
                self._max_inflight_waves = self._inflight_waves

    def inflight_waves(self) -> int:
        with self._lock:
            return self._inflight_waves

    def note_overlap(self, ms: float,
                     scope: Optional[LedgerScope] = None) -> None:
        """One wave's measured overlap: how long its host prepare +
        async dispatch ran while an earlier wave's device_get was in
        flight on the collector thread — the pipeline's win as a
        first-class number, not a wall-clock inference."""
        if scope is not None:
            scope.overlap_ms += ms
        if not self.enabled:
            return
        with self._lock:
            self._overlap_events += 1
            self._overlap_ms += ms
        self.wave_overlap_ms.observe(ms)

    @contextmanager
    def tagged(self, tag: str):
        """Prefix this thread's channel names (warmup replays record as
        `warmup.upload.literals` etc. so replay traffic never pollutes
        the serving channels). A tagged region is attribution-marked:
        replay syncs are ledger-owned by construction."""
        prev = getattr(self._tls, "tag", None)
        self._tls.tag = tag if prev is None else f"{prev}.{tag}"
        self._tls.attr_depth = getattr(self._tls, "attr_depth", 0) + 1
        try:
            yield
        finally:
            self._tls.tag = prev
            self._tls.attr_depth -= 1

    @contextmanager
    def ambient(self, scope: Optional[LedgerScope]):
        """Bind a request's scope to this thread for call sites too deep
        to plumb it into (the fetch phase's inner-hit gathers). Safe
        ONLY around single-request phases — the msearch envelope must
        keep passing scopes explicitly (B requests share one thread)."""
        prev = getattr(self._tls, "scope", None)
        self._tls.scope = scope
        self._tls.attr_depth = getattr(self._tls, "attr_depth", 0) + 1
        try:
            yield
        finally:
            self._tls.scope = prev
            self._tls.attr_depth -= 1

    @contextmanager
    def attributed(self, scope: Optional[LedgerScope] = None):
        """Mark this thread as inside a ledger-attributed region — the
        contract the sync sanitizer (common/sanitize.py) enforces: every
        query-path `device_get` must execute under one of `attributed`/
        `ambient`/`tagged`, i.e. inside code whose transfers the ledger
        can explain. Unlike `ambient`, a None scope does NOT unbind an
        outer ambient scope (the region is attributed even when this
        request's accounting gate returned None)."""
        tls = self._tls
        prev = getattr(tls, "scope", None)
        if scope is not None:
            tls.scope = scope
        tls.attr_depth = getattr(tls, "attr_depth", 0) + 1
        try:
            yield
        finally:
            tls.scope = prev
            tls.attr_depth -= 1

    def attribution_depth(self) -> int:
        """How many attributed regions are active on this thread (0 =
        a sync here is unattributed — the sanitizer's trip condition)."""
        return getattr(self._tls, "attr_depth", 0)

    def current(self) -> Optional[LedgerScope]:
        """The thread's ambient per-request scope, if a phase bound one."""
        return getattr(self._tls, "scope", None)

    # --------------------------------------------------------------- reading

    def snapshot(self) -> dict:
        with self._lock:
            chans = {d: {} for d in (H2D, D2H)}
            totals = {H2D: 0, D2H: 0}
            for (channel, direction), (n, rt, b) in sorted(
                    self._channels.items()):
                chans[direction][channel] = {
                    "transfers": n, "round_trips": rt, "bytes": b}
                totals[direction] += b
            calls, total_ms = self._device_get_calls, self._device_get_ms
            waves = self._wave_seq
            pipeline = {
                "inflight_waves": self._inflight_waves,
                "max_inflight_waves": self._max_inflight_waves,
                "overlap_events": self._overlap_events,
                "overlap_ms": round(self._overlap_ms, 3),
            }
        return {
            "enabled": self.enabled,
            "waves": waves,
            "pipeline": pipeline,
            "device_get": {"calls": calls,
                           "total_ms": round(total_ms, 3)},
            "bytes_total": dict(totals),
            "channels": chans,
            "rolling": {"wave_bytes": self.wave_bytes.summary(),
                        "wave_device_get_ms": self.wave_ms.summary(),
                        "wave_overlap_ms":
                            self.wave_overlap_ms.summary()},
        }

    def reset(self) -> None:
        with self._lock:
            self._channels.clear()
            self._wave_seq = 0
            self._device_get_calls = 0
            self._device_get_ms = 0.0
            # the inflight gauge itself is NOT reset: waves still in
            # flight at reset time must drain to zero, not go negative
            self._max_inflight_waves = self._inflight_waves
            self._overlap_events = 0
            self._overlap_ms = 0.0
        self.wave_bytes.reset()
        self.wave_ms.reset()
        self.wave_overlap_ms.reset()


class ChurnScope:
    """Per-event (one refresh / one merge) accumulator for the device-
    side consequence of a write-path event: which segment images shipped
    (`upload.corpus` bytes), whether each new segment's device shape
    bucket had been seen before (executable reuse) or is novel (the next
    query over it pays an XLA compile), and live-mask-only re-uploads.
    Filled by ShardReader while bound ambient on the refreshing thread
    (write events run start-to-finish on one thread)."""

    __slots__ = ("uploads", "upload_bytes", "live_mask_bytes")

    def __init__(self):
        # (seg_id, nbytes, shape_known)
        self.uploads: List[Tuple[str, int, bool]] = []
        self.upload_bytes = 0
        self.live_mask_bytes = 0

    def note_upload(self, seg_id: str, nbytes: int,
                    shape_known: bool) -> None:
        self.uploads.append((seg_id, int(nbytes), bool(shape_known)))
        self.upload_bytes += int(nbytes)

    def note_live_mask(self, nbytes: int) -> None:
        self.live_mask_bytes += int(nbytes)


# cap on the seen-shape-bucket set: device shapes are power-of-two
# bucketed (ops/device_segment.py), so a real node sees tens of
# buckets; the cap bounds pathological shape churn (randomized tests)
_MAX_SEEN_SHAPES = 4096


class ChurnLedger:
    """Segment-churn ledger (ISSUE 13): one `churn` record per
    refresh/merge event, attributing the *device-side* marginal cost of
    the write path — the measurement ROADMAP item 5's incremental
    segment publish will be judged against.

    Per record: the `upload.corpus` bytes the event re-shipped, a
    recompile/warmup-hit verdict per new segment (did its device shape
    bucket land in an already-compiled (plan-struct, shape-bucket)
    family, or will the first query over it pay a fresh XLA compile),
    and how many interned RotatingMemo entries the event invalidated —
    both the wholesale ShardStats-memo drop a segment-list change
    causes (every skeleton + bundle recompiles on the host) and the
    subset keyed to the removed (segment-uid, mapper-version) pairs.

    No-op discipline (tracer/ledger/faults contract, gate-lint row,
    asserted by bench.py): OFF by default, `scope()` returns None when
    disabled. `observe_shape` alone is live regardless (the
    inflight-wave-gauge contract): it is one lock + set-add per SEGMENT
    UPLOAD, never per query, and the verdict is only honest if the
    seen-set covers uploads from before the ledger was enabled."""

    def __init__(self, ring_size: int = 128):
        self.enabled = False
        self._lock = threading.Lock()
        self._ring: List[dict] = []
        self._ring_size = ring_size
        self._seq = 0
        self._shapes_seen: set = set()
        self._tls = threading.local()
        self.totals = {"events": 0, "refresh": 0, "merge": 0,
                       "recompile_segments": 0, "warm_hit_segments": 0,
                       "upload_bytes": 0, "live_mask_bytes": 0,
                       "memo_entries_dropped": 0,
                       "memo_entries_keyed": 0,
                       "memo_invalidations": 0,
                       "memo_entries_kept": 0,
                       "precompiled": 0,
                       "recompile_on_serve": 0}

    # ------------------------------------------------------------- hot path

    def scope(self) -> Optional[ChurnScope]:
        """The per-event accounting gate: a ChurnScope when the ledger
        is enabled, else None — IndexShard guards its whole attribution
        block with `if scope is not None`, so the disabled refresh path
        costs one attribute load and a branch."""
        if not self.enabled:
            return None
        return ChurnScope()

    def current(self) -> Optional[ChurnScope]:
        """The thread's bound churn scope (ShardReader's read). Tests
        the flag first: the disabled segment-upload path never touches
        thread-local state."""
        if not self.enabled:
            return None
        return getattr(self._tls, "scope", None)

    @contextmanager
    def bound(self, scope: Optional[ChurnScope]):
        prev = getattr(self._tls, "scope", None)
        self._tls.scope = scope
        try:
            yield scope
        finally:
            self._tls.scope = prev

    def observe_shape(self, shape_sig: str) -> bool:
        """Record a segment's device shape-bucket signature; returns
        whether it was already known. Known = some segment with
        byte-identical device array shapes was uploaded before, i.e.
        every executable compiled against that shape family is reusable
        for the new segment (XLA caches per plan signature, and plan
        signatures embed input shapes). Live regardless of `enabled`."""
        with self._lock:
            known = shape_sig in self._shapes_seen
            if not known:
                if len(self._shapes_seen) >= _MAX_SEEN_SHAPES:
                    self._shapes_seen.clear()
                self._shapes_seen.add(shape_sig)
        return known

    def publish(self, scope: ChurnScope, kind: str,
                segments_before: int, segments_after: int,
                docs: int, wall_ms: float,
                memo_entries_dropped: int = 0,
                memo_entries_keyed: int = 0,
                removed_seg_ids: Optional[List[str]] = None,
                event_id: Optional[int] = None,
                shard: Optional[str] = None,
                warmup_registered: Optional[int] = None,
                memo_invalidations: Optional[int] = None,
                memo_entries_kept: Optional[int] = None) -> dict:
        """Close one refresh/merge event's attribution into a churn
        record. The verdict is per NEW segment: `recompile` when its
        shape bucket was unseen at upload time, `warmup_hit` when an
        already-compiled shape family absorbs it."""
        recompiles = sum(1 for _, _, known in scope.uploads if not known)
        warm_hits = sum(1 for _, _, known in scope.uploads if known)
        rec = {
            "kind": kind,
            "shard": shard,
            "segments": {"before": int(segments_before),
                         "after": int(segments_after)},
            "docs": int(docs),
            "wall_ms": round(wall_ms, 3),
            "uploads": [{"seg_id": sid, "bytes": nb,
                         "verdict": "warmup_hit" if known
                         else "recompile"}
                        for sid, nb, known in scope.uploads],
            "upload_bytes": scope.upload_bytes,
            "live_mask_bytes": scope.live_mask_bytes,
            "verdict": ("warmup_hit" if scope.uploads and recompiles == 0
                        else ("recompile" if recompiles else "none")),
            "memo_entries_dropped": int(memo_entries_dropped),
            "memo_entries_keyed": int(memo_entries_keyed),
            # entries actually evicted: with segment-keyed carry on this
            # is the uid-touched subset; without it, the wholesale drop
            "memo_invalidations": int(
                memo_invalidations if memo_invalidations is not None
                else memo_entries_dropped),
        }
        if memo_entries_kept is not None:
            rec["memo_entries_kept"] = int(memo_entries_kept)
        if removed_seg_ids:
            rec["removed_segments"] = list(removed_seg_ids)
        if event_id is not None:
            rec["event_id"] = event_id
        if warmup_registered is not None:
            rec["warmup_registered"] = int(warmup_registered)
        with self._lock:
            self._seq += 1
            rec["churn_id"] = self._seq
            self._ring.append(rec)
            if len(self._ring) > self._ring_size:
                del self._ring[:len(self._ring) - self._ring_size]
            t = self.totals
            t["events"] += 1
            t[kind] = t.get(kind, 0) + 1
            t["recompile_segments"] += recompiles
            t["warm_hit_segments"] += warm_hits
            t["upload_bytes"] += scope.upload_bytes
            t["live_mask_bytes"] += scope.live_mask_bytes
            t["memo_entries_dropped"] += int(memo_entries_dropped)
            t["memo_entries_keyed"] += int(memo_entries_keyed)
            t["memo_invalidations"] += rec["memo_invalidations"]
            if memo_entries_kept is not None:
                t["memo_entries_kept"] += int(memo_entries_kept)
        return rec

    # ---------------------------------------------- verdict lifecycle
    # (ISSUE 16): a `recompile` verdict is provisional — the shape was
    # novel at upload, but WHO pays the compile is decided later. The
    # off-path precompiler flips pending records to `precompiled`; the
    # first serving-thread compile flips them to `recompile-on-serve`
    # (the failure mode the acceptance criterion pins to zero).

    def mark_precompiled(self, churn_ids, took_ms: float,
                         by: str = "precompiler") -> int:
        """Resolve pending `recompile` records for the given churn ids:
        the precompiler absorbed their compiles off-path."""
        if not self.enabled:
            return 0
        ids = set(churn_ids)
        n = 0
        with self._lock:
            for rec in self._ring:
                if rec.get("churn_id") in ids and \
                        rec.get("verdict") == "recompile":
                    rec["verdict"] = "precompiled"
                    rec["precompile_ms"] = round(float(took_ms), 3)
                    rec["precompiled_by"] = by
                    n += 1
            self.totals["precompiled"] += n
        return n

    def note_serve_compile(self) -> int:
        """A serving thread just paid an XLA compile: every still-pending
        `recompile` record escalates to `recompile-on-serve` — the write
        path published a shape the precompiler did not cover in time."""
        if not self.enabled:
            return 0
        n = 0
        with self._lock:
            for rec in self._ring:
                if rec.get("verdict") == "recompile":
                    rec["verdict"] = "recompile-on-serve"
                    n += 1
            self.totals["recompile_on_serve"] += n
        return n

    # --------------------------------------------------------------- reading

    def records(self, size: Optional[int] = None) -> List[dict]:
        """Most-recent-first churn records."""
        with self._lock:
            out = list(self._ring)
        out.reverse()
        return out[:size] if size is not None else out

    def snapshot(self) -> dict:
        with self._lock:
            return {"enabled": self.enabled,
                    "totals": dict(self.totals),
                    "shapes_seen": len(self._shapes_seen),
                    "retained": len(self._ring)}

    def reset(self) -> None:
        """Clear records + totals; the seen-shape set SURVIVES (clearing
        it would turn every post-reset upload into a false `recompile`
        verdict — shapes compiled before the reset stay compiled)."""
        with self._lock:
            self._ring = []
            self._seq = 0
            self.totals = {k: 0 for k in self.totals}


class DeviceMemoryAccounting:
    """Live-bytes gauges per device-memory class.

    Two feeding styles:
      - register/release/adjust: the owning layer reports exact bytes
        (in-flight wave buffers, agg executable constants);
      - providers: a callable sampled at stats() time over live objects
        (corpus columns via the executor's ShardReader weak-set, interned
        bundle memos, compiled-executable counts) — nothing to release,
        dead owners just stop being summed.

    `stats()` also samples `jax.local_devices()[0].memory_stats()` where
    the backend exposes it (TPU runtimes do; CPU returns nothing) — the
    HBM analog of `_nodes/stats`' JVM mem block.
    """

    def __init__(self):
        self._lock = threading.Lock()
        # cls -> {key: (nbytes, per-device split or None)}
        self._registered: Dict[str, Dict[Any, Tuple[int, Any]]] = {}
        self._gauges: Dict[str, int] = {}
        self._providers: Dict[str, Any] = {}

    def register(self, cls: str, key: Any, nbytes: int,
                 devices: Optional[List[Tuple[int, int]]] = None) -> None:
        """`devices`: optional per-device byte split [(device_id,
        nbytes), ...] for allocations sharded over a mesh (ISSUE 14 —
        the HbmShardSet's stacked image); stats() folds the splits into
        a per-class `by_device` breakdown."""
        with self._lock:
            self._registered.setdefault(cls, {})[key] = (
                int(nbytes),
                [(int(d), int(b)) for d, b in devices]
                if devices is not None else None)

    def release(self, cls: str, key: Any) -> None:
        with self._lock:
            self._registered.get(cls, {}).pop(key, None)

    def adjust(self, cls: str, delta: int) -> None:
        """Plain up/down gauge for churny classes (in-flight buffers)."""
        with self._lock:
            self._gauges[cls] = max(self._gauges.get(cls, 0) + int(delta),
                                    0)

    def add_provider(self, name: str, fn) -> None:
        """Idempotent by name: module re-imports keep the latest."""
        with self._lock:
            self._providers[name] = fn

    def live_bytes(self, cls: str) -> int:
        with self._lock:
            if cls in self._gauges:
                return self._gauges[cls]
            return sum(nb for nb, _ in
                       self._registered.get(cls, {}).values())

    def stats(self) -> dict:
        classes: Dict[str, dict] = {}
        with self._lock:
            for cls, entries in self._registered.items():
                ent: Dict[str, Any] = {
                    "live_bytes": sum(nb for nb, _ in entries.values()),
                    "entries": len(entries)}
                by_device: Dict[str, int] = {}
                for nb, split in entries.values():
                    if split:
                        for dev, b in split:
                            by_device[str(dev)] = \
                                by_device.get(str(dev), 0) + b
                if by_device:
                    ent["by_device"] = dict(sorted(by_device.items()))
                classes[cls] = ent
            for cls, v in self._gauges.items():
                classes[cls] = {"live_bytes": v}
            providers = list(self._providers.items())
        for name, fn in providers:
            try:
                classes[name] = dict(fn())
            except Exception:   # except-ok: third-party provider callables; a stats poll must never 500 the node
                classes[name] = {"error": "provider failed"}
        return {"classes": classes, "hbm": _hbm_stats()}

    def reset(self) -> None:
        with self._lock:
            self._registered.clear()
            self._gauges.clear()


def _hbm_stats() -> Optional[dict]:
    """Raw backend memory stats where available (TPU runtimes expose
    bytes_in_use / peak_bytes_in_use etc.; CPU backends return None).

    Strictly passive: a `_nodes/stats` poll must never FORCE backend
    initialization (multi-second on the tunneled TPU, and the tunnel can
    hang) — if jax isn't imported or no backend has been created yet,
    report nothing and let the first real device use pay that cost."""
    try:
        import sys
        jax = sys.modules.get("jax")
        if jax is None:
            return None
        from jax._src import xla_bridge
        if not getattr(xla_bridge, "_backends", None):
            return None
        stats = jax.local_devices()[0].memory_stats()
        if not stats:
            return None
        return {k: v for k, v in stats.items()
                if isinstance(v, (int, float))}
    except Exception:   # except-ok: backend memory_stats is best-effort across jax versions; stats must degrade to None
        return None
