"""Rolling live percentiles: fixed-memory streaming quantile estimators.

The metrics registry's fixed-bucket histograms (metrics.py) answer "what
was the latency distribution since node start" — an all-time view that a
p99-budget-aware scheduler cannot use: after an hour of traffic a burst
of slow waves barely moves the cumulative p99. This module is the LIVE
view: a geometric-bucket histogram with exponential time decay, so
`quantile(p)` reflects roughly the last `half_life_s` of traffic and is
queryable in O(1) with respect to the number of samples (a fixed ~170
bucket walk, no sample retention).

Design constraints (the ROADMAP item-2 wave scheduler is the consumer):

- `observe()` is one bisect + one float add — cheap enough to ride
  every histogram observation in the always-on registry;
- decay is applied LAZILY in whole intervals (one O(buckets) scale per
  `decay interval`, not per observation);
- buckets are geometric (ratio 1.15 over [1e-3, 1e7]) so one estimator
  shape serves milliseconds and bytes alike with a bounded ~7% worst-
  case relative quantile error (geometric interpolation inside the
  winning bucket); convergence against an offline numpy percentile is
  pinned in tests/test_transfer_ledger.py.

Thread-safety: `observe`/`quantile`/`reset` are lock-guarded. The old
"lost float increments under the GIL are tolerable" stance broke once
the decay path existed — two threads entering `_maybe_decay` in the
same interval would BOTH scale the counts (a real distortion, not a
lost sample), and the open-loop concurrent-clients bench (bench.py
--clients) drives N writer threads through every estimator. The lock is
uncontended in steady state and costs well under the per-observation
bisect it guards (pinned by tests/test_rolling_concurrent.py).
"""

from __future__ import annotations

import threading
import time
from bisect import bisect_left
from typing import List, Optional, Tuple

_LO = 1e-3
_HI = 1e7
_RATIO = 1.15


def _make_bounds(lo: float, hi: float, ratio: float) -> Tuple[float, ...]:
    out: List[float] = []
    v = lo
    while v < hi:
        out.append(v)
        v *= ratio
    out.append(v)
    return tuple(out)


_SHARED_BOUNDS = _make_bounds(_LO, _HI, _RATIO)


class RollingEstimator:
    """Exponentially-decayed geometric histogram with p50/p95/p99 reads.

    `half_life_s`: observations lose half their weight every this many
    seconds (None disables decay — the estimator becomes an all-time
    geometric histogram, used by tests for deterministic convergence).
    """

    __slots__ = ("bounds", "counts", "total", "half_life_s",
                 "_decay_interval", "_last_decay", "max", "_clock",
                 "_lock")

    def __init__(self, half_life_s: Optional[float] = 300.0,
                 clock=time.monotonic):
        self.bounds = _SHARED_BOUNDS
        self.counts: List[float] = [0.0] * (len(self.bounds) + 1)
        self.total = 0.0
        self.half_life_s = half_life_s
        # scale at most once per 1/8th half-life: decay stays O(1)
        # amortized per observation while the window error stays small
        self._decay_interval = (half_life_s / 8.0) if half_life_s else None
        self._last_decay = clock()
        self.max: Optional[float] = None
        self._clock = clock
        self._lock = threading.Lock()

    # ------------------------------------------------------------- recording

    def _maybe_decay(self) -> None:
        if self._decay_interval is None:
            return
        now = self._clock()
        elapsed = now - self._last_decay
        if elapsed < self._decay_interval:
            return
        factor = 0.5 ** (elapsed / self.half_life_s)
        counts = self.counts
        for i, c in enumerate(counts):
            if c:
                counts[i] = c * factor
        self.total *= factor
        self._last_decay = now

    def observe(self, value: float) -> None:
        with self._lock:
            self._maybe_decay()
            i = bisect_left(self.bounds, value)
            self.counts[i] += 1.0
            self.total += 1.0
            if self.max is None or value > self.max:
                self.max = value

    # --------------------------------------------------------------- reading

    def quantile(self, p: float) -> Optional[float]:
        """Estimated p-quantile of the decayed window; None when empty.
        Geometric interpolation inside the winning bucket; the overflow
        bucket reports the observed max."""
        with self._lock:
            return self._quantile_locked(p)

    def _quantile_locked(self, p: float) -> Optional[float]:
        self._maybe_decay()
        total = self.total
        if total <= 0.0:
            return None
        target = p * total
        cum = 0.0
        n = len(self.bounds)
        for i, c in enumerate(self.counts):
            if not c:
                continue
            cum += c
            if cum >= target:
                if i >= n:
                    return self.max
                upper = self.bounds[i]
                lower = self.bounds[i - 1] if i else upper / _RATIO
                frac = (target - (cum - c)) / c
                val = lower * (upper / lower) ** frac
                # in-bucket interpolation can overshoot the largest value
                # actually seen; an estimator that reports p95 > max reads
                # as broken to a scheduler, so clamp
                return val if self.max is None else min(val, self.max)
        return self.max

    def summary(self) -> dict:
        """O(1) live digest — what a p99-budget-aware scheduler reads."""
        return {
            "count": round(self.total, 1),
            "p50": _round(self.quantile(0.5)),
            "p95": _round(self.quantile(0.95)),
            "p99": _round(self.quantile(0.99)),
            "max": _round(self.max),
        }

    def reset(self) -> None:
        with self._lock:
            self.counts = [0.0] * (len(self.bounds) + 1)
            self.total = 0.0
            self.max = None
            self._last_decay = self._clock()


def _round(v: Optional[float]) -> Optional[float]:
    return None if v is None else round(v, 4)
