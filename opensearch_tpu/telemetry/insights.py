"""Query Insights: per-shape cost attribution + the heavy-query top-N
registry (ISSUE 15).

Every observability layer so far answers "where did the time go" —
phases (PR 4), transfers (PR 7), lifecycle (PR 10), ingest events
(PR 13), devices and scanned bytes (PR 14) — but none answers "WHICH
queries cost what". The reference OpenSearch ships a Query Insights
subsystem (top-N queries by latency/cpu/memory behind
`/_insights/top_queries`); this module is its analog, built on the
grouping key the repo already interns: PR 5's template signatures.

The join: every completed search / msearch sub-request is attributed to
its **shape class** — the interned `dsl.QueryTemplate.sig` (the query's
structure with literals stripped: `match:3fa2bc01`), falling back to a
structural hash for bodies the interner declines (`~match_phrase:ab12`,
`~hybrid:…`). Per shape class the recorder maintains

  - rolling p50/p99 latency and per-request device milliseconds (the
    wave's `device_get` wall split across co-batched owners exactly as
    PR 14's `device_share_ms` splits the scheduler's shared waves),
  - scanned bytes (telemetry/scan.py's per-query posting/dense counters,
    joined per request — byte-exact against the global heat map),
  - transfer-ledger bytes and round trips (when the ledger is on),
  - co-batch ratio (what fraction of this shape's requests rode a
    shared wave, and with how many companions),
  - compile / bundle-warm-hit counts and the request-cache hit count,
  - a bounded per-tenant count breakdown,

plus three bounded **top-N rings** (latency, device_ms, scan_bytes)
holding full capture records like the flight recorder's — the
"top_queries" face.

Why it matters (ROADMAP items 3/4): the block-max go/no-go trigger is a
global scanned-bytes heat map today, but BM25S-style posting pruning
(arxiv 2407.03618) pays off per query CLASS — head-term dense-kernel
queries and candidate-kernel queries have ~10× different scan profiles
— and the MaxSim rerank tier's multi-stage cost budget (arxiv
1707.08275) needs per-stage per-class attribution from day one. This
recorder is that input, live.

No-op discipline (the tracer/ledger/faults/flight contract, gate-lint
registry row, asserted pristine by bench.py): OFF by default, `gate()`
returns None — the disabled query path costs one attribute load and a
branch per sub-request. Enabled cost is one lock + dict adds per
completed sub-request (no per-hit or per-lane work), gated <2% by the
analytic overhead check in bench.py --insights.

The same shape vocabulary also prices admission: the shape-aware
`DeadlineShedder` pricing (common/admission.py, its own OFF-by-default
`shape_gate()`) replaces the global service median with the arriving
shape's rolling median once that shape has enough samples — a cheap
`match_all` no longer prices a heavy aggs arrival.
"""

from __future__ import annotations

import hashlib
import heapq
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from opensearch_tpu.telemetry.rolling import RollingEstimator

# the three top-N registries (the reference's top_queries metric axes,
# mapped to what THIS node measures: wall, device wall, scanned bytes)
TOP_METRICS = ("latency", "device_ms", "scan_bytes")

DEFAULT_TOP_N = 8

# bound on distinct tracked shape classes: the shape key derives from
# client-supplied bodies, so an unbounded dict would be a memory-DoS
# vector inside the observability layer itself (the TenantQuotas /
# scan-heat-map bounding pattern). Past the cap, new shapes fold into
# the overflow row.
MAX_TRACKED_SHAPES = 256
OVERFLOW_SHAPE = "_other"
# per-shape tenant breakdown bound (tenant ids are client-supplied too)
MAX_TENANTS_PER_SHAPE = 16


def _h8(obj: Any) -> str:
    """Stable 8-hex digest of a structure. md5 over repr: reprs of
    nested tuples/strings/numbers are deterministic across processes
    (unlike hash(), which PYTHONHASHSEED salts), so shape ids compare
    equal across bench rounds — the bench_compare equal-shape-key
    contract."""
    return hashlib.md5(repr(obj).encode()).hexdigest()[:8]


def _skeleton(q: Any) -> Any:
    """Structure-only skeleton of a raw query body: dict keys and
    nesting survive, scalar literals collapse to their type name — the
    fallback grouping key for bodies `dsl.intern_query` declines
    (match_phrase, hybrid, spans, joins, now-math, …). Two bodies with
    the same clause tree and different literals hash equal."""
    if isinstance(q, dict):
        return ("d", tuple((k, _skeleton(v)) for k in sorted(q)
                           for v in (q[k],)))
    if isinstance(q, (list, tuple)):
        return ("l", tuple(_skeleton(v) for v in q))
    return type(q).__name__


# label memo: the envelope renders a label per ITEM when insights or
# the flight recorder is on, and a B=1024 batch of repeated templates
# would otherwise pay 1024 repr+md5 walks per wave — a dict hit is the
# warm cost. Bounded by wholesale clear (shape cardinality is tiny).
_LABEL_MEMO: Dict[tuple, str] = {}


def template_shape(sig: tuple) -> str:
    """Shape id of an interned template signature (dsl.QueryTemplate
    .sig): `<top-clause>:<h8>`, e.g. `match:3fa2bc01`."""
    label = _LABEL_MEMO.get(sig)
    if label is None:
        if len(_LABEL_MEMO) >= 4096:
            _LABEL_MEMO.clear()
        label = f"{sig[0]}:{_h8(sig)}"
        _LABEL_MEMO[sig] = label  # shared-state-ok: benign double-render race; dict slot write is GIL-atomic
    return label


def structural_shape(q: Any) -> str:
    """Fallback shape id for a non-internable body: `~<top>:<h8>` over
    the structural skeleton. The `~` marks the hash family so a report
    reader knows the group key is structural, not an interned
    template."""
    top = "q"
    if isinstance(q, dict) and len(q) == 1:
        top = next(iter(q))
    elif q is None:
        top = "match_all"
    return f"~{top}:{_h8(_skeleton(q))}"


def query_shape(q: Any) -> Tuple[str, str]:
    """(shape id, kind) for a raw query body — THE public join helper
    (the REST shed-pricing hook and the controller both call this).
    kind ∈ {"template", "hash"}."""
    from opensearch_tpu.search import dsl
    tpl = dsl.intern_query(q)
    if tpl is not None:
        return template_shape(tpl.sig), "template"
    return structural_shape(q), "hash"


class _TopN:
    """Bounded top-N ring over one metric, holding full capture
    records. A min-heap keyed (value, seq): the retained set is exactly
    the N largest values ever offered — deterministic regardless of
    offer interleaving (equal values tie-break on arrival seq, which
    the owner assigns under its lock). `records()` renders
    largest-first."""

    __slots__ = ("n", "_heap")

    def __init__(self, n: int = DEFAULT_TOP_N):
        self.n = max(int(n), 1)
        self._heap: List[Tuple[float, int, dict]] = []

    def offer(self, value: float, seq: int, record: dict) -> None:
        if len(self._heap) < self.n:
            heapq.heappush(self._heap, (value, seq, record))
        elif (value, seq) > self._heap[0][:2]:
            heapq.heapreplace(self._heap, (value, seq, record))

    def records(self, size: Optional[int] = None) -> List[dict]:
        out = [rec for _v, _s, rec in
               sorted(self._heap, key=lambda e: e[:2], reverse=True)]
        return out[:size] if size is not None else out

    def clear(self) -> None:
        self._heap = []


def _new_row(kind: str) -> dict:
    return {"kind": kind, "count": 0, "errors": 0, "cached": 0,
            "took_total_ms": 0.0, "device_ms_total": 0.0,
            "posting_bytes": 0, "dense_bytes": 0, "pruned_bytes": 0,
            "h2d_bytes": 0, "d2h_bytes": 0, "round_trips": 0,
            "co_batched_sum": 0, "co_batched_max": 0, "coalesced": 0,
            "compiled": 0, "warm_hits": 0,
            "tenants": {}, "kernels": {},
            "took": RollingEstimator(), "device": RollingEstimator()}


class QueryInsights:
    """Node-wide per-shape cost recorder + the heavy-query top-N rings.

    Thread model: `note()` takes one lock for the row/total/ring
    updates (the rolling estimators carry their own locks and observe
    outside it). The tenant binding and the scan join are thread-local
    — a write-ahead channel the executor/controller read back on the
    SAME thread, never across the wave-collector boundary."""

    def __init__(self, top_n: int = DEFAULT_TOP_N):
        self.enabled = False
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._seq = 0
        self._shapes: Dict[str, dict] = {}
        self.top = {m: _TopN(top_n) for m in TOP_METRICS}
        # global conservation totals, updated ATOMICALLY with the rows:
        # sum-over-shapes == these, and these == the window deltas of
        # the global counters (SCAN byte-exact, ledger byte-exact,
        # msearch.bodies ±1) — the acceptance's conservation contract
        self.totals = {"queries": 0, "errors": 0, "cached": 0,
                       "took_total_ms": 0.0, "device_ms_total": 0.0,
                       "posting_bytes": 0, "dense_bytes": 0,
                       "pruned_bytes": 0,
                       "h2d_bytes": 0, "d2h_bytes": 0, "round_trips": 0}

    # ------------------------------------------------------------- gating

    def gate(self) -> Optional["QueryInsights"]:
        """The per-request gate: None when insights is disabled —
        callers fall straight through (one attribute load + branch)."""
        if not self.enabled:
            return None
        return self

    # ------------------------------------------- thread-local join channels

    def bind_tenant(self, tenant: Optional[str]) -> Optional[str]:
        """Bind the request's tenant to this thread (the REST layer
        owns the request; the executor's note reads it back). Returns
        the previous binding for unbind — only reached when enabled."""
        prev = getattr(self._tls, "tenant", None)
        self._tls.tenant = tenant
        return prev

    def unbind_tenant(self, prev: Optional[str]) -> None:
        self._tls.tenant = prev

    def current_tenant(self) -> Optional[str]:
        return getattr(self._tls, "tenant", None)

    def add_scan(self, posting_bytes: int, dense_bytes: int,
                 pruned_bytes: int = 0) -> None:
        """Accumulate one query-phase execution's scan bytes for the
        CURRENT request (general host loop / SPMD path — the same
        numbers those paths feed telemetry.scan, so the per-shape join
        stays byte-exact). `pruned_bytes`: posting bytes the block-max
        phase-B mask kept out of the gathers (0 with the gate off).
        Read-and-reset by `take_scan` at the request's note point, same
        thread."""
        t = self._tls
        t.scan_p = getattr(t, "scan_p", 0) + int(posting_bytes)
        t.scan_d = getattr(t, "scan_d", 0) + int(dense_bytes)
        if pruned_bytes:
            t.scan_pr = getattr(t, "scan_pr", 0) + int(pruned_bytes)

    def take_scan(self) -> Tuple[int, int, int]:
        t = self._tls
        out = (getattr(t, "scan_p", 0), getattr(t, "scan_d", 0),
               getattr(t, "scan_pr", 0))
        t.scan_p = 0
        t.scan_d = 0
        t.scan_pr = 0
        return out

    def add_family(self, family: str) -> None:
        """Accumulate one kernel-family label for the CURRENT request
        (ISSUE 19): the executor's query phase records which family its
        dispatched program belongs to; the controller's note point
        reads it back on the same thread and splits the request's
        device wall across the recorded families."""
        t = self._tls
        fams = getattr(t, "families", None)
        if fams is None:
            fams = t.families = []
        if family not in fams:
            fams.append(family)

    def take_families(self) -> Tuple[str, ...]:
        t = self._tls
        out = tuple(getattr(t, "families", ()) or ())
        t.families = None
        return out

    # ------------------------------------------------------------- hot path

    def note(self, shape: str, kind: str = "template",
             took_ms: float = 0.0, device_ms: float = 0.0,
             posting_bytes: int = 0, dense_bytes: int = 0,
             pruned_bytes: int = 0,
             h2d_bytes: int = 0, d2h_bytes: int = 0,
             round_trips: int = 0, co_batched: int = 1,
             compiled: bool = False, warm_hit: bool = False,
             cached: bool = False, tenant: Optional[str] = None,
             status: str = "ok",
             kernels: Optional[Dict[str, float]] = None) -> None:
        """Attribute one COMPLETED sub-request to its shape class. One
        lock acquire + dict adds; the two rolling estimators observe
        outside the lock (they carry their own)."""
        scan_bytes = int(posting_bytes) + int(dense_bytes)
        with self._lock:
            row = self._shapes.get(shape)
            if row is None:
                if len(self._shapes) >= MAX_TRACKED_SHAPES \
                        and shape != OVERFLOW_SHAPE:
                    shape = OVERFLOW_SHAPE
                    row = self._shapes.get(shape)
                if row is None:
                    row = self._shapes[shape] = _new_row(kind)
            row["count"] += 1
            self.totals["queries"] += 1
            if status != "ok":
                row["errors"] += 1
                self.totals["errors"] += 1
            if cached:
                row["cached"] += 1
                self.totals["cached"] += 1
            row["took_total_ms"] += float(took_ms)
            row["device_ms_total"] += float(device_ms)
            row["posting_bytes"] += int(posting_bytes)
            row["dense_bytes"] += int(dense_bytes)
            row["pruned_bytes"] = \
                row.get("pruned_bytes", 0) + int(pruned_bytes)
            row["h2d_bytes"] += int(h2d_bytes)
            row["d2h_bytes"] += int(d2h_bytes)
            row["round_trips"] += int(round_trips)
            row["co_batched_sum"] += int(co_batched)
            if co_batched > row["co_batched_max"]:
                row["co_batched_max"] = int(co_batched)
            if co_batched > 1:
                row["coalesced"] += 1
            if compiled:
                row["compiled"] += 1
            if warm_hit:
                row["warm_hits"] += 1
            if kernels:
                # per-shape kernel-family device-ms breakdown (ISSUE
                # 19): which executable family owns this shape's cost
                krow = row["kernels"]
                for fam, ms in kernels.items():
                    krow[fam] = krow.get(fam, 0.0) + float(ms)
            t = tenant or "_default"
            tenants = row["tenants"]
            if t not in tenants and len(tenants) >= MAX_TENANTS_PER_SHAPE:
                t = OVERFLOW_SHAPE
            tenants[t] = tenants.get(t, 0) + 1
            self.totals["took_total_ms"] += float(took_ms)
            self.totals["device_ms_total"] += float(device_ms)
            self.totals["posting_bytes"] += int(posting_bytes)
            self.totals["dense_bytes"] += int(dense_bytes)
            self.totals["pruned_bytes"] = \
                self.totals.get("pruned_bytes", 0) + int(pruned_bytes)
            self.totals["h2d_bytes"] += int(h2d_bytes)
            self.totals["d2h_bytes"] += int(d2h_bytes)
            self.totals["round_trips"] += int(round_trips)
            self._seq += 1
            seq = self._seq
            # the heavy-query registries: full capture records like the
            # flight recorder's, bounded, deterministic eviction (the
            # retained set is the N largest per metric)
            rec = {"shape": shape, "kind": kind, "seq": seq,
                   "ts_ms": int(time.time() * 1000),
                   "took_ms": round(float(took_ms), 3),
                   "device_ms": round(float(device_ms), 3),
                   "scan_bytes": scan_bytes,
                   "posting_bytes": int(posting_bytes),
                   "dense_bytes": int(dense_bytes),
                   "transfer_bytes": int(h2d_bytes) + int(d2h_bytes),
                   "co_batched": int(co_batched),
                   "tenant": t, "cached": bool(cached),
                   "status": status}
            self.top["latency"].offer(float(took_ms), seq, rec)
            self.top["device_ms"].offer(float(device_ms), seq, rec)
            self.top["scan_bytes"].offer(float(scan_bytes), seq, rec)
        row["took"].observe(float(took_ms))
        if device_ms:
            row["device"].observe(float(device_ms))

    # --------------------------------------------------------------- reading

    def _render_row(self, row: dict) -> dict:
        count = row["count"]
        took = row["took"].summary()
        dev = row["device"].summary()
        return {
            "kind": row["kind"],
            "count": count,
            "errors": row["errors"],
            "cached": row["cached"],
            "took_total_ms": round(row["took_total_ms"], 3),
            "p50_ms": took["p50"],
            "p99_ms": took["p99"],
            "max_ms": took["max"],
            "device_ms_total": round(row["device_ms_total"], 3),
            "device_p50_ms": dev["p50"],
            "device_p99_ms": dev["p99"],
            "posting_bytes": row["posting_bytes"],
            # effective = static posting minus block-max pruned bytes;
            # equal to posting_bytes whenever the gate is off
            "pruned_bytes": row.get("pruned_bytes", 0),
            "effective_posting_bytes":
                row["posting_bytes"] - row.get("pruned_bytes", 0),
            "dense_bytes": row["dense_bytes"],
            "h2d_bytes": row["h2d_bytes"],
            "d2h_bytes": row["d2h_bytes"],
            "round_trips": row["round_trips"],
            "co_batch_ratio": round(row["coalesced"] / count, 3)
            if count else 0.0,
            "co_batched_mean": round(row["co_batched_sum"] / count, 2)
            if count else 0.0,
            "co_batched_max": row["co_batched_max"],
            "compiled": row["compiled"],
            "warm_hits": row["warm_hits"],
            "tenants": dict(sorted(row["tenants"].items())),
            "kernels": {f: round(ms, 3)
                        for f, ms in sorted(row["kernels"].items())},
            "dominant_kernel": max(row["kernels"],
                                   key=row["kernels"].get)
            if row["kernels"] else None,
        }

    def snapshot(self, top: bool = False) -> dict:
        """The `insights` block: per-shape rows (device-ms-hottest
        first) + conservation totals; `top=True` adds the three top-N
        registries (the `/_insights` face — `_nodes/stats` keeps the
        lighter shape)."""
        with self._lock:
            shapes = {shape: self._render_row(row)
                      for shape, row in self._shapes.items()}
            totals = dict(self.totals)
            totals["took_total_ms"] = round(totals["took_total_ms"], 3)
            totals["device_ms_total"] = round(
                totals["device_ms_total"], 3)
            out = {
                "enabled": self.enabled,
                "shapes_tracked": len(self._shapes),
                "totals": totals,
                "shapes": dict(sorted(
                    shapes.items(),
                    key=lambda kv: -kv[1]["device_ms_total"])),
            }
            if top:
                out["top"] = {m: ring.records()
                              for m, ring in self.top.items()}
        return out

    def top_queries(self, metric: str,
                    size: Optional[int] = None) -> List[dict]:
        """The reference's `GET /_insights/top_queries?metric=…` face:
        the bounded registry for one metric, heaviest first."""
        ring = self.top.get(metric)
        if ring is None:
            raise KeyError(metric)
        with self._lock:
            return ring.records(size)

    def stats(self) -> dict:
        with self._lock:
            return {"enabled": self.enabled,
                    "shapes_tracked": len(self._shapes),
                    "queries": self.totals["queries"],
                    "errors": self.totals["errors"]}

    def clear(self) -> None:
        with self._lock:
            self._shapes.clear()
            self._seq = 0
            for ring in self.top.values():
                ring.clear()
            for k in self.totals:
                self.totals[k] = 0.0 if k.endswith("_ms") else 0


# process-wide singleton (the SCAN / INGEST_EVENTS pattern: deep call
# sites — executor wave merge, controller epilogue — need no service
# plumbing); TELEMETRY.insights is this instance
INSIGHTS = QueryInsights()
