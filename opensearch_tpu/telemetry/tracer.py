"""Request-scoped tracing: explicit-context spans over the search path.

Re-design of the reference telemetry tracing layer (libs/telemetry
TracerFactory + the spans the REST/transport interceptors open). Two
deliberate departures, both forced by this build's execution model:

- Context is a plain object passed DOWN the call chain (`trace=` params),
  never a thread-local: the msearch envelope executes B requests inside
  one device program on one thread, so ambient context would attribute
  every sub-request's device work to whichever request happened to be
  "current".
- Spans time with `time.perf_counter_ns()` and close via context manager
  (`with span.child("phase"):`), so failure paths — exceptions,
  backpressure rejections — still close every opened span.

When tracing is disabled (the default), `start_trace` returns a shared
NOOP span whose every method is a constant-time no-op — the query path
pays a couple of attribute loads, nothing else.

Completed root spans land in a bounded in-memory ring buffer served by
`GET /_telemetry/traces` and, when configured with a data dir, are
appended as JSONL under `_state/traces.jsonl` for offline analysis
(tools/trace_report.py).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

DEFAULT_RING_SIZE = 256


class Span:
    """One timed operation. `children` nest; attributes are flat K/V."""

    __slots__ = ("name", "attributes", "children", "start_ns", "end_ns",
                 "status", "error")

    recording = True

    def __init__(self, name: str, attributes: Optional[dict] = None):
        self.name = name
        self.attributes: Dict[str, Any] = dict(attributes) \
            if attributes else {}
        self.children: List["Span"] = []
        self.start_ns = time.perf_counter_ns()
        self.end_ns: Optional[int] = None
        self.status = "ok"
        self.error: Optional[str] = None

    # ------------------------------------------------------------- lifecycle

    def child(self, name: str, **attributes) -> "Span":
        s = Span(name, attributes)
        self.children.append(s)
        return s

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[key] = value

    def end(self, status: Optional[str] = None,
            error: Optional[BaseException] = None) -> None:
        if self.end_ns is None:
            self.end_ns = time.perf_counter_ns()
        if error is not None:
            self.status = "error"
            self.error = f"{type(error).__name__}: {error}"
        if status is not None:
            self.status = status

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.end(error=exc if exc_type is not None else None)
        return False

    # --------------------------------------------------------------- reading

    def duration_ns(self) -> int:
        end = self.end_ns if self.end_ns is not None \
            else time.perf_counter_ns()
        return end - self.start_ns

    def to_dict(self) -> dict:
        out: Dict[str, Any] = {
            "name": self.name,
            "duration_ms": round(self.duration_ns() / 1e6, 3),
            "status": self.status,
        }
        if self.error is not None:
            out["error"] = self.error
        if self.attributes:
            out["attributes"] = self.attributes
        if self.children:
            out["children"] = [c.to_dict() for c in self.children]
        return out


class _NoopSpan:
    """Shared constant returned when tracing is off: absorbs the whole
    Span API in O(1) with no allocation."""

    __slots__ = ()
    recording = False
    children: List[Any] = []
    attributes: Dict[str, Any] = {}
    status = "ok"

    def child(self, name: str, **attributes) -> "_NoopSpan":
        return self

    def set_attribute(self, key: str, value: Any) -> None:
        pass

    def end(self, status=None, error=None) -> None:
        pass

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        return False

    def duration_ns(self) -> int:
        return 0

    def to_dict(self) -> dict:
        return {}


NOOP_SPAN = _NoopSpan()


class Tracer:
    """Node-wide tracer: opens root spans, retains completed traces."""

    def __init__(self, ring_size: int = DEFAULT_RING_SIZE):
        self.enabled = False
        self._ring: "deque[dict]" = deque(maxlen=ring_size)
        self._lock = threading.Lock()
        # separate lock for file appends: a slow disk must not block
        # other threads' ring appends
        self._io_lock = threading.Lock()
        self.jsonl_path: Optional[str] = None
        self.started = 0
        self.finished = 0
        self.export_errors = 0

    # ------------------------------------------------------------- lifecycle

    def start_trace(self, name: str, force: bool = False, **attributes):
        """Root span for one request. `force=True` returns a real span
        even when tracing is disabled (the profile API builds its
        response from request-scoped spans regardless of node-wide
        tracing) — forced traces are NOT retained in the ring unless the
        tracer is enabled."""
        if not self.enabled and not force:
            return NOOP_SPAN
        if self.enabled and not force:
            # forced (profile-only) spans are request-local and never
            # reach finish(); counting them would make started/finished
            # read as leaked spans
            self.started += 1
        return Span(name, attributes)

    def finish(self, span) -> None:
        """Close a root span and retain it (ring + optional JSONL).
        Spans for failed/rejected requests close here too — the caller
        sets status before finishing. No-op for NOOP spans and, when the
        tracer is disabled, for forced (profile-only) spans."""
        if not getattr(span, "recording", False):
            return
        span.end()
        # count the finish even if tracing was disabled mid-request: the
        # span was counted started, and started != finished is this API's
        # leaked-span signal — it must not fire on a runtime toggle
        with self._lock:
            self.finished += 1
        if not self.enabled:
            return
        rec = {"trace": span.to_dict(), "ts_ms": int(time.time() * 1000)}
        with self._lock:
            self._ring.append(rec)
        path = self.jsonl_path
        if path is not None:
            line = json.dumps(rec, default=str) + "\n"
            try:
                # serialized append: concurrent finishers must not
                # interleave partial lines (one json line can span
                # multiple write() syscalls)
                with self._io_lock, open(path, "a") as f:
                    f.write(line)
            except OSError:
                self.export_errors += 1

    # --------------------------------------------------------------- reading

    def traces(self, size: Optional[int] = None) -> List[dict]:
        """Most-recent-first dump of the ring buffer."""
        with self._lock:
            out = list(self._ring)
        out.reverse()
        return out[:size] if size is not None else out

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def resize(self, ring_size: int) -> None:
        with self._lock:
            self._ring = deque(self._ring, maxlen=max(int(ring_size), 1))

    def stats(self) -> dict:
        with self._lock:
            retained = len(self._ring)
            maxlen = self._ring.maxlen
        return {"enabled": self.enabled, "started": self.started,
                "finished": self.finished, "retained": retained,
                "ring_size": maxlen, "jsonl_path": self.jsonl_path,
                "export_errors": self.export_errors}
