"""Kernel-level device-compute profiler (ISSUE 19): executable census,
XLA cost/roofline ledger, and per-family device-time attribution.

The five committed observability layers measure host walls, transfer
bytes, scan bytes and per-chip partials — nothing attributes device
compute to the EXECUTABLES that spend it. This module is that sixth
layer, in three parts:

1. **Executable census (always-on).** Every JIT-cache miss registers an
   executable record — kernel-family label, cache-key fingerprint,
   shape bucket, synchronous compile wall — harvested inside the
   existing first-call timing wrapper (`timed_first_call`, moved here
   from search/executor.py so the ops-layer jit sites can reach it
   without an import cycle). Static cost comes from XLA's own
   `lowered.cost_analysis()` (flops / bytes accessed, captured without
   a second compile) where the backend provides it, and from the
   analytic scan formulas (telemetry/scan.py) where it does not; the
   `cost_source` field says which. Census writes happen ONLY at compile
   time — the steady state (cache hit) takes no lock and allocates
   nothing, the same discipline the <2% gate demands of every layer.
   Census `compile_ms` totals reconcile with the always-on
   `search.xla_compile_ms` histogram by construction: both are fed by
   the SAME `note_compile` call on the same wrapper.

2. **Gated timed dispatch (`telemetry.kernels.enabled`, OFF by
   default).** When on, runners wrap their cached executables in a
   sampling timer: every Nth dispatch per family (``sample_every``)
   runs synchronously under `jax.block_until_ready` and feeds a rolling
   p50/p99 (telemetry/rolling.py) plus a per-family device-ms ledger.
   The block is a measurement mechanism, not overhead — the wave's
   result pull would absorb those waits — and sampling bounds the lost
   dispatch overlap. Scaled totals (`sampled_ms * calls / sampled`)
   conserve against the transfer ledger's wave collect walls: they
   explain at least 90% of the clean-run collect wall (bench.py
   asserts this per workload); any excess is the async pipeline's
   dispatch/host overlap made measurable — the timer sees TOTAL
   compute, the collect only the part no host work hid.

3. **Roofline classification.** Arithmetic intensity flops/bytes vs the
   configurable `telemetry.kernels.peak_flops` / `peak_bw` ridge marks
   each family compute- vs memory-bound — the first table a TPU tuning
   session reads (ROADMAP item 1) and the device-ms price list the
   insight-driven adaptive loop (item 5) needs per executable.

Kernel-family vocabulary (the label every census/timing row carries):
``bm25_candidate`` / ``bm25_dense`` (the two envelope kernels),
``agg_env`` (fused agg envelope + agg-bearing general path),
``hybrid_env`` (fused hybrid envelope), ``page_merger`` (single-round-
trip result page), ``knn`` (vector scoring + IVF k-means build),
``maxsim`` / ``maxsim_adc`` (late-interaction exact / PQ-fused),
``expand`` (delta-publish decompressors).

Surfaced via `GET /_telemetry/kernels` (+ `_enable`/`_disable`/
`_clear`), the `kernels` block of `GET /_nodes/stats`, Profile API
per-shard `kernels` entries, and tools/kernel_report.py.
"""

from __future__ import annotations

import hashlib
import threading
import time
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional, Tuple

from opensearch_tpu.telemetry.rolling import RollingEstimator

KERNEL_FAMILIES = ("bm25_candidate", "bm25_dense", "agg_env",
                   "hybrid_env", "page_merger", "knn", "maxsim",
                   "maxsim_adc", "expand", "other")

# census ring cap: one record per compiled executable — real nodes hold
# hundreds of executables, not thousands; overflow counts, not crashes
MAX_CENSUS_ENTRIES = 2048

# default roofline peaks (overridable via telemetry.kernels.peak_flops /
# telemetry.kernels.peak_bw node settings): deliberately round numbers a
# CPU-backend dev box roughly matches — the TPU session sets real ones
DEFAULT_PEAK_FLOPS = 1.0e12     # 1 TFLOP/s
DEFAULT_PEAK_BW = 1.0e11        # 100 GB/s
DEFAULT_SAMPLE_EVERY = 16


def fingerprint(key: Any) -> str:
    """Stable 8-hex digest of a JIT-cache key (repr is deterministic for
    the tuple-of-primitives keys the executor builds)."""
    return hashlib.md5(repr(key).encode("utf-8"),
                       usedforsecurity=False).hexdigest()[:8]


# ---------------------------------------------------------------- compiles
#
# Per-THREAD compile accounting for request attribution (moved here from
# search/executor.py so ops-layer jit sites — knn k-means, delta-publish
# expanders — share one wrapper without importing the executor): the XLA
# compile happens synchronously on the dispatching thread during the
# wrapped first call, so a thread-local is the correct request scope.

THREAD_COMPILES = threading.local()


def note_compile(ms: float) -> None:
    from opensearch_tpu.telemetry import TELEMETRY
    m = TELEMETRY.metrics
    if getattr(THREAD_COMPILES, "offpath", False):
        # precompiler replay thread (ISSUE 16): the compile happened
        # OFF the serving path — it must not count as a serving-thread
        # cache miss (the steady-state assertion is `xla_cache_miss`
        # delta == 0 under ingest), but stays visible under its own name
        m.counter("search.xla_compile_offpath").inc()
        m.histogram("search.xla_compile_ms").observe(ms)
    else:
        m.counter("search.xla_cache_miss").inc()
        m.histogram("search.xla_compile_ms").observe(ms)
        # a serving thread paid the cliff: flip any pending `recompile`
        # churn verdicts to `recompile-on-serve` (gated internally —
        # disabled ledger costs one attribute load + branch)
        TELEMETRY.churn.note_serve_compile()
    if getattr(THREAD_COMPILES, "active", False):
        THREAD_COMPILES.count += 1
        THREAD_COMPILES.ms += ms


@contextmanager
def offpath_compiles():
    """Mark this thread's XLA compiles as OFF-PATH (the precompiler's
    replay, search/warmup.py Precompiler): note_compile routes them to
    `search.xla_compile_offpath` instead of `search.xla_cache_miss`, so
    background compilation never pollutes the serving-thread compile
    counters a bench or operator watches for the first-touch cliff."""
    prev = getattr(THREAD_COMPILES, "offpath", False)
    THREAD_COMPILES.offpath = True
    try:
        yield
    finally:
        THREAD_COMPILES.offpath = prev


def timed_first_call(fn, family: Optional[str] = None, shape: str = "",
                     key: Any = None,
                     cost: Optional[Tuple[float, float]] = None):
    """Wrap a freshly jitted program so its FIRST invocation — where jax
    traces, lowers and XLA-compiles synchronously before the async
    execution dispatch — is timed and recorded as a compile event
    (`search.xla_cache_miss` counter + `search.xla_compile_ms`
    histogram, plus the current thread's request attribution). Only the
    miss occurrence gets the wrapper; cache hits return the raw jitted
    fn, so the steady state pays nothing.

    When `family` is given the call also registers an executable-census
    record (always-on — the registration is a compile-time event, never
    a steady-state cost): fingerprint from `key`, static flops/bytes
    from XLA `cost_analysis()` when the backend provides it, from the
    analytic `cost` estimate (telemetry/scan.py formulas) otherwise."""

    def first(*args):
        t0 = time.perf_counter_ns()
        out = fn(*args)
        ms = (time.perf_counter_ns() - t0) / 1e6
        note_compile(ms)
        if family is not None:
            KERNELS.census_note(fn, args, family, shape,
                                fingerprint(key), ms, cost)
        return out

    return first


# ---------------------------------------------------------------- profiler


def _xla_cost(fn, args) -> Tuple[Optional[float], Optional[float]]:
    """Best-effort static cost from XLA: `lowered.cost_analysis()` on
    jax 0.4 re-traces but does NOT compile a second time. Any failure
    (backend without cost model, non-lowerable args) degrades to the
    analytic fallback — census registration must never fail a query."""
    try:
        ca = fn.lower(*args).cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else None
        if not isinstance(ca, dict):
            return None, None
        flops = ca.get("flops")
        nbytes = ca.get("bytes accessed")
        return (float(flops) if flops is not None else None,
                float(nbytes) if nbytes is not None else None)
    except Exception:  # except-ok: census is best-effort -- cost capture must never fail the first dispatch
        return None, None


def _family_row() -> dict:
    return {"calls": 0, "sampled": 0, "sampled_ms": 0.0,
            "est": RollingEstimator(), "shapes": {}}


class KernelProfiler:
    """The sixth gated observability layer (see module docstring).

    Census methods are always-on but only run at compile time; the
    per-dispatch timing rides the None-returning `gate()` discipline —
    disabled, the hot path pays one attribute load and a branch, and
    executables are returned UNWRAPPED (no timer closure at all)."""

    def __init__(self):
        self.enabled = False
        self.sample_every = DEFAULT_SAMPLE_EVERY
        self.peak_flops = DEFAULT_PEAK_FLOPS
        self.peak_bw = DEFAULT_PEAK_BW
        self._census_lock = threading.Lock()
        self._census: List[dict] = []
        self._census_dropped = 0
        self._exec_lock = threading.Lock()
        self._families: Dict[str, dict] = {}

    # ------------------------------------------------------------- gate

    def gate(self) -> Optional["KernelProfiler"]:
        """None when disabled — callers guard with `if k is not None`,
        so the default query path never builds a timer closure."""
        if not self.enabled:
            return None
        return self

    # ----------------------------------------------------------- census

    def census_note(self, fn, args, family: str, shape: str,
                    fp: str, compile_ms: float,
                    cost: Optional[Tuple[float, float]] = None) -> None:
        """Register one compiled executable (compile-time only — called
        from the first-call wrapper, never on a cache hit)."""
        flops, nbytes = _xla_cost(fn, args)
        source = "xla"
        if flops is None and nbytes is None:
            source = "analytic" if cost is not None else "none"
        if cost is not None:
            if flops is None:
                flops = float(cost[0])
            if nbytes is None:
                nbytes = float(cost[1])
        rec = {"family": family, "fingerprint": fp, "shape": shape,
               "compile_ms": round(compile_ms, 3), "flops": flops,
               "bytes": nbytes, "cost_source": source}
        with self._census_lock:
            if len(self._census) >= MAX_CENSUS_ENTRIES:
                self._census_dropped += 1
            else:
                self._census.append(rec)

    # ----------------------------------------------------------- timing

    def timed(self, fn: Callable, family: str, shape: str = ""):
        """Wrap a cached executable in the sampling timer (enabled path
        only — reached through `gate()`). Every call counts; every Nth
        call per family runs synchronously under block_until_ready and
        feeds the rolling estimator + the per-family sampled-ms ledger."""

        def run(*args):
            if not self._tick(family, shape):
                return fn(*args)
            t0 = time.perf_counter_ns()
            out = fn(*args)
            import jax
            from opensearch_tpu.telemetry import TELEMETRY
            # the sampled sync is ledger-owned measurement by
            # construction (PR 7 sanitizer contract): the wave's result
            # pull would absorb this wait if the timer didn't take it
            with TELEMETRY.ledger.attributed():
                jax.block_until_ready(out)  # sync-ok: kernels.sample -- gated sampling timer owns this wall
            self._note_exec(family, shape,
                            (time.perf_counter_ns() - t0) / 1e6)
            return out

        return run

    def _tick(self, family: str, shape: str) -> bool:
        """Count one dispatch; True when this call is the sampled one.
        Deterministic (call-count modulus, first call always sampled) so
        tests can pin the sample schedule under threaded load."""
        with self._exec_lock:
            row = self._families.get(family)
            if row is None:
                row = self._families[family] = _family_row()
            row["calls"] += 1
            srow = row["shapes"].get(shape)
            if srow is None:
                srow = row["shapes"][shape] = {
                    "calls": 0, "sampled": 0, "sampled_ms": 0.0}
            srow["calls"] += 1
            n = max(1, int(self.sample_every))
            return (row["calls"] - 1) % n == 0

    def _note_exec(self, family: str, shape: str, ms: float) -> None:
        with self._exec_lock:
            row = self._families[family]
            row["sampled"] += 1
            row["sampled_ms"] += ms
            srow = row["shapes"][shape]
            srow["sampled"] += 1
            srow["sampled_ms"] += ms
        row["est"].observe(ms)

    # ---------------------------------------------------------- reading

    def _census_by_family(self) -> Dict[str, dict]:
        out: Dict[str, dict] = {}
        with self._census_lock:
            census = list(self._census)
        for rec in census:
            agg = out.setdefault(rec["family"], {
                "compiles": 0, "compile_ms": 0.0, "flops": 0.0,
                "bytes": 0.0, "cost_known": 0})
            agg["compiles"] += 1
            agg["compile_ms"] += rec["compile_ms"]
            if rec["flops"] is not None and rec["bytes"] is not None:
                agg["flops"] += rec["flops"]
                agg["bytes"] += rec["bytes"]
                agg["cost_known"] += 1
        return out

    def _roofline(self, flops: Optional[float],
                  nbytes: Optional[float]) -> Tuple[Optional[float], str]:
        """(arithmetic intensity, bound class) against the configured
        ridge point peak_flops/peak_bw."""
        if not flops or not nbytes:
            return None, "unknown"
        ai = flops / nbytes
        ridge = self.peak_flops / max(self.peak_bw, 1.0)
        return ai, ("compute" if ai >= ridge else "memory")

    def snapshot(self, census: bool = True) -> dict:
        """The `GET /_telemetry/kernels` body (and, with census=False,
        the compact `_nodes/stats` block): per-family census aggregates
        + roofline verdicts + (when timing ran) sampled device walls
        with the scaled total estimate."""
        by_fam = self._census_by_family()
        with self._exec_lock:
            fams = {f: {"calls": r["calls"], "sampled": r["sampled"],
                        "sampled_ms": r["sampled_ms"],
                        "shapes": {s: dict(sr)
                                   for s, sr in r["shapes"].items()},
                        "est": r["est"]}
                    for f, r in self._families.items()}
        families = {}
        for fam in sorted(set(by_fam) | set(fams)):
            agg = by_fam.get(fam)
            run = fams.get(fam)
            flops = agg["flops"] if agg else None
            nbytes = agg["bytes"] if agg else None
            ai, bound = self._roofline(flops, nbytes)
            row = {"compiles": agg["compiles"] if agg else 0,
                   "compile_ms": round(agg["compile_ms"], 3)
                   if agg else 0.0,
                   "flops": flops, "bytes": nbytes,
                   "arithmetic_intensity": round(ai, 4)
                   if ai is not None else None,
                   "bound": bound,
                   "calls": run["calls"] if run else 0,
                   "sampled": run["sampled"] if run else 0,
                   "sampled_ms": round(run["sampled_ms"], 3)
                   if run else 0.0}
            if run and run["sampled"]:
                # scaled estimate: sampled walls extrapolated over every
                # dispatch — the number that conserves (within the bench
                # bound) against the ledger's wave collect walls
                row["device_ms_est"] = round(
                    run["sampled_ms"] * run["calls"] / run["sampled"], 3)
                row["p50_ms"] = _round(run["est"].quantile(0.5))
                row["p99_ms"] = _round(run["est"].quantile(0.99))
                row["shapes"] = {
                    s: {"calls": sr["calls"], "sampled": sr["sampled"],
                        "sampled_ms": round(sr["sampled_ms"], 3),
                        "device_ms_est": round(
                            sr["sampled_ms"] * sr["calls"]
                            / sr["sampled"], 3) if sr["sampled"] else 0.0}
                    for s, sr in run["shapes"].items()}
            families[fam] = row
        with self._census_lock:
            n_census = len(self._census)
            dropped = self._census_dropped
            compile_total = sum(r["compile_ms"] for r in self._census)
            dump = list(self._census) if census else None
        out = {"enabled": self.enabled,
               "sample_every": self.sample_every,
               "peak_flops": self.peak_flops, "peak_bw": self.peak_bw,
               "ridge_intensity": round(
                   self.peak_flops / max(self.peak_bw, 1.0), 4),
               "census": {"entries": n_census, "dropped": dropped,
                          "compile_ms_total": round(compile_total, 3)},
               "families": families}
        if dump is not None:
            out["census"]["executables"] = dump
        return out

    def stats(self) -> dict:
        """Compact block for `_nodes/stats` (no per-executable dump)."""
        return self.snapshot(census=False)

    def clear(self) -> None:
        """Drop census + timing state (config and gate flag survive)."""
        with self._census_lock:
            self._census = []
            self._census_dropped = 0
        with self._exec_lock:
            self._families = {}


def _round(v: Optional[float]) -> Optional[float]:
    return None if v is None else round(v, 4)


# process-wide singleton, like SCAN / INSIGHTS
KERNELS = KernelProfiler()
