"""Metrics registry: named counters + fixed-bucket latency histograms.

Re-design of the reference telemetry metrics surface (libs/telemetry
MetricsRegistry + the OTel plugin's DefaultMetricsRegistry): producers
grab a counter or histogram by name and record; the registry renders one
JSON-able snapshot for `GET /_nodes/stats` (the `telemetry` section).

Histograms use FIXED bucket boundaries (milliseconds) shared node-wide,
so p50/p99 are estimated by linear interpolation inside the winning
bucket — the same fidelity/overhead trade the reference's explicit-bucket
histograms make. Recording is always-on (like the request-cache hit/miss
counters): one dict lookup + a few float ops per observation, cheap
enough to sit under the query path whether or not tracing is enabled.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from opensearch_tpu.telemetry.rolling import RollingEstimator

# default latency buckets (upper bounds, milliseconds): sub-ms resolution
# for warmed device queries up to the multi-second compile cliff
DEFAULT_BUCKETS_MS: Tuple[float, ...] = (
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0, 5000.0, 10000.0)


class Counter:
    """A monotonically increasing named count. Lock-guarded: `value +=`
    is a read-modify-write the interpreter can interleave, and the
    concurrent-clients serving path (bench.py --clients, ROADMAP item 2)
    drives these from N threads — a drifting counter reads as a lost
    request (tests/test_rolling_concurrent.py pins exactness)."""

    __slots__ = ("name", "value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self.value += n


class Histogram:
    """Fixed-bucket latency histogram (values in milliseconds).

    Every histogram also feeds a rolling live-percentile estimator
    (telemetry/rolling.py): the fixed buckets keep the since-start
    distribution, `rolling` answers "what is the p99 RIGHT NOW" in O(1)
    — the read the wave scheduler (ROADMAP item 2) budgets against."""

    __slots__ = ("name", "buckets", "counts", "count", "sum", "min",
                 "max", "rolling", "_lock")

    def __init__(self, name: str,
                 buckets: Optional[Tuple[float, ...]] = None):
        self.name = name
        self.buckets: Tuple[float, ...] = tuple(buckets or
                                                DEFAULT_BUCKETS_MS)
        self.counts: List[int] = [0] * (len(self.buckets) + 1)  # +overflow
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self.rolling = RollingEstimator()
        # concurrent writers (the N-client serving path) must not lose
        # observations: count/sum are read-modify-write races unguarded.
        # Reads (percentile/to_dict) stay lock-free — estimates tolerate
        # a torn snapshot, the ingest path does not.
        self._lock = threading.Lock()

    def observe(self, value_ms: float) -> None:
        i = 0
        n = len(self.buckets)
        while i < n and value_ms > self.buckets[i]:
            i += 1
        with self._lock:
            self.counts[i] += 1
            self.count += 1
            self.sum += value_ms
            if self.min is None or value_ms < self.min:
                self.min = value_ms
            if self.max is None or value_ms > self.max:
                self.max = value_ms
        self.rolling.observe(value_ms)

    def percentile(self, p: float) -> Optional[float]:
        """Estimated p-quantile (0 < p < 1) by linear interpolation inside
        the winning bucket; the overflow bucket reports the observed max."""
        if self.count == 0:
            return None
        target = p * self.count
        cum = 0
        lower = 0.0
        for i, upper in enumerate(self.buckets):
            prev = cum
            cum += self.counts[i]
            if cum >= target:
                frac = (target - prev) / max(self.counts[i], 1)
                return round(lower + (upper - lower) * frac, 4)
            lower = upper
        return self.max

    def to_dict(self) -> dict:
        live = self.rolling.summary()
        return {
            "count": self.count,
            "sum_ms": round(self.sum, 3),
            "min_ms": round(self.min, 4) if self.min is not None else None,
            "max_ms": round(self.max, 4) if self.max is not None else None,
            "p50_ms": self.percentile(0.5),
            "p95_ms": self.percentile(0.95),
            "p99_ms": self.percentile(0.99),
            # server-computed LIVE percentiles (exponentially decayed
            # rolling window) — distinct from the since-start estimates
            # above; what `GET /_telemetry/metrics` consumers and the
            # future wave scheduler should read for "current" tail
            "summary": {"p50_ms": live["p50"], "p95_ms": live["p95"],
                        "p99_ms": live["p99"], "count": live["count"]},
            "buckets": {
                **{f"le_{b:g}": c
                   for b, c in zip(self.buckets, self.counts)},
                "le_inf": self.counts[-1],
            },
        }


class MetricsRegistry:
    """All named counters/histograms on this node."""

    def __init__(self):
        self._counters: Dict[str, Counter] = {}
        self._histograms: Dict[str, Histogram] = {}
        self._lock = threading.Lock()

    def counter(self, name: str) -> Counter:
        c = self._counters.get(name)
        if c is None:
            with self._lock:
                c = self._counters.setdefault(name, Counter(name))
        return c

    def histogram(self, name: str,
                  buckets: Optional[Tuple[float, ...]] = None) -> Histogram:
        h = self._histograms.get(name)
        if h is None:
            with self._lock:
                h = self._histograms.setdefault(name,
                                                Histogram(name, buckets))
        return h

    def to_dict(self) -> dict:
        return {
            "counters": {name: c.value
                         for name, c in sorted(self._counters.items())},
            "histograms": {name: h.to_dict()
                           for name, h in sorted(self._histograms.items())},
        }

    def reset(self) -> None:
        """Test/bench helper: zero every series IN PLACE — producers hold
        module-level Counter/Histogram handles, so instances must
        survive a reset."""
        with self._lock:
            for c in self._counters.values():
                with c._lock:
                    c.value = 0
            for h in self._histograms.values():
                with h._lock:
                    h.counts = [0] * (len(h.buckets) + 1)
                    h.count = 0
                    h.sum = 0.0
                    h.min = None
                    h.max = None
                h.rolling.reset()
