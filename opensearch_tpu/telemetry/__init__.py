"""Node-wide telemetry: request-scoped tracing + the metrics registry.

The analog of the reference's `libs/telemetry` (TracerFactory +
MetricsRegistry behind the OTel plugin), reduced to what a single-process
node needs: one `TELEMETRY` singleton (the same pattern as
`REQUEST_CACHE` / `WARMUP`) holding

  - `TELEMETRY.tracer`  — request-scoped spans over the search path
    (rest → parse → can_match → per-shard query/device dispatch →
    reduce → fetch → pipeline processors), ring-buffered and dumpable
    via `GET /_telemetry/traces`; OFF by default, a no-op on the hot
    path until enabled;
  - `TELEMETRY.metrics` — always-on named counters and fixed-bucket
    latency histograms (each carrying a rolling live-percentile
    estimator, telemetry/rolling.py) surfaced as the `telemetry`
    section of `GET /_nodes/stats`;
  - `TELEMETRY.ledger` — the transfer ledger (telemetry/ledger.py):
    per-channel host↔device byte/round-trip attribution on the query
    path, OFF by default with the tracer's no-op discipline, served by
    `GET /_telemetry/transfers`;
  - `TELEMETRY.device_memory` — live-bytes gauges per device-memory
    class (corpus columns, interned bundles, in-flight wave buffers,
    ...) plus raw backend `memory_stats()` — the HBM analog of the
    reference's JVM mem stats on `_nodes/stats`;
  - `TELEMETRY.flight` — the request-lifecycle flight recorder
    (telemetry/lifecycle.py): per-request arrive/admit/queue_wait/
    coalesce/dispatch/collect/respond timelines with SLO-breach tail
    capture, OFF by default with the same no-op gate discipline, served
    by `GET /_telemetry/tail`.

Node wires it from settings (`telemetry.tracing.enabled`,
`telemetry.tracing.ring_size`, `telemetry.tracing.jsonl`,
`telemetry.transfers.enabled`, `telemetry.tail.enabled`,
`telemetry.tail.threshold_ms`) and the data dir (`_state/traces.jsonl`,
`_state/tail.jsonl`); tests and bench.py drive it directly.
"""

from __future__ import annotations

import os
from typing import Optional

from opensearch_tpu.telemetry.ledger import (
    ChurnLedger, ChurnScope, DeviceLedger, DeviceMemoryAccounting,
    DeviceScope, LedgerScope, TransferLedger)
from opensearch_tpu.telemetry.lifecycle import (
    INGEST_EVENTS, FlightRecorder, IngestEventLog, IngestRecorder,
    SpmdTimeline, Timeline)
from opensearch_tpu.telemetry.insights import INSIGHTS, QueryInsights
from opensearch_tpu.telemetry.kernels import KERNELS, KernelProfiler
from opensearch_tpu.telemetry.metrics import MetricsRegistry
from opensearch_tpu.telemetry.rolling import RollingEstimator
from opensearch_tpu.telemetry.scan import SCAN, ScanAccounting
from opensearch_tpu.telemetry.tracer import (
    DEFAULT_RING_SIZE, NOOP_SPAN, Span, Tracer)

__all__ = ["TELEMETRY", "TelemetryService", "Span", "NOOP_SPAN",
           "MetricsRegistry", "Tracer", "TransferLedger", "LedgerScope",
           "DeviceMemoryAccounting", "RollingEstimator",
           "FlightRecorder", "Timeline", "IngestRecorder",
           "IngestEventLog", "INGEST_EVENTS", "ChurnLedger",
           "ChurnScope", "DeviceLedger", "DeviceScope", "SpmdTimeline",
           "ScanAccounting", "SCAN", "QueryInsights", "INSIGHTS",
           "KernelProfiler", "KERNELS"]


class TelemetryService:
    """Tracer + metrics + transfer ledger + device-memory accounting +
    lifecycle flight recorder under one configuration surface."""

    def __init__(self):
        self.tracer = Tracer()
        self.metrics = MetricsRegistry()
        self.ledger = TransferLedger()
        self.device_memory = DeviceMemoryAccounting()
        self.flight = FlightRecorder()
        # write-path observability (ISSUE 13): ingest lifecycle recorder
        # + segment-churn ledger, both OFF by default behind
        # None-returning gates; the always-on engine event log rides the
        # lifecycle module singleton (INGEST_EVENTS)
        self.ingest = IngestRecorder()
        self.churn = ChurnLedger()
        # sharded-serving observability (ISSUE 14): the per-device
        # ledger rides the transfer ledger (its `device` dimension);
        # the SPMD collective-phase timeline emitter is its own gate;
        # the scan counters are ALWAYS-ON (the block-max trigger metric
        # — inflight-wave-gauge contract, not the per-request gate
        # discipline)
        self.device_ledger = self.ledger.devices
        self.spmd_timeline = SpmdTimeline()
        self.scan = SCAN
        # query insights (ISSUE 15): per-shape cost attribution + the
        # heavy-query top-N registry, OFF by default behind a
        # None-returning gate() — the "which queries cost what" join
        # over interning + lifecycle + scan + ledger
        self.insights = INSIGHTS
        # kernel profiler (ISSUE 19): executable census (always-on,
        # compile-time-only writes) + gated sampled device walls +
        # roofline classification per kernel family
        self.kernels = KERNELS

    def configure(self, data_path: Optional[str] = None,
                  enabled: bool = False, jsonl: bool = False,
                  ring_size: int = DEFAULT_RING_SIZE,
                  transfers: bool = False, tail: bool = False,
                  tail_threshold_ms: Optional[float] = None,
                  ingest: bool = False, churn: bool = False,
                  devices: bool = False,
                  spmd_timeline: bool = False,
                  insights: bool = False,
                  kernels: bool = False,
                  kernels_peak_flops: Optional[float] = None,
                  kernels_peak_bw: Optional[float] = None,
                  kernels_sample_every: Optional[int] = None) -> None:
        """Bind to a node's settings/data dir. Called from Node.__init__;
        re-configuration by a later Node in the same process wins (the
        singleton is process-wide, like WARMUP)."""
        self.tracer.enabled = bool(enabled)
        self.ledger.enabled = bool(transfers)
        self.flight.enabled = bool(tail)
        self.flight.threshold_ms = tail_threshold_ms
        self.ingest.enabled = bool(ingest)
        self.churn.enabled = bool(churn)
        self.device_ledger.enabled = bool(devices)
        self.spmd_timeline.enabled = bool(spmd_timeline)
        self.insights.enabled = bool(insights)
        self.kernels.enabled = bool(kernels)
        if kernels_peak_flops is not None:
            self.kernels.peak_flops = float(kernels_peak_flops)
        if kernels_peak_bw is not None:
            self.kernels.peak_bw = float(kernels_peak_bw)
        if kernels_sample_every is not None:
            self.kernels.sample_every = max(1, int(kernels_sample_every))
        self.tracer.resize(ring_size)
        self.tracer.jsonl_path = None
        self.flight.jsonl_path = None
        if jsonl and data_path is not None:
            state_dir = os.path.join(data_path, "_state")
            try:
                os.makedirs(state_dir, exist_ok=True)
                self.tracer.jsonl_path = os.path.join(state_dir,
                                                      "traces.jsonl")
                self.flight.jsonl_path = os.path.join(state_dir,
                                                      "tail.jsonl")
            except OSError:
                pass

    def enable(self) -> None:
        self.tracer.enabled = True

    def disable(self) -> None:
        self.tracer.enabled = False

    def stats(self) -> dict:
        return {"tracing": self.tracer.stats(),
                "metrics": self.metrics.to_dict(),
                "transfers": self.ledger.snapshot(),
                "device_memory": self.device_memory.stats(),
                "tail": self.flight.stats(),
                # the write-path block (ISSUE 13): ingest lifecycle +
                # engine event log + segment-churn attribution
                "indexing": {"ingest": self.ingest.stats(),
                             "churn": self.churn.snapshot()},
                # sharded-serving observability (ISSUE 14): per-chip
                # attribution + the always-on scanned-bytes heat map
                # (the block-max trigger metric, live)
                "devices": self.device_ledger.snapshot(),
                "scan": self.scan.stats(),
                # query insights (ISSUE 15): per-shape cost attribution
                # (the top-N rings ride GET /_insights, not this block)
                "insights": self.insights.snapshot(),
                # kernel profiler (ISSUE 19): executable census +
                # per-family device-ms/roofline (compact — the full
                # census dump rides GET /_telemetry/kernels)
                "kernels": self.kernels.stats()}


# process-wide singleton, like REQUEST_CACHE / QUERY_CACHE / WARMUP
TELEMETRY = TelemetryService()
